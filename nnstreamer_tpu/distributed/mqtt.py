"""Minimal MQTT 3.1.1 transport: client + in-process broker (stdlib only).

The reference's mqttsink/mqttsrc (``gst/mqtt/``) link against paho.mqtt.c;
this image has no MQTT library, so the TPU build carries its own small
implementation of the subset the elements need — QoS 0/1 publish (PUBACK +
DUP redelivery), subscribe with ``+``/``#`` wildcards, keep-alive pings,
automatic reconnect with re-subscribe — plus a localhost broker so
pipelines (and tests) run without external infrastructure.  Protocol per
the public OASIS MQTT 3.1.1 spec; reconnect semantics match the
reference's paho ``MQTTAsync`` usage (``gst/mqtt/mqttsrc.c`` reconnects
and resumes its subscription; ``mqttsink.h`` ``mqtt_qos``).

QoS 1 is at-least-once: a publish unacknowledged when the connection
drops is re-sent (DUP flag) after reconnect — receivers may see
duplicates, never corruption or silent loss.

This is control-plane-grade transport (sensor streams, events); bulk
tensor traffic between hosts should ride the gRPC query/edge elements.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..core.log import get_logger

log = get_logger("mqtt")

# packet types (MQTT 3.1.1 §2.2.1)
CONNECT, CONNACK = 1, 2
PUBLISH, PUBACK = 3, 4
SUBSCRIBE, SUBACK = 8, 9
UNSUBSCRIBE, UNSUBACK = 10, 11
PINGREQ, PINGRESP = 12, 13
DISCONNECT = 14


def _encode_len(n: int) -> bytes:
    out = b""
    while True:
        d = n % 128
        n //= 128
        out += bytes([d | (0x80 if n else 0)])
        if not n:
            return out


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("MQTT peer closed")
        buf += chunk
    return buf


def _read_packet(sock: socket.socket) -> Tuple[int, int, bytes]:
    head = _read_exact(sock, 1)[0]
    mult, length = 1, 0
    while True:
        b = _read_exact(sock, 1)[0]
        length += (b & 0x7F) * mult
        if not (b & 0x80):
            break
        mult *= 128
        if mult > 128**3:
            raise ConnectionError("malformed MQTT length")
    payload = _read_exact(sock, length) if length else b""
    return head >> 4, head & 0xF, payload


def _mqtt_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


class MqttProtocolError(ValueError):
    pass


def _parse_publish(flags: int, body: bytes) -> Tuple[str, bytes, Optional[int]]:
    """PUBLISH variable header -> (topic, payload, packet_id|None); shared
    by broker and client so malformed-body handling stays in one place."""
    if len(body) < 2:
        raise MqttProtocolError("PUBLISH body too short")
    tlen = struct.unpack(">H", body[:2])[0]
    off = 2 + tlen
    if off > len(body):
        raise MqttProtocolError("PUBLISH topic length exceeds body")
    try:
        topic = body[2:off].decode()
    except UnicodeDecodeError as e:
        raise MqttProtocolError(f"PUBLISH topic not UTF-8: {e}") from None
    pid = None
    if (flags >> 1) & 0x3:  # QoS > 0 carries a packet id
        if off + 2 > len(body):
            raise MqttProtocolError("PUBLISH missing packet id")
        pid = struct.unpack(">H", body[off : off + 2])[0]
        off += 2
    return topic, body[off:], pid


def _publish_packet(topic: str, payload: bytes, retain: bool = False,
                    qos: int = 0, pid: int = 0, dup: bool = False) -> bytes:
    var = _mqtt_str(topic)
    if qos:
        var += struct.pack(">H", pid)
    var += payload
    head = (PUBLISH << 4) | (1 if retain else 0) | ((qos & 0x3) << 1)
    if dup:
        head |= 0x8
    return bytes([head]) + _encode_len(len(var)) + var


# persistent-session store across broker restarts, keyed by port (see
# MiniBroker.__init__/close).  Entries carry a timestamp: a successor
# only adopts FRESH state (a restart follows its crash within seconds) —
# stale entries would contaminate an unrelated broker when the OS reuses
# an ephemeral port — and stale entries are evicted on every touch so a
# long-lived process cannot accumulate dead backlogs.
_SESSION_STORE: Dict[int, Tuple[float, Dict[str, "_BrokerSession"]]] = {}
_SESSION_STORE_TTL_S = 300.0


def _session_store_evict_stale(now: Optional[float] = None) -> None:
    now = time.monotonic() if now is None else now
    for port in [p for p, (ts, _) in _SESSION_STORE.items()
                 if now - ts > _SESSION_STORE_TTL_S]:
        del _SESSION_STORE[port]


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT wildcard match: ``+`` one level, ``#`` rest (spec §4.7)."""
    pp, tp = pattern.split("/"), topic.split("/")
    for i, p in enumerate(pp):
        if p == "#":
            return True
        if i >= len(tp):
            return False
        if p != "+" and p != tp[i]:
            return False
    return len(pp) == len(tp)


class _BrokerSession:
    """Per-client-id broker state: subscriptions (pattern -> granted QoS),
    the live socket (None while offline), QoS-1 messages in flight to the
    subscriber, and — for persistent (clean_session=0) sessions — messages
    queued while offline."""

    __slots__ = ("cid", "clean", "subs", "sock", "inflight", "queue",
                 "next_pid", "dropped")

    QUEUE_LIMIT = 1024     # offline/overflow backlog bound per session
    INFLIGHT_LIMIT = 512   # unacked deliveries per connected subscriber

    def __init__(self, cid: str, clean: bool):
        self.cid = cid
        self.clean = clean
        self.subs: Dict[str, int] = {}
        self.sock: Optional[socket.socket] = None
        # pid -> [topic, payload, last_sent_ts, retain]
        self.inflight: Dict[int, list] = {}
        self.queue: List[Tuple[str, bytes, bool]] = []
        self.next_pid = 0
        self.dropped = 0

    def alloc_pid(self) -> int:
        # never reuse a pid that is still awaiting its PUBACK (wraparound
        # would silently overwrite an undelivered message); INFLIGHT_LIMIT
        # << 65535 keeps this loop trivially bounded
        while True:
            self.next_pid = (self.next_pid % 0xFFFF) + 1
            if self.next_pid not in self.inflight:
                return self.next_pid


class MiniBroker:
    """Tiny localhost MQTT broker: wildcards, retained messages, QoS 0/1
    end-to-end.  Subscriber-side QoS 1 honors the spec: the requested QoS
    is granted in SUBACK, deliveries carry packet ids and are retransmitted
    (DUP) until PUBACKed, and persistent sessions (CONNECT clean=0) keep
    subscriptions + undelivered QoS-1 messages across subscriber death so
    a reconnecting subscriber loses nothing (≙ paho/mosquitto behavior the
    reference relies on, gst/mqtt/mqttsink.h:77 ``mqtt_qos``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 retransmit_s: float = 1.0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # REUSEADDR (not REUSEPORT: two live brokers on one port would
        # silently load-balance clients between them) — restart rebinding
        # works because close() shuts every client sock down first, so the
        # old listener and its connections are gone before the new bind
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()
        self._lock = threading.Lock()
        self._sessions: Dict[str, _BrokerSession] = {}
        # broker "persistence": a rebind on the same port adopts the
        # previous instance's persistent sessions (subscriptions +
        # undelivered QoS-1 backlog), the in-process analog of
        # mosquitto's persistence file — without it, messages the broker
        # PUBACKed but had not yet delivered die with the process (the
        # at-least-once chain is only per-hop)
        _session_store_evict_stale()
        stored = _SESSION_STORE.pop(self.port, None)
        if stored is not None:
            ts, sessions = stored
            if time.monotonic() - ts <= _SESSION_STORE_TTL_S:
                self._sessions.update(sessions)
        self._by_sock: Dict[socket.socket, _BrokerSession] = {}
        # per-sock write locks so a publisher fan-out and the subscriber's
        # own control responses (SUBACK/PINGRESP/retained) cannot
        # interleave mid-sendall
        self._wlocks: Dict[socket.socket, threading.Lock] = {}
        self._retained: Dict[str, bytes] = {}
        self._retransmit_s = retransmit_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="mqtt-broker", daemon=True
        )
        self._thread.start()
        self._redeliver = threading.Thread(
            target=self._redeliver_loop, name="mqtt-broker-qos1", daemon=True
        )
        self._redeliver.start()

    def has_subscriber(self, topic: str) -> bool:
        """True when some LIVE session holds a subscription matching
        ``topic`` — the event-driven readiness signal tests use instead
        of sleeping an arbitrary margin after starting a subscriber."""
        with self._lock:
            return any(
                sess.sock is not None and topic_matches(pat, topic)
                for sess in self._sessions.values()
                for pat in sess.subs
            )

    def wait_subscriber(self, topic: str, timeout_s: float = 10.0) -> bool:
        """Block until :meth:`has_subscriber` (bounded); True on success."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.has_subscriber(topic):
                return True
            time.sleep(0.01)
        return False

    def close(self) -> None:
        self._stop.set()
        # persist BEFORE freeing the port: a successor binding the port
        # must never win the race against the store write (it would miss
        # the PUBACKed-but-undelivered backlog — the exact loss this
        # persistence exists to prevent)
        with self._lock:
            keep: Dict[str, _BrokerSession] = {}
            for cid, sess in self._sessions.items():
                if sess.clean:
                    continue
                sess.sock = None
                requeue = [(t, p, bool(r))
                           for t, p, _, r in sess.inflight.values()]
                sess.inflight = {}
                merged = requeue + sess.queue
                if len(merged) > sess.QUEUE_LIMIT:
                    sess.dropped += len(merged) - sess.QUEUE_LIMIT
                sess.queue = merged[: sess.QUEUE_LIMIT]
                keep[cid] = sess
            _session_store_evict_stale()
            if keep:
                _SESSION_STORE[self.port] = (time.monotonic(), keep)
        try:
            # shutdown wakes a thread blocked in accept() (plain close of
            # a listening fd can leave it blocked forever on Linux)
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            socks = list(self._by_sock)
        for s in socks:
            try:
                # shutdown BEFORE close: close() alone neither wakes a
                # thread blocked in recv on this fd nor guarantees a
                # prompt FIN to the peer; shutdown does both, so
                # clients detect broker death immediately
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        with self._lock:
            self._by_sock.clear()
            self._wlocks.clear()
            self._sessions.clear()

    # -- internals ----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._client_loop, args=(sock,), daemon=True
            ).start()

    @staticmethod
    def _parse_connect(body: bytes) -> Tuple[str, bool]:
        """CONNECT variable header + payload -> (client_id, clean_session).
        MQTT 3.1.1 §3.1: proto name str, level byte, flags byte,
        keepalive u16, then the client id string."""
        off = 2 + struct.unpack(">H", body[:2])[0]  # skip protocol name
        flags = body[off + 1]
        off += 4  # level + flags + keepalive
        cid_len = struct.unpack(">H", body[off : off + 2])[0]
        cid = body[off + 2 : off + 2 + cid_len].decode()
        return cid, bool(flags & 0x02)

    def _open_session(self, sock: socket.socket,
                      body: bytes) -> Tuple[_BrokerSession, bool]:
        cid, clean = self._parse_connect(body)
        with self._lock:
            existing = self._sessions.get(cid) if cid else None
            # a still-live connection under this client id is displaced
            # whatever the clean flag (MQTT 3.1.1 §3.1.4: new wins)
            old = existing.sock if existing is not None else None
            sess = existing if (existing is not None and not clean) else None
            present = sess is not None
            if sess is None:
                sess = _BrokerSession(cid or f"anon-{id(sock):x}", clean)
            sess.clean = clean
            sess.sock = sock
            self._sessions[sess.cid] = sess
            self._by_sock[sock] = sess
            self._wlocks[sock] = threading.Lock()
        if old is not None and old is not sock:
            try:
                old.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        return sess, present

    def _client_loop(self, sock: socket.socket) -> None:
        sess = None
        try:
            # bound SENDS only (SO_SNDTIMEO, not settimeout: recv must
            # stay blocking): a wedged subscriber whose TCP window filled
            # would otherwise stall the shared redelivery/fan-out threads
            # in sendall forever
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", 5, 0),
            )
            # bounded handshake: a peer that connects and never sends
            # CONNECT must not wedge this thread until process exit
            sock.settimeout(10.0)
            ptype, _, body = _read_packet(sock)
            if ptype != CONNECT:
                sock.close()
                return
            # allow-blocking: post-handshake reads are stream semantics
            # (clients ping on their own schedule); close() shutdown()s
            # every client socket, so the blocked recv has an escape
            sock.settimeout(None)
            sess, present = self._open_session(sock, body)
            sock.sendall(bytes([CONNACK << 4, 2, 1 if present else 0, 0]))
            if present:
                self._resume_delivery(sess)
            while not self._stop.is_set():
                ptype, flags, body = _read_packet(sock)
                if ptype == PUBLISH:
                    self._handle_publish(sock, flags, body)
                elif ptype == PUBACK:
                    if len(body) >= 2:
                        (pid,) = struct.unpack(">H", body[:2])
                        with self._lock:
                            sess.inflight.pop(pid, None)
                elif ptype == SUBSCRIBE:
                    self._handle_subscribe(sock, sess, body)
                elif ptype == UNSUBSCRIBE:
                    self._handle_unsubscribe(sock, sess, body)
                elif ptype == PINGREQ:
                    self._send(sock, bytes([PINGRESP << 4, 0]))
                elif ptype == DISCONNECT:
                    break
        except (ConnectionError, OSError):
            pass
        except (MqttProtocolError, struct.error, IndexError,
                UnicodeDecodeError) as e:
            log.warning("broker: dropping client on malformed packet: %s", e)
        finally:
            with self._lock:
                self._by_sock.pop(sock, None)
                self._wlocks.pop(sock, None)
                if sess is not None and sess.sock is sock:
                    sess.sock = None
                    # drop only OUR session entry: a reconnect may already
                    # have replaced this cid with a fresh session object
                    if sess.clean and self._sessions.get(sess.cid) is sess:
                        self._sessions.pop(sess.cid, None)
            try:
                sock.close()
            except OSError:
                pass

    def _resume_delivery(self, sess: _BrokerSession) -> None:
        """Persistent-session reconnect: retransmit unacked inflight
        (DUP) and flush the offline queue as fresh QoS-1 deliveries."""
        with self._lock:
            sock = sess.sock
            inflight = sorted(sess.inflight.items())
            queued, sess.queue = sess.queue, []
        if sock is None:
            return
        for pid, entry in inflight:
            self._send(sock, _publish_packet(
                entry[0], entry[1], entry[3], qos=1, pid=pid, dup=True))
            entry[2] = time.monotonic()
        for topic, payload, retain in queued:
            self._deliver_qos1(sess, topic, payload, retain)

    def _deliver_qos1(self, sess: _BrokerSession, topic: str,
                      payload: bytes, retain: bool = False) -> None:
        with self._lock:
            sock = sess.sock
            # offline subscriber — or a connected one that stopped acking
            # (inflight full): park in the bounded queue; the redelivery
            # loop promotes queued entries as PUBACKs free inflight room
            if sock is None or len(sess.inflight) >= sess.INFLIGHT_LIMIT:
                if len(sess.queue) < sess.QUEUE_LIMIT:
                    sess.queue.append((topic, payload, retain))
                else:
                    sess.dropped += 1
                return
            pid = sess.alloc_pid()
            sess.inflight[pid] = [topic, payload, time.monotonic(), retain]
        self._send(sock, _publish_packet(topic, payload, retain, 1, pid))

    def _redeliver_loop(self) -> None:
        """QoS-1 redelivery to subscribers: resend inflight entries older
        than the retransmit interval with DUP until PUBACKed, and promote
        queued messages into freed inflight slots."""
        while not self._stop.wait(max(0.05, self._retransmit_s / 2)):
            now = time.monotonic()
            with self._lock:
                stale = [
                    (sess.sock, pid, e)
                    for sess in self._sessions.values() if sess.sock
                    for pid, e in sess.inflight.items()
                    if now - e[2] >= self._retransmit_s
                ]
                promotable = [
                    sess for sess in self._sessions.values()
                    if sess.sock and sess.queue
                    and len(sess.inflight) < sess.INFLIGHT_LIMIT
                ]
            for sock, pid, entry in stale:
                entry[2] = now
                self._send(sock, _publish_packet(
                    entry[0], entry[1], entry[3], qos=1, pid=pid, dup=True))
            for sess in promotable:
                with self._lock:
                    room = sess.INFLIGHT_LIMIT - len(sess.inflight)
                    batch, sess.queue = (
                        sess.queue[:room], sess.queue[room:])
                for topic, payload, retain in batch:
                    self._deliver_qos1(sess, topic, payload, retain)

    def _handle_publish(self, sock: socket.socket, flags: int,
                        body: bytes) -> None:
        topic, payload, pid = _parse_publish(flags, body)
        pub_qos = (flags >> 1) & 0x3
        if flags & 0x1:  # retain; empty payload DELETES (MQTT 3.1.1 §3.3.1.3)
            with self._lock:
                if payload:
                    self._retained[topic] = payload
                else:
                    self._retained.pop(topic, None)
        # fan out at min(publish QoS, granted subscription QoS) per
        # subscriber (MQTT 3.1.1 §3.8.4)
        with self._lock:
            targets = [
                (sess, max(
                    (q for p, q in sess.subs.items()
                     if topic_matches(p, topic)), default=-1,
                ))
                for sess in self._sessions.values()
            ]
        qos0_packet = None
        for sess, sub_qos in targets:
            if sub_qos < 0:
                continue
            if min(pub_qos, sub_qos) >= 1:
                self._deliver_qos1(sess, topic, payload)
            elif sess.sock is not None:
                if qos0_packet is None:
                    qos0_packet = _publish_packet(topic, payload)
                self._send(sess.sock, qos0_packet)
        if pid is not None:
            # QoS 1 in: acknowledge the publisher only AFTER the message
            # is enqueued/tracked for every matching subscriber — an ack
            # before fan-out leaves a crash window where an acked message
            # exists nowhere (found by the 20-min soak: 3 of 57k frames
            # lost across 9 broker kills)
            self._send(sock, bytes([PUBACK << 4, 2]) + struct.pack(">H", pid))

    def _send(self, sock: socket.socket, data: bytes) -> None:
        with self._lock:
            wl = self._wlocks.get(sock)
        if wl is None:
            return
        try:
            with wl:
                sock.sendall(data)
        except socket.timeout:
            # send window stayed full for the whole SNDTIMEO: the peer is
            # wedged — tear it down so its session goes offline (messages
            # queue) instead of letting it stall shared delivery threads
            log.warning("broker: peer stopped reading; disconnecting it")
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        except OSError:
            pass

    def _handle_subscribe(self, sock: socket.socket, sess: _BrokerSession,
                          body: bytes) -> None:
        pid = body[:2]
        off = 2
        grants = []
        new_pats = []
        with self._lock:
            while off < len(body):
                ln = struct.unpack(">H", body[off : off + 2])[0]
                pat = body[off + 2 : off + 2 + ln].decode()
                req_qos = body[off + 2 + ln] & 0x3
                granted = min(req_qos, 1)  # QoS 2 not implemented
                sess.subs[pat] = granted  # re-subscribe replaces
                grants.append(granted)
                new_pats.append(pat)
                off += 2 + ln + 1
            retained = [
                (t, p, max(
                    (sess.subs[pat] for pat in new_pats
                     if topic_matches(pat, t)), default=0,
                ))
                for t, p in self._retained.items()
                if any(topic_matches(pat, t) for pat in new_pats)
            ]
        self._send(
            sock,
            bytes([SUBACK << 4]) + _encode_len(2 + len(grants)) + pid
            + bytes(grants),
        )
        # retained state rides at the granted QoS (§3.3.1.3): a qos-1
        # subscription gets tracked, retransmitted retained delivery
        for t, p, q in retained:
            if q >= 1:
                self._deliver_qos1(sess, t, p, retain=True)
            else:
                self._send(sock, _publish_packet(t, p, retain=True))

    def _handle_unsubscribe(self, sock: socket.socket, sess: _BrokerSession,
                            body: bytes) -> None:
        pid = body[:2]
        off = 2
        with self._lock:
            while off < len(body):
                ln = struct.unpack(">H", body[off : off + 2])[0]
                pat = body[off + 2 : off + 2 + ln].decode()
                sess.subs.pop(pat, None)
                off += 2 + ln
        self._send(sock, bytes([UNSUBACK << 4, 2]) + pid)


class MqttClient:
    """MQTT 3.1.1 client: QoS 0/1 publish, subscribe(callback), automatic
    reconnect with re-subscribe and QoS-1 redelivery.

    ≙ the reference's paho ``MQTTAsync`` usage: ``mqtt_qos``
    (``gst/mqtt/mqttsink.h:77``) and mqttsrc's reconnect-and-resume."""

    def __init__(self, host: str, port: int, client_id: str = "",
                 keepalive: int = 60, timeout: float = 10.0,
                 reconnect: bool = True, retransmit_s: float = 2.0,
                 reconnect_delay_s: float = 0.1,
                 clean_session: bool = True,
                 brokers: Optional[Iterable[Tuple[str, int]]] = None):
        self._host, self._port, self._timeout = host, port, timeout
        # ordered failover list: (host, port) first, extras after.  The
        # reconnect loop dials each in turn per failed attempt, so a dead
        # primary fails over within one dial timeout — clients never need
        # to know which broker of the set is the live one.
        self._brokers: List[Tuple[str, int]] = [(host, int(port))]
        for h, p in (brokers or ()):
            if (h, int(p)) not in self._brokers:
                self._brokers.append((h, int(p)))
        self._broker_i = 0
        self._cid = client_id or f"nns-tpu-{id(self) & 0xFFFFFF:x}"
        # clean_session=False + a stable client_id = persistent session:
        # the broker keeps subscriptions and queues/retransmits QoS-1
        # deliveries across this client's death (at-least-once end-to-end)
        self._clean_session = clean_session
        self._keepalive = max(1, keepalive)
        self._reconnect_enabled = reconnect
        self._retransmit_s = retransmit_s
        # initial reconnect backoff (≙ paho MQTTAsync_setReconnectDelay):
        # publishers should use a LARGER delay than subscribers so that
        # after a broker restart the subscriptions are back before QoS-1
        # redelivery lands (a broker with no session persistence acks a
        # publish even when nobody is subscribed yet)
        self._reconnect_delay_s = max(0.05, reconnect_delay_s)
        self._wlock = threading.Lock()
        # per-pattern callbacks: a second subscribe() must not reroute
        # earlier patterns' messages to the newest callback
        self._subs: Dict[str, Callable[[str, bytes], None]] = {}
        self._sub_qos: Dict[str, int] = {}
        self._stop = threading.Event()
        self._pid_lock = threading.Lock()
        self._pid = 0
        # QoS-1 in flight: pid -> [topic, payload, retain, last_sent_ts]
        self._pending: Dict[int, list] = {}
        self._pending_lock = threading.Lock()
        self.connected = threading.Event()
        # connection-plane accounting (exact): successful reconnects and
        # retained QoS-1 publishes superseded while the broker was away
        self.reconnects = 0
        self.coalesced = 0
        self._on_connect: List[Callable[[], None]] = []
        self._sock: Optional[socket.socket] = None
        # first connect walks the failover list too: a dead primary with
        # a live standby must not fail construction.  Raises only when
        # EVERY broker refused.
        err: Optional[OSError] = None
        for i in range(len(self._brokers)):
            self._broker_i = i
            try:
                self._connect()
                err = None
                break
            except OSError as e:
                err = e
        if err is not None:
            raise err
        self._reader = threading.Thread(
            target=self._read_loop, name="mqtt-client", daemon=True
        )
        self._reader.start()
        # keepalive: a broker may drop us after 1.5x the advertised interval
        # with no inbound packets (MQTT 3.1.1 §3.1.2.10), so ping on a
        # timer; the same timer drives QoS-1 retransmission
        self._pinger = threading.Thread(
            target=self._ping_loop, name="mqtt-ping", daemon=True
        )
        self._pinger.start()

    # -- connection ---------------------------------------------------------
    @property
    def broker(self) -> Tuple[str, int]:
        """The (host, port) this client last connected (or dialed) to."""
        return self._brokers[self._broker_i]

    def on_connect(self, cb: Callable[[], None]) -> None:
        """Register a callback fired (from the reader thread) after every
        successful RE-connect, once the session is resumed — the hook an
        :class:`~..distributed.hybrid.Announcement` uses to re-publish its
        retained state into a restarted (amnesiac) or failed-over broker."""
        self._on_connect.append(cb)

    def _connect(self) -> None:
        self._host, self._port = self._brokers[self._broker_i]
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        var = (
            _mqtt_str("MQTT") + bytes([4])  # protocol level 4 = 3.1.1
            + bytes([0x02 if self._clean_session else 0x00])
            + struct.pack(">H", self._keepalive)
            + _mqtt_str(self._cid)
        )
        sock.sendall(bytes([CONNECT << 4]) + _encode_len(len(var)) + var)
        ptype, _, body = _read_packet(sock)
        if ptype != CONNACK or body[1] != 0:
            sock.close()
            raise ConnectionError(f"MQTT connect refused: {body!r}")
        # bounded read: the ping loop elicits a PINGRESP well inside
        # every keepalive window, so a silent link for 1.5x keepalive
        # means the broker is gone — the reader's timeout then lands in
        # its (ConnectionError, OSError) handler and reconnects, instead
        # of blocking forever on a black-holed connection
        sock.settimeout(max(1.0, self._keepalive * 1.5))
        with self._wlock:
            self._sock = sock
        self.connected.set()

    def _resume_session(self) -> None:
        """After reconnect: re-subscribe every pattern (clean-session
        broker forgot them) and re-send unacked QoS-1 publishes (DUP)."""
        for pattern in list(self._subs):
            try:
                self._send_subscribe(pattern)
            except OSError:
                return
        with self._pending_lock:
            pending = sorted(self._pending.items())
        for pid, entry in pending:
            topic, payload, retain, _ = entry
            try:
                self._send(_publish_packet(
                    topic, payload, retain, qos=1, pid=pid, dup=True
                ))
                entry[3] = time.monotonic()
            except OSError:
                return

    def _reconnect_loop(self) -> None:
        backoff = self._reconnect_delay_s
        self._stop.wait(self._reconnect_delay_s)
        while not self._stop.is_set():
            try:
                self._connect()
                log.info("mqtt client reconnected to %s:%d",
                         self._host, self._port)
                self.reconnects += 1
                self._resume_session()
                for cb in list(self._on_connect):
                    try:
                        cb()
                    except Exception:  # hook bugs must not kill the reader
                        log.exception("mqtt on_connect hook failed")
                return
            except OSError:
                # failover: advance to the next broker in the ordered list
                # before the next dial; back off only after a full cycle
                # of the list has been refused, so a live standby broker
                # is reached within one dial per dead predecessor
                self._broker_i = (self._broker_i + 1) % len(self._brokers)
                if self._broker_i == 0:
                    self._stop.wait(backoff)
                    backoff = min(backoff * 2, 2.0)

    # -- io -----------------------------------------------------------------
    def _send(self, data: bytes) -> None:
        with self._wlock:
            if self._sock is None:
                raise OSError("mqtt client not connected")
            self._sock.sendall(data)

    def _ping_loop(self) -> None:
        interval = min(self._keepalive / 2.0, max(self._retransmit_s, 0.2))
        while not self._stop.wait(interval):
            now = time.monotonic()
            with self._pending_lock:
                stale = [
                    (pid, e) for pid, e in sorted(self._pending.items())
                    if now - e[3] >= self._retransmit_s
                ]
            for pid, entry in stale:  # QoS-1 redelivery
                try:
                    self._send(_publish_packet(
                        entry[0], entry[1], entry[2], qos=1, pid=pid, dup=True
                    ))
                    entry[3] = now
                except OSError:
                    break
            try:
                self.ping()
            except OSError:
                continue  # reader notices and reconnects

    def _next_pid(self) -> int:
        with self._pid_lock:
            self._pid = (self._pid % 0xFFFF) + 1
            return self._pid

    # -- API ----------------------------------------------------------------
    def publish(self, topic: str, payload: bytes, retain: bool = False,
                qos: int = 0) -> None:
        if qos not in (0, 1):
            raise ValueError("only QoS 0/1 supported")
        if qos == 1:
            pid = self._next_pid()
            with self._pending_lock:
                if retain:
                    # retained semantics are last-writer-wins: a newer
                    # retained publish on the same topic supersedes any
                    # still-unacked one, so the outage backlog is bounded
                    # at ONE entry per retained topic and a reconnect
                    # never replays a stale announce/digest over a fresh
                    # one (subscribers additionally dedupe by seq)
                    for old_pid in [
                        p for p, e in self._pending.items()
                        if e[2] and e[0] == topic
                    ]:
                        del self._pending[old_pid]
                        self.coalesced += 1
                self._pending[pid] = [topic, payload, retain, time.monotonic()]
            try:
                self._send(_publish_packet(topic, payload, retain, 1, pid))
            except OSError:
                if not self._reconnect_enabled:
                    with self._pending_lock:
                        self._pending.pop(pid, None)
                    raise
                # stays pending; redelivered after reconnect
            return
        try:
            self._send(_publish_packet(topic, payload, retain))
        except OSError:
            if not self._reconnect_enabled:
                raise
            # fire-and-forget during the reconnect window: QoS 0 has no
            # delivery guarantee — dropping beats killing the pipeline
            log.debug("QoS-0 publish dropped while reconnecting")

    def unacked(self) -> int:
        """Outstanding QoS-1 publishes (0 = everything acknowledged)."""
        with self._pending_lock:
            return len(self._pending)

    def drain(self, timeout_s: float = 5.0) -> int:
        """Wait up to `timeout_s` for all QoS-1 publishes to be PUBACKed;
        returns how many remain unacknowledged (0 = clean)."""
        deadline = time.monotonic() + timeout_s
        while self.unacked() and time.monotonic() < deadline:
            time.sleep(0.05)
        return self.unacked()

    def _send_subscribe(self, pattern: str) -> None:
        var = (
            struct.pack(">H", self._next_pid()) + _mqtt_str(pattern)
            + bytes([self._sub_qos.get(pattern, 0)])
        )
        self._send(bytes([(SUBSCRIBE << 4) | 0x2]) + _encode_len(len(var)) + var)

    def subscribe(self, pattern: str,
                  callback: Callable[[str, bytes], None],
                  qos: int = 0) -> None:
        if qos not in (0, 1):
            raise ValueError("only QoS 0/1 supported")
        self._subs[pattern] = callback
        self._sub_qos[pattern] = qos
        try:
            self._send_subscribe(pattern)
        except OSError:
            if not self._reconnect_enabled:
                raise
            # recorded; _resume_session re-sends it after reconnect

    def ping(self) -> None:
        self._send(bytes([PINGREQ << 4, 0]))

    def close(self) -> None:
        self._stop.set()
        try:
            self._send(bytes([DISCONNECT << 4, 0]))
        except OSError:
            pass
        with self._wlock:
            if self._sock is not None:
                try:  # wake the reader blocked in recv (see MiniBroker.close)
                    self._sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    # -- reader -------------------------------------------------------------
    def _read_loop(self) -> None:
        while not self._stop.is_set():
            sock = self._sock
            if sock is None:
                return
            try:
                ptype, flags, body = _read_packet(sock)
            except (ConnectionError, OSError):
                self.connected.clear()
                try:  # release the dead fd (one leak per reconnect otherwise)
                    sock.close()
                except OSError:
                    pass
                if self._stop.is_set() or not self._reconnect_enabled:
                    return
                self._reconnect_loop()
                continue
            if ptype == PUBACK and len(body) >= 2:
                (pid,) = struct.unpack(">H", body[:2])
                with self._pending_lock:
                    self._pending.pop(pid, None)
                continue
            if ptype != PUBLISH or not self._subs:
                continue
            try:
                topic, payload, pid = _parse_publish(flags, body)
            except MqttProtocolError as e:
                log.warning("client: dropping malformed PUBLISH: %s", e)
                continue
            if pid is not None:  # QoS-1 inbound: acknowledge
                try:
                    self._send(
                        bytes([PUBACK << 4, 2]) + struct.pack(">H", pid)
                    )
                except OSError:
                    pass
            for pattern, cb in list(self._subs.items()):
                if not topic_matches(pattern, topic):
                    continue
                try:
                    cb(topic, payload)
                except Exception:  # subscriber bugs must not kill the reader
                    log.exception("mqtt subscribe callback failed")
