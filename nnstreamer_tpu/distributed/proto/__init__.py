"""Wire IDLs for non-framework peers.

``nns_tensors.proto`` is the tensors schema external systems speak
(≙ reference ``ext/nnstreamer/include/nnstreamer.proto``); the checked-in
``nns_tensors_pb2.py`` is its protoc output.  Regenerate after editing the
schema::

    protoc --python_out=nnstreamer_tpu/distributed/proto \
           --proto_path=nnstreamer_tpu/distributed/proto nns_tensors.proto
"""
