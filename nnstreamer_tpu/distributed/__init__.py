"""Distributed layer (L4): wire framing, gRPC query/edge services.

Reference analog: tensor_query_*, edgesrc/edgesink, gst/mqtt, grpc elements
(SURVEY §2.3) over nnstreamer-edge; here one gRPC data plane.
"""

from .wire import (  # noqa: F401
    WireCorruptionError,
    WireError,
    WireTruncationError,
    decode_frame,
    encode_frame,
)
from .service import (  # noqa: F401
    EdgeBroker,
    EdgePublisher,
    EdgeSubscriber,
    QueryConnection,
    QueryServerCore,
    get_edge_broker,
    get_query_server,
    release_edge_broker,
    release_query_server,
)
