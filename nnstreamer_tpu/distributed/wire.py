"""Wire serialization for tensor frames.

The reference ships ``other/tensors`` over the wire via protobuf/flatbuf
IDLs (``ext/nnstreamer/include/nnstreamer.proto``/``.fbs``) or
nnstreamer-edge's custom TCP framing.  This is the TPU build's framing: a
compact self-describing binary layout reusing the flexible-tensor header
from the core type system (one schema for in-process flexible streams AND
the wire — the reference keeps two).

Frame layout (little-endian):

  v1: u32 magic 'NNSQ' | u16 version=1 | u64 seq | f64 pts (NaN = none) |
      u32 meta_len | meta JSON | u16 ntensors |
      per tensor: flex header | u64 payload_len | raw bytes

  v2: identical, except version=2 and the fixed header grows a trailing
      u32 CRC-32 (zlib) computed over the ENTIRE encoded frame with the
      crc field zeroed — header fields, meta, flex headers, and tensor
      payloads are all covered, so any single flipped bit on the wire is
      detected at decode instead of served as a silently-garbage tensor.

Batch envelope (wire micro-batching):

  v1: u32 magic 'NNSB' | u16 count | per frame: u64 len | NNSQ bytes
  v2: u32 magic 'NNSC' | u16 count | u32 crc | per frame: u64 len | bytes
      The batch crc covers the SKELETON (header with crc zeroed + every
      u64 length prefix); frame contents are already covered by their own
      per-frame v2 checksums, so the envelope never pays a second pass
      over the payload bytes.

Integrity contract (Documentation/wire-protocol.md):

* ``decode_frame``/``decode_frames`` validate EVERY declared size
  (meta_len, tensor count, rank, dtype, payload/entry lengths) against
  hard limits and the actual buffer BEFORE any allocation or
  ``frombuffer`` — hostile input can neither crash the decoder with a raw
  ``struct``/numpy error nor make it allocate beyond :data:`MAX_BODY`.
* Every malformed input raises a typed :class:`WireError` subclass:
  :class:`WireTruncationError` (buffer ends before declared data) or
  :class:`WireCorruptionError` (checksum mismatch / internally
  inconsistent or implausible fields).  Both are marked transient
  (``nns_transient``) — corruption is a property of one transmission,
  not of the stream.
* v2 decoders accept v1 frames (a v2 node interoperates with v1 peers on
  receive); a v1 decoder rejects v2, so senders negotiate (tcp_query 'V'
  handshake) or pin ``NNS_WIRE_V=1`` for fleet rollback.
"""

from __future__ import annotations

import json
import math
import os
import struct
import zlib
from typing import Any, Dict

import numpy as np

from ..core.buffer import TensorFrame
from ..core.liveness import DEADLINE_META
from ..core.telemetry import TL_PREFIX as _TL_PREFIX
from ..core.tracer import META_SRC_TS as _SRC_TS_META
from ..core.types import (
    TENSOR_COUNT_LIMIT,
    FlexHeaderTruncated,
    TensorSpec,
    pack_flex_header,
    unpack_flex_header,
)

_MAGIC = 0x4E4E5351  # 'NNSQ'
V1 = 1
V2 = 2

#: hard cap on any peer-declared body/payload length before allocation —
#: shared with every transport (≙ gRPC max_receive_message_length)
MAX_BODY = 512 * 1024 * 1024
#: sane bound on the JSON meta blob inside one frame
MAX_META = 16 * 1024 * 1024

_HEAD1 = struct.Struct("<IHQdI")
_HEAD2 = struct.Struct("<IHQdII")  # v2: + u32 crc32 (over frame, crc zeroed)
_MAGVER = struct.Struct("<IH")
_CRC_OFF = _HEAD1.size  # the crc field rides at the end of the v2 header
_ZERO4 = b"\x00\x00\x00\x00"
_NT = struct.Struct("<H")
_PLEN = struct.Struct("<Q")


def default_version() -> int:
    """Envelope version encoders use when none is given.  ``NNS_WIRE_V=1``
    is the fleet-rollback knob: it pins every encoder in this process
    back to checksum-free v1 frames (decoders accept both regardless)."""
    return V1 if os.environ.get("NNS_WIRE_V", "") == "1" else V2


class WireError(ValueError):
    """Base class for every malformed-wire-data condition."""


class WireCorruptionError(WireError):
    """Bytes parsed but can't be trusted: checksum mismatch, bad magic,
    or internally inconsistent / implausible declared fields."""

    #: resilience classification (core/resilience.py): corruption is a
    #: property of ONE transmission — retrying the exchange may succeed
    nns_transient = True


class WireTruncationError(WireError):
    """The buffer ends before the data its headers declare."""

    nns_transient = True


def get_codec(name: str):
    """(encode, decode) for a wire IDL name.

    ``flex``/``nnsq`` = this module's compact framing (default);
    ``protobuf`` = interop IDL #1 (``protobuf_codec.py``,
    ≙ reference nnstreamer.proto + nnstreamer_grpc_protobuf.cc);
    ``flatbuf`` = interop IDL #2 (``flatbuf_codec.py``, the reference's
    actual nnstreamer.fbs binary schema).

    Every decode callable accepts ``verify=`` (the flex codec checks its
    v2 CRC; the interop IDLs have no checksum field and ignore it).
    """
    if name in ("", "flex", "nnsq"):
        return encode_frame, decode_frame
    if name == "protobuf":
        from . import protobuf_codec

        return protobuf_codec.encode_frame, protobuf_codec.decode_frame
    if name == "flatbuf":
        from . import flatbuf_codec

        return flatbuf_codec.encode_frame, flatbuf_codec.decode_frame
    raise WireError(f"unknown wire idl {name!r} (flex|protobuf|flatbuf)")


def _clean_meta(meta: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in meta.items():
        if k == DEADLINE_META or k == _SRC_TS_META or k.startswith(
                _TL_PREFIX):
            # host-local instants never cross the wire: the deadline
            # stamp (core/liveness.py — the remaining BUDGET travels in
            # the transport header instead), the tracer's interlatency
            # origin stamp, and every trace-local telemetry key
            # (core/telemetry.py TL_PREFIX — client enqueue / server rx
            # stamps).  Only DURATIONS travel (SRV_SPAN_META), and the
            # receiver re-stamps on its own clock.
            continue
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            continue  # non-serializable entries stay process-local
    return out


def _check_version(version) -> int:
    version = int(version)
    if version not in (V1, V2):
        raise WireError(f"cannot encode wire version {version} (have 1|2)")
    return version


def encode_frame_parts(frame: TensorFrame, version: int = None) -> list:
    """Vectored encoding: the frame as a list of buffer objects with NO
    payload copies — tensor data rides as memoryviews of the arrays.
    Callers either gather-send the parts directly (``socket.sendmsg``,
    zero user-space copies) or join them (``encode_frame``).

    v2 (default): the header carries a CRC-32 over the whole frame (crc
    field zeroed) — computed in one streaming pass over the parts, still
    without copying any payload."""
    version = default_version() if version is None else _check_version(version)
    meta = json.dumps(_clean_meta(frame.meta)).encode()
    pts = frame.pts if frame.pts is not None else math.nan
    head = (
        _HEAD2.pack(_MAGIC, V2, frame.seq, pts, len(meta), 0)
        if version == V2
        else _HEAD1.pack(_MAGIC, V1, frame.seq, pts, len(meta))
    )
    parts = [head, meta, _NT.pack(len(frame.tensors))]
    for t in frame.tensors:
        arr = np.ascontiguousarray(np.asarray(t))
        spec = TensorSpec(tuple(arr.shape), arr.dtype)
        parts.append(pack_flex_header(spec))
        parts.append(_PLEN.pack(arr.nbytes))
        parts.append(arr.reshape(-1).view(np.uint8).data)
    if version == V2:
        crc = 0
        for p in parts:
            crc = zlib.crc32(p, crc)
        parts[0] = _HEAD2.pack(_MAGIC, V2, frame.seq, pts, len(meta), crc)
    return parts


def parts_nbytes(parts) -> int:
    return sum(memoryview(p).nbytes for p in parts)


def encode_frame(frame: TensorFrame, version: int = None) -> bytes:
    return b"".join(bytes(p) if not isinstance(p, bytes) else p
                    for p in encode_frame_parts(frame, version=version))


# -- multi-frame envelope (wire micro-batching) -----------------------------
_BMAGIC = 0x4E4E5342   # 'NNSB' (v1, no checksum)
_B2MAGIC = 0x4E4E5343  # 'NNSC' (v2, skeleton crc)
_BHEAD = struct.Struct("<IH")
_B2HEAD = struct.Struct("<IHI")
_BLEN = struct.Struct("<Q")


def encode_frames_parts(frames, version: int = None) -> list:
    """Vectored multi-frame envelope — no payload copies, for
    gather-sends.  v2 adds a skeleton CRC-32 (batch header + every length
    prefix); the frames inside carry their own v2 checksums."""
    version = default_version() if version is None else _check_version(version)
    if version == V1:
        parts = [_BHEAD.pack(_BMAGIC, len(frames))]
        for f in frames:
            fparts = encode_frame_parts(f, version=V1)
            parts.append(_BLEN.pack(parts_nbytes(fparts)))
            parts.extend(fparts)
        return parts
    head0 = _B2HEAD.pack(_B2MAGIC, len(frames), 0)
    parts = [head0]
    crc = zlib.crc32(head0)
    for f in frames:
        fparts = encode_frame_parts(f, version=V2)
        blen = _BLEN.pack(parts_nbytes(fparts))
        crc = zlib.crc32(blen, crc)
        parts.append(blen)
        parts.extend(fparts)
    parts[0] = _B2HEAD.pack(_B2MAGIC, len(frames), crc)
    return parts


def encode_frames(frames, version: int = None) -> bytes:
    """Pack several frames into ONE envelope.  The query path uses this
    to amortize per-RPC transport overhead over a micro-batch — the wire
    analog of the filter's batched XLA invoke."""
    return b"".join(bytes(p) if not isinstance(p, bytes) else p
                    for p in encode_frames_parts(frames, version=version))


def _need(have: int, off: int, n: int, what: str) -> None:
    """Bounds gate run before EVERY read of declared data: truncated and
    hostile-length inputs fail typed here, never as struct/numpy errors
    or oversized allocations."""
    if off + n > have:
        raise WireTruncationError(
            f"truncated: {what} needs {n} byte(s) at offset {off}, "
            f"buffer has {have}"
        )


def decode_frames(buf, verify: bool = True):
    """Inverse of :func:`encode_frames`; returns a list of frames.

    Strict bounded decode: entry lengths are validated against
    :data:`MAX_BODY` and the real buffer before any slice; a v2 envelope
    additionally has its skeleton checksum verified (``verify=True``)."""
    mv = memoryview(buf)
    total = len(mv)
    _need(total, 0, 4, "batch magic")
    (magic,) = struct.unpack_from("<I", mv, 0)
    if magic == _BMAGIC:
        _need(total, 0, _BHEAD.size, "batch header")
        _, count = _BHEAD.unpack_from(mv, 0)
        off = _BHEAD.size
        crc = None
    elif magic == _B2MAGIC:
        _need(total, 0, _B2HEAD.size, "batch header")
        _, count, crc = _B2HEAD.unpack_from(mv, 0)
        off = _B2HEAD.size
    else:
        raise WireCorruptionError(f"bad batch magic 0x{magic:08x}")
    if crc is not None and verify:
        # skeleton pass: header (crc zeroed) + every length prefix, with
        # the same bounds checks the decode pass below applies
        actual = zlib.crc32(_B2HEAD.pack(_B2MAGIC, count, 0))
        woff = off
        for i in range(count):
            _need(total, woff, _BLEN.size, f"batch entry {i} length")
            actual = zlib.crc32(mv[woff : woff + _BLEN.size], actual)
            (blen,) = _BLEN.unpack_from(mv, woff)
            woff += _BLEN.size
            if blen > MAX_BODY:
                raise WireCorruptionError(
                    f"batch frame {i} declares {blen} bytes (cap {MAX_BODY})"
                )
            _need(total, woff, blen, f"batch frame {i}")
            woff += blen
        if actual != crc:
            raise WireCorruptionError(
                f"batch checksum mismatch (crc32 {actual:#010x} != "
                f"declared {crc:#010x})"
            )
    frames = []
    for i in range(count):
        _need(total, off, _BLEN.size, f"batch entry {i} length")
        (blen,) = _BLEN.unpack_from(mv, off)
        off += _BLEN.size
        if blen > MAX_BODY:
            raise WireCorruptionError(
                f"batch frame {i} declares {blen} bytes (cap {MAX_BODY})"
            )
        _need(total, off, blen, f"batch frame {i}")
        # no copy: decode_frame works on any buffer (memoryview slicing)
        frames.append(decode_frame(mv[off : off + blen], verify=verify))
        off += blen
    if off != total:
        raise WireCorruptionError(
            f"{total - off} trailing byte(s) after batch envelope"
        )
    return frames


def is_batch_payload(buf) -> bool:
    return (
        len(buf) >= 4
        and struct.unpack_from("<I", memoryview(buf), 0)[0]
        in (_BMAGIC, _B2MAGIC)
    )


def frame_version(buf) -> int:
    """Peek the envelope version of one encoded frame (negotiation and
    test helper); raises typed WireErrors like :func:`decode_frame`."""
    mv = memoryview(buf)
    _need(len(mv), 0, _MAGVER.size, "frame magic/version")
    magic, version = _MAGVER.unpack_from(mv, 0)
    if magic != _MAGIC:
        raise WireCorruptionError(f"bad frame magic 0x{magic:08x}")
    return version


def decode_frame(buf, verify: bool = True) -> TensorFrame:
    """Decode one frame (v1 or v2 envelope) with zero payload copies.

    ``verify=True`` (default) checks the v2 CRC-32 before anything else —
    one streaming pass over the buffer, the whole integrity tax (see
    ``tools/bench_wire.py``); v1 frames have no checksum to check.
    Every malformed input raises :class:`WireTruncationError` or
    :class:`WireCorruptionError`; nothing is allocated or reshaped until
    the fields describing it have been validated."""
    mv = memoryview(buf)
    have = len(mv)
    version = frame_version(mv)
    if version == V2:
        _need(have, 0, _HEAD2.size, "v2 frame header")
        _, _, seq, pts, meta_len, crc = _HEAD2.unpack_from(mv, 0)
        if verify:
            actual = zlib.crc32(mv[:_CRC_OFF])
            actual = zlib.crc32(_ZERO4, actual)
            actual = zlib.crc32(mv[_HEAD2.size:], actual)
            if actual != crc:
                raise WireCorruptionError(
                    f"frame checksum mismatch (crc32 {actual:#010x} != "
                    f"declared {crc:#010x})"
                )
        off = _HEAD2.size
    elif version == V1:
        _need(have, 0, _HEAD1.size, "frame header")
        _, _, seq, pts, meta_len = _HEAD1.unpack_from(mv, 0)
        off = _HEAD1.size
    else:
        # a bit flipped INSIDE the version field evades the CRC (the
        # field selects which header to verify), so an unknown version
        # is corruption — typed and transient like every other case
        raise WireCorruptionError(f"unsupported wire version {version}")
    if meta_len > MAX_META:
        raise WireCorruptionError(
            f"implausible meta length {meta_len} (cap {MAX_META})"
        )
    _need(have, off, meta_len, "frame meta")
    if meta_len:
        try:
            meta = json.loads(bytes(mv[off : off + meta_len]).decode())
        except (UnicodeDecodeError, ValueError) as e:
            raise WireCorruptionError(f"malformed frame meta: {e}") from None
        if not isinstance(meta, dict):
            raise WireCorruptionError("frame meta is not a JSON object")
    else:
        meta = {}
    off += meta_len
    _need(have, off, _NT.size, "tensor count")
    (ntensors,) = _NT.unpack_from(mv, off)
    off += _NT.size
    if ntensors > TENSOR_COUNT_LIMIT:
        raise WireCorruptionError(
            f"tensor count {ntensors} exceeds limit {TENSOR_COUNT_LIMIT}"
        )
    tensors = []
    for i in range(ntensors):
        try:
            spec, hlen = unpack_flex_header(mv[off:])
        except FlexHeaderTruncated as e:
            raise WireTruncationError(f"tensor {i}: {e}") from None
        except ValueError as e:
            raise WireCorruptionError(f"tensor {i}: {e}") from None
        off += hlen
        _need(have, off, _PLEN.size, f"tensor {i} payload length")
        (plen,) = _PLEN.unpack_from(mv, off)
        off += _PLEN.size
        # header-consistency BEFORE the buffer check: a corrupted giant
        # plen is corruption, not truncation, and must never reach a
        # frombuffer/reshape (spec.nbytes is exact — flex specs are
        # always concrete, so this also pins payload size to shape*dtype)
        if plen != spec.nbytes:
            raise WireCorruptionError(
                f"tensor {i} payload {plen}B contradicts header "
                f"{tuple(spec.shape)} x {spec.dtype} ({spec.nbytes}B)"
            )
        _need(have, off, plen, f"tensor {i} payload")
        payload = mv[off : off + plen]
        off += plen
        # ALIASING CONTRACT: this view shares memory with the receive
        # buffer (zero-copy decode).  It is explicitly marked
        # read-only — over an immutable bytes buffer numpy already
        # refuses writes, but a pooled/reused bytearray receive buffer
        # would otherwise hand out WRITABLE views, and an in-place
        # downstream transform would silently corrupt every other
        # frame decoded from the same buffer.  Elements that need to
        # mutate must copy first (tensor_transform and friends are
        # out-of-place, so the common pipelines never pay the copy).
        arr = np.frombuffer(payload, dtype=spec.dtype)
        arr.flags.writeable = False
        tensors.append(arr.reshape(spec.shape))
    if off != have:
        raise WireCorruptionError(
            f"{have - off} trailing byte(s) after frame"
        )
    frame = TensorFrame(tensors, pts=None if math.isnan(pts) else pts, meta=meta)
    frame.seq = seq
    return frame
