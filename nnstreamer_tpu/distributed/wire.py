"""Wire serialization for tensor frames.

The reference ships ``other/tensors`` over the wire via protobuf/flatbuf
IDLs (``ext/nnstreamer/include/nnstreamer.proto``/``.fbs``) or
nnstreamer-edge's custom TCP framing.  This is the TPU build's framing: a
compact self-describing binary layout reusing the flexible-tensor header
from the core type system (one schema for in-process flexible streams AND
the wire — the reference keeps two).

Layout (little-endian):
  u32 magic 'NNSQ' | u16 version | u64 seq | f64 pts (NaN = none) |
  u32 meta_len | meta JSON | u16 ntensors |
  per tensor: flex header | u64 payload_len | raw bytes
"""

from __future__ import annotations

import json
import math
import struct
from typing import Any, Dict

import numpy as np

from ..core.buffer import TensorFrame
from ..core.liveness import DEADLINE_META
from ..core.types import TensorSpec, pack_flex_header, unpack_flex_header

_MAGIC = 0x4E4E5351  # 'NNSQ'
_VERSION = 1
_HEAD = struct.Struct("<IHQdI")
_NT = struct.Struct("<H")
_PLEN = struct.Struct("<Q")


class WireError(ValueError):
    pass


def get_codec(name: str):
    """(encode, decode) for a wire IDL name.

    ``flex``/``nnsq`` = this module's compact framing (default);
    ``protobuf`` = interop IDL #1 (``protobuf_codec.py``,
    ≙ reference nnstreamer.proto + nnstreamer_grpc_protobuf.cc);
    ``flatbuf`` = interop IDL #2 (``flatbuf_codec.py``, the reference's
    actual nnstreamer.fbs binary schema).
    """
    if name in ("", "flex", "nnsq"):
        return encode_frame, decode_frame
    if name == "protobuf":
        from . import protobuf_codec

        return protobuf_codec.encode_frame, protobuf_codec.decode_frame
    if name == "flatbuf":
        from . import flatbuf_codec

        return flatbuf_codec.encode_frame, flatbuf_codec.decode_frame
    raise WireError(f"unknown wire idl {name!r} (flex|protobuf|flatbuf)")


def _clean_meta(meta: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in meta.items():
        if k == DEADLINE_META:
            # deadline QoS (core/liveness.py): an absolute instant on
            # THIS host's monotonic clock — meaningless to a peer.  The
            # remaining BUDGET crosses the wire instead (tcp_query
            # header deadline_s / gRPC time_remaining) and the receiver
            # re-stamps on its own clock.
            continue
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            continue  # non-serializable entries stay process-local
    return out


def encode_frame_parts(frame: TensorFrame) -> list:
    """Vectored encoding: the frame as a list of buffer objects with NO
    payload copies — tensor data rides as memoryviews of the arrays.
    Callers either gather-send the parts directly (``socket.sendmsg``,
    zero user-space copies) or join them (``encode_frame``)."""
    meta = json.dumps(_clean_meta(frame.meta)).encode()
    pts = frame.pts if frame.pts is not None else math.nan
    parts = [
        _HEAD.pack(_MAGIC, _VERSION, frame.seq, pts, len(meta)),
        meta,
        _NT.pack(len(frame.tensors)),
    ]
    for t in frame.tensors:
        arr = np.ascontiguousarray(np.asarray(t))
        spec = TensorSpec(tuple(arr.shape), arr.dtype)
        parts.append(pack_flex_header(spec))
        parts.append(_PLEN.pack(arr.nbytes))
        parts.append(arr.reshape(-1).view(np.uint8).data)
    return parts


def parts_nbytes(parts) -> int:
    return sum(memoryview(p).nbytes for p in parts)


def encode_frame(frame: TensorFrame) -> bytes:
    return b"".join(bytes(p) if not isinstance(p, bytes) else p
                    for p in encode_frame_parts(frame))


# -- multi-frame envelope (wire micro-batching) -----------------------------
_BMAGIC = 0x4E4E5342  # 'NNSB'
_BHEAD = struct.Struct("<IH")
_BLEN = struct.Struct("<Q")


def encode_frames_parts(frames) -> list:
    """Vectored multi-frame envelope (u32 'NNSB' | u16 count | per frame
    u64 len + NNSQ parts) — no payload copies, for gather-sends."""
    parts = [_BHEAD.pack(_BMAGIC, len(frames))]
    for f in frames:
        fparts = encode_frame_parts(f)
        parts.append(_BLEN.pack(parts_nbytes(fparts)))
        parts.extend(fparts)
    return parts


def encode_frames(frames) -> bytes:
    """Pack several frames into ONE envelope (u32 'NNSB' | u16 count |
    per frame u64 len + NNSQ bytes).  The query path uses this to
    amortize per-RPC transport overhead over a micro-batch — the wire
    analog of the filter's batched XLA invoke."""
    return b"".join(bytes(p) if not isinstance(p, bytes) else p
                    for p in encode_frames_parts(frames))


def decode_frames(buf: bytes):
    """Inverse of :func:`encode_frames`; returns a list of frames."""
    try:
        magic, count = _BHEAD.unpack_from(buf, 0)
    except struct.error as e:
        raise WireError(f"truncated batch header: {e}") from None
    if magic != _BMAGIC:
        raise WireError("bad batch magic")
    off = _BHEAD.size
    mv = memoryview(buf)
    frames = []
    for _ in range(count):
        try:
            (blen,) = _BLEN.unpack_from(buf, off)
        except struct.error as e:
            raise WireError(f"truncated batch entry: {e}") from None
        off += _BLEN.size
        blob = mv[off : off + blen]
        if len(blob) != blen:
            raise WireError("truncated batch frame")
        # no copy: decode_frame works on any buffer (memoryview slicing)
        frames.append(decode_frame(blob))
        off += blen
    return frames


def is_batch_payload(buf) -> bool:
    return (
        len(buf) >= _BHEAD.size
        and _BHEAD.unpack_from(buf, 0)[0] == _BMAGIC
    )


def decode_frame(buf: bytes) -> TensorFrame:
    try:
        magic, version, seq, pts, meta_len = _HEAD.unpack_from(buf, 0)
    except struct.error as e:
        raise WireError(f"truncated frame header: {e}") from None
    if magic != _MAGIC:
        raise WireError("bad frame magic")
    if version != _VERSION:
        raise WireError(f"unsupported wire version {version}")
    off = _HEAD.size
    mv = memoryview(buf)  # zero-copy slicing on the hot receive path
    try:
        meta = json.loads(bytes(mv[off : off + meta_len]).decode()) if meta_len else {}
        off += meta_len
        (ntensors,) = _NT.unpack_from(buf, off)
        off += _NT.size
        tensors = []
        for _ in range(ntensors):
            spec, hlen = unpack_flex_header(mv[off:])
            off += hlen
            (plen,) = _PLEN.unpack_from(buf, off)
            off += _PLEN.size
            payload = mv[off : off + plen]
            if len(payload) != plen:
                raise WireError("truncated tensor payload")
            off += plen
            # ALIASING CONTRACT: this view shares memory with the receive
            # buffer (zero-copy decode).  It is explicitly marked
            # read-only — over an immutable bytes buffer numpy already
            # refuses writes, but a pooled/reused bytearray receive buffer
            # would otherwise hand out WRITABLE views, and an in-place
            # downstream transform would silently corrupt every other
            # frame decoded from the same buffer.  Elements that need to
            # mutate must copy first (tensor_transform and friends are
            # out-of-place, so the common pipelines never pay the copy).
            arr = np.frombuffer(payload, dtype=spec.dtype)
            arr.flags.writeable = False
            tensors.append(arr.reshape(spec.shape))
    except (struct.error, ValueError) as e:
        if isinstance(e, WireError):
            raise
        raise WireError(f"malformed frame: {e}") from None
    frame = TensorFrame(tensors, pts=None if math.isnan(pts) else pts, meta=meta)
    frame.seq = seq
    return frame
