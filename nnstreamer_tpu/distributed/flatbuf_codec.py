"""FlatBuffers wire codec speaking the reference's public schema.

Interop IDL #2: emits/parses the exact binary schema of the reference's
``ext/nnstreamer/include/nnstreamer.fbs`` (root table ``Tensors`` with
``num_tensor``, inline ``frame_rate`` struct, a vector of ``Tensor``
tables — name / type enum / uint32[16] dimension / ubyte data — and a
``format`` enum), built with the stock ``flatbuffers`` Python runtime.
A peer that ran ``flatc`` over the reference schema parses these buffers
unmodified, and vice versa — the contract of the reference's
``tensordec-flatbuf.cc`` / ``tensor_converter/converter-flatbuf.cc``.

Field slots below mirror the schema's declaration order (what flatc
assigns); the decode side uses the runtime's generic ``Table`` accessors
— the same machinery flatc-generated readers are sugar over.

Schema limits (vs the richer NNSQ/protobuf codecs): no pts/seq/meta on
the wire — senders' frame meta is dropped, exactly as the reference's
flatbuf path drops GstBuffer metadata.  Dimensions ride innermost-first
(the reference dialect), padded to rank 16 with zeros.
"""

from __future__ import annotations

import math

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import RANK_LIMIT as _REPO_RANK_LIMIT
from .wire import WireCorruptionError, WireError

_RANK_LIMIT = 16  # NNS_TENSOR_RANK_LIMIT (tensor_typedef.h:34)

# Tensor_type enum (nnstreamer.fbs) — indices are the wire contract
_TO_FB = {
    "int32": 0, "uint32": 1, "int16": 2, "uint16": 3, "int8": 4,
    "uint8": 5, "float64": 6, "float32": 7, "int64": 8, "uint64": 9,
}
_FROM_FB = {v: k for k, v in _TO_FB.items()}

# vtable slots in schema declaration order (flatc assignment)
_TENSOR_NAME, _TENSOR_TYPE, _TENSOR_DIM, _TENSOR_DATA = 0, 1, 2, 3
_TENSORS_NUM, _TENSORS_FR, _TENSORS_VEC, _TENSORS_FORMAT = 0, 1, 2, 3
_NNS_END = 10  # Tensor.type schema default

_FORMAT_STATIC = 0  # Tensor_format enum


def _slot(i: int) -> int:
    """Slot index -> vtable byte offset (flatbuffers layout: 4 + 2*i)."""
    return 4 + 2 * i


def encode_frame(frame: TensorFrame) -> bytes:
    import flatbuffers

    b = flatbuffers.Builder(1024)
    tensor_offs = []
    for t in frame.tensors:
        arr = np.ascontiguousarray(np.asarray(t))
        name = str(np.dtype(arr.dtype))
        if name not in _TO_FB:
            raise WireError(
                f"dtype {name} not representable in nnstreamer.fbs"
            )
        if arr.ndim > _RANK_LIMIT:
            raise WireError(f"rank {arr.ndim} exceeds fbs limit {_RANK_LIMIT}")
        if 0 in arr.shape:
            # 0 is the dimension terminator on this wire — a zero-size
            # tensor cannot be represented (the peer would misparse it)
            raise WireError(
                f"zero-size tensor shape {arr.shape} not representable "
                "in nnstreamer.fbs"
            )
        # reference dialect: innermost-first, zero-padded to rank 16
        dims = np.zeros(_RANK_LIMIT, np.uint32)
        dims[: arr.ndim] = arr.shape[::-1]
        name_off = b.CreateString(frame.meta.get("tensor_name", "") or "")
        dim_off = b.CreateNumpyVector(dims)
        data_off = b.CreateByteVector(arr.tobytes())
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(_TENSOR_NAME, name_off, 0)
        b.PrependInt32Slot(_TENSOR_TYPE, _TO_FB[name], _NNS_END)
        b.PrependUOffsetTRelativeSlot(_TENSOR_DIM, dim_off, 0)
        b.PrependUOffsetTRelativeSlot(_TENSOR_DATA, data_off, 0)
        tensor_offs.append(b.EndObject())

    b.StartVector(4, len(tensor_offs), 4)
    for off in reversed(tensor_offs):
        b.PrependUOffsetTRelative(off)
    vec_off = b.EndVector()

    rate_n, rate_d = _framerate_of(frame)
    b.StartObject(4)
    b.PrependInt32Slot(_TENSORS_NUM, len(frame.tensors), 0)
    # frame_rate is a struct: built inline while its parent table is open
    b.Prep(4, 8)
    b.PrependInt32(rate_d)
    b.PrependInt32(rate_n)
    b.PrependStructSlot(_TENSORS_FR, b.Offset(), 0)
    b.PrependUOffsetTRelativeSlot(_TENSORS_VEC, vec_off, 0)
    b.PrependInt32Slot(_TENSORS_FORMAT, _FORMAT_STATIC, 0)
    b.Finish(b.EndObject())
    return bytes(b.Output())


def _framerate_of(frame: TensorFrame):
    fr = frame.meta.get("framerate")
    if isinstance(fr, (list, tuple)) and len(fr) == 2:
        try:
            return int(fr[0]), int(fr[1])
        except (TypeError, ValueError):
            pass
    return 0, 1


def decode_frame(buf: bytes, verify: bool = True) -> TensorFrame:
    """``verify`` is accepted for codec-API parity; the reference fbs
    schema carries no checksum field (structural validation only)."""
    del verify
    import flatbuffers
    from flatbuffers import number_types as NT

    # no copy: the runtime's Table reads any buffer-protocol object, and
    # decoded arrays alias the payload (same ownership convention as the
    # NNSQ codec's memoryview slicing)
    data = buf if isinstance(buf, (bytes, bytearray)) else bytes(buf)
    try:
        root = flatbuffers.encode.Get(NT.UOffsetTFlags.packer_type, data, 0)
        tab = flatbuffers.table.Table(data, root)
        tensors = []
        names = []
        o = tab.Offset(_slot(_TENSORS_VEC))
        n_declared = 0
        num_o = tab.Offset(_slot(_TENSORS_NUM))
        if num_o:
            n_declared = tab.Get(NT.Int32Flags, num_o + tab.Pos)
        if o:
            vec = tab.Vector(o)
            n = tab.VectorLen(o)
            for i in range(n):
                elem = tab.Indirect(vec + i * 4)
                tt = flatbuffers.table.Table(data, elem)
                to = tt.Offset(_slot(_TENSOR_TYPE))
                type_id = (
                    tt.Get(NT.Int32Flags, to + tt.Pos) if to else _NNS_END
                )
                if type_id not in _FROM_FB:
                    raise WireError(f"unknown Tensor_type {type_id}")
                dtype = np.dtype(_FROM_FB[type_id])
                do = tt.Offset(_slot(_TENSOR_DIM))
                dims = (
                    tt.GetVectorAsNumpy(NT.Uint32Flags, do)
                    if do else np.zeros(0, np.uint32)
                )
                # innermost-first, zero-terminated -> numpy shape
                keep = []
                for d in dims:
                    if d == 0:
                        break
                    keep.append(int(d))
                shape = tuple(reversed(keep))
                po = tt.Offset(_slot(_TENSOR_DATA))
                payload = (
                    tt.GetVectorAsNumpy(NT.Uint8Flags, po)
                    if po else np.zeros(0, np.uint8)
                )
                # math.prod: exact python ints — np.prod wraps at int64,
                # letting a hostile dim vector alias a small payload
                expect = math.prod(shape) * dtype.itemsize
                if payload.nbytes != expect:
                    raise WireError(
                        f"tensor payload {payload.nbytes}B != "
                        f"shape {shape} x {dtype}"
                    )
                if len(shape) > _REPO_RANK_LIMIT:
                    raise WireError(f"rank {len(shape)} over limit")
                tensors.append(payload.view(dtype).reshape(shape))
                no = tt.Offset(_slot(_TENSOR_NAME))
                names.append(
                    tt.String(no + tt.Pos).decode() if no else ""
                )
        if n_declared and n_declared != len(tensors):
            raise WireError(
                f"num_tensor={n_declared} != {len(tensors)} tensors present"
            )
        fo = tab.Offset(_slot(_TENSORS_FR))
        meta = {}
        if fo:
            pos = fo + tab.Pos
            rate_n = tab.Get(NT.Int32Flags, pos)
            rate_d = tab.Get(NT.Int32Flags, pos + 4)
            if rate_d:
                meta["framerate"] = [int(rate_n), int(rate_d)]
        name = next((n for n in names if n), "")
        if name:
            meta["tensor_name"] = name
    except WireError:
        raise
    except Exception as e:  # runtime raises assorted struct/index errors
        raise WireCorruptionError(f"malformed flatbuffers frame: {e}") from None
    return TensorFrame(tensors, meta=meta)
