"""Raw-TCP query transport: the zero-copy data plane for tensor_query.

Reference analog: the query elements delegate transport to the nns-edge C
library's custom TCP framing (``tensor_query_client.c:657-699`` →
nns_edge_send; ``nnstreamer-edge`` repo).  The gRPC transport
(:mod:`.service`) stays the default for interop; this one exists to feed
a chip at target rate: Python gRPC costs several whole-payload copies per
request, which caps the measured client ceiling below chip rate at real
payload sizes (BENCH_FANOUT r3: 713 fps @150 KB).

Design for copy-freedom on the hot path:

* **TX is zero-copy**: requests are gather-sent with ``socket.sendmsg``
  over the vectored parts from :func:`..distributed.wire.encode_frame_parts`
  — tensor payloads go to the kernel straight from the numpy buffers.
* **RX is one-copy**: a fresh ``bytearray`` per response filled with
  ``recv_into`` (no intermediate chunks, no joins), then
  :func:`decode_frame` builds zero-copy numpy views into it.
* **N parallel connections per client** (``nconns``): each in-flight
  request owns one socket for its round trip, so pipelined requests
  never serialize behind one another (the client element's thread pool
  provides the concurrency; this pool provides the sockets).

Socket protocol (little-endian):
  1-byte type | u64 body_len | f64 deadline_s | body
  'H' handshake: body = caps utf-8; reply 'H' caps or 'E' error utf-8
  'Q' query:     body = NNSQ frame or NNSB batch; reply 'Q' or 'E'
``deadline_s`` carries the client's remaining timeout so the server-side
pipeline wait honors it (the gRPC transport gets the same via
``context.time_remaining()``); 0 on replies.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import List, Optional, Tuple

from ..core.buffer import TensorFrame
from ..core.liveness import ServerBusyError
from ..core.log import get_logger
from ..core.resilience import FAULTS, RemoteApplicationError
from .wire import (
    WireError,
    decode_frame,
    decode_frames,
    encode_frame_parts,
    encode_frames_parts,
    is_batch_payload,
    parts_nbytes,
)

log = get_logger("tcp_query")

_HDR = struct.Struct("<BQd")
_T_HANDSHAKE = ord("H")
_T_QUERY = ord("Q")
_T_ERROR = ord("E")
# admission control: the server REFUSED the request before ingest (load
# shed); body = ascii retry-after seconds.  Clients treat it as transient
# backpressure (ServerBusyError), never as remote ill-health.
_T_BUSY = ord("B")
# the server PIPELINE produced no answer in time.  Distinct from 'E' app
# errors because it IS a health signal: the client raises TimeoutError so
# breakers/cooldowns count it — the same classification this condition
# gets over gRPC (DEADLINE_EXCEEDED).
_T_TIMEOUT = ord("T")

# liveness bound for the server reader: a peer that begins a message and
# then stalls (no bytes) this long is dropped instead of wedging the
# connection thread until process exit
_MID_MSG_STALL_S = 30.0
# reply sends get a long-but-bounded timeout (big payloads on a slow
# link), distinct from the short recv poll used for idle detection
_SEND_TIMEOUT_S = 30.0

# one gather-send syscall tops out at IOV_MAX buffers; chunk above it
_IOV_MAX = 512

# refuse absurd peer-declared body lengths before allocating (matches the
# gRPC transport's 512 MB max_receive_message_length)
_MAX_BODY = 512 * 1024 * 1024


def _sendmsg_all(sock: socket.socket, parts: List) -> None:
    """Gather-send every buffer, handling partial sends without copying:
    a short write re-enters with the same memoryviews sliced forward."""
    bufs = [memoryview(p).cast("B") for p in parts if len(memoryview(p))]
    while bufs:
        sent = sock.sendmsg(bufs[:_IOV_MAX])
        if sent <= 0:
            raise ConnectionError("socket closed mid-send")
        # drop fully-sent buffers, slice the partially-sent one
        i = 0
        while i < len(bufs) and sent >= bufs[i].nbytes:
            sent -= bufs[i].nbytes
            i += 1
        bufs = bufs[i:]
        if sent and bufs:
            bufs[0] = bufs[0][sent:]


def _recv_exact(sock: socket.socket, n: int) -> memoryview:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("socket closed mid-receive")
        got += r
    return memoryview(buf)


def _send_msg(sock: socket.socket, mtype: int, parts: List,
              deadline_s: float = 0.0) -> None:
    _sendmsg_all(
        sock, [_HDR.pack(mtype, parts_nbytes(parts), deadline_s)] + parts)


def _recv_msg(sock: socket.socket) -> Tuple[int, memoryview, float]:
    head = _recv_exact(sock, _HDR.size)
    mtype, blen, deadline_s = _HDR.unpack(head)
    if blen > _MAX_BODY:
        raise WireError(f"declared body length {blen} exceeds {_MAX_BODY}")
    return mtype, _recv_exact(sock, blen), deadline_s


def _recv_exact_bounded(sock: socket.socket, n: int, stop: threading.Event,
                        idle_ok: bool = False) -> memoryview:
    """``_recv_exact`` for the server reader thread: the socket carries a
    short poll timeout, so idle waits stay responsive to `stop`, and a
    peer that goes silent MID-read for ``_MID_MSG_STALL_S`` is treated
    as broken (no unbounded blocking in the reader — audit contract,
    tools/check_blocking_timeouts.py).  ``idle_ok`` = message-boundary
    read: the stall bound only starts once the first byte arrives (an
    idle connection may legitimately wait forever, polling `stop`)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    last_progress = None if idle_ok else time.monotonic()
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except socket.timeout:
            if stop.is_set():
                raise ConnectionError("server stopping") from None
            if (last_progress is not None
                    and time.monotonic() - last_progress >= _MID_MSG_STALL_S):
                raise ConnectionError(
                    f"peer stalled mid-message ({got}/{n} bytes)"
                ) from None
            continue
        if r == 0:
            raise ConnectionError("socket closed mid-receive")
        got += r
        last_progress = time.monotonic()
    return memoryview(buf)


def _recv_msg_bounded(sock: socket.socket,
                      stop: threading.Event) -> Tuple[int, memoryview, float]:
    """Server-side ``_recv_msg`` with liveness bounds: blocks
    indefinitely only BETWEEN messages (polling `stop`); within one it
    inherits the mid-message stall bound."""
    head = _recv_exact_bounded(sock, _HDR.size, stop, idle_ok=True)
    mtype, blen, deadline_s = _HDR.unpack(head)
    if blen > _MAX_BODY:
        raise WireError(f"declared body length {blen} exceeds {_MAX_BODY}")
    return mtype, _recv_exact_bounded(sock, blen, stop), deadline_s


class TcpQueryConnection:
    """Client side: a pool of persistent sockets to one server.

    API-compatible with :class:`.service.QueryConnection` (handshake /
    invoke / invoke_batch / close / addr), so the query client element
    swaps transports by construction only.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 nconns: int = 4):
        self.addr = f"{host}:{port}"
        self._host, self._port = host, port
        self._timeout = timeout
        self._nconns = max(1, nconns)
        self._free: List[socket.socket] = []
        self._live = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False

    # -- socket pool --------------------------------------------------------
    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _checkout(self, timeout: float,
                  fresh: bool = False) -> Tuple[socket.socket, bool]:
        """Returns ``(sock, reused)`` — `reused` means the socket came
        from the idle pool and may have gone stale while parked (the
        peer can close an idle connection at any time); `_roundtrip`
        uses it to decide whether a send-phase failure merits one
        fresh-dial retry.  ``fresh=True`` (the retry) guarantees a NEW
        dial: the idle pool is drained and closed first — a send failure
        on one parked socket means the peer restarted, so every other
        parked socket is equally suspect."""
        with self._cv:
            while True:
                if self._closed:
                    raise ConnectionError("connection closed")
                if fresh:
                    while self._free:
                        stale = self._free.pop()
                        self._live -= 1
                        try:
                            stale.close()
                        except OSError:
                            pass
                elif self._free:
                    return self._free.pop(), True
                if self._live < self._nconns:
                    self._live += 1
                    break
                if not self._cv.wait(timeout):
                    raise TimeoutError(
                        f"no free connection to {self.addr} in {timeout}s")
        try:
            return self._connect(), False
        except Exception:
            with self._cv:
                self._live -= 1
                self._cv.notify()
            raise

    def _checkin(self, sock: socket.socket, broken: bool) -> None:
        with self._cv:
            if broken or self._closed:
                self._live -= 1
                try:
                    sock.close()
                except OSError:
                    pass
            else:
                self._free.append(sock)
            self._cv.notify()

    def _roundtrip(self, mtype: int, parts: List,
                   timeout: Optional[float]) -> Tuple[int, memoryview]:
        """One request/response exchange.

        Failure contract (audited — see Documentation/resilience.md):
        a socket that raised during send OR recv is closed and evicted
        from the pool (``broken=True`` checkin), never handed to the
        next caller.  A send-phase failure on a REUSED socket gets one
        retry on a fresh dial: an idle pooled connection the peer
        half-closed fails exactly there, and an incompletely-sent
        request provably never executed server-side, so the resend is
        safe even at-most-once.  Recv-phase failures are never retried
        here — the server may already have processed the request; the
        caller's retry policy owns that decision."""
        timeout = self._timeout if timeout is None else timeout
        for attempt in (0, 1):
            sock, reused = self._checkout(timeout, fresh=(attempt == 1))
            broken = True
            sent = False
            try:
                sock.settimeout(timeout)
                FAULTS.check("tcp_query.send")
                _send_msg(sock, mtype, parts, deadline_s=timeout)
                sent = True
                FAULTS.check("tcp_query.recv")
                rtype, body, _ = _recv_msg(sock)
                broken = False
                return rtype, body
            except (ConnectionError, OSError) as e:
                if (attempt == 0 and reused and not sent
                        and not isinstance(e, TimeoutError)):
                    log.debug(
                        "stale pooled socket to %s (%s); retrying on a "
                        "fresh connection", self.addr, e)
                    continue
                raise
            finally:
                self._checkin(sock, broken)
        raise AssertionError("unreachable")  # loop always returns/raises

    # -- public API ---------------------------------------------------------
    @staticmethod
    def _check_reply(rtype: int, body: memoryview) -> None:
        if rtype == _T_BUSY:
            # admission shed: provably never executed, safe to re-send
            try:
                retry_after = float(bytes(body).decode() or 0.05)
            except ValueError:
                retry_after = 0.05
            raise ServerBusyError(retry_after=retry_after)
        if rtype == _T_TIMEOUT:
            # server pipeline timeout: ill-health, NOT an app reply —
            # must reach breakers/cooldowns (gRPC parity:
            # DEADLINE_EXCEEDED)
            raise TimeoutError(bytes(body).decode())
        if rtype == _T_ERROR:
            # RemoteApplicationError (a RuntimeError): the server is UP
            # and answered — health machinery must not count this
            raise RemoteApplicationError(bytes(body).decode())

    def handshake(self, caps: str) -> str:
        rtype, body = self._roundtrip(_T_HANDSHAKE, [caps.encode()], None)
        self._check_reply(rtype, body)
        return bytes(body).decode()

    def invoke(self, frame: TensorFrame,
               timeout: Optional[float] = None) -> TensorFrame:
        rtype, body = self._roundtrip(
            _T_QUERY, encode_frame_parts(frame), timeout)
        self._check_reply(rtype, body)
        return decode_frame(body)

    def invoke_batch(self, frames: List[TensorFrame],
                     timeout: Optional[float] = None) -> List[TensorFrame]:
        rtype, body = self._roundtrip(
            _T_QUERY, encode_frames_parts(frames), timeout)
        self._check_reply(rtype, body)
        return decode_frames(body)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            socks, self._free = self._free, []
            self._cv.notify_all()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass


class TcpQueryServer:
    """Server side: accept loop + one reader thread per connection, all
    funnelling into the shared :class:`.service.QueryServerCore` (same
    ingress queue / pending table / caps logic as the gRPC transport)."""

    def __init__(self, core, host: str = "", port: int = 0):
        self._core = core
        self._host = host or "0.0.0.0"
        self.port = port
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()

    def start(self) -> None:
        if self._listener is not None:
            return
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self._host, self.port))
        ls.listen(64)
        ls.settimeout(0.2)
        self.port = ls.getsockname()[1]
        self._listener = ls
        self._stop.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tcpq-accept", daemon=True)
        self._accept_thread.start()
        log.info("tcp query server on :%d", self.port)

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)
            self._accept_thread = None
        for t in self._conn_threads:
            t.join(timeout=2)
        self._conn_threads = []

    # -- internals ----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # short poll timeout: the reader thread must never block
            # unbounded (idle waits poll the stop flag; mid-message
            # stalls are bounded by _recv_msg_bounded)
            conn.settimeout(0.5)
            with self._conns_lock:
                self._conns.append(conn)
            # prune finished handler threads (connection churn must not
            # accumulate dead Thread objects for the server's lifetime)
            self._conn_threads = [t for t in self._conn_threads
                                  if t.is_alive()]
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="tcpq-conn", daemon=True)
            t.start()
            self._conn_threads.append(t)

    def _reply(self, conn: socket.socket, mtype: int, parts: List) -> None:
        """Send one reply under the send timeout, then restore the short
        recv-poll timeout (settimeout governs BOTH directions)."""
        conn.settimeout(_SEND_TIMEOUT_S)
        try:
            _send_msg(conn, mtype, parts)
        finally:
            conn.settimeout(0.5)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    mtype, body, deadline_s = _recv_msg_bounded(
                        conn, self._stop)
                except WireError as e:
                    # unparseable/oversized header: tell the peer and drop
                    # the connection (framing is lost at this point)
                    try:
                        self._reply(conn, _T_ERROR, [str(e).encode()])
                    except OSError:
                        pass
                    return
                except (ConnectionError, OSError):
                    return
                try:
                    if mtype == _T_HANDSHAKE:
                        try:
                            caps = self._core.check_caps(bytes(body).decode())
                            self._reply(conn, _T_HANDSHAKE, [caps.encode()])
                        except ValueError as e:
                            self._reply(conn, _T_ERROR, [str(e).encode()])
                    elif mtype == _T_QUERY:
                        batched = is_batch_payload(body)
                        frames = (decode_frames(body) if batched
                                  else [decode_frame(body)])
                        try:
                            answers = self._core.process(
                                frames,
                                deadline_s if deadline_s > 0 else 30.0)
                        except TimeoutError as e:
                            # caught HERE, not at the message boundary:
                            # socket.timeout from the reply sends below is
                            # the same class and must stay an OSError-path
                            # connection drop, not a 'T' reply
                            self._reply(conn, _T_TIMEOUT, [str(e).encode()])
                            continue
                        parts = (encode_frames_parts(answers) if batched
                                 else encode_frame_parts(answers[0]))
                        self._reply(conn, _T_QUERY, parts)
                    else:
                        self._reply(
                            conn, _T_ERROR,
                            [f"unknown message type {mtype}".encode()])
                except ServerBusyError as e:
                    # admission shed: the cheapest possible reply — the
                    # request never touched the pipeline
                    try:
                        self._reply(conn, _T_BUSY,
                                    [f"{e.retry_after:.6f}".encode()])
                    except OSError:
                        return
                except OSError:
                    return  # peer gone mid-reply
                except Exception as e:  # noqa: BLE001 — transport boundary:
                    # any pipeline-side failure (timeout, full ingress,
                    # malformed frame) becomes a protocol error reply; the
                    # connection and its socket survive
                    try:
                        self._reply(conn, _T_ERROR, [str(e).encode()])
                    except OSError:
                        return
        finally:
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass
