"""Raw-TCP query transport: the zero-copy data plane for tensor_query.

Reference analog: the query elements delegate transport to the nns-edge C
library's custom TCP framing (``tensor_query_client.c:657-699`` →
nns_edge_send; ``nnstreamer-edge`` repo).  The gRPC transport
(:mod:`.service`) stays the default for interop; this one exists to feed
a chip at target rate: Python gRPC costs several whole-payload copies per
request, which caps the measured client ceiling below chip rate at real
payload sizes (BENCH_FANOUT r3: 713 fps @150 KB).

Design for copy-freedom on the hot path:

* **TX is zero-copy**: requests are gather-sent with ``socket.sendmsg``
  over the vectored parts from :func:`..distributed.wire.encode_frame_parts`
  — tensor payloads go to the kernel straight from the numpy buffers.
* **RX is one-copy**: a fresh ``bytearray`` per response filled with
  ``recv_into`` (no intermediate chunks, no joins), then
  :func:`decode_frame` builds zero-copy numpy views into it.
* **N parallel connections per client** (``nconns``): each in-flight
  request owns one socket for its round trip, so pipelined requests
  never serialize behind one another (the client element's thread pool
  provides the concurrency; this pool provides the sockets).

Socket protocol (little-endian):
  v1 framing: 1-byte type | u64 body_len | f64 deadline_s | body
  v2 framing: 1-byte type | u64 body_len | f64 deadline_s | u32 crc | body
              (crc = CRC-32 over the header with the crc field zeroed,
              then the body — message-level integrity, on top of the
              per-frame NNSQ v2 checksums inside 'Q' bodies)
  'H' handshake: body = caps utf-8; reply 'H' caps or 'E' error utf-8
  'Q' query:     body = NNSQ frame or NNSB/NNSC batch; reply 'Q' or 'E'
  'V' version:   body = ascii max version the sender speaks.  A v2
                 server replies 'V' with the AGREED version
                 (min of both maxes) and switches THAT connection to it
                 for all subsequent messages; a v1 peer answers 'E'
                 unknown-message-type, so the client stays on v1 —
                 zero-config interop both ways.
  'C' corrupt:   the request failed integrity verification (checksum
                 mismatch / malformed envelope).  The request provably
                 never executed, so clients treat it as a resend-safe
                 transient; the server connection stays alive.
  'G' goaway:    the server is DRAINING (rolling restart) and refused the
                 request before ingest; body = error text.  Provably
                 never executed — clients fail over to another host
                 immediately (no pacing, no breaker event).
``deadline_s`` carries the client's remaining timeout so the server-side
pipeline wait honors it (the gRPC transport gets the same via
``context.time_remaining()``); 0 on replies.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..core.buffer import TensorFrame
from ..core.lifecycle import ServerGoawayError
from ..core.liveness import ServerBusyError
from ..core.log import get_logger
from ..core.resilience import FAULTS, RemoteApplicationError
from .wire import (
    V1,
    V2,
    WireCorruptionError,
    WireError,
    WireTruncationError,
    decode_frame,
    decode_frames,
    encode_frame_parts,
    encode_frames_parts,
    is_batch_payload,
    parts_nbytes,
)

log = get_logger("tcp_query")

#: highest message framing / envelope version this build speaks
WIRE_VERSION = V2

_HDR = struct.Struct("<BQd")     # v1 framing
_HDR2 = struct.Struct("<BQdI")   # v2: + u32 crc (header w/ crc zeroed + body)
_T_HANDSHAKE = ord("H")
_T_QUERY = ord("Q")
_T_ERROR = ord("E")
# admission control: the server REFUSED the request before ingest (load
# shed); body = ascii retry-after seconds.  Clients treat it as transient
# backpressure (ServerBusyError), never as remote ill-health.
_T_BUSY = ord("B")
# the server PIPELINE produced no answer in time.  Distinct from 'E' app
# errors because it IS a health signal: the client raises TimeoutError so
# breakers/cooldowns count it — the same classification this condition
# gets over gRPC (DEADLINE_EXCEEDED).
_T_TIMEOUT = ord("T")
# wire-version negotiation (see module docstring)
_T_VERSION = ord("V")
# rolling restart: the server is DRAINING and refused the request before
# ingest (core/lifecycle.py).  Provably never executed -> immediate
# resend-safe failover; unlike 'B' there is no pacing to honor and the
# reply is health (never a breaker event): the host is leaving, not sick.
_T_GOAWAY = ord("G")
# integrity: the request failed checksum/envelope verification before any
# execution — resend-safe; body = error text
_T_CORRUPT = ord("C")
# server-streaming invoke (continuous batching / tensor_generator): ONE
# request frame in, a sequence of 'S' replies out — each body one NNSQ
# answer frame — until a reply's meta carries ``final`` True (or no
# ``final`` key: a plain 1:1 graph answers once).  Errors keep their
# usual types ('B'/'G'/'C' before the first chunk, 'T' on a silent
# pipeline, 'E' app errors); the connection is HELD by the stream for
# its whole life (the client pool provides concurrency across streams).
_T_STREAM = ord("S")

# liveness bound for the server reader: a peer that begins a message and
# then stalls (no bytes) this long is dropped instead of wedging the
# connection thread until process exit
_MID_MSG_STALL_S = 30.0
# reply sends get a long-but-bounded timeout (big payloads on a slow
# link), distinct from the short recv poll used for idle detection
_SEND_TIMEOUT_S = 30.0

# one gather-send syscall tops out at IOV_MAX buffers; chunk above it
_IOV_MAX = 512

# refuse absurd peer-declared body lengths before allocating (matches the
# gRPC transport's 512 MB max_receive_message_length)
_MAX_BODY = 512 * 1024 * 1024


def _sendmsg_all(sock: socket.socket, parts: List) -> None:
    """Gather-send every buffer, handling partial sends without copying:
    a short write re-enters with the same memoryviews sliced forward."""
    bufs = [memoryview(p).cast("B") for p in parts if len(memoryview(p))]
    while bufs:
        sent = sock.sendmsg(bufs[:_IOV_MAX])
        if sent <= 0:
            raise ConnectionError("socket closed mid-send")
        # drop fully-sent buffers, slice the partially-sent one
        i = 0
        while i < len(bufs) and sent >= bufs[i].nbytes:
            sent -= bufs[i].nbytes
            i += 1
        bufs = bufs[i:]
        if sent and bufs:
            bufs[0] = bufs[0][sent:]


def _recv_exact(sock: socket.socket, n: int) -> memoryview:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("socket closed mid-receive")
        got += r
    return memoryview(buf)


def _hdr_struct(version: int) -> struct.Struct:
    return _HDR2 if version >= V2 else _HDR


def _msg_crc(mtype: int, blen: int, deadline_s: float, parts: List) -> int:
    """v2 message checksum: header with the crc field zeroed, then every
    body part — one streaming pass, no copies."""
    crc = zlib.crc32(_HDR2.pack(mtype, blen, deadline_s, 0))
    for p in parts:
        crc = zlib.crc32(memoryview(p), crc)
    return crc


def _send_msg(sock: socket.socket, mtype: int, parts: List,
              deadline_s: float = 0.0, version: int = V1) -> None:
    n = parts_nbytes(parts)
    if version >= V2:
        head = _HDR2.pack(mtype, n, deadline_s,
                          _msg_crc(mtype, n, deadline_s, parts))
    else:
        head = _HDR.pack(mtype, n, deadline_s)
    _sendmsg_all(sock, [head] + parts)


def _parse_head(head, version: int) -> Tuple[int, int, float, Optional[int]]:
    """Unpack + bounds-check one message header (both framings); the
    declared body length is validated BEFORE any allocation."""
    if version >= V2:
        mtype, blen, deadline_s, crc = _HDR2.unpack(head)
    else:
        mtype, blen, deadline_s = _HDR.unpack(head)
        crc = None
    if blen > _MAX_BODY:
        raise WireCorruptionError(
            f"declared body length {blen} exceeds {_MAX_BODY}")
    return mtype, blen, deadline_s, crc


def _verify_msg(mtype: int, blen: int, deadline_s: float,
                crc: Optional[int], body) -> None:
    if crc is None:
        return
    actual = _msg_crc(mtype, blen, deadline_s, [body])
    if actual != crc:
        raise WireCorruptionError(
            f"message checksum mismatch (crc32 {actual:#010x} != "
            f"declared {crc:#010x})"
        )


def encode_msg(mtype: int, body: bytes, deadline_s: float = 0.0,
               version: int = V1) -> bytes:
    """One complete message as bytes (tests + tools/fuzz_wire.py)."""
    n = len(body)
    if version >= V2:
        return _HDR2.pack(mtype, n, deadline_s,
                          _msg_crc(mtype, n, deadline_s, [body])) + body
    return _HDR.pack(mtype, n, deadline_s) + body


def parse_msg(data, version: int = V1,
              verify: bool = True) -> Tuple[int, memoryview, float]:
    """Pure-bytes inverse of :func:`encode_msg`: parse ONE complete
    message from a byte string with the same typed-error bounds contract
    as the socket readers (the fuzz harness drives this directly)."""
    mv = memoryview(data)
    hs = _hdr_struct(version)
    if len(mv) < hs.size:
        raise WireTruncationError(
            f"truncated message header: {len(mv)}/{hs.size} bytes")
    mtype, blen, deadline_s, crc = _parse_head(bytes(mv[:hs.size]), version)
    body = mv[hs.size:]
    if len(body) != blen:
        raise WireTruncationError(
            f"message body {len(body)}B != declared {blen}B")
    if verify:
        _verify_msg(mtype, blen, deadline_s, crc, body)
    return mtype, body, deadline_s


def _recv_msg(sock: socket.socket, version: int = V1,
              verify: bool = True) -> Tuple[int, memoryview, float]:
    head = _recv_exact(sock, _hdr_struct(version).size)
    mtype, blen, deadline_s, crc = _parse_head(head, version)
    body = _recv_exact(sock, blen)
    if verify:
        _verify_msg(mtype, blen, deadline_s, crc, body)
    return mtype, body, deadline_s


def _recv_exact_bounded(sock: socket.socket, n: int, stop: threading.Event,
                        idle_ok: bool = False) -> memoryview:
    """``_recv_exact`` for the server reader thread: the socket carries a
    short poll timeout, so idle waits stay responsive to `stop`, and a
    peer that goes silent MID-read for ``_MID_MSG_STALL_S`` is treated
    as broken (no unbounded blocking in the reader — audit contract,
    tools/check_blocking_timeouts.py).  ``idle_ok`` = message-boundary
    read: the stall bound only starts once the first byte arrives (an
    idle connection may legitimately wait forever, polling `stop`)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    last_progress = None if idle_ok else time.monotonic()
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except socket.timeout:
            if stop.is_set():
                raise ConnectionError("server stopping") from None
            if (last_progress is not None
                    and time.monotonic() - last_progress >= _MID_MSG_STALL_S):
                raise ConnectionError(
                    f"peer stalled mid-message ({got}/{n} bytes)"
                ) from None
            continue
        if r == 0:
            raise ConnectionError("socket closed mid-receive")
        got += r
        last_progress = time.monotonic()
    return memoryview(buf)


def _recv_msg_bounded(sock: socket.socket, stop: threading.Event,
                      version: int = V1,
                      verify: bool = True) -> Tuple[int, memoryview, float]:
    """Server-side ``_recv_msg`` with liveness bounds: blocks
    indefinitely only BETWEEN messages (polling `stop`); within one it
    inherits the mid-message stall bound."""
    head = _recv_exact_bounded(
        sock, _hdr_struct(version).size, stop, idle_ok=True)
    mtype, blen, deadline_s, crc = _parse_head(head, version)
    body = _recv_exact_bounded(sock, blen, stop)
    if verify:
        _verify_msg(mtype, blen, deadline_s, crc, body)
    return mtype, body, deadline_s


class TcpQueryConnection:
    """Client side: a pool of persistent sockets to one server.

    API-compatible with :class:`.service.QueryConnection` (handshake /
    invoke / invoke_batch / close / addr), so the query client element
    swaps transports by construction only.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 nconns: int = 4, wire_version: int = WIRE_VERSION,
                 verify_checksum: bool = True):
        self.addr = f"{host}:{port}"
        self._host, self._port = host, port
        self._timeout = timeout
        self._nconns = max(1, nconns)
        self._free: List[socket.socket] = []
        self._live = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False
        # integrity / negotiation state: every fresh socket that may
        # speak v2 sends a 'V' probe at dial time; a v1 peer's 'E' reply
        # latches _peer_v1 so later dials skip the probe round trip.
        # _sock_ver maps each pooled socket to ITS negotiated framing
        # (single dict ops — GIL-atomic, no extra lock needed).
        self._wire_version = V2 if int(wire_version) >= V2 else V1
        self._verify = bool(verify_checksum)
        self._peer_v1 = self._wire_version == V1
        self._sock_ver: Dict[socket.socket, int] = {}
        # sockets currently checked out to callers: close() force-closes
        # them too, so an in-flight STREAM dies with its client element
        # (the server sees the break and cancels the generation) instead
        # of outliving it until the consumer generator is collected
        self._held: set = set()

    # -- socket pool --------------------------------------------------------
    def _negotiate(self, sock: socket.socket) -> int:
        """Upgrade one fresh socket to v2 framing: 'V' probe sent in v1
        framing.  A v2 server replies 'V' and switches that connection;
        a v1 peer replies 'E' unknown-message-type — stay on v1."""
        _send_msg(sock, _T_VERSION, [str(WIRE_VERSION).encode()], version=V1)
        rtype, body, _ = _recv_msg(sock, version=V1)
        if rtype != _T_VERSION:
            return V1
        try:
            peer = int(bytes(body) or b"1")
        except ValueError:
            return V1
        return V2 if peer >= V2 else V1

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        ver = V1
        if self._wire_version >= V2 and not self._peer_v1:
            try:
                ver = self._negotiate(sock)
            except (ConnectionError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass
                raise
            if ver == V1:
                # benign race between concurrent dialers: worst case a
                # few extra probes before everyone learns the peer is v1
                self._peer_v1 = True
        self._sock_ver[sock] = ver
        return sock

    def _checkout(self, timeout: float,
                  fresh: bool = False) -> Tuple[socket.socket, bool]:
        """Returns ``(sock, reused)`` — `reused` means the socket came
        from the idle pool and may have gone stale while parked (the
        peer can close an idle connection at any time); `_roundtrip`
        uses it to decide whether a send-phase failure merits one
        fresh-dial retry.  ``fresh=True`` (the retry) guarantees a NEW
        dial: the idle pool is drained and closed first — a send failure
        on one parked socket means the peer restarted, so every other
        parked socket is equally suspect."""
        with self._cv:
            while True:
                if self._closed:
                    raise ConnectionError("connection closed")
                if fresh:
                    while self._free:
                        stale = self._free.pop()
                        self._live -= 1
                        self._sock_ver.pop(stale, None)
                        try:
                            stale.close()
                        except OSError:
                            pass
                elif self._free:
                    sock = self._free.pop()
                    self._held.add(sock)
                    return sock, True
                if self._live < self._nconns:
                    self._live += 1
                    break
                if not self._cv.wait(timeout):
                    raise TimeoutError(
                        f"no free connection to {self.addr} in {timeout}s")
        try:
            sock = self._connect()
        except Exception:
            with self._cv:
                self._live -= 1
                self._cv.notify()
            raise
        with self._cv:
            if self._closed:
                # close() ran while we dialed: don't leak a live socket
                self._live -= 1
                self._sock_ver.pop(sock, None)
                try:
                    sock.close()
                except OSError:
                    pass
                self._cv.notify()
                raise ConnectionError("connection closed")
            self._held.add(sock)
        return sock, False

    def _checkin(self, sock: socket.socket, broken: bool) -> None:
        with self._cv:
            self._held.discard(sock)
            if broken or self._closed:
                self._live -= 1
                self._sock_ver.pop(sock, None)
                try:
                    sock.close()
                except OSError:
                    pass
            else:
                self._free.append(sock)
            self._cv.notify()

    def _roundtrip(self, mtype: int, make_parts,
                   timeout: Optional[float]) -> Tuple[int, memoryview]:
        """One request/response exchange.  ``make_parts(version)`` builds
        the body parts for the framing the checked-out socket negotiated
        (a v1 peer must receive v1-encoded frames).

        Failure contract (audited — see Documentation/resilience.md):
        a socket that raised during send OR recv is closed and evicted
        from the pool (``broken=True`` checkin), never handed to the
        next caller.  A send-phase failure on a REUSED socket gets one
        retry on a fresh dial: an idle pooled connection the peer
        half-closed fails exactly there, and an incompletely-sent
        request provably never executed server-side, so the resend is
        safe even at-most-once.  Recv-phase failures are never retried
        here — the server may already have processed the request; the
        caller's retry policy owns that decision."""
        timeout = self._timeout if timeout is None else timeout
        for attempt in (0, 1):
            sock, reused = self._checkout(timeout, fresh=(attempt == 1))
            ver = self._sock_ver.get(sock, V1)
            broken = True
            sent = False
            try:
                sock.settimeout(timeout)
                FAULTS.check("tcp_query.send")
                send_parts = make_parts(ver)
                if FAULTS.is_armed():
                    # corrupt= faults mutate the encoded request AFTER its
                    # checksums were computed (wire-corruption simulation:
                    # the server's verify-on-decode must catch it)
                    send_parts = FAULTS.mangle_parts(
                        "tcp_query.send", send_parts)
                _send_msg(sock, mtype, send_parts,
                          deadline_s=timeout, version=ver)
                sent = True
                FAULTS.check("tcp_query.recv")
                rtype, body, _ = _recv_msg(sock, version=ver,
                                           verify=self._verify)
                if FAULTS.is_armed():
                    # reply-path corruption lands AFTER the message-level
                    # check — the frame-level checksum inside the body is
                    # what must catch it at decode
                    body = FAULTS.mangle("tcp_query.recv", body)
                broken = False
                return rtype, body
            except (ConnectionError, OSError) as e:
                if (attempt == 0 and reused and not sent
                        and not isinstance(e, TimeoutError)):
                    log.debug(
                        "stale pooled socket to %s (%s); retrying on a "
                        "fresh connection", self.addr, e)
                    continue
                raise
            finally:
                self._checkin(sock, broken)
        raise AssertionError("unreachable")  # loop always returns/raises

    # -- public API ---------------------------------------------------------
    @staticmethod
    def _check_reply(rtype: int, body: memoryview) -> None:
        if rtype == _T_GOAWAY:
            # the server is draining (rolling restart): the request
            # provably never executed — the client fails over to another
            # host immediately, with no pacing and no breaker event
            raise ServerGoawayError(bytes(body).decode() or
                                    "server draining (goaway)")
        if rtype == _T_CORRUPT:
            # the server refused a request that failed integrity checks:
            # provably never executed, so resend-safe — the query client
            # retries it on its corrupt-retries budget and counts it
            raise WireCorruptionError(bytes(body).decode())
        if rtype == _T_BUSY:
            # admission shed: provably never executed, safe to re-send
            try:
                retry_after = float(bytes(body).decode() or 0.05)
            except ValueError:
                retry_after = 0.05
            raise ServerBusyError(retry_after=retry_after)
        if rtype == _T_TIMEOUT:
            # server pipeline timeout: ill-health, NOT an app reply —
            # must reach breakers/cooldowns (gRPC parity:
            # DEADLINE_EXCEEDED)
            raise TimeoutError(bytes(body).decode())
        if rtype == _T_ERROR:
            # RemoteApplicationError (a RuntimeError): the server is UP
            # and answered — health machinery must not count this
            raise RemoteApplicationError(bytes(body).decode())

    def handshake(self, caps: str) -> str:
        rtype, body = self._roundtrip(
            _T_HANDSHAKE, lambda ver: [caps.encode()], None)
        self._check_reply(rtype, body)
        return bytes(body).decode()

    def invoke(self, frame: TensorFrame,
               timeout: Optional[float] = None) -> TensorFrame:
        rtype, body = self._roundtrip(
            _T_QUERY,
            lambda ver: encode_frame_parts(frame, version=ver),
            timeout)
        self._check_reply(rtype, body)
        return decode_frame(body, verify=self._verify)

    def invoke_batch(self, frames: List[TensorFrame],
                     timeout: Optional[float] = None) -> List[TensorFrame]:
        rtype, body = self._roundtrip(
            _T_QUERY,
            lambda ver: encode_frames_parts(frames, version=ver),
            timeout)
        self._check_reply(rtype, body)
        return decode_frames(body, verify=self._verify)

    def invoke_stream(self, frame: TensorFrame,
                      timeout: Optional[float] = None):
        """Server-streaming invoke over raw TCP ('S' message): yields
        answer frames as they arrive until one is final-flagged (or has
        no ``final`` meta).  ``timeout`` bounds the WHOLE stream; one
        pooled socket is held for its duration (API parity with
        :meth:`.service.QueryConnection.invoke_stream`).

        Failure contract: a send-phase failure on a REUSED socket gets
        one fresh-dial retry (the request provably never executed);
        anything after the send follows the stream rules — typed refusal
        replies ('B'/'G'/'C'/'T'/'E') leave the socket aligned and
        poolable, a transport break or an abandoned stream evicts it."""
        timeout = self._timeout if timeout is None else timeout
        for attempt in (0, 1):
            sock, reused = self._checkout(timeout, fresh=(attempt == 1))
            ver = self._sock_ver.get(sock, V1)
            broken = True
            sent = False
            try:
                sock.settimeout(timeout)
                FAULTS.check("tcp_query.send")
                parts = encode_frame_parts(frame, version=ver)
                if FAULTS.is_armed():
                    parts = FAULTS.mangle_parts("tcp_query.send", parts)
                _send_msg(sock, _T_STREAM, parts,
                          deadline_s=timeout, version=ver)
                sent = True
                FAULTS.check("tcp_query.recv")
                deadline = time.monotonic() + timeout
                while True:
                    # the WHOLE-stream budget is a hard bound (gRPC
                    # parity: the RPC deadline kills the stream): a
                    # server still producing chunks past it must not
                    # keep the stream alive through per-recv grace
                    if time.monotonic() >= deadline:
                        raise TimeoutError(
                            f"stream to {self.addr} exceeded its "
                            f"{timeout}s budget")
                    # each chunk wait is carved from the stream budget
                    sock.settimeout(
                        max(0.05, deadline - time.monotonic()))
                    try:
                        rtype, body, _ = _recv_msg(
                            sock, version=ver, verify=self._verify)
                    except socket.timeout:
                        raise TimeoutError(
                            f"stream to {self.addr}: no (further) answer "
                            f"within the {timeout}s budget") from None
                    if FAULTS.is_armed():
                        body = FAULTS.mangle("tcp_query.recv", body)
                    if rtype != _T_STREAM:
                        # typed refusal/timeout reply: the framing is
                        # intact — socket back to the pool, error raised
                        broken = False
                        self._check_reply(rtype, body)
                        raise RemoteApplicationError(
                            f"unexpected stream reply type {rtype}")
                    ans = decode_frame(body, verify=self._verify)
                    if ans.meta.get("final", True):
                        broken = False  # clean completion
                        yield ans
                        return
                    yield ans
            except (ConnectionError, OSError) as e:
                if (attempt == 0 and reused and not sent
                        and not isinstance(e, TimeoutError)):
                    log.debug(
                        "stale pooled socket to %s (%s); retrying stream "
                        "on a fresh connection", self.addr, e)
                    continue
                raise
            finally:
                self._checkin(sock, broken)
            return

    def close(self) -> None:
        with self._cv:
            self._closed = True
            socks, self._free = self._free, []
            # force-close HELD sockets too: the caller blocked on them
            # gets a prompt OSError (its checkin then evicts the entry),
            # and a server streaming into one sees the break and cancels
            # the generation — a stopped client must look dead, not idle
            socks.extend(self._held)
            self._sock_ver.clear()
            self._cv.notify_all()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass


class TcpQueryServer:
    """Server side: accept loop + one reader thread per connection, all
    funnelling into the shared :class:`.service.QueryServerCore` (same
    ingress queue / pending table / caps logic as the gRPC transport)."""

    def __init__(self, core, host: str = "", port: int = 0,
                 wire_version: int = WIRE_VERSION,
                 verify_checksum: bool = True):
        self._core = core
        self._host = host or "0.0.0.0"
        self.port = port
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()
        # wire_version=1 pins LEGACY behavior (pre-checksum framing, 'V'
        # probes answered 'E') — the stand-in for a v1 peer in interop
        # tests and the rollback knob in mixed fleets
        self._wire_version = V2 if int(wire_version) >= V2 else V1
        self._verify = bool(verify_checksum)
        #: corrupt requests answered with 'C' (the server stayed alive)
        self.corruption_detected = 0

    def _note_corrupt(self, err: WireError) -> None:
        self.corruption_detected += 1
        if hasattr(self._core, "corrupt_requests"):
            self._core.corrupt_requests += 1
        log.warning("corrupt request refused ('C' reply): %s", err)

    def start(self) -> None:
        if self._listener is not None:
            return
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self._host, self.port))
        ls.listen(64)
        ls.settimeout(0.2)
        self.port = ls.getsockname()[1]
        self._listener = ls
        self._stop.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="tcpq-accept", daemon=True)
        self._accept_thread.start()
        log.info("tcp query server on :%d", self.port)

    def close_listener(self) -> None:
        """Rolling-restart drain: stop ACCEPTING (listener closed, accept
        thread joined) while existing connection readers keep serving —
        a drained server must never cut a final in-flight reply mid-send.
        ``start()`` re-binds the same port afterwards."""
        ls = self._listener
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)
            self._accept_thread = None
        self._listener = None
        log.info("tcp query server :%d stopped accepting (drained)",
                 self.port)

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2)
            self._accept_thread = None
        for t in self._conn_threads:
            t.join(timeout=2)
        self._conn_threads = []

    # -- internals ----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # short poll timeout: the reader thread must never block
            # unbounded (idle waits poll the stop flag; mid-message
            # stalls are bounded by _recv_msg_bounded)
            conn.settimeout(0.5)
            with self._conns_lock:
                self._conns.append(conn)
            # prune finished handler threads (connection churn must not
            # accumulate dead Thread objects for the server's lifetime)
            self._conn_threads = [t for t in self._conn_threads
                                  if t.is_alive()]
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="tcpq-conn", daemon=True)
            t.start()
            self._conn_threads.append(t)

    def _reply(self, conn: socket.socket, mtype: int, parts: List,
               version: int = V1) -> None:
        """Send one reply under the send timeout, then restore the short
        recv-poll timeout (settimeout governs BOTH directions)."""
        conn.settimeout(_SEND_TIMEOUT_S)
        try:
            _send_msg(conn, mtype, parts, version=version)
        finally:
            conn.settimeout(0.5)

    def _serve_conn(self, conn: socket.socket) -> None:
        # every connection starts in v1 framing; a 'V' message upgrades
        # it (and the frames inside replies) for the rest of its life
        conn_ver = V1
        try:
            while not self._stop.is_set():
                try:
                    mtype, body, deadline_s = _recv_msg_bounded(
                        conn, self._stop, version=conn_ver,
                        verify=self._verify)
                except WireCorruptionError as e:
                    # message-level corruption: the declared length was
                    # honored so WE survived, but a corrupted header may
                    # have desynced the stream — tell the peer ('C',
                    # resend-safe) and drop this connection only
                    self._note_corrupt(e)
                    try:
                        self._reply(conn, _T_CORRUPT, [str(e).encode()],
                                    conn_ver)
                    except OSError:
                        pass
                    return
                except WireError as e:
                    # unparseable/oversized header: tell the peer and drop
                    # the connection (framing is lost at this point)
                    try:
                        self._reply(conn, _T_ERROR, [str(e).encode()],
                                    conn_ver)
                    except OSError:
                        pass
                    return
                except (ConnectionError, OSError):
                    return
                try:
                    if mtype == _T_VERSION and self._wire_version >= V2:
                        # negotiate: answer in the CURRENT framing, then
                        # upgrade to min(peer max, our max) — a peer that
                        # advertises only v1 stays on v1 framing (a
                        # v1-pinned SERVER falls through to
                        # unknown-message-type below, exactly like a
                        # true legacy peer)
                        try:
                            peer = int(bytes(body) or b"1")
                        except ValueError:
                            peer = V1
                        agreed = V2 if peer >= V2 else V1
                        self._reply(
                            conn, _T_VERSION,
                            [str(agreed).encode()], conn_ver)
                        conn_ver = agreed
                    elif mtype == _T_HANDSHAKE:
                        try:
                            caps = self._core.check_caps(bytes(body).decode())
                            self._reply(conn, _T_HANDSHAKE, [caps.encode()],
                                        conn_ver)
                        except ValueError as e:
                            self._reply(conn, _T_ERROR, [str(e).encode()],
                                        conn_ver)
                    elif mtype == _T_QUERY:
                        batched = is_batch_payload(body)
                        try:
                            frames = (
                                decode_frames(body, verify=self._verify)
                                if batched
                                else [decode_frame(body, verify=self._verify)]
                            )
                        except WireError as e:
                            # frame-level corruption/truncation: the
                            # request never executed — answer 'C' and KEEP
                            # SERVING (framing is intact; hostile or
                            # corrupted payloads must not kill the reader)
                            self._note_corrupt(e)
                            self._reply(conn, _T_CORRUPT, [str(e).encode()],
                                        conn_ver)
                            continue
                        try:
                            answers = self._core.process(
                                frames,
                                deadline_s if deadline_s > 0 else 30.0)
                        except TimeoutError as e:
                            # caught HERE, not at the message boundary:
                            # socket.timeout from the reply sends below is
                            # the same class and must stay an OSError-path
                            # connection drop, not a 'T' reply
                            self._reply(conn, _T_TIMEOUT, [str(e).encode()],
                                        conn_ver)
                            continue
                        parts = (
                            encode_frames_parts(answers, version=conn_ver)
                            if batched
                            else encode_frame_parts(answers[0],
                                                    version=conn_ver)
                        )
                        self._reply(conn, _T_QUERY, parts, conn_ver)
                    elif mtype == _T_STREAM:
                        try:
                            frame = decode_frame(body, verify=self._verify)
                        except WireError as e:
                            self._note_corrupt(e)
                            self._reply(conn, _T_CORRUPT, [str(e).encode()],
                                        conn_ver)
                            continue
                        gen = self._core.process_stream(
                            frame, deadline_s if deadline_s > 0 else 30.0)
                        try:
                            while True:
                                try:
                                    ans = next(gen)
                                except StopIteration:
                                    break
                                except TimeoutError as e:
                                    # scoped to the GENERATOR only: a
                                    # socket.timeout from the chunk
                                    # sends below is a TimeoutError too
                                    # and must stay an OSError-path
                                    # connection drop, not a 'T' reply
                                    # on a wedged socket (same contract
                                    # as the unary handler)
                                    self._reply(conn, _T_TIMEOUT,
                                                [str(e).encode()],
                                                conn_ver)
                                    break
                                self._reply(
                                    conn, _T_STREAM,
                                    encode_frame_parts(ans,
                                                       version=conn_ver),
                                    conn_ver)
                        finally:
                            # a peer that died mid-stream breaks the
                            # reply send (OSError path below): closing
                            # the generator HERE frees the pending slot
                            # + admission deterministically, so the next
                            # chunk delivery sees client-gone and the
                            # generation stream is cancelled upstream
                            gen.close()
                    else:
                        self._reply(
                            conn, _T_ERROR,
                            [f"unknown message type {mtype}".encode()],
                            conn_ver)
                except ServerGoawayError as e:
                    # rolling restart: draining — refuse before ingest;
                    # the connection stays alive so in-flight replies on
                    # it still complete
                    try:
                        self._reply(conn, _T_GOAWAY, [str(e).encode()],
                                    conn_ver)
                    except OSError:
                        return
                except ServerBusyError as e:
                    # admission shed: the cheapest possible reply — the
                    # request never touched the pipeline
                    try:
                        self._reply(conn, _T_BUSY,
                                    [f"{e.retry_after:.6f}".encode()],
                                    conn_ver)
                    except OSError:
                        return
                except OSError:
                    return  # peer gone mid-reply
                except Exception as e:  # noqa: BLE001 — transport boundary:
                    # any pipeline-side failure (timeout, full ingress,
                    # malformed frame) becomes a protocol error reply; the
                    # connection and its socket survive
                    try:
                        self._reply(conn, _T_ERROR, [str(e).encode()],
                                    conn_ver)
                    except OSError:
                        return
        finally:
            with self._conns_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass
