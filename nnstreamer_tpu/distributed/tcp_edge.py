"""Raw-TCP edge transport: the dependency-free data channel.

Reference: nnstreamer-edge's plain-TCP connect type
(``gst/edge/edge_common.c:23-35`` lists TCP / HYBRID / MQTT / AITT; the
TCP transport itself lives in nnstreamer-edge's socket layer).  The gRPC
edge broker (``distributed/service.py``) is the feature-rich default;
this module is the minimal-footprint alternative for peers that speak
only sockets — embedded subscribers, containers without grpc.

Protocol (all little-endian, layered on the NNSQ wire framing):
  subscribe:  client -> server   u32 topic_len | topic utf8
  stream:     server -> client   per frame: u32 payload_len | payload
payload = ``distributed/wire.py`` NNSQ bytes (or any codec the caller
pairs); topic matching is exact (no wildcards — parity with edge topics,
which are opaque strings, not MQTT filters).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, Iterator, List, Optional

from ..core.log import get_logger
from ..core.resilience import FAULTS

log = get_logger("tcp_edge")

_LEN = struct.Struct("<I")
_MAX_FRAME = 1 << 30  # 1 GiB sanity bound on a length prefix


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("tcp edge peer closed")
        buf += chunk
    return buf


class TcpEdgeServer:
    """Publisher-side endpoint: subscribers dial in, name a topic, and
    receive every frame published to it until they hang up."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._lock = threading.Lock()
        # topic -> list of (sock, per-sock write lock)
        self._subs: Dict[str, List[tuple]] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="tcp-edge-server", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._register, args=(sock,), daemon=True
            ).start()

    def _register(self, sock: socket.socket) -> None:
        try:
            # bounded handshake: a peer that connects and never names a
            # topic must not wedge this thread until process exit
            sock.settimeout(10.0)
            (tlen,) = _LEN.unpack(_read_exact(sock, _LEN.size))
            if tlen > 4096:
                raise ConnectionError("absurd topic length")
            topic = _read_exact(sock, tlen).decode()
            sock.settimeout(None)  # allow-blocking: send path below is
            # bounded by SO_SNDTIMEO; this socket is only ever written to
            # bound sends so one wedged subscriber cannot stall publish
            # fan-out for the healthy ones (see MiniBroker._send)
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", 5, 0),
            )
        except (ConnectionError, OSError, UnicodeDecodeError) as e:
            log.warning("tcp edge: dropping bad subscriber: %s", e)
            try:
                sock.close()
            except OSError:
                pass
            return
        with self._lock:
            self._subs.setdefault(topic, []).append(
                (sock, threading.Lock())
            )
        log.info("tcp edge: subscriber attached to topic %r", topic)

    def publish(self, topic: str, payload: bytes) -> int:
        """Send to every live subscriber of `topic`; returns how many
        received it (dead/wedged ones are dropped on the way)."""
        if FAULTS.is_armed():
            # corrupt= faults mutate the encoded payload post-checksum
            # (the length prefix stays honest so framing survives; the
            # subscriber's verify-on-decode is what must catch it)
            payload = FAULTS.mangle("tcp_edge.publish", payload)
        header = _LEN.pack(len(payload))
        with self._lock:
            targets = list(self._subs.get(topic, ()))
        delivered, dead = 0, []
        for sock, wlock in targets:
            try:
                FAULTS.check("tcp_edge.publish")
                with wlock:
                    sock.sendall(header + payload)
                delivered += 1
            except (socket.timeout, OSError):
                # audit contract: a subscriber whose send failed is
                # evicted and closed below — never kept for the next
                # publish (a wedged peer would stall every fan-out)
                dead.append((sock, wlock))
        if dead:
            with self._lock:
                subs = self._subs.get(topic, [])
                for entry in dead:
                    if entry in subs:
                        subs.remove(entry)
                    try:
                        entry[0].close()
                    except OSError:
                        pass
        return delivered

    def subscriber_count(self, topic: str) -> int:
        with self._lock:
            return len(self._subs.get(topic, ()))

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.shutdown(socket.SHUT_RDWR)  # wake accept()
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            socks = [s for subs in self._subs.values() for s, _ in subs]
            self._subs.clear()
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class TcpEdgeSubscriber:
    """Subscriber-side endpoint: dial, name the topic, iterate payloads."""

    def __init__(self, host: str, port: int, topic: str,
                 timeout: float = 10.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        t = topic.encode()
        self._sock.sendall(_LEN.pack(len(t)) + t)
        # allow-blocking: a pub/sub stream legitimately idles for as long
        # as the publisher is quiet; close() shutdown()s the socket, so a
        # blocked recv always has a bounded escape hatch
        self._sock.settimeout(None)
        self._closed = False

    def payloads(self, idle_timeout: Optional[float] = None
                 ) -> Iterator[bytes]:
        """Yield raw frame payloads until the publisher hangs up (or
        `idle_timeout` seconds pass without one).  The socket is closed
        when the stream ends for any reason — a broken stream must not
        park a dead fd on the subscriber until GC."""
        # allow-blocking: idle_timeout=None = stream semantics (see
        # __init__) — interruptible via close()
        self._sock.settimeout(idle_timeout)
        try:
            while not self._closed:
                try:
                    (plen,) = _LEN.unpack(_read_exact(self._sock, _LEN.size))
                    if plen > _MAX_FRAME:
                        raise ConnectionError("absurd frame length")
                    yield _read_exact(self._sock, plen)
                except (ConnectionError, OSError):
                    return
        finally:
            self.close()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
