"""Protobuf wire codec for tensor frames.

The interop IDL: anything that can speak protobuf can exchange frames with
the framework without linking it — the role of the reference's
``nnstreamer.proto`` + ``nnstreamer_grpc_protobuf.cc``
(``ext/nnstreamer/extra/``).  Selected per element via ``idl=protobuf``
(grpc src/sink, mqtt elements); the default ``idl=flex`` NNSQ framing
(``distributed/wire.py``) stays the compact intra-framework format.

Schema: ``proto/nns_tensors.proto`` (checked-in protoc output
``nns_tensors_pb2.py``).
"""

from __future__ import annotations

import json
import math

import numpy as np

from ..core.types import RANK_LIMIT, TENSOR_COUNT_LIMIT
from ..core.buffer import TensorFrame
from .wire import WireCorruptionError, WireError, _clean_meta

_TO_PB = {
    "int32": 0, "uint32": 1, "int16": 2, "uint16": 3, "int8": 4,
    "uint8": 5, "float64": 6, "float32": 7, "int64": 8, "uint64": 9,
    "float16": 10, "bfloat16": 11,
}
_FROM_PB = {v: k for k, v in _TO_PB.items()}


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _dtype_name(dt) -> str:
    return str(np.dtype(dt))


def _pb2():
    from .proto import nns_tensors_pb2

    return nns_tensors_pb2


def encode_frame(frame: TensorFrame) -> bytes:
    pb = _pb2()
    msg = pb.TensorFrame(
        num_tensors=len(frame.tensors),
        pts=frame.pts if frame.pts is not None else math.nan,
        seq=frame.seq,
        meta_json=json.dumps(_clean_meta(frame.meta)),
    )
    for t in frame.tensors:
        arr = np.ascontiguousarray(np.asarray(t))
        name = _dtype_name(arr.dtype)
        if name not in _TO_PB:
            raise WireError(f"dtype {name} not representable in nns_tensors.proto")
        msg.tensor.append(
            pb.Tensor(
                type=_TO_PB[name],
                dimension=list(arr.shape),
                data=arr.tobytes(),
            )
        )
    return msg.SerializeToString()


def decode_frame(buf: bytes, verify: bool = True) -> TensorFrame:
    """``verify`` is accepted for codec-API parity (the flex codec checks
    its v2 CRC there); the protobuf schema carries no checksum field, so
    integrity here is structural validation only."""
    del verify
    pb = _pb2()
    msg = pb.TensorFrame()
    try:
        msg.ParseFromString(bytes(buf))
    except Exception as e:
        raise WireCorruptionError(f"malformed protobuf frame: {e}") from None
    if len(msg.tensor) > TENSOR_COUNT_LIMIT:
        raise WireCorruptionError(
            f"tensor count {len(msg.tensor)} exceeds limit {TENSOR_COUNT_LIMIT}"
        )
    tensors = []
    for t in msg.tensor:
        if t.type not in _FROM_PB:
            raise WireCorruptionError(f"unknown tensor type id {t.type}")
        dtype = _np_dtype(_FROM_PB[t.type])
        if len(t.dimension) > RANK_LIMIT:
            raise WireCorruptionError(
                f"rank {len(t.dimension)} exceeds limit {RANK_LIMIT}"
            )
        shape = tuple(int(d) for d in t.dimension)
        if any(d < 0 for d in shape):
            raise WireCorruptionError(f"negative dimension in {shape}")
        # math.prod: exact python ints — np.prod silently wraps at int64,
        # which would let a hostile shape alias a small payload
        expect = math.prod(shape) * dtype.itemsize if shape else dtype.itemsize
        if len(t.data) != expect:
            raise WireError(
                f"tensor payload {len(t.data)}B != shape {shape} x {dtype}"
            )
        tensors.append(np.frombuffer(t.data, dtype=dtype).reshape(shape))
    try:
        meta = json.loads(msg.meta_json) if msg.meta_json else {}
    except ValueError as e:
        raise WireCorruptionError(f"malformed frame meta: {e}") from None
    if not isinstance(meta, dict):
        raise WireCorruptionError("frame meta is not a JSON object")
    frame = TensorFrame(
        tensors, pts=None if math.isnan(msg.pts) else msg.pts, meta=meta
    )
    frame.seq = int(msg.seq)  # sender's seq, even 0 (proto3 default)
    return frame
