"""Hybrid control plane: MQTT-retained endpoint announce/discover.

One implementation of the reference's MQTT-hybrid split (control over
MQTT, data direct — nnstreamer-edge HYBRID connect type, ``CHANGES:8-13``)
shared by the edge elements (single retained announce per topic) and the
tensor_query elements (one retained announce per server instance under a
topic prefix, wildcard discovery for pod fan-out).

Contract: an announce is a RETAINED JSON object carrying at least
``{"host", "port"}``; deleting it is publishing an empty retained payload
on the same topic (MQTT 3.3.1.3 tombstone).  "Announced" implies
"discoverable": publishes are QoS-1 and drained before returning.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, Iterable, Optional, Tuple

from ..core.log import get_logger
from .mqtt import MqttClient

log = get_logger("hybrid")


class Announcement:
    """A live retained announce; ``clear()`` tombstones it."""

    def __init__(self, broker_host: str, broker_port: int, topic: str,
                 info: dict, logger=None,
                 brokers: Optional[Iterable[Tuple[str, int]]] = None):
        self.topic = topic
        self.log = logger or log
        self.info = dict(info)
        # guards info-merge + publish: update() runs on element threads
        # while _reannounce() runs on the MQTT reader thread
        self._lock = threading.Lock()
        # exact count of retained re-publishes forced by broker
        # reconnects (restart amnesia / failover to an empty standby)
        self.reannounces = 0
        self._client = MqttClient(broker_host, broker_port, brokers=brokers)
        # a restarted broker forgot every retained message; a failed-over
        # standby never had them.  Re-publishing the CURRENT announce on
        # every reconnect reconverges the discovery plane within one
        # digest interval — subscribers dedupe redeliveries by seq.
        self._client.on_connect(self._reannounce)
        self._client.publish(
            topic, json.dumps(self.info).encode(), retain=True, qos=1
        )
        # QoS-1 ack before the caller proceeds: "started" must imply
        # "discoverable", or a client racing the start misses the server
        if self._client.drain(5.0):
            self.log.warning(
                "endpoint announce on %s unacknowledged by the broker",
                topic,
            )

    @property
    def connected(self) -> bool:
        client = self._client
        return client is not None and client.connected.is_set()

    @property
    def reconnects(self) -> int:
        client = self._client
        return client.reconnects if client is not None else 0

    def _reannounce(self) -> None:
        client = self._client
        if client is None:
            return
        with self._lock:
            payload = json.dumps(self.info).encode()
            self.reannounces += 1
        try:
            client.publish(self.topic, payload, retain=True, qos=1)
        except OSError:
            pass  # connection flapped again; the next reconnect retries

    def update(self, patch: dict, wait_ack: bool = True,
               require_connected: bool = False) -> None:
        """Merge ``patch`` into the announce and re-publish it retained:
        the discovery plane carries live server STATE (draining flag,
        load summary), not just topology — late discoverers see the
        current state, subscribed discoverers see the change.

        ``wait_ack=False`` skips the QoS-1 ack wait: a state update
        published from a serving thread (the serversrc's drain entry)
        must not stall behind a slow broker — the publish is still
        QoS-1 on the socket, only the confirmation wait is elided.

        ``require_connected=True`` raises :class:`ConnectionError` when
        the broker is unreachable at publish time — the merge into
        ``self.info`` still happens (the reconnect re-announce carries
        it), but the caller gets an exact failure signal it can count
        instead of silently queueing into the reconnect backlog."""
        if self._client is None:
            return
        with self._lock:
            self.info.update(patch)
            payload = json.dumps(self.info).encode()
            if require_connected and not self._client.connected.is_set():
                raise ConnectionError(
                    f"announce broker unreachable; {self.topic} update "
                    "deferred to the reconnect re-announce")
            self._client.publish(self.topic, payload, retain=True, qos=1)
        if wait_ack and self._client.drain(5.0):
            self.log.warning(
                "endpoint announce update on %s unacknowledged by the "
                "broker", self.topic,
            )

    def clear(self) -> None:
        """Delete the retained announce (empty retained payload): late
        clients must not dial a released port."""
        if self._client is None:
            return
        try:
            self._client.publish(self.topic, b"", retain=True, qos=1)
            if self._client.drain(5.0):
                self.log.warning(
                    "retained-announce delete on %s unacknowledged; a "
                    "stale endpoint may remain on the broker", self.topic,
                )
        except OSError:
            pass
        self._client.close()
        self._client = None


def discover_endpoints(
    broker_host: str, broker_port: int, topic_filter: str,
    timeout_s: float, settle_s: float = 0.25,
    validate: Optional[Callable[[str, dict], bool]] = None,
    logger=None,
) -> Dict[str, Tuple[str, int]]:
    """Collect retained announces matching ``topic_filter`` (wildcards ok).

    Waits (bounded by ``timeout_s``) for the first announce, then a short
    settle window so a whole pod's retained set is gathered.  Tombstones
    received during the window REMOVE their entry — a server that shuts
    down mid-discovery must not be dialed.  ``validate(topic, info)``
    filters announces (e.g. transport match).  Returns {topic: (host,
    port)}; empty when nothing (valid) was announced.
    """
    lg = logger or log
    found: Dict[str, Tuple[str, int]] = {}
    lock = threading.Lock()

    def on_msg(topic: str, payload: bytes) -> None:
        if not payload:
            with lock:
                found.pop(topic, None)  # tombstone: server went away
            return
        try:
            info = json.loads(payload.decode())
            entry = (str(info["host"]), int(info["port"]))
        except (ValueError, KeyError, TypeError):
            lg.warning("undecodable announce on %s", topic)
            return
        if validate is not None and not validate(topic, info):
            return
        with lock:
            found[topic] = entry

    client = MqttClient(broker_host, broker_port)
    try:
        client.subscribe(topic_filter, on_msg, qos=0)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with lock:
                n = len(found)
            if n:
                time.sleep(settle_s)  # gather the rest of the pod
                break
            time.sleep(0.02)
    finally:
        client.close()
    with lock:
        return dict(found)


def probe_endpoint(host: str, port: int, timeout_s: float = 0.5) -> bool:
    """TCP connect probe: a crashed server never tombstones its retained
    announce, so discoverers drop endpoints that no longer accept."""
    import socket

    try:
        with socket.create_connection((host, port), timeout=timeout_s):
            return True
    except OSError:
        return False
