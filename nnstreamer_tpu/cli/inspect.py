"""nns-tpu-inspect: introspect elements and subplugins.

≙ ``gst-inspect-1.0`` — list every registered element, or print one
element's properties/pads (the reference CLI the launch/debug workflow
leans on; SURVEY §1 L6 tooling).

CLI:
  nns-tpu-inspect                 # list all elements (+ subplugin kinds)
  nns-tpu-inspect tensor_filter   # one element's properties and pads
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def _ensure_registered() -> None:
    """Import side-effect registration of every element/subplugin."""
    import nnstreamer_tpu.converters  # noqa: F401
    import nnstreamer_tpu.decoders  # noqa: F401
    import nnstreamer_tpu.elements  # noqa: F401


def _list_all(out) -> None:
    from ..core import registry
    from ..pipeline.element import ELEMENT_TYPES

    out.write(f"{len(ELEMENT_TYPES)} elements:\n")
    for name in sorted(ELEMENT_TYPES):
        cls = ELEMENT_TYPES[name]
        doc = (cls.__doc__ or "").strip().splitlines()
        out.write(f"  {name:<24} {doc[0] if doc else ''}\n")
    for kind in registry.KINDS:
        names = sorted(registry.get_all(kind))
        if names:
            out.write(f"{len(names)} {kind} subplugins: {', '.join(names)}\n")


def _inspect_one(name: str, out) -> int:
    from ..pipeline.element import ELEMENT_TYPES, SinkElement, SourceElement

    cls = ELEMENT_TYPES.get(name)
    if cls is None:
        close = [n for n in sorted(ELEMENT_TYPES) if name in n]
        out.write(f"no element {name!r}")
        out.write(f" (did you mean: {', '.join(close)})\n" if close else "\n")
        return 1
    out.write(f"Element: {name}\n")
    if cls.__doc__:
        for line in cls.__doc__.strip().splitlines():
            out.write(f"  {line.strip()}\n")
    kind = (
        "source" if issubclass(cls, SourceElement)
        else "sink" if issubclass(cls, SinkElement)
        else "transform/filter"
    )
    out.write(f"Kind: {kind}\n")

    def pads(n):  # None = request pads, created on link (≙ Sometimes/Request)
        return "dynamic (on request)" if n is None else str(n)

    out.write(
        f"Pads: sink={pads(cls.NUM_SINK_PADS)} "
        f"src={pads(cls.NUM_SRC_PADS)}\n"
    )
    props = getattr(cls, "PROPERTIES", {})
    out.write(f"Properties ({len(props)}):\n")
    for pname, p in props.items():
        out.write(
            f"  {pname:<24} {p.type.__name__:<7} "
            f"default={p.default!r:<12} {p.doc}\n"
        )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nns-tpu-inspect",
        description="list elements or show one element's properties",
    )
    ap.add_argument("element", nargs="?", help="element name (omit to list)")
    args = ap.parse_args(argv)
    _ensure_registered()
    if args.element:
        return _inspect_one(args.element, sys.stdout)
    _list_all(sys.stdout)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
