"""nns-tpu-launch: run a textual pipeline description to completion.

≙ ``gst-launch-1.0`` — the reference's de-facto CLI (SURVEY §1 L6).

CLI: ``python -m nnstreamer_tpu.cli.launch "<pipeline text>" [--timeout S]``
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nns-tpu-launch", description="run a pipeline description"
    )
    ap.add_argument("pipeline", nargs="+", help="pipeline text (joined by spaces)")
    ap.add_argument("--timeout", type=float, default=None, help="max seconds")
    ap.add_argument(
        "-q", "--quiet", action="store_true", help="suppress bus messages"
    )
    ap.add_argument(
        "--trace",
        action="store_true",
        help="print the per-element tracer table on exit "
        "(proctime/framerate/interlatency/queue/bitrate; ≙ GstShark)",
    )
    ap.add_argument(
        "--dot",
        metavar="FILE",
        default="",
        help="write the pipeline graph as Graphviz DOT after negotiation "
        "(≙ GST_DEBUG_DUMP_DOT_DIR)",
    )
    args = ap.parse_args(argv)

    from ..pipeline import parse_pipeline

    text = " ".join(args.pipeline)
    pipe = parse_pipeline(text)
    if not args.quiet:
        pipe.add_bus_watcher(lambda msg: print(f"[bus] {msg}", file=sys.stderr))
    tracer = pipe.enable_tracing() if args.trace else None
    t0 = time.monotonic()
    pipe.start()
    try:
        # inside the try: a bad --dot path must still stop the pipeline
        if args.dot:
            with open(args.dot, "w") as f:
                f.write(pipe.to_dot())
        pipe.wait(timeout=args.timeout)
    except KeyboardInterrupt:
        print("interrupted", file=sys.stderr)
    finally:
        pipe.stop()
        # a failing run is exactly when the timing table matters most
        if tracer is not None:
            print("\n".join(tracer.summary_lines()), file=sys.stderr)
    if not args.quiet:
        print(
            f"pipeline finished in {time.monotonic() - t0:.3f}s", file=sys.stderr
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
