"""nns-tpu-convert: ahead-of-time model conversion to the native format.

Imports a third-party model (.tflite / .onnx), lowers the whole graph to
JAX, and serializes it as a ``.jaxexport`` artifact — compile-ready
StableHLO with the weights baked in.  The converted file loads with zero
import cost and no importer in the serving path:

    nns-tpu-convert mobilenet_v2_quant.tflite model.jaxexport
    nns-tpu-launch "appsrc ! tensor_filter model=model.jaxexport ! ..."

Reference analog: vendor offline compilers around the subplugin zoo
(SNPE's snpe-onnx-to-dlc, edgetpu_compiler, trtexec --saveEngine …) —
here the "engine" is a portable StableHLO module and the compiler is XLA
at load time.

Options:
  --batch-polymorphic / --fixed   symbolic leading batch dim (default) or
                                  the file's declared shapes only
  --int8                          tflite quantized models: lower conv /
                                  depthwise / dense to true int8 MXU
                                  arithmetic before export
  --fake-quant=off                tflite: relax per-tensor requantization
                                  (range clamps kept)
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def convert(src: str, dst: str, batch_polymorphic: bool = True,
            int8: bool = False, fake_quant: bool = True) -> dict:
    """Returns a summary dict (inputs/outputs/ops) for reporting."""
    import numpy as np

    from ..backends.jax_xla import export_model

    ext = os.path.splitext(src)[1].lower()
    if ext == ".tflite":
        from ..importers.tflite_reader import read_tflite
        from ..importers.tflite_lower import _Lowering

        model = read_tflite(src)
        lowering = _Lowering(model, fake_quant=fake_quant,
                             int8_compute=int8)
        frame_specs = [
            (model.tensors[i].shape, model.tensors[i].dtype)
            for i in model.inputs
        ]
        histogram = model.op_histogram()
    elif ext == ".onnx":
        from ..importers.onnx_reader import read_onnx
        from ..importers.onnx_lower import _Lowering

        model = read_onnx(src)
        lowering = _Lowering(model)
        frame_specs = []
        for vi in model.inputs:
            if vi.shape is None or vi.dtype is None or any(
                    d is None or d < 0 for d in vi.shape):
                raise SystemExit(
                    f"{src}: input {vi.name!r} has dynamic dims; "
                    "conversion needs concrete shapes")
            frame_specs.append((vi.shape, vi.dtype))
        histogram = model.op_histogram()
    else:
        raise SystemExit(f"unsupported source format {ext!r} "
                         "(want .tflite or .onnx)")

    params = lowering.params()
    # same batch semantics as the serving path: the exporter's symbolic
    # leading dim vmaps over the graph (shape-sensitive ops like Conv
    # must never see the extra axis)
    from ..backends._importer_common import batching_model_fn

    fn = batching_model_fn(
        lowering.run, [len(s) for s, _ in frame_specs])
    export_model(fn, params, frame_specs, dst,
                 batch_polymorphic=batch_polymorphic)
    return {
        "source": src,
        "artifact": dst,
        "bytes": os.path.getsize(dst),
        "inputs": [tuple(s) for s, _ in frame_specs],
        "ops": histogram,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nns-tpu-convert", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("src", help="source model (.tflite or .onnx)")
    ap.add_argument("dst", nargs="?", default=None,
                    help="output artifact (default: <src>.jaxexport)")
    ap.add_argument("--fixed", action="store_true",
                    help="export the file's declared shapes only "
                         "(no symbolic batch dim)")
    ap.add_argument("--int8", action="store_true",
                    help="tflite: true int8 MXU arithmetic")
    ap.add_argument("--fake-quant", choices=("on", "off"), default="on")
    args = ap.parse_args(argv)

    dst = args.dst or os.path.splitext(args.src)[0] + ".jaxexport"
    summary = convert(
        args.src, dst,
        batch_polymorphic=not args.fixed,
        int8=args.int8,
        fake_quant=args.fake_quant == "on",
    )
    ops = ", ".join(f"{k}×{v}" for k, v in sorted(summary["ops"].items()))
    print(f"{summary['source']} -> {summary['artifact']} "
          f"({summary['bytes']} bytes)")
    print(f"  inputs: {summary['inputs']}")
    print(f"  ops: {ops}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
