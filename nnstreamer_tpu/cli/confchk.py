"""nns-tpu-check: dump installed elements, subplugins, and configuration.

≙ the reference's ``nnstreamer-check`` / confchk CLI
(``tools/development/confchk/confchk.c``): prints what is registered per
subplugin kind and where the active configuration came from.

CLI: ``python -m nnstreamer_tpu.cli.confchk``
"""

from __future__ import annotations

import sys
from typing import List, Optional

from ..core import config, registry


def report() -> str:
    # importing the element/subplugin packages triggers self-registration
    from .. import backends as _b  # noqa: F401
    from .. import converters as _c  # noqa: F401
    from .. import decoders as _d  # noqa: F401
    from .. import elements as _e  # noqa: F401
    from ..pipeline.element import ELEMENT_TYPES

    lines: List[str] = []
    lines.append("nnstreamer_tpu configuration check")
    lines.append("=" * 40)
    from ..core import hw

    # time-bounded probe: device enumeration through a wedged accelerator
    # tunnel must not hang a conf-check tool
    hw_info = hw.probe()
    dev_desc = hw_info["devices"] or [hw_info.get("error", "none found")]
    lines.append(f"jax backend devices : {dev_desc}")
    lines.append(f"config loaded from  : {config.loaded_from() or '(defaults)'}")
    lines.append("")
    factories = sorted(set(ELEMENT_TYPES))
    lines.append(f"pipeline elements ({len(factories)}):")
    for n in factories:
        cls = ELEMENT_TYPES[n]
        alias = "" if cls.FACTORY_NAME == n else f"  (alias of {cls.FACTORY_NAME})"
        lines.append(f"  {n}{alias}")
    for kind in registry.KINDS:
        names = sorted(registry.get_all(kind))
        lines.append("")
        lines.append(f"{kind} subplugins ({len(names)}):")
        if kind == registry.KIND_CUSTOM and not names:
            # the custom kind holds RUNTIME registrations (tensor_if
            # custom conditions via register_if_condition, ≙ the
            # reference's nnstreamer_if_custom_register) — empty at
            # import time by design, not a missing subplugin class
            lines.append(
                "  (runtime-registered tensor_if conditions; none "
                "registered in this process)"
            )
        for n in names:
            desc = registry.get_custom_property_desc(kind, n)
            if desc:  # Dict[str, str] -> readable "key: help" list
                desc_text = ", ".join(f"{k}: {v}" for k, v in desc.items())
                lines.append(f"  {n}  [{desc_text}]")
            else:
                lines.append(f"  {n}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    sys.stdout.write(report())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
