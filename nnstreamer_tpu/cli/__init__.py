"""L6 tools (SURVEY §2.8): launch (gst-launch analog), confchk
(nnstreamer-check analog), pbtxt converter (tools/development/parser
analog), custom-filter codegen (nnstreamerCodeGenCustomFilter.py analog)."""
