"""Custom-filter scaffolding generator.

≙ ``tools/development/nnstreamerCodeGenCustomFilter.py`` in the reference:
emits a ready-to-build skeleton for a user filter, in either dialect:

* ``--lang python`` — a :class:`FilterBackend` subclass plus registration
  (load with ``tensor_filter framework=python3 model=<file.py>`` or import
  it to self-register).
* ``--lang c`` — a native shared object implementing the
  ``nns_tpu_custom_filter.h`` C ABI plus a Makefile (run with
  ``tensor_filter framework=custom model=<path.so>``).

CLI: ``python -m nnstreamer_tpu.cli.codegen <name> [--lang python|c] [-o DIR]``
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

_PY_TEMPLATE = '''"""Custom filter `{name}` — generated scaffold.

Run in a pipeline:  tensor_filter framework=python3 model={name}.py
or register in-process by importing this module.
"""

import numpy as np


class {cls}:
    """User filter: implement getInputDim/getOutputDim (static schema) or
    setInputDim (shape-polymorphic), plus invoke."""

    def __init__(self, custom_props=""):
        self.custom_props = custom_props

    def getInputDim(self):
        # (dims, dtype) per input tensor; dims outermost-first
        return [((3, 224, 224), np.uint8)]

    def getOutputDim(self):
        return [((3, 224, 224), np.uint8)]

    def invoke(self, inputs):
        # inputs: list of np.ndarray; return list of np.ndarray
        return [inputs[0]]


filter = {cls}
'''

_C_TEMPLATE = """/* Custom filter `{name}` — generated scaffold.
 * Build: make      Run: tensor_filter framework=custom model=./{name}.so
 */

#include <stdlib.h>
#include <string.h>
#include "nns_tpu_custom_filter.h"

typedef struct {{
  int dummy;
}} {name}_ctx;

void *
nns_custom_open (const char *custom_props)
{{
  {name}_ctx *ctx = calloc (1, sizeof ({name}_ctx));
  (void) custom_props;
  return ctx;
}}

int
nns_custom_get_model_info (void *handle,
    nns_tensor_spec *in_specs, uint32_t *num_in,
    nns_tensor_spec *out_specs, uint32_t *num_out)
{{
  (void) handle;
  /* one uint8 tensor (3,224,224) in and out — edit to taste, or return
   * nonzero and implement nns_custom_set_input_info instead. */
  in_specs[0].dtype = NNS_UINT8;
  in_specs[0].rank = 3;
  in_specs[0].dims[0] = 3;
  in_specs[0].dims[1] = 224;
  in_specs[0].dims[2] = 224;
  *num_in = 1;
  out_specs[0] = in_specs[0];
  *num_out = 1;
  return 0;
}}

int
nns_custom_invoke (void *handle,
    const nns_tensor_mem *inputs, uint32_t num_in,
    nns_tensor_mem *outputs, uint32_t num_out)
{{
  (void) handle;
  (void) num_in;
  (void) num_out;
  /* passthrough — replace with real work */
  memcpy (outputs[0].data, inputs[0].data, inputs[0].nbytes);
  return 0;
}}

void
nns_custom_close (void *handle)
{{
  free (handle);
}}
"""

_MAKEFILE_TEMPLATE = """CXXFLAGS ?= -O2 -fPIC -Wall
INCLUDE := {include_dir}

{name}.so: {name}.c
\t$(CC) $(CXXFLAGS) -I$(INCLUDE) -shared -o $@ $<

clean:
\trm -f {name}.so
"""


def generate(name: str, lang: str, outdir: str) -> List[str]:
    os.makedirs(outdir, exist_ok=True)
    written: List[str] = []
    cls = "".join(w.capitalize() for w in name.replace("-", "_").split("_"))
    if lang == "python":
        path = os.path.join(outdir, f"{name}.py")
        with open(path, "w") as f:
            f.write(_PY_TEMPLATE.format(name=name, cls=cls))
        written.append(path)
    elif lang == "c":
        include_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "native",
            "include",
        )
        cpath = os.path.join(outdir, f"{name}.c")
        with open(cpath, "w") as f:
            f.write(_C_TEMPLATE.format(name=name))
        mpath = os.path.join(outdir, "Makefile")
        with open(mpath, "w") as f:
            f.write(_MAKEFILE_TEMPLATE.format(name=name, include_dir=include_dir))
        written.extend([cpath, mpath])
    else:
        raise ValueError(f"unknown lang {lang!r}")
    return written


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nns-tpu-codegen", description="generate custom-filter scaffolding"
    )
    ap.add_argument("name", help="filter name (file/symbol prefix)")
    ap.add_argument("--lang", choices=("python", "c"), default="python")
    ap.add_argument("-o", "--outdir", default=".")
    args = ap.parse_args(argv)
    for path in generate(args.name, args.lang, args.outdir):
        print(f"wrote {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
