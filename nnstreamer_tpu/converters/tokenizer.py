"""tokenizer converter: text bytes -> int32 token ids (net-new).

The reference frames text as fixed-size uint8 tensors
(``gsttensor_converter.c`` text chain) and stops there — it has no LLM
serving story.  This subplugin completes the textual pipeline for the
transformer family: byte-level tokenization (ids 0-255, the zoo
transformer's default vocab) so

    appsrc ! tensor_converter mode=custom:tokenizer
        ! tensor_filter custom=arch:transformer,generate:N
        ! tensor_decoder mode=detokenizer ! tensor_sink

round-trips prompt text to completion text (the tokenizer consumes raw
text bytes directly — no fixed-size text framing stage, whose NUL
padding would append id-0 tokens to every prompt).
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import ANY, StreamSpec


class TokenizerConverter:
    NAME = "tokenizer"

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        return ANY  # token count = byte count, known per frame

    def convert(self, frame: TensorFrame) -> TensorFrame:
        t = frame.tensors[0]
        raw = bytes(t) if isinstance(t, (bytes, bytearray, memoryview)) \
            else np.ascontiguousarray(np.asarray(t)).tobytes()
        toks = np.frombuffer(raw, np.uint8).astype(np.int32)
        out = frame.with_tensors([toks])
        out.meta.pop("media_type", None)
        return out
