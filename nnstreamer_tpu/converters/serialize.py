"""flexbuf / flatbuf / protobuf converters: serialized bytes -> tensors.

Reference: ``ext/nnstreamer/tensor_converter/tensor_converter_{flexbuf,
flatbuf,protobuf}.cc`` — parse a framework-neutral byte schema back into an
``other/tensors`` frame; the exact inverse of the same-named decoder
subplugins (decoders/serialize.py).  flexbuf speaks the canonical wire
format (``distributed/wire.py``); protobuf parses the PUBLIC
``nns_tensors.proto`` schema and flatbuf parses the reference's ACTUAL
``nnstreamer.fbs`` binary schema, so non-framework producers with only a
protobuf/flatbuffers runtime interop here.
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import ANY, StreamSpec
from ..distributed import wire


class _DeserializeBase:
    NAME = "deserialize"
    IDL = "flex"  # wire.get_codec name

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        return ANY  # per-payload shapes; known only after decode

    def convert(self, frame: TensorFrame) -> TensorFrame:
        _, decode = wire.get_codec(self.IDL)
        t = frame.tensors[0]
        payload = bytes(t) if isinstance(t, (bytes, bytearray, memoryview)) \
            else np.ascontiguousarray(np.asarray(t)).tobytes()
        decoded = decode(payload)
        out = frame.with_tensors(list(decoded.tensors))
        for k, v in decoded.meta.items():
            out.meta.setdefault(k, v)
        out.meta.pop("media_type", None)  # now a plain tensor stream again
        return out


class FlexbufConverter(_DeserializeBase):
    NAME = "flexbuf"


class FlatbufConverter(_DeserializeBase):
    NAME = "flatbuf"
    IDL = "flatbuf"


class ProtobufConverter(_DeserializeBase):
    NAME = "protobuf"
    IDL = "protobuf"
