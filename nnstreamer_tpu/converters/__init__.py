"""Converter subplugins (≙ ext/nnstreamer/tensor_converter/).

Importing registers every external converter in the subplugin registry
(kind "converter"); ``tensor_converter mode=custom:<name>`` selects one.
"""

from ..core import registry

registry.register_lazy(registry.KIND_CONVERTER, "flexbuf", "nnstreamer_tpu.converters.serialize:FlexbufConverter")
registry.register_lazy(registry.KIND_CONVERTER, "flatbuf", "nnstreamer_tpu.converters.serialize:FlatbufConverter")
registry.register_lazy(registry.KIND_CONVERTER, "protobuf", "nnstreamer_tpu.converters.serialize:ProtobufConverter")
registry.register_lazy(registry.KIND_CONVERTER, "python3", "nnstreamer_tpu.converters.python3:Python3Converter")
registry.register_lazy(registry.KIND_CONVERTER, "tokenizer", "nnstreamer_tpu.converters.tokenizer:TokenizerConverter")
