"""python3 converter: user-scripted media -> tensors conversion.

Reference: ``ext/nnstreamer/tensor_converter/tensor_converter_python3.cc``
(user script with a ``convert`` method).  Contract: the script (path given
via the element's ``script`` custom property or set_options) defines either
a class ``CustomConverter`` (method ``convert(self, payload, meta) ->
tensors``) or a module-level ``convert(payload)``.

Select with ``tensor_converter mode=custom-script:python3`` and configure
the script path with ``set_script`` before start, or register your own
converter class directly via the registry.
"""

from __future__ import annotations

import importlib.util
import os

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import ANY, StreamSpec

_SCRIPT_ENV = "NNS_TPU_CONVERTER_SCRIPT"


def _load_script(path: str):
    if not os.path.isfile(path):
        raise FileNotFoundError(f"python3 converter script not found: {path}")
    name = "nns_tpu_converter_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class Python3Converter:
    NAME = "python3"

    def __init__(self, script: str = ""):
        self._script = script
        self._impl = None
        self._fn = None

    def set_script(self, path: str) -> None:
        self._script = path

    def open(self) -> None:
        path = self._script or os.environ.get(_SCRIPT_ENV, "")
        if not path:
            raise ValueError(
                "python3 converter needs a script (set_script or "
                f"${_SCRIPT_ENV})")
        mod = _load_script(path)
        if hasattr(mod, "CustomConverter"):
            self._impl = mod.CustomConverter()
        elif hasattr(mod, "convert"):
            self._fn = mod.convert
        else:
            raise ValueError(
                f"{path}: defines neither CustomConverter nor convert()")

    def close(self) -> None:
        self._impl = self._fn = None

    def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec:
        if self._impl is not None and hasattr(self._impl, "get_out_spec"):
            return self._impl.get_out_spec(in_spec)
        return ANY

    def convert(self, frame: TensorFrame) -> TensorFrame:
        payload = frame.tensors[0]
        if self._impl is not None:
            res = self._impl.convert(payload, dict(frame.meta))
        else:
            res = self._fn(payload)
        if isinstance(res, TensorFrame):
            return res
        if not isinstance(res, (list, tuple)):
            res = [res]
        return frame.with_tensors([np.asarray(t) for t in res])
