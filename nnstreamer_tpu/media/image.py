"""Still-image codecs (PNG/JPEG/BMP/...) via Pillow.

The reference ingests images through stock GStreamer decoders
(``multifilesrc ! pngdec/jpegdec ! videoconvert`` in its example
pipelines and datarepo "image" samples); Pillow is this framework's
equivalent codec layer.  Import is gated so environments without it
still load everything except the image paths.
"""

from __future__ import annotations

import numpy as np


def _pil():
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover — PIL is in the base image
        raise RuntimeError(
            "image support requires Pillow (PIL) — not installed"
        ) from e
    return Image


def read_image(path: str, fmt: str = "RGB") -> np.ndarray:
    """Decode to uint8 (H, W, C): fmt RGB -> C=3, GRAY8 -> C=1."""
    img = _pil().open(path)
    if fmt == "RGB":
        arr = np.asarray(img.convert("RGB"), np.uint8)
    elif fmt == "GRAY8":
        arr = np.asarray(img.convert("L"), np.uint8)[..., None]
    else:
        raise ValueError(f"unsupported image format {fmt!r} (RGB|GRAY8)")
    return arr


def write_image(path: str, arr: np.ndarray) -> None:
    """Encode uint8 (H, W, C) or (H, W); container chosen by extension."""
    a = np.asarray(arr, np.uint8)
    if a.ndim == 3 and a.shape[-1] == 1:
        a = a[..., 0]
    _pil().fromarray(a).save(path)
