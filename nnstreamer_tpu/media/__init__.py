"""Media layer: raw media payloads (video/audio/text/octet) entering the
tensor world.

The reference sits on GStreamer, so media arrives as negotiated
``video/x-raw``/``audio/x-raw``/``text/x-raw`` GstBuffers and
``tensor_converter`` only has to strip strides and batch frames
(``gst/nnstreamer/elements/gsttensor_converter.c:750-1005``).  This
framework has no GStreamer underneath, so the media layer provides:

- :class:`MediaInfo` / :class:`MediaSpec` — the ``video/x-raw,...`` caps
  analog, carried through schema negotiation so ``tensor_converter`` can
  derive the exact tensor schema statically;
- container readers/writers (`y4m`, `wav`) used by the file sources —
  the minimal in-process stand-in for ``filesrc ! decodebin !
  videoconvert``.
"""

from .caps import MediaInfo, MediaSpec, parse_media_caps  # noqa: F401
