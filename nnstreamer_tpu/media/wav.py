"""WAV reader/writer (stdlib ``wave`` + numpy) for the audio file source.

≙ the ``filesrc ! wavparse`` front of reference audio example pipelines,
feeding ``audio/x-raw`` into tensor_converter
(``gsttensor_converter.c`` audio framing).
"""

from __future__ import annotations

import wave
from typing import Tuple

import numpy as np

_WIDTH_FMT = {1: "U8", 2: "S16LE", 4: "S32LE"}
_FMT_WIDTH = {v: k for k, v in _WIDTH_FMT.items()}


def read_wav(path: str) -> Tuple[int, int, str, np.ndarray]:
    """-> (rate, channels, format_name, samples (n, channels))."""
    with wave.open(path, "rb") as w:
        channels = w.getnchannels()
        rate = w.getframerate()
        width = w.getsampwidth()
        if width not in _WIDTH_FMT:
            raise ValueError(f"unsupported sample width {width}")
        raw = w.readframes(w.getnframes())
    fmt = _WIDTH_FMT[width]
    from .caps import AUDIO_FORMATS

    data = np.frombuffer(raw, AUDIO_FORMATS[fmt]).reshape(-1, channels)
    return rate, channels, fmt, data


def write_wav(path: str, samples: np.ndarray, rate: int) -> None:
    """samples (n,) or (n, channels) of u8/i16/i32."""
    arr = np.asarray(samples)
    if arr.ndim == 1:
        arr = arr[:, None]
    width = arr.dtype.itemsize
    if width not in _WIDTH_FMT:
        raise ValueError(f"unsupported dtype {arr.dtype}")
    with wave.open(path, "wb") as w:
        w.setnchannels(arr.shape[1])
        w.setsampwidth(width)
        w.setframerate(rate)
        w.writeframes(arr.tobytes())
