"""YUV4MPEG2 (.y4m) container reader/writer + I420<->RGB conversion.

The in-process stand-in for ``filesrc ! decodebin ! videoconvert`` in
reference example pipelines: reads uncompressed planar video so real file
-> converter -> filter pipelines run without GStreamer.  Colorimetry is
BT.601 limited range (the GStreamer default for SD raw video), vectorized
over whole planes.

Format: ASCII stream header ``YUV4MPEG2 W<w> H<h> F<n>:<d> ...`` then per
frame ``FRAME\\n`` + packed I420 planes (Y w*h, U and V w/2*h/2).
"""

from __future__ import annotations

from fractions import Fraction
from typing import BinaryIO, Iterator, Tuple

import numpy as np

# BT.601 limited-range YCbCr <-> full-range RGB
_KR, _KG, _KB = 0.299, 0.587, 0.114


def i420_to_rgb(y: np.ndarray, u: np.ndarray, v: np.ndarray) -> np.ndarray:
    """(h,w) luma + (h/2,w/2) chroma planes -> (h,w,3) uint8 RGB."""
    h, w = y.shape
    yf = y.astype(np.float32) - 16.0
    # nearest-neighbor chroma upsample to full resolution
    uf = np.repeat(np.repeat(u, 2, axis=0), 2, axis=1)[:h, :w].astype(np.float32) - 128.0
    vf = np.repeat(np.repeat(v, 2, axis=0), 2, axis=1)[:h, :w].astype(np.float32) - 128.0
    r = 1.164 * yf + 1.596 * vf
    g = 1.164 * yf - 0.392 * uf - 0.813 * vf
    b = 1.164 * yf + 2.017 * uf
    return np.clip(np.stack([r, g, b], axis=-1), 0, 255).astype(np.uint8)


def rgb_to_i420(rgb: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(h,w,3) uint8 RGB -> I420 planes (limited-range BT.601).

    h and w must be even (I420 2x2 chroma subsampling).
    """
    h, w, _ = rgb.shape
    if h % 2 or w % 2:
        raise ValueError("I420 needs even width/height")
    f = rgb.astype(np.float32)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    ey = _KR * r + _KG * g + _KB * b  # 0..255
    y = np.clip(16.0 + 219.0 * ey / 255.0, 16, 235).astype(np.uint8)
    cb = (b - ey) / (2.0 * (1.0 - _KB))  # -127.5..127.5
    cr = (r - ey) / (2.0 * (1.0 - _KR))
    # 2x2 box average then quantize
    cb = cb.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))
    cr = cr.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))
    u = np.clip(128.0 + 224.0 * cb / 255.0, 16, 240).astype(np.uint8)
    v = np.clip(128.0 + 224.0 * cr / 255.0, 16, 240).astype(np.uint8)
    return y, u, v


def write_y4m(path: str, frames_rgb, framerate: Fraction = Fraction(30, 1)) -> None:
    """Write RGB uint8 frames (N,(h,w,3)) as an I420 .y4m file."""
    frames = list(frames_rgb)
    if not frames:
        raise ValueError("no frames")
    h, w, _ = frames[0].shape
    fr = Fraction(framerate)
    with open(path, "wb") as f:
        f.write(
            f"YUV4MPEG2 W{w} H{h} F{fr.numerator}:{fr.denominator} "
            f"Ip A1:1 C420jpeg\n".encode()
        )
        for img in frames:
            y, u, v = rgb_to_i420(np.asarray(img, np.uint8))
            f.write(b"FRAME\n")
            f.write(y.tobytes())
            f.write(u.tobytes())
            f.write(v.tobytes())


class Y4MReader:
    """Streaming .y4m reader: header on open, frames via :meth:`frames`."""

    def __init__(self, path_or_file):
        if isinstance(path_or_file, (str, bytes)):
            self._f: BinaryIO = open(path_or_file, "rb")
            self._own = True
        else:
            self._f = path_or_file
            self._own = False
        header = self._f.readline().decode("ascii", "replace").strip()
        if not header.startswith("YUV4MPEG2"):
            raise ValueError("not a YUV4MPEG2 stream")
        self.width = self.height = 0
        self.framerate = Fraction(30, 1)
        self.colorspace = "420"
        for tok in header.split()[1:]:
            tag, val = tok[0], tok[1:]
            if tag == "W":
                self.width = int(val)
            elif tag == "H":
                self.height = int(val)
            elif tag == "F":
                n, _, d = val.partition(":")
                self.framerate = Fraction(int(n), int(d or "1"))
            elif tag == "C":
                self.colorspace = val
        if not self.colorspace.startswith("420"):
            raise ValueError(f"only I420 y4m supported, got C{self.colorspace}")
        if not (self.width and self.height):
            raise ValueError("y4m header missing W/H")

    def frames(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        w, h = self.width, self.height
        ysz, csz = w * h, (w // 2) * (h // 2)
        while True:
            marker = self._f.readline()
            if not marker:
                return
            if not marker.startswith(b"FRAME"):
                raise ValueError(f"bad frame marker {marker[:20]!r}")
            raw = self._f.read(ysz + 2 * csz)
            if len(raw) < ysz + 2 * csz:
                return  # truncated trailing frame
            y = np.frombuffer(raw, np.uint8, ysz).reshape(h, w)
            u = np.frombuffer(raw, np.uint8, csz, offset=ysz).reshape(h // 2, w // 2)
            v = np.frombuffer(raw, np.uint8, csz, offset=ysz + csz).reshape(h // 2, w // 2)
            yield y, u, v

    def frames_rgb(self) -> Iterator[np.ndarray]:
        for y, u, v in self.frames():
            yield i420_to_rgb(y, u, v)

    def close(self) -> None:
        if self._own:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
