"""Media capabilities: the ``video/x-raw,format=RGB,...`` caps analog.

Reference media caps accepted by tensor_converter
(``gsttensor_converter.c`` pad template + per-type framing :750-1005):

- ``video/x-raw`` formats RGB / BGRx / GRAY8, with rows padded to 4-byte
  boundaries (the converter strips the padding unless width is aligned);
- ``audio/x-raw`` formats S8/U8/S16/U16/S32/U32/F32/F64, interleaved
  channels, N samples per buffer;
- ``text/x-raw`` (utf8), fixed bytes-per-frame set by ``input-dim``;
- ``application/octet-stream``, reshaped per ``input-dim``/``input-type``.

A :class:`MediaSpec` is a wildcard tensor schema (it constrains nothing
tensor-wise) that carries a :class:`MediaInfo`; sources advertise it, the
schema-negotiation pass flows it through untouched, and
``tensor_converter.derive_spec`` turns it into the exact static tensor
schema — so pipelines negotiate media -> tensors up front exactly like the
reference's caps negotiation does.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Optional

import numpy as np

from ..core.types import FORMAT_FLEXIBLE, StreamSpec

# (numpy dtype, bytes/sample) per audio format name (reference: GstAudioFormat)
AUDIO_FORMATS = {
    "S8": np.dtype(np.int8),
    "U8": np.dtype(np.uint8),
    "S16LE": np.dtype("<i2"),
    "U16LE": np.dtype("<u2"),
    "S32LE": np.dtype("<i4"),
    "U32LE": np.dtype("<u4"),
    "F32LE": np.dtype("<f4"),
    "F64LE": np.dtype("<f8"),
}

# channels per pixel per video format (reference: converter caps RGB/BGRx/GRAY8)
VIDEO_CHANNELS = {"RGB": 3, "BGR": 3, "BGRx": 4, "RGBx": 4, "GRAY8": 1}


def round_up_4(n: int) -> int:
    """GStreamer video rows are padded to 4-byte boundaries."""
    return (n + 3) & ~3


@dataclass(frozen=True)
class MediaInfo:
    """What kind of raw media a payload is, and how it is laid out."""

    mtype: str  # "video" | "audio" | "text" | "octet"
    format: str = ""  # video: RGB|BGRx|GRAY8; audio: S16LE|F32LE|...
    width: int = 0
    height: int = 0
    stride: int = 0  # bytes per video row (0 = packed, no padding)
    framerate: Optional[Fraction] = None
    rate: int = 0  # audio sample rate, Hz
    channels: int = 0  # audio channels
    samples_per_buffer: int = 0  # audio frames per payload (0 = unknown)

    def __post_init__(self):
        if self.mtype == "video":
            if self.format not in VIDEO_CHANNELS:
                raise ValueError(f"unsupported video format {self.format!r}")
            if self.stride == 0:
                object.__setattr__(
                    self, "stride", round_up_4(self.width * self.pixel_channels)
                )
        elif self.mtype == "audio":
            if self.format not in AUDIO_FORMATS:
                raise ValueError(f"unsupported audio format {self.format!r}")
        elif self.mtype not in ("text", "octet"):
            raise ValueError(f"unknown media type {self.mtype!r}")
        if self.framerate is not None:
            object.__setattr__(self, "framerate", Fraction(self.framerate))

    # -- video --------------------------------------------------------------
    @property
    def pixel_channels(self) -> int:
        return VIDEO_CHANNELS[self.format]

    @property
    def row_bytes(self) -> int:
        """Meaningful pixel bytes per row (before stride padding)."""
        return self.width * self.pixel_channels

    # -- audio --------------------------------------------------------------
    @property
    def sample_dtype(self) -> np.dtype:
        return AUDIO_FORMATS[self.format]

    @property
    def bytes_per_frame(self) -> int:
        """One audio frame = one sample across all channels."""
        return self.sample_dtype.itemsize * max(self.channels, 1)

    # -- caps text ----------------------------------------------------------
    def caps_string(self) -> str:
        if self.mtype == "video":
            s = (
                f"video/x-raw,format={self.format},width={self.width},"
                f"height={self.height}"
            )
            if self.framerate is not None:
                s += (
                    f",framerate={self.framerate.numerator}/"
                    f"{self.framerate.denominator}"
                )
            return s
        if self.mtype == "audio":
            return (
                f"audio/x-raw,format={self.format},rate={self.rate},"
                f"channels={self.channels}"
            )
        if self.mtype == "text":
            return "text/x-raw,format=utf8"
        return "application/octet-stream"


def parse_media_caps(text: str) -> MediaInfo:
    """Parse a reference-dialect media caps string into MediaInfo."""
    head, *rest = [p.strip() for p in text.strip().split(",")]
    fields = {}
    for item in rest:
        k, _, v = item.partition("=")
        fields[k.strip()] = v.strip()
    fr = None
    if "framerate" in fields:
        n, _, d = fields["framerate"].partition("/")
        fr = Fraction(int(n), int(d or "1"))
    if head == "video/x-raw":
        return MediaInfo(
            "video",
            fields.get("format", "RGB"),
            width=int(fields.get("width", 0)),
            height=int(fields.get("height", 0)),
            framerate=fr,
        )
    if head == "audio/x-raw":
        return MediaInfo(
            "audio",
            fields.get("format", "S16LE"),
            rate=int(fields.get("rate", 0)),
            channels=int(fields.get("channels", 1)),
        )
    if head == "text/x-raw":
        return MediaInfo("text")
    if head == "application/octet-stream":
        return MediaInfo("octet")
    raise ValueError(f"unknown media caps {text!r}")


@dataclass(frozen=True)
class MediaSpec(StreamSpec):
    """A stream schema for raw media payloads.

    Tensor-wise it is the wildcard (zero tensors, flexible format), so it
    intersects with anything; the attached :class:`MediaInfo` tells
    ``tensor_converter`` how to frame the payload.
    """

    media: Optional[MediaInfo] = None

    def __post_init__(self):
        object.__setattr__(self, "tensors", ())
        object.__setattr__(self, "fmt", FORMAT_FLEXIBLE)
        super().__post_init__()

    def intersect(self, other: StreamSpec) -> Optional[StreamSpec]:
        # media survives intersection with wildcards (the base rule would
        # collapse self.is_any -> other, silently dropping the MediaInfo);
        # note a MediaSpec is itself is_any tensor-wise, so the MediaSpec
        # check must come first
        if isinstance(other, MediaSpec):
            return self if other.media == self.media else None
        if other.is_any:
            return self
        return super().intersect(other)

    def to_string(self) -> str:
        return self.media.caps_string() if self.media else super().to_string()
