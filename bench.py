#!/usr/bin/env python
"""Headline benchmark: MobileNet-v2 image-labeling pipeline, fps per chip.

Mirrors the reference's flagship configuration (BASELINE.md: MobileNet-v2
labeling via tensor_filter; target >= 1000 fps/chip on TPU v5e-1): a full
streaming pipeline — source -> tensor_filter(jax-xla, MobileNet-v2 bf16,
micro-batched) -> tensor_decoder(image_labeling) -> tensor_sink — measured
end-to-end, not a bare model loop.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} with
self-describing fields (model/batch/dtype/input/platform).  On backend
failure it still prints one JSON line with an "error" field (fail-soft) so
the driver artifact is diagnosable instead of a stack trace.

Env knobs:
  BENCH_MODEL     mobilenet|ssd|yolov5|posenet|vit|mnist_trainer|overhead|generate
                  (default mobilenet; overhead = CPU-safe 5-element
                  identity passthrough isolating scheduler cost)
  BENCH_FUSE      0|1 (default 1) streaming-thread fusion for every
                  pipeline the bench builds (the overhead row always
                  reports BOTH dataplanes: fused_fps/unfused_fps)
  BENCH_BATCH     micro-batch size (default 128)
  BENCH_FRAMES    measured frames (default 4096)
  BENCH_DTYPE     model dtype (default bfloat16)
  BENCH_HOST      1 = frames sourced from host memory (includes transfer)
  BENCH_HOST_CAP  per-row seconds cap for input=host rows (default 180);
                  an over-cap row is emitted labeled timed_out instead of
                  eating the whole bench budget (never banked)
  BENCH_INGEST_LANE  auto|on|off (default auto) — the filter's
                  double-buffered host->device staging lane; a signature
                  axis (pre-lane banked rows read as ingest_lane=off)
  BENCH_PROXY     1 (default) = on probe failure, attach labeled
                  proxy:true CPU micro-measures for the async-feed axes
                  (cpu_proxy field) alongside the banked/stale row
  BENCH_RAW       1 = also measure the bare jitted model at the same
                  batch (adds raw_fps / pipeline_vs_raw to the row — the
                  framework-overhead contract: pipeline >= 0.9x raw)
  BENCH_DEPTH     micro-batches kept in flight by the filter (default 4)
  BENCH_BATCH_TIMEOUT  ms a partial micro-batch waits for fill (default
                  20; latency-optimized rows use 2)
  BENCH_INGEST    block = frames enter pre-batched (one BatchFrame per
                  micro-batch, ≙ converter frames-per-tensor); default
                  per-frame pushes
  BENCH_SINK_SPLIT 0 = sink delivers whole blocks to callbacks (skips the
                  per-frame fan-out; counters use batch_size)
  BENCH_PLATFORM  cpu = force CPU (debug; numbers not comparable)
  BENCH_MESH      mesh spec for the filter ('tp:4' / 'dp:2,tp:2'; empty
                  = unsharded) — a signature axis (pre-mesh banked rows
                  read as mesh=0 and never stand in for sharded runs)
  BENCH_PROBE_TRIES / BENCH_PROBE_TIMEOUT  backend probe retry knobs
"""

import contextlib
import fcntl
import json
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

_T0 = time.time()  # child-process start; deadline windows anchor here
NORTH_STAR_FPS = 1000.0  # BASELINE.json north star, MobileNet headline row

_HERE = os.path.dirname(os.path.abspath(__file__))
EVIDENCE_PATH = os.path.join(_HERE, "BENCH_EVIDENCE.json")
ROWS_PATH = os.path.join(_HERE, "BENCH_ROWS.json")

# the config axes that make two rows comparable; a banked row may only
# stand in for a live one when every axis matches
_SIG_KEYS = (
    "metric", "model", "batch", "dtype", "quantize", "dispatch_depth",
    "ingest", "sink_split", "input", "platform", "batch_timeout_ms",
    "fuse", "ingest_lane", "slots", "mesh", "prefix_cache",
)
# rows captured before an axis existed carry its then-implicit value
# (fuse=0: pre-fusion rows measured the unfused seed dataplane, so they
# can never stand in for a fused run; ingest_lane=off: pre-lane rows
# measured serialized host->device staging; slots=0: pre-slot rows
# measured request-serial generation, never continuous batching; mesh=0:
# pre-mesh rows measured single-device serving, never a sharded hot path;
# prefix_cache=0: pre-prefix rows prefilled every prompt token from
# scratch — cold-cache evidence can never stand in for warm-prefix runs)
_SIG_DEFAULTS = {"ingest": "frame", "sink_split": True,
                 "batch_timeout_ms": 20, "fuse": 0, "ingest_lane": "off",
                 "slots": 0, "mesh": 0, "prefix_cache": 0}


def _sig(row: dict, exclude: tuple = ()) -> str:
    return "|".join(
        f"{k}={row.get(k, _SIG_DEFAULTS.get(k))}"
        for k in _SIG_KEYS if k not in exclude
    )


# the RUN default for bench (distinct from _SIG_DEFAULTS, which records
# the historical implicit value of already-banked rows and must stay 20)
BATCH_TIMEOUT_DEFAULT_MS = "20"


def _normalize_cache(cache: dict) -> dict:
    """Rekey every entry by its row's RECOMPUTED signature (the key may
    predate a signature-axis addition) and dedupe collisions keeping the
    newest ``captured_at`` — latest-evidence-wins must survive schema
    evolution, not just same-key overwrites."""
    out: dict = {}
    for ent in cache.values():
        if not isinstance(ent, dict) or not isinstance(ent.get("row"), dict):
            continue
        key = _sig(ent["row"])
        old = out.get(key)
        if old is None or str(ent.get("captured_at", "")) >= str(
            old.get("captured_at", "")
        ):
            out[key] = ent
    return out


def _bankable(row: dict) -> bool:
    """One predicate for both sides of the evidence cache: what bank_row
    stores is exactly what lookup_banked may return.  ``timed_out`` rows
    (host rows that hit their per-row cap) are partial evidence — emitted
    and labeled, but never banked as a stand-in for a completed run."""
    return (
        isinstance(row, dict) and row.get("value") is not None
        and not row.get("stale") and not row.get("timed_out")
        and row.get("platform") != "cpu"
    )


def _utc_iso(ts: float = None) -> str:
    return time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(time.time() if ts is None else ts)
    )


def age_days(captured_at: str, now: float = None) -> "float | None":
    """Days since an evidence row's ISO-8601 ``captured_at``/``stale_since``
    stamp (None when unparseable) — every stale row the bench serves
    carries this explicitly so the trend report (tools/perf_truth.py
    --report) and the driver artifact can label row age without
    re-deriving timestamp math."""
    import calendar

    try:
        # timegm, not mktime-minus-timezone: the stamp is UTC, and
        # mktime's DST guess for the stamp's date would skew the epoch
        # by up to an hour on DST-observing boxes
        then = calendar.timegm(time.strptime(
            str(captured_at), "%Y-%m-%dT%H:%M:%SZ"))
    except (ValueError, OverflowError):
        return None
    now = time.time() if now is None else now
    return round(max(0.0, (now - then) / 86400.0), 1)


def git_rev() -> "str | None":
    """Short git revision of the harness tree (None outside a checkout).
    Stamped onto cpu_proxy rows so proxy history aligns with commits in
    the trend report."""
    try:
        r = subprocess.run(
            ["git", "-C", _HERE, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    rev = r.stdout.strip()
    return rev if r.returncode == 0 and rev else None


@contextlib.contextmanager
def _cache_lock(path: str):
    """Serialize read-modify-replace on the evidence cache: overlapping
    bench processes (manual run during a sweep) must not erase each
    other's banked rows.  Best-effort — lock failure degrades to the
    unsynchronized behavior rather than blocking the bench."""
    lock_path = path + ".lock"
    f = None
    try:
        f = open(lock_path, "w")
        fcntl.flock(f, fcntl.LOCK_EX)
    except OSError:
        pass
    try:
        yield
    finally:
        if f is not None:
            try:
                fcntl.flock(f, fcntl.LOCK_UN)
            except OSError:
                pass
            f.close()


def bank_row(row: dict, path: str = None) -> None:
    """Persist a successful chip row into the evidence cache.

    The dev tunnel to the chip wedges for hours-to-days (round-2/round-4
    post-mortems): a probe window that happens to land during an outage
    must not erase evidence captured hours earlier in the same round
    (BENCH_r04.json was `value: null` while BENCH_ROWS.json held a 1.82x
    headline).  Every non-null, non-stale, non-CPU row is banked keyed by
    its config signature; `main` falls back to it when the live probe
    fails."""
    if not _bankable(row):
        return
    path = path or EVIDENCE_PATH
    with _cache_lock(path):
        try:
            with open(path) as f:
                cache = json.load(f)
        except (OSError, ValueError):
            cache = {}
        if not isinstance(cache, dict):
            cache = {}
        cache = _normalize_cache(cache)
        cache[_sig(row)] = {"captured_at": _utc_iso(), "row": row}
        _write_cache(cache, path)


def _write_cache(cache: dict, path: str) -> None:
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(cache, f, indent=1)
        os.replace(tmp, path)
    except OSError as e:
        # banking is best-effort: a full disk / read-only checkout must
        # not crash a run that just SUCCEEDED before its row is emitted
        sys.stderr.write(f"[bench] evidence bank failed: {e}\n")


def lookup_banked(meta: dict, metric: str, path: str = None,
                  rows_path: str = None) -> tuple:
    """(row, captured_at, source) for the banked evidence row matching this
    config, or (None, None, None).  Checks the evidence cache first, then
    seeds from the sweep artifact (rows banked before the cache existed,
    stamped with the file's mtime since they carry no timestamp).

    Platform matching is two-pass: exact first, then platform-wildcard
    over non-cpu rows — when the probe FAILS the caller only has the env
    label (``JAX_PLATFORMS`` may be unset or "axon,cpu" while rows were
    banked under the probed name "axon"), and a label mismatch must not
    erase real chip evidence.  The caller keeps the banked row's own
    platform field, so evidence is never relabeled across platforms."""
    want_meta = {**meta, "metric": metric}
    want = _sig(want_meta)
    want_wild = _sig(want_meta, exclude=("platform",))

    def _match(candidates):
        # candidates: iterable of (row, captured_at, source)
        for exact in (True, False):
            for row, since, source in candidates:
                if not _bankable(row):
                    continue
                if exact and _sig(row) == want:
                    return row, since, source
                if not exact and _sig(row, exclude=("platform",)) == want_wild:
                    return row, since, source
        return None, None, None

    cands = []
    try:
        with open(path or EVIDENCE_PATH) as f:
            cache = json.load(f)
        if isinstance(cache, dict):
            cands = [
                (ent.get("row", {}), ent.get("captured_at", "unknown"),
                 "BENCH_EVIDENCE.json")
                for ent in _normalize_cache(cache).values()
            ]
    except (OSError, ValueError):
        pass
    hit = _match(cands)
    if hit[0] is not None:
        return hit
    rows_path = rows_path or ROWS_PATH
    try:
        with open(rows_path) as f:
            rows = json.load(f)
        if isinstance(rows, list):
            mtime = _utc_iso(os.path.getmtime(rows_path))
            src = os.path.basename(rows_path)
            # promote EVERY bankable seed row into the cache now: sweep
            # re-runs overwrite the rows file (bench_all checkpoints from
            # row 1), so pre-cache evidence read once must survive in
            # BENCH_EVIDENCE.json for every config, not just this one
            promote = {
                _sig(row): {"captured_at": mtime, "row": row}
                for row in rows if _bankable(row)
            }
            if promote:
                ev_path = path or EVIDENCE_PATH
                with _cache_lock(ev_path):
                    try:
                        with open(ev_path) as f:
                            existing = json.load(f)
                    except (OSError, ValueError):
                        existing = {}
                    if not isinstance(existing, dict):
                        existing = {}
                    # existing (possibly newer) entries win over seeds
                    merged = {**promote, **existing}
                    if merged != existing:
                        _write_cache(merged, ev_path)
            return _match([(row, mtime, src) for row in rows])
    except (OSError, ValueError):
        pass
    return None, None, None


def emit(result: dict) -> None:
    print(json.dumps(result), flush=True)


def measure_ingest_overlap(nb: int = 14, h2d_s: float = 0.004,
                           comp_s: float = 0.004) -> "tuple[float, float]":
    """(t_serial, t_lane) for the host-ingest structure on equal sleep
    costs: serialized stack+transfer-then-compute vs the double-buffered
    staging lane (transfer overlaps the previous batch's compute).
    Shared by the cpu_proxy evidence and the `pytest -m perf` overlap
    floor, so the two published ratios measure the SAME harness."""
    import numpy as np

    from nnstreamer_tpu.core.feed import HostStagingLane

    frames = [[np.zeros((256,), np.float32)] for _ in range(8)]

    def to_dev(arrs):
        time.sleep(h2d_s)
        return [np.array(a) for a in arrs]

    t0 = time.perf_counter()
    for _ in range(nb):  # serialized: stack+transfer then compute
        to_dev([np.stack([f[0] for f in frames])])
        time.sleep(comp_s)
    t_serial = time.perf_counter() - t0

    lane = HostStagingLane(to_dev, name="overlap")
    try:
        t0 = time.perf_counter()
        prev = None
        for _ in range(nb):  # double-buffered: transfer overlaps compute
            job = lane.submit(frames)
            if prev is not None:
                prev.result()
                time.sleep(comp_s)
            prev = job
        prev.result()
        time.sleep(comp_s)
        t_lane = time.perf_counter() - t0
    finally:
        lane.close()
    return t_serial, t_lane


def measure_pipeline_vs_raw(nbatches: int = 24) -> "tuple[float, float]":
    """(raw_fps, pipeline_fps) for the SAME async-sim device costs — the
    CPU-proxy of the headline ``pipeline_vs_raw`` roofline ratio
    (ROADMAP item 1: the gap may only shrink).

    raw: the bare backend driven with the same depth-8 in-flight
    structure ``measure_raw_fps`` uses on a real chip (async dispatch,
    sync at window granularity).  pipeline: the full
    appsrc!tensor_filter!tensor_sink dataplane over the identical
    backend knobs.  Shared by the cpu_proxy evidence and the
    ``pytest -m perf`` floor, so the published ratio and the pinned
    gate measure the SAME harness."""
    import numpy as np

    from nnstreamer_tpu.backends.base import find_backend
    from nnstreamer_tpu.pipeline import parse_pipeline

    compute_ms, transfer_ms, dispatch_ms, mb = 4.0, 2.0, 0.5, 8
    custom = (
        f"compute_ms:{compute_ms},transfer_ms:{transfer_ms},"
        f"dispatch_ms:{dispatch_ms}"
    )
    # -- raw ceiling: bare invoke_batch, depth-8 window, periodic sync --
    be = find_backend("async-sim")()
    be.open(None, {"custom": custom})
    try:
        batch = np.zeros((mb, 64), np.float32)
        window = []
        done = 0
        t0 = time.perf_counter()
        for _ in range(nbatches):
            window.append(be.invoke_batch([batch]))
            if len(window) >= 8:
                for o in window.pop(0):
                    np.asarray(o)  # device_get at window granularity
            done += mb
        for out in window:
            for o in out:
                np.asarray(o)
        raw_fps = done / (time.perf_counter() - t0)
    finally:
        be.close()
    # -- pipeline: the full dataplane over identical device knobs -------
    pipe = parse_pipeline(
        "appsrc name=src max-buffers=512 ! tensor_filter name=f "
        f"framework=async-sim custom={custom} max-batch={mb} "
        "dispatch-depth=8 ingest-lane=off ! tensor_sink name=out "
        "max-stored=1",
        name="pvr",
    )
    pipe.start()
    try:
        done_d = {"n": 0}
        pipe["out"].connect_new_data(
            lambda f: done_d.__setitem__("n", done_d["n"] + 1))
        arr = np.zeros((64,), np.float32)
        n = mb * nbatches
        for _ in range(mb * 4):  # warmup: fill the window, settle batching
            pipe["src"].push(arr)
        t_w = time.time()
        while done_d["n"] < mb * 4 and time.time() - t_w < 20:
            time.sleep(0.002)
        if done_d["n"] < mb * 4:
            raise RuntimeError(
                f"pipeline_vs_raw warmup incomplete: {done_d['n']}/"
                f"{mb * 4} frames in 20s")
        # stability drain: a straggler warmup completion counted inside
        # the timed window would inflate pipeline_fps (always in the
        # passing direction)
        stable_since, last = time.time(), done_d["n"]
        while time.time() - stable_since < 0.3:
            time.sleep(0.02)
            if done_d["n"] != last:
                stable_since, last = time.time(), done_d["n"]
        done_d["n"] = 0
        t0 = time.perf_counter()
        for _ in range(n):
            pipe["src"].push(arr)
        while done_d["n"] < n and time.perf_counter() - t0 < 30:
            time.sleep(0.002)
        pipeline_fps = done_d["n"] / (time.perf_counter() - t0)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
    finally:
        pipe.stop()
    return raw_fps, pipeline_fps


GEN_PROPS = (
    "dtype:float32,vocab:61,d_model:32,heads:2,layers:2,d_ff:64,"
    "seq:128,seed:11"
)


def _drive_generate(custom: str, slot_width: int, prompts, max_new: int,
                    chunk: int, timeout_s: float) -> dict:
    """Drive one tensor_generator pipeline with ``prompts`` pushed
    concurrently; measure aggregate tokens/s + per-stream latency at
    the sink.  A warmup wave (first prompt alone) runs outside the
    timed window so compile/jit-bucket costs never land in it."""
    import numpy as np

    from nnstreamer_tpu.pipeline import parse_pipeline

    streams = len(prompts)
    pipe = parse_pipeline(
        f"appsrc name=src max-buffers=64 ! "
        f"tensor_generator name=gen slots={slot_width} "
        f"custom={custom} max-new={max_new} chunk={chunk} ! "
        "tensor_sink name=out",
        name=f"genbench{slot_width}",
    )
    pipe.start()
    try:
        arrivals = []  # (t, tokens_in_chunk, stream_seq, final)
        pipe["out"].connect_new_data(
            lambda f: arrivals.append((
                time.perf_counter(),
                int(np.asarray(f.tensors[0]).shape[1])
                if f.tensors else 0,
                f.meta.get("stream_seq"), bool(f.meta.get("final")),
            )))
        pipe["src"].push(prompts[0])
        t_w = time.perf_counter()
        while (not any(a[3] for a in arrivals)
               and time.perf_counter() - t_w < timeout_s):
            time.sleep(0.005)
        if not any(a[3] for a in arrivals):
            raise RuntimeError(
                f"generate warmup incomplete after {timeout_s}s")
        arrivals.clear()
        # fleet-rollup evidence (slotted runs): digest the pipeline at
        # the window edges through the SAME builder the serversrc
        # publishes with, so banked generation rows carry the capacity
        # view (tokens/s, occupancy, headroom) a fleet controller reads
        digest_pub = None
        if slot_width > 0:
            from nnstreamer_tpu.core.fleet import (
                DigestPublisher,
                pipeline_digest_stats,
            )

            digest_pub = DigestPublisher(
                lambda: pipeline_digest_stats(pipe), lambda d: None,
                interval_s=0.05, name="bench")
            digest_pub.poll(force=True)  # tokens baseline at the window
        t0 = time.perf_counter()
        for p in prompts:
            pipe["src"].push(p)
        finals = 0
        while finals < streams and time.perf_counter() - t0 < timeout_s:
            finals = sum(1 for a in arrivals if a[3])
            time.sleep(0.002)
        dt = time.perf_counter() - t0
        if finals < streams:
            raise RuntimeError(
                f"generate run incomplete: {finals}/{streams} "
                f"streams finished in {timeout_s}s")
        got = sum(a[1] for a in arrivals)
        # per-stream wall / tokens -> per-token latency, p50 across
        # streams (every stream's tokens arrived by its final chunk)
        per_stream_end: dict = {}
        for t, _ntok, seq, _fin in arrivals:
            per_stream_end[seq] = max(t, per_stream_end.get(seq, t))
        per_token_ms = sorted(
            (end - t0) * 1e3 / max_new for end in per_stream_end.values()
        )
        gen_health = pipe.health()["gen"]
        out = {
            "tokens": got,
            "tokens_per_s": got / dt,
            "p50_ms_per_token": per_token_ms[len(per_token_ms) // 2],
            # EWMA of ACTIVE SLOTS per decode scan (scan length varies,
            # so tokens/steps would conflate the two)
            "tokens_per_step": (
                gen_health.get("gen_tokens_per_step", 0.0)
                if slot_width > 0 else 1.0
            ),
        }
        if digest_pub is not None:
            from nnstreamer_tpu.core.fleet import FleetObservatory

            d = digest_pub.poll(force=True)  # window-end digest
            obs = FleetObservatory(topic="bench")
            obs.ingest("bench", {"host": "local", "port": 0, "digest": d})
            roll = obs.rollup()
            out["fleet"] = {
                k: roll[k] for k in (
                    "tokens", "tokens_per_s", "occupancy",
                    "slot_headroom", "mem_headroom_bytes", "slots")
            }
        return out
    finally:
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        pipe.stop()


def measure_generate_throughput(slots: int = 4, streams: int = 4,
                                max_new: int = 48, chunk: int = 8,
                                prompt_len: int = 8,
                                timeout_s: float = 120.0) -> dict:
    """Continuous batching vs request-serial generation on the CPU-safe
    zoo transformer (REAL tokens — functional truth for the bench row):
    ``streams`` concurrent prompts through a slotted ``tensor_generator``
    vs the SAME prompts through the pre-slot per-request path.

    NOTE on the speedup field: XLA-CPU batch economics at zoo-model
    sizes do not match an accelerator's (decode there is weight-
    streaming-bound, i.e. step cost is nearly batch-independent), so
    the SCHEDULER's multiplexing win is pinned by
    :func:`measure_slot_multiplex_speedup` (async-sim proxy) — this
    function reports what the real model measures on this host."""
    import numpy as np

    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(0, 61, (1, prompt_len)).astype(np.int32)
        for _ in range(streams)
    ]
    total = streams * max_new
    slotted = _drive_generate(GEN_PROPS, slots, prompts, max_new, chunk,
                              timeout_s)
    serial = _drive_generate(GEN_PROPS, 0, prompts, max_new, chunk,
                             timeout_s)
    for tag, r in (("slotted", slotted), ("serial", serial)):
        if r["tokens"] != total:
            raise RuntimeError(
                f"generate {tag} run lost tokens: {r['tokens']} != {total}")
    return {
        "tokens_per_s": round(slotted["tokens_per_s"], 1),
        "serialized_tokens_per_s": round(serial["tokens_per_s"], 1),
        "speedup": round(
            slotted["tokens_per_s"] / serial["tokens_per_s"], 2)
        if serial["tokens_per_s"] else None,
        "concurrent_streams": streams,
        "p50_ms_per_token": round(slotted["p50_ms_per_token"], 3),
        "serialized_p50_ms_per_token": round(
            serial["p50_ms_per_token"], 3),
        "slot_occupancy": round(
            slotted["tokens_per_step"] / max(1, slots), 3),
        # fleet-rollup capacity view of the slotted run (observatory
        # machinery — tokens/s, occupancy, admittable headroom) rides
        # the banked row next to the telemetry dump
        "fleet": slotted.get("fleet"),
    }


def measure_prefix_ttft(prefix_tokens: int = 256, suffix_tokens: int = 16,
                        trials: int = 3, grain: int = 64,
                        max_new: int = 2,
                        timeout_s: float = 180.0) -> dict:
    """Cold vs warm time-to-first-token with the shared-prefix KV cache
    (CPU-safe zoo transformer, REAL tokens): every prompt carries a
    ``prefix_tokens`` prefix + a fresh suffix; cold trials use a fresh
    random prefix (cache miss — full chunked prefill), warm trials reuse
    ONE shared prefix whose pages are already published (attach skips
    the covered tokens).  Trials interleave cold/warm so ambient load
    drift cancels; a separate warmup stream pays every compile bucket
    outside the timed windows.

    Shared by the BENCH_PREFIX_CACHE=1 generate row, the perf-truth
    ``prefix_ttft_speedup`` axis, and the ``pytest -m perf`` >=2x floor
    (warm TTFT <= 0.5x cold at 256 shared tokens), so the published
    ratio and the pinned gate measure the same harness.  The hit/miss
    ledger is asserted exactly — a silently-cold cache would otherwise
    publish a plausible-looking 1.0x ratio."""
    import numpy as np

    from nnstreamer_tpu.pipeline import parse_pipeline

    seq = prefix_tokens + suffix_tokens + max_new + 32
    # d_model 128 (not the 32-wide zoo default): prefill must COST
    # something on CPU or TTFT is pure pipeline overhead and the ratio
    # measures nothing (at d_model 32 cold ~= warm ~= 22ms fixed cost)
    props = (
        "dtype:float32,vocab:61,d_model:128,heads:4,layers:4,d_ff:512,"
        f"seq:{seq},seed:11"
    )
    pipe = parse_pipeline(
        f"appsrc name=src max-buffers=64 ! "
        f"tensor_generator name=gen slots=1 custom={props} "
        f"max-new={max_new} chunk=1 prefix-cache=on prefix-grain={grain} "
        "! tensor_sink name=out",
        name="prefixbench",
    )
    pipe.start()
    try:
        arrivals = []  # (t, final)
        pipe["out"].connect_new_data(
            lambda f: arrivals.append(
                (time.perf_counter(), bool(f.meta.get("final")))))
        rng = np.random.default_rng(7)

        def rand(n):
            return rng.integers(0, 61, (1, n)).astype(np.int32)

        def run_one(prefix):
            prompt = np.concatenate(
                [prefix, rand(suffix_tokens)], axis=1)
            finals = sum(1 for a in arrivals if a[1])
            mark = len(arrivals)
            t0 = time.perf_counter()
            pipe["src"].push(prompt)
            while time.perf_counter() - t0 < timeout_s:
                if sum(1 for a in arrivals if a[1]) > finals:
                    return (arrivals[mark][0] - t0) * 1e3
                time.sleep(0.0005)
            raise RuntimeError(
                f"prefix-ttft stream incomplete after {timeout_s}s")

        run_one(rand(prefix_tokens))  # warmup: compile buckets, untimed
        shared = rand(prefix_tokens)
        run_one(shared)               # prime: publish the shared prefix
        run_one(shared)               # attach warmup: compile the
        warmup_hits = 1               # export/concat/update ops, untimed
        cold, warm = [], []
        for _ in range(trials):
            cold.append(run_one(rand(prefix_tokens)))
            warm.append(run_one(shared))
        health = pipe.health()["gen"]
        # functional truth: exactly one hit per warm trial, one miss per
        # cold trial + warmup + prime, and every warm hit covered the
        # full shared-prefix grain span
        grain_eff = pipe["gen"]._prefix_pool.grain
        covered = (prefix_tokens // grain_eff) * grain_eff
        want_hits = trials + warmup_hits
        if health["prefix_hits"] != want_hits:
            raise RuntimeError(
                f"prefix-ttft cache never warmed: "
                f"{health['prefix_hits']} hits != {want_hits}")
        if health["prefix_misses"] != trials + 2:
            raise RuntimeError(
                f"prefix-ttft miss ledger off: {health['prefix_misses']} "
                f"!= {trials + 2}")
        if health["prefix_hit_tokens"] != want_hits * covered:
            raise RuntimeError(
                f"prefix-ttft short attach: {health['prefix_hit_tokens']} "
                f"hit tokens != {want_hits} * {covered}")
        c_med = sorted(cold)[len(cold) // 2]
        w_med = sorted(warm)[len(warm) // 2]
        return {
            "cold_ttft_ms": round(c_med, 3),
            "warm_ttft_ms": round(w_med, 3),
            "prefix_ttft_speedup": round(c_med / w_med, 2),
            "prefix_tokens": prefix_tokens,
            "prefix_hit_tokens": int(health["prefix_hit_tokens"]),
        }
    finally:
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        pipe.stop()


def measure_slot_multiplex_speedup(slots: int = 4, streams: int = 4,
                                   max_new: int = 64, chunk: int = 8,
                                   step_base_ms: float = 1.0,
                                   per_slot_ms: float = 0.05,
                                   timeout_s: float = 60.0) -> dict:
    """The continuous-batching SCHEDULER win on the async-sim proxy
    (PR-6 discipline): simulated device steps pay a batch-independent
    base cost (the weight-streaming/dispatch regime of real LLM decode)
    plus a small per-active-slot increment, so the measured ratio
    isolates what this PR builds — slot multiplexing through the full
    pipeline — from host GEMM quirks.  slots=1 is the request-serial
    baseline: SAME engine, same emission path, one request at a time.

    Shared by the BENCH_MODEL=generate row (``sim_speedup``) and the
    ``pytest -m perf`` >=2x floor, so the published ratio and the
    pinned gate measure the same harness."""
    import numpy as np

    custom = (
        f"sim:1,sim_step_ms:{step_base_ms},sim_per_slot_ms:{per_slot_ms},"
        "sim_prefill_ms:0.02,vocab:997"
    )
    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(0, 997, (1, 8)).astype(np.int32) for _ in range(streams)
    ]
    total = streams * max_new
    slotted = _drive_generate(custom, slots, prompts, max_new, chunk,
                              timeout_s)
    serial = _drive_generate(custom, 1, prompts, max_new, chunk, timeout_s)
    for tag, r in (("slotted", slotted), ("serial", serial)):
        if r["tokens"] != total:
            raise RuntimeError(
                f"sim {tag} run lost tokens: {r['tokens']} != {total}")
    return {
        "sim_speedup": round(
            slotted["tokens_per_s"] / serial["tokens_per_s"], 2),
        "sim_tokens_per_s": round(slotted["tokens_per_s"], 1),
        "sim_serialized_tokens_per_s": round(serial["tokens_per_s"], 1),
        "sim_p50_ms_per_token": round(slotted["p50_ms_per_token"], 3),
        "sim_slot_occupancy": round(
            slotted["tokens_per_step"] / max(1, slots), 3),
    }


def measure_dispatch_overlap(nbatches: int = 24,
                             budget_s: float = 8.0) -> dict:
    """``{"dispatch_overlap", "dispatch_thread_blocking_syncs"}`` for the
    async dispatch window on the async-sim fake device (compute 4ms
    single-server, transfer 3ms on the syncing thread, dispatch 1ms):
    pipeline throughput over the device's own serial service rate (1.0 =
    the window hides all framework cost), plus the structural count of
    dispatch-thread blocking syncs (must be 0 — the reaper owns those
    waits).  Shared by the cpu_proxy evidence and the perf-truth
    baseline, so the published ratio and the gated one measure the SAME
    harness."""
    import numpy as np

    from nnstreamer_tpu.pipeline import parse_pipeline

    compute_ms, transfer_ms, dispatch_ms, mb = 4.0, 3.0, 1.0, 8
    pipe = parse_pipeline(
        "appsrc name=src max-buffers=512 ! tensor_filter name=f "
        "framework=async-sim "
        f"custom=compute_ms:{compute_ms},transfer_ms:{transfer_ms},"
        f"dispatch_ms:{dispatch_ms} "
        f"max-batch={mb} dispatch-depth=8 ! tensor_sink name=out "
        "max-stored=1",
        name="proxy",
    )
    pipe.start()
    done = {"n": 0}
    pipe["out"].connect_new_data(
        lambda f: done.__setitem__("n", done["n"] + 1))
    n = mb * nbatches
    arr = np.zeros((64,), np.float32)
    t0 = time.perf_counter()
    for _ in range(n):
        pipe["src"].push(arr)
    cap = max(5.0, budget_s)
    while done["n"] < n and time.perf_counter() - t0 < cap:
        time.sleep(0.002)
    elapsed = time.perf_counter() - t0
    be = pipe["f"].backend
    blocked = [
        t for t in be.blocking_syncs if not t.endswith("-reaper")
    ]
    pipe["src"].end_of_stream()
    pipe.wait(timeout=15)
    pipe.stop()
    # device service rate = 1000/compute_ms batches/s (single server);
    # 1.0 means the window hid every framework cost behind compute
    pipeline_rate = (done["n"] / mb) / elapsed if elapsed else 0.0
    return {
        "dispatch_overlap": round(pipeline_rate / (1000.0 / compute_ms), 3),
        "dispatch_thread_blocking_syncs": len(blocked),
    }


def _simmesh_pipeline_fps(mesh_dp: int, nbatches: int = 30,
                          compute_ms: float = 6.0,
                          budget_s: float = 10.0) -> float:
    """Full-dataplane fps over the async-sim MESH twin: ``mesh_dp``
    independent sleeping shard servers, each serving its 1/N batch shard
    concurrently, outputs ready only when every shard is.  What the dp
    aggregate-throughput floor actually measures is the sharded FEED
    STRUCTURE (scatter, window readiness over all shards, no per-shard
    serialization) — deliberately NOT XLA-CPU dp scaling, which a
    single-core box cannot exhibit (both virtual devices share the one
    core; the PR-9 SimSlotModel discipline)."""
    import numpy as np

    from nnstreamer_tpu.pipeline import parse_pipeline

    mb = 8
    pipe = parse_pipeline(
        "appsrc name=src max-buffers=512 ! tensor_filter name=f "
        "framework=async-sim "
        f"custom=compute_ms:{compute_ms},transfer_ms:0.5,dispatch_ms:0.2,"
        f"mesh_dp:{mesh_dp} "
        f"max-batch={mb} dispatch-depth=8 ! tensor_sink name=out "
        "max-stored=1",
        name=f"simmesh{mesh_dp}",
    )
    pipe.start()
    try:
        done = {"n": 0}
        pipe["out"].connect_new_data(
            lambda f: done.__setitem__("n", done["n"] + 1))
        n = mb * nbatches
        arr = np.zeros((64,), np.float32)
        t0 = time.perf_counter()
        for _ in range(n):
            pipe["src"].push(arr)
        while done["n"] < n and time.perf_counter() - t0 < budget_s:
            time.sleep(0.002)
        elapsed = time.perf_counter() - t0
        if done["n"] < n:
            raise RuntimeError(
                f"simmesh dp:{mesh_dp} run incomplete: {done['n']}/{n} "
                f"in {budget_s:.0f}s")
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
    finally:
        pipe.stop()
    return done["n"] / elapsed


SHARDED_PROPS = (
    "arch:transformer,dtype:float32,vocab:64,d_model:64,heads:4,"
    "layers:3,d_ff:256,seq:32,seed:5"
)


def measure_sharded_overhead(batch: int = 16, rounds: int = 6,
                             iters: int = 4) -> dict:
    """The two sharded-dataplane truths, chip-free:

    * ``sharded_ratio`` — jax-xla ``invoke_batch`` fps on a
      SINGLE-DEVICE-EQUIVALENT mesh (``mesh=dp:1``: the full sharded
      machinery — NamedSharding in/out specs, scatter path, mesh-keyed
      pooling — with zero parallelism to hide it) over the unsharded
      backend on the same zoo transformer.  1.0 = the mesh plumbing is
      free; the perf gate floors it at 0.85 (<= 15% dispatch overhead).
      Rounds INTERLEAVE the two configs and the ratio takes best-of-
      round, so ambient box load cancels instead of biasing one side.
    * ``dp2_speedup`` — aggregate full-pipeline fps of the sharded
      dataplane over the async-sim mesh twin, ``mesh_dp:2`` vs
      ``mesh_dp:1`` on identical compute-bound knobs (see
      :func:`_simmesh_pipeline_fps` for why the device layer is
      simulated).  Floor >= 1.5x.

    Shared by the bench cpu_proxy evidence, the ``pytest -m perf``
    floors, and the perf-truth ``sharded_overhead`` axis — the
    published numbers and the gated ones measure the SAME harness."""
    import numpy as np

    from nnstreamer_tpu.elements.filter import SingleShot

    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, (batch, 32)).astype(np.int32)

    def fps_of(shot) -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            out = shot.invoke_batch([toks])
        np.asarray(out[0])
        return iters * batch / (time.perf_counter() - t0)

    with SingleShot(framework="jax-xla", model="zoo",
                    custom=SHARDED_PROPS) as plain, \
            SingleShot(framework="jax-xla", model="zoo",
                       custom=SHARDED_PROPS, mesh="dp:1") as sharded:
        # warmup: compile both buckets outside the timed rounds
        np.asarray(plain.invoke_batch([toks])[0])
        np.asarray(sharded.invoke_batch([toks])[0])
        best = 0.0
        for _ in range(rounds):
            # interleaved A/B: ambient load hits both sides of a round
            f_plain = fps_of(plain)
            f_shard = fps_of(sharded)
            best = max(best, f_shard / f_plain)
    dp1 = _simmesh_pipeline_fps(1)
    dp2 = _simmesh_pipeline_fps(2)
    return {
        "sharded_ratio": round(best, 3),
        "dp2_speedup": round(dp2 / dp1, 2),
        "simmesh_dp1_fps": round(dp1, 1),
        "simmesh_dp2_fps": round(dp2, 1),
    }


def cpu_proxy_measures(budget_s: float = 8.0) -> dict:
    """Fresh, explicitly-labeled CPU-proxy evidence for the async-feed
    axes, measured in-process in a few seconds (no accelerator, no jit):
    used when the chip probe fails so a perf PR still lands with live
    numbers for THIS code instead of only banked chip rows.

    * ``dispatch_overlap`` — async-window pipeline throughput over the
      fake device's own serial service rate (1.0 = the dispatch window
      hides all framework cost; the pre-async design was bounded by
      serial block-on-oldest, i.e. service + transfer + dispatch).
    * ``dispatch_thread_blocking_syncs`` — times the dispatch thread
      blocked inside a device_get-style sync (must be 0: the reaper
      thread owns those waits).
    * ``pipeline_vs_raw`` — full dataplane throughput over the bare
      backend driven with the same window structure (the roofline
      distance proxy; ``measure_pipeline_vs_raw`` is shared with the
      `pytest -m perf` floor).
    * ``ingest_overlap_speedup`` — double-buffered staging lane vs
      serialized stack+transfer+compute on the same costs.
    * ``device_pool_reuse_rate`` — staging-buffer reuse across the run.
    """
    from nnstreamer_tpu.core.buffer import DEVICE_POOL

    proxy: dict = {"proxy": True, "platform": "cpu",
                   "captured_at": _utc_iso(), "git_rev": git_rev()}
    t_start = time.time()
    # pool counters are process-global: snapshot so the reported reuse
    # rate is THIS measurement's, not the process's lifetime history
    pool_reused0, pool_alloc0 = DEVICE_POOL.reused, DEVICE_POOL.allocated

    # -- dispatch window overlap (shared perf-truth harness) -------------
    proxy.update(measure_dispatch_overlap(
        nbatches=24, budget_s=max(5.0, budget_s - (time.time() - t_start))))

    # -- pipeline-vs-raw roofline distance (shared perf-gate harness) ----
    raw_fps, pipe_fps = measure_pipeline_vs_raw()
    proxy["pipeline_vs_raw"] = round(pipe_fps / raw_fps, 3) if raw_fps else None

    # -- host-ingest overlap: staged lane vs serialized ------------------
    t_serial, t_lane = measure_ingest_overlap()
    proxy["ingest_overlap_speedup"] = round(t_serial / t_lane, 2)

    # -- sharded serving floors (shared perf-gate harness): mesh-plumbing
    # overhead on a single-device-equivalent mesh + dp:2 aggregate over
    # the sim mesh twin — chip-free evidence for the sharded hot path
    try:
        proxy.update(measure_sharded_overhead())
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        sys.stderr.write(f"[bench] sharded proxy failed: {e}\n")
    reused = DEVICE_POOL.reused - pool_reused0
    allocated = DEVICE_POOL.allocated - pool_alloc0
    pool_total = reused + allocated
    proxy["device_pool_reuse_rate"] = round(
        reused / pool_total, 3) if pool_total else None
    proxy["elapsed_s"] = round(time.time() - t_start, 1)
    return proxy


def emit_failure(metric: str, unit: str, meta: dict, err: str,
                 extra: dict = None) -> None:
    """Emit the failure row — but never a bare null when banked evidence
    for the exact same configuration exists on disk.  The stale row keeps
    the banked value/latency fields and adds `stale`/`stale_since`/
    `stale_source`/`live_error` so the driver artifact records both the
    evidence and the fact that this window's live attempt failed.
    ``extra`` fields (e.g. the labeled `cpu_proxy` measures) ride on the
    emitted row either way.  BENCH_NO_STALE=1 restores the bare-null
    behavior (debug)."""
    extra = extra or {}
    no_stale = os.environ.get("BENCH_NO_STALE", "").lower() in (
        "1", "true", "yes",
    )
    # mirror bank_row's cpu exclusion on the LOOKUP side too: a failed
    # forced-cpu run must never be answered with banked chip evidence
    # relabeled platform=cpu
    if not no_stale and meta.get("platform") != "cpu":
        row, since, source = lookup_banked(meta, metric)
        if row is not None:
            # banked row wins key-for-key (notably platform: evidence is
            # never relabeled to this window's env string); meta only
            # fills fields the banked row lacks
            emit({
                **meta, **row, "stale": True, "stale_since": since,
                "stale_source": source, "age_days": age_days(since),
                "live_error": err, **extra,
            })
            return
    emit({
        "metric": metric, "value": None, "unit": unit,
        "vs_baseline": None, "error": err, **meta, **extra,
    })


def probe_backend(tries: int, timeout_s: float) -> tuple:
    """Verify the accelerator backend actually initializes and can run an
    op, from a THROWAWAY subprocess with a hard timeout.

    Round-1 post-mortem (VERDICT.md item 1): the dev tunnel to the chip is
    flaky — backend init can hang indefinitely inside a C call, where no
    in-process alarm can interrupt it.  A subprocess probe is killable, so
    the bench can retry with backoff and fail SOFT with a diagnosable JSON
    line instead of rc=1/rc=124 and a stack trace (BENCH_r01.json).

    Returns ("", platform) on success — platform is the ACTUAL probed
    device platform (e.g. "axon"), not the env label, so a silent
    jax fallback to CPU can never be measured-and-banked as chip
    evidence — else (short error description, "").
    """
    probe_src = (
        "import jax, jax.numpy as jnp;"
        "d = jax.devices();"
        "x = jnp.ones((128, 128), jnp.bfloat16);"
        "(x @ x).block_until_ready();"
        "print('PROBE_OK', d[0].platform, len(d))"
    )
    last_err = "unknown"
    for attempt in range(1, tries + 1):
        t0 = time.time()
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe_src],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                toks = r.stdout.split()
                i = toks.index("PROBE_OK") if "PROBE_OK" in toks else -1
                plat = toks[i + 1] if 0 <= i < len(toks) - 1 else ""
                return "", plat
            tail = (r.stderr or r.stdout).strip().splitlines()
            last_err = (
                f"probe rc={r.returncode}: {tail[-1] if tail else 'no output'}"
            )
        except subprocess.TimeoutExpired:
            last_err = f"probe timed out after {timeout_s:.0f}s"
        sys.stderr.write(
            f"[bench] backend probe attempt {attempt}/{tries} failed "
            f"({time.time() - t0:.0f}s): {last_err}\n"
        )
        if attempt < tries:
            time.sleep(min(10.0 * attempt, 30.0))
    return last_err, ""


def quant_applied(which: str) -> bool:
    """True when BENCH_QUANT actually changes the model that runs —
    mobilenet/ssd/yolov5 (int8 convs) and vit (int8 dense) have int8
    paths; one definition keeps the executed pipeline and the emitted row
    label in agreement."""
    return which in ("mobilenet", "ssd", "yolov5", "vit") and os.environ.get(
        "BENCH_QUANT", ""
    ) in ("1", "int8")


def measure_raw_fps(fn, params, pool, batch: int, n_frames: int,
                    host_input: bool = False, cap_s: float = 20.0,
                    out_meta: dict = None) -> float:
    """Bare jitted-model throughput at `batch` — the ceiling the pipeline
    is judged against (shared by bench.py BENCH_RAW and
    tools/bench_overhead.py so the two published ratios can't diverge).

    Bounded iterations with a periodic sync every 8 dispatches: async
    dispatch must be allowed to pipeline (that's the ceiling) but never
    to queue minutes of executions and their output buffers.  With
    ``host_input`` the per-iteration host->device put is INSIDE the timed
    loop, matching what a BENCH_HOST pipeline pays — a slow link makes
    this loop deadline-risky, so ``cap_s`` is a hard per-row cap and
    ``out_meta`` (when given) records ``timed_out``/completed iterations
    instead of letting the row eat the whole bench budget."""
    import jax
    import numpy as np

    jit_fn = jax.jit(lambda xs: fn(params, [xs]))
    host_batch = np.stack(
        [np.asarray(pool[i % len(pool)]) for i in range(batch)]
    )
    stacked = jax.device_put(host_batch)
    jax.block_until_ready(jit_fn(stacked))  # compile
    n_iters = max(1, n_frames // batch)
    t0 = time.perf_counter()
    out = None
    done = 0
    capped = False
    for i in range(n_iters):
        x = jax.device_put(host_batch) if host_input else stacked
        out = jit_fn(x)
        done += 1
        if done % 8 == 0:
            jax.block_until_ready(out)
        if time.perf_counter() - t0 > cap_s:
            capped = done < n_iters
            break
    jax.block_until_ready(out)
    if out_meta is not None:
        out_meta["timed_out"] = capped
        out_meta["iters_done"] = done
        out_meta["iters_wanted"] = n_iters
    return done * batch / (time.perf_counter() - t0)


METRICS = {
    "mobilenet": ("mobilenet_v2_image_labeling_fps_per_chip", "fps"),
    "ssd": ("ssd_mobilenet_v2_bbox_fps_per_chip", "fps"),
    "yolov5": ("yolov5s_bbox_fps_per_chip", "fps"),
    "posenet": ("posenet_pose_fps_per_chip", "fps"),
    "vit": ("vit_image_labeling_fps_per_chip", "fps"),
    "mnist_trainer": ("mnist_cnn_trainer_epoch_seconds", "s"),
    # scheduler-overhead row: 5-element identity passthrough (CPU, no
    # accelerator, no model) — isolates the dataplane's per-frame cost so
    # a fusion/handoff regression is a one-line measurable delta
    "overhead": ("scheduler_overhead_passthrough_fps", "fps"),
    # continuous-batching row: N concurrent generation streams share one
    # slot batch (CPU-safe zoo transformer) vs the same requests served
    # one at a time — decode must be token-batch-bound, not request-bound
    "generate": ("continuous_batching_tokens_per_s", "tokens/s"),
}


def bench_fuse() -> bool:
    """BENCH_FUSE=0|1 (default 1): streaming-thread fusion for every
    pipeline this bench builds; exported to the pipeline layer as
    NNS_FUSE so parse_pipeline picks it up."""
    return os.environ.get("BENCH_FUSE", "1").lower() not in (
        "0", "false", "no",
    )


def bench_prefix_cache() -> bool:
    """BENCH_PREFIX_CACHE=0|1 (default 0): make the generate row also
    measure the shared-prefix KV cache (cold vs warm TTFT) and stamp the
    ``prefix_cache`` signature axis — warm-prefix evidence must never
    stand in for a cold-cache row or vice versa."""
    return os.environ.get("BENCH_PREFIX_CACHE", "0").lower() in (
        "1", "true", "yes", "on",
    )


def bench_mesh():
    """BENCH_MESH ('tp:4' / 'dp:2,tp:2'; empty = unsharded): the mesh
    signature-axis value — 0 (the pre-mesh implicit default, matching
    _SIG_DEFAULTS) when unset, else the CANONICAL spec string so two
    spellings of one mesh can't mint two evidence signatures."""
    raw = os.environ.get("BENCH_MESH", "").strip()
    if not raw or raw == "0":
        return 0
    from nnstreamer_tpu.parallel.mesh import mesh_spec_str, parse_mesh_spec

    axes = parse_mesh_spec(raw)
    if any(v == -1 for v in axes.values()):
        # a wildcard resolves differently per box, so one signature
        # string would label physically different meshes — evidence
        # rows must name the mesh they actually measured
        raise SystemExit(
            f"BENCH_MESH={raw!r}: -1 wildcards are not allowed in bench "
            "signatures; spell out the axis sizes")
    return mesh_spec_str(axes) if axes else 0


def measure_fuse_overhead(n_frames: int = 30000, cap_s: float = 60.0,
                          deadline_ts: float = None) -> dict:
    """Fused vs unfused identity-chain fps on the 5-element scheduler-
    overhead chain (appsrc ! identity x3 ! tensor_sink, CPU-safe) —
    ``{"fused_fps", "unfused_fps", "fuse_speedup", "telemetry"}``.
    Shared by the BENCH_MODEL=overhead row and the perf-truth baseline,
    so the published speedup and the regression-gated one measure the
    SAME harness.

    Both runs are measured with the TRACER ARMED (always-on latency
    histograms recording), symmetrically — the ratio stays fair, the
    published fps IS the histograms-armed number (the per-frame cost
    claim is in the evidence, not beside it), and the row's telemetry
    dump carries the per-element p50/p95/p99."""
    import numpy as np

    from nnstreamer_tpu.pipeline import parse_pipeline

    pool = [np.zeros((64,), np.float32) for _ in range(16)]

    def run(fuse: bool):
        # the cap is re-derived PER RUN from the absolute deadline (when
        # given): a stalled fused run must shrink the unfused run's
        # window, not grant it a second full budget past the deadline
        cap = cap_s
        if deadline_ts is not None:
            cap = max(10.0, min(cap_s, deadline_ts - time.time() - 15.0))
        pipe = parse_pipeline(
            "appsrc name=src max-buffers=256 ! identity ! identity ! "
            "identity ! tensor_sink name=out max-stored=1",
            name="overhead", fuse=fuse,
        )
        pipe.enable_tracing()
        pipe.start()
        src, sink = pipe["src"], pipe["out"]
        done = {"n": 0}
        sink.connect_new_data(
            lambda f: done.__setitem__("n", done["n"] + 1)
        )
        for i in range(256):  # warmup: settle thread scheduling
            src.push(pool[i % 16])
        t_w = time.time()
        while done["n"] < 256 and time.time() - t_w < cap:
            time.sleep(0.005)
        done["n"] = 0
        t0 = time.perf_counter()
        for i in range(n_frames):
            src.push(pool[i % 16])
        while done["n"] < n_frames and time.perf_counter() - t0 < cap:
            time.sleep(0.002)
        dt = time.perf_counter() - t0
        measured = done["n"]
        src.end_of_stream()
        pipe.wait(timeout=30)
        telemetry = pipe.telemetry_summary()
        pipe.stop()
        return measured / dt, telemetry

    fused, fused_telemetry = run(True)
    unfused, _ = run(False)
    return {
        "fused_fps": round(fused, 1),
        "unfused_fps": round(unfused, 1),
        "fuse_speedup": round(fused / unfused, 2) if unfused else None,
        "telemetry": fused_telemetry,
    }


def overhead_row(deadline_ts: float) -> dict:
    """Scheduler-overhead microbench: appsrc ! identity x3 ! tensor_sink
    (5 elements), tiny host frames, CPU-safe (no accelerator, no model).
    Measures BOTH dataplanes every run — `value` is the configured
    BENCH_FUSE mode's fps, `fused_fps`/`unfused_fps`/`fuse_speedup`
    record the tentpole's delta explicitly."""
    n_frames = int(os.environ.get("BENCH_FRAMES", "30000"))
    res = measure_fuse_overhead(
        n_frames=n_frames, cap_s=60.0, deadline_ts=deadline_ts,
    )
    value = res["fused_fps"] if bench_fuse() else res["unfused_fps"]
    return {
        "metric": METRICS["overhead"][0],
        "value": round(value, 1),
        "unit": "fps",
        "vs_baseline": None,
        "fused_fps": res["fused_fps"],
        "unfused_fps": res["unfused_fps"],
        "fuse_speedup": res["fuse_speedup"],
        "chain": "appsrc!identity!identity!identity!tensor_sink",
        "frames": n_frames,
        "telemetry": res["telemetry"],
    }


def generate_row(deadline_ts: float) -> dict:
    """Continuous-batching row (CPU-safe zoo transformer, no accelerator):
    N concurrent generation streams multiplexed into shared slots vs the
    same requests served one at a time.  ``value`` is the slotted
    aggregate tokens/s; the serialized baseline and speedup ride along so
    the roofline claim (token-batch-bound, not request-bound) is a
    one-line delta."""
    slots = int(os.environ.get("BENCH_SLOTS", "4"))
    streams = int(os.environ.get("BENCH_STREAMS", str(max(4, slots))))
    budget = max(30.0, min(240.0, deadline_ts - time.time() - 30.0))
    res = measure_generate_throughput(
        slots=slots, streams=streams, timeout_s=budget)
    res.update(measure_slot_multiplex_speedup(
        slots=slots, streams=streams, timeout_s=min(60.0, budget)))
    if bench_prefix_cache():
        res.update(measure_prefix_ttft(
            timeout_s=min(180.0, max(30.0, deadline_ts - time.time() - 30.0))))
    return {
        "metric": METRICS["generate"][0],
        "value": res["tokens_per_s"],
        "unit": "tokens/s",
        "vs_baseline": None,
        **{k: v for k, v in res.items() if k != "tokens_per_s"},
    }


def pipeline_row(which: str, batch: int, n_frames: int, dtype: str,
                 host_frames: bool, deadline_ts: float) -> dict:
    """``deadline_ts`` is the absolute time.time() by which this function
    must have returned (the parent kills 60s after it): every internal
    wait is carved from time-remaining, so imports/model-build/compile
    time spent before any given phase shrinks that phase's window instead
    of pushing the whole run past the kill."""
    import numpy as np

    # fail BEFORE any pipeline/device work: a zero-block run would
    # otherwise publish a plausible-looking 0-fps row
    if os.environ.get("BENCH_INGEST", "") == "block" and n_frames < batch:
        raise SystemExit(
            f"BENCH_INGEST=block needs BENCH_FRAMES >= batch "
            f"({n_frames} < {batch})"
        )

    # host rows additionally get a PER-ROW cap: frames crossing the
    # host->device link make every phase link-speed-bound, and a wedged
    # or slow tunnel must produce a labeled `timed_out` row instead of
    # eating the entire bench budget (the r05 input=host failure mode:
    # the row blew the full 480s deadline and reported nothing)
    if host_frames:
        host_cap = float(os.environ.get("BENCH_HOST_CAP", "180"))
        deadline_ts = min(deadline_ts, time.time() + host_cap)

    from nnstreamer_tpu.backends.jax_xla import register_jax_model
    from nnstreamer_tpu.models import build
    from nnstreamer_tpu.pipeline import parse_pipeline

    labels_path = "/tmp/nns_bench_labels.txt"
    with open(labels_path, "w") as f:
        f.write("\n".join(f"class{i}" for i in range(1001)))

    # BASELINE.md tracked rows: mobilenet (headline), ssd+bbox decode,
    # yolov5, posenet+pose decode — all measured as full pipelines
    if which == "mobilenet":
        size, family, props = 224, "mobilenet_v2", {"dtype": dtype}
        if quant_applied(which):
            # int8 MXU path ≙ the reference's quantized-tflite flagship
            # (mobilenet_v2_1.0_224_quant.tflite)
            props["quantize"] = "int8"
        decoder = f"tensor_decoder mode=image_labeling option1={labels_path} ! "
    elif which == "ssd":
        from nnstreamer_tpu.models.ssd_mobilenet import write_box_priors

        priors = write_box_priors("/tmp/nns_bench_priors.txt")
        size, family, props = 300, "ssd_mobilenet_v2", {"dtype": dtype}
        if quant_applied(which):
            props["quantize"] = "int8"
        decoder = (
            "tensor_decoder mode=bounding_boxes option1=mobilenet-ssd "
            f"option2={labels_path} option3={priors} option4=300:300 "
            "option5=300:300 ! "
        )
    elif which == "yolov5":
        size = int(os.environ.get("BENCH_SIZE", "640"))
        family, props = "yolov5s", {"dtype": dtype, "size": str(size)}
        if quant_applied(which):
            props["quantize"] = "int8"
        decoder = (
            "tensor_decoder mode=bounding_boxes option1=yolov5 "
            f"option2={labels_path} option4={size}:{size} "
            f"option5={size}:{size} ! "
        )
    elif which == "posenet":
        size, family, props = 257, "posenet", {"dtype": dtype}
        decoder = (
            "tensor_decoder mode=pose_estimation option1=257:257 "
            "option2=257:257 option4=heatmap-offset ! "
        )
    elif which == "vit":
        # transformer-era vision row (net-new vs BASELINE.md): flash
        # attention on TPU, same labeling pipeline as the headline
        size, family, props = 224, "vit", {"dtype": dtype, "attn": "flash"}
        if quant_applied(which):
            props["quantize"] = "int8"
        decoder = f"tensor_decoder mode=image_labeling option1={labels_path} ! "
    else:
        raise SystemExit(f"unknown BENCH_MODEL {which!r}")

    metric = METRICS[which][0]
    fn, params, in_spec, out_spec = build(family, props)
    register_jax_model("bench_model", fn, params, in_spec, out_spec)

    sink_split = os.environ.get("BENCH_SINK_SPLIT", "1") not in ("0", "false")
    if not sink_split:
        # whole-block delivery: the decoder's host half must also keep
        # blocks whole (vectorized decode_fused_batch) or it re-splits.
        # Fail LOUD for decoders without that path — a silently-split
        # pipeline would publish a row labeled sink_split:false that
        # measured the default configuration
        from nnstreamer_tpu.core import registry as _registry

        m = re.search(r"mode=([a-z_0-9]+)", decoder)
        if m is None:
            raise SystemExit(
                "BENCH_SINK_SPLIT=0: whole-block delivery needs a "
                f"tensor_decoder with a mode= (got {decoder!r})"
            )
        mode = m.group(1)
        dec_cls = _registry.get(_registry.KIND_DECODER, mode)
        if not hasattr(dec_cls, "decode_fused_batch"):
            raise SystemExit(
                f"BENCH_SINK_SPLIT=0: decoder mode {mode!r} has no "
                "decode_fused_batch (whole-block delivery unsupported)"
            )
        decoder = decoder.replace(
            "tensor_decoder ", "tensor_decoder split-batches=false ", 1
        )
    # batch-timeout: how long a partial micro-batch waits for fill.  20 ms
    # suits throughput configs (the e2e latency instrument below pushes
    # LONE frames, which would eat the whole wait); the latency-optimized
    # row overrides it down so p50 measures serving, not the fill timer.
    batch_timeout_ms = os.environ.get(
        "BENCH_BATCH_TIMEOUT", BATCH_TIMEOUT_DEFAULT_MS
    )
    mesh_spec = bench_mesh()
    pipe = parse_pipeline(
        "appsrc name=src max-buffers=512 ! "
        "tensor_filter name=f framework=jax-xla model=bench_model "
        f"max-batch={batch} batch-timeout={batch_timeout_ms} "
        "latency=1 throughput=1 "
        f"dispatch-depth={os.environ.get('BENCH_DEPTH', '4')} "
        f"ingest-lane={os.environ.get('BENCH_INGEST_LANE', 'auto')} "
        + (f"mesh={mesh_spec} " if mesh_spec != 0 else "")
        + "! " + decoder
        + "tensor_sink name=out max-stored=1"
        + ("" if sink_split else " split-batches=false"),
        name="bench",
    )
    # frame pool: realistic uint8 camera frames, cycled (generation off the
    # measured path).  Device-resident by default: on-host TPU deployments
    # feed frames over PCIe at GB/s, but this dev harness reaches the chip
    # through a ~30 MB/s tunnel whose transfer latency would swamp the
    # pipeline being measured; BENCH_HOST=1 measures host-sourced frames.
    rng = np.random.default_rng(0)
    pool = [
        rng.integers(0, 255, (size, size, 3), dtype=np.uint8) for _ in range(16)
    ]
    # BENCH_INGEST=block: frames enter pre-batched, one BatchFrame per
    # micro-batch (≙ the reference converter's frames-per-tensor batching)
    # — per-frame Python ingest/stacking costs are paid once per block.
    # fps still counts LOGICAL frames (the sink splits the batch).
    ingest_block = os.environ.get("BENCH_INGEST", "") == "block"
    blocks = []
    if ingest_block:
        blocks = [
            np.stack([pool[(i + j) % len(pool)] for j in range(batch)])
            for i in range(4)
        ]
    if not host_frames:
        import jax

        pool = [jax.device_put(p) for p in pool]
        blocks = [jax.device_put(b) for b in blocks]
        jax.block_until_ready(pool)
        jax.block_until_ready(blocks)

    pipe.start()
    src, sink = pipe["src"], pipe["out"]

    # compile time dominates warmup; whatever remains is the measure cap
    warmup_cap = max(30.0, (deadline_ts - time.time()) * 0.7)

    # warmup: trigger compiles for the full bucket and any tail buckets
    done = {"n": 0}
    # counts LOGICAL frames either way: split mode delivers per-frame
    # (batch_size absent -> 1), block-delivery mode delivers whole blocks
    sink.connect_new_data(
        lambda f: done.__setitem__(
            "n", done["n"] + getattr(f, "batch_size", 1)
        )
    )
    if ingest_block:
        for i in range(2):
            src.push_block(blocks[i % len(blocks)])
    else:
        for i in range(batch * 2):
            src.push(pool[i % len(pool)])
    t_wait = time.time()
    while done["n"] < batch * 2 and time.time() - t_wait < warmup_cap:
        time.sleep(0.01)
    if done["n"] < batch * 2:
        pipe.stop()
        if host_frames:
            # deadline-safe host row: the link couldn't even finish
            # warmup inside the per-row cap — report that, labeled,
            # instead of dying rc!=0 with the budget burned
            return {
                "metric": metric, "value": None, "unit": "fps",
                "vs_baseline": None, "timed_out": True,
                "error": (
                    f"host ingest warmup incomplete: {done['n']}/"
                    f"{batch * 2} frames in {warmup_cap:.0f}s"
                ),
            }
        raise RuntimeError(
            f"warmup incomplete: {done['n']}/{batch * 2} frames in "
            f"{warmup_cap:.0f}s"
        )
    # drain stragglers so leftover warmup completions can never leak into
    # the measured counter: wait until the count is stable for 2 s
    stable_since, last = time.time(), done["n"]
    while time.time() - stable_since < 2.0:
        time.sleep(0.1)
        if done["n"] != last:
            stable_since, last = time.time(), done["n"]

    # measured run (cap: whatever remains of the budget, minus EOS margin)
    measure_cap = max(30.0, deadline_ts - time.time() - 15.0)
    done["n"] = 0
    t0 = time.perf_counter()
    if ingest_block:
        n_frames = (n_frames // batch) * batch
        for i in range(n_frames // batch):
            src.push_block(blocks[i % len(blocks)])
    else:
        for i in range(n_frames):
            src.push(pool[i % len(pool)])
    while done["n"] < n_frames and time.perf_counter() - t0 < measure_cap:
        time.sleep(0.005)
    dt = time.perf_counter() - t0
    fps = done["n"] / dt
    # a host row that ran out of its per-row cap mid-measure still
    # reports the throughput it sustained, labeled — partial evidence
    # beats a dead 480s window
    row_timed_out = host_frames and done["n"] < n_frames

    # BASELINE.md tracks p50 per-frame latency alongside fps for the
    # detector/pose rows.  Two instruments: the filter's latency prop
    # measures the (async) invoke DISPATCH per logical frame; true
    # end-to-end latency is measured below with lone frames — push one,
    # wait for its arrival at the sink — which includes batching wait,
    # device time, decode, and delivery.
    dispatch_latency_us = round(pipe["f"].latency_us, 1)
    lat_samples = []
    lat_deadline = time.time() + max(5.0, deadline_ts - time.time() - 10.0)
    for i in range(0 if row_timed_out else 13):
        if time.time() > lat_deadline:
            break
        c0 = done["n"]
        t_send = time.perf_counter()
        src.push(pool[i % len(pool)])
        while done["n"] <= c0 and time.time() < lat_deadline:
            time.sleep(0.001)
        if done["n"] > c0 and i > 0:
            # sample 0 discarded: a lone frame hits the batch-1 bucket's
            # first compile, which is startup cost, not serving latency
            lat_samples.append(time.perf_counter() - t_send)

    src.end_of_stream()
    pipe.wait(timeout=60)
    # labeled telemetry snapshot (registry dump) rides the evidence row:
    # perf claims and live metrics come from ONE source and cannot drift
    telemetry = pipe.telemetry_summary()
    pipe.stop()

    extra = {
        "dispatch_latency_us": dispatch_latency_us,
        "telemetry": telemetry,
    }
    if row_timed_out:
        extra["timed_out"] = True
        extra["frames_done"] = done["n"]
        extra["frames_wanted"] = n_frames
    if lat_samples:
        import numpy as _np

        extra["e2e_latency_ms_p50"] = round(
            float(_np.percentile(lat_samples, 50)) * 1e3, 2
        )
        extra["e2e_latency_ms_max"] = round(max(lat_samples) * 1e3, 2)
        # the floor under every e2e number: a bare device round trip
        # (tiny op, block_until_ready).  Over the dev tunnel this is
        # ~90 ms-class — the framework-attributable latency is
        # e2e_p50 MINUS this, not e2e_p50 itself; on-host deployments
        # (PCIe) have a sub-ms floor and the same framework delta.
        try:
            import jax as _jax
            import jax.numpy as _jnp

            x = _jax.device_put(_jnp.ones((8, 8), _jnp.bfloat16))
            f = _jax.jit(lambda a: a @ a)
            _jax.block_until_ready(f(x))  # compile
            rtts = []
            for _ in range(5):
                t_r = time.perf_counter()
                _jax.block_until_ready(f(x))
                rtts.append(time.perf_counter() - t_r)
            extra["device_rtt_ms"] = round(
                float(_np.median(rtts)) * 1e3, 2
            )
        except Exception as e:  # noqa: BLE001 — diagnostic field only
            sys.stderr.write(f"[bench] rtt probe failed: {e}\n")
    if os.environ.get("BENCH_RAW", "0").lower() in ("1", "true", "yes"):
        # bare-model reference in the SAME window/process: the r2 verdict
        # contract is pipeline >= 0.9x raw — measure both or the ratio
        # claim is unfalsifiable
        raw_meta = {}
        raw_fps = measure_raw_fps(
            fn, params, pool, batch,
            n_frames=min(n_frames, 4096),
            host_input=host_frames,
            cap_s=min(20.0, max(10.0, deadline_ts - time.time() - 10.0)),
            out_meta=raw_meta,
        )
        extra["raw_fps"] = round(raw_fps, 1)
        extra["pipeline_vs_raw"] = round(fps / raw_fps, 3)
        if raw_meta.get("timed_out"):
            extra["raw_timed_out"] = True

    # the >=1000 fps/chip north-star target applies to the MobileNet
    # headline row only; the other BASELINE.md rows are "tracked" (no
    # numeric target), so vs_baseline is null for them
    return {
        "metric": metric,
        "value": round(fps, 1),
        "unit": "fps",
        "vs_baseline": (
            round(fps / NORTH_STAR_FPS, 3) if which == "mobilenet" else None
        ),
        **extra,
    }


def trainer_row(dtype: str, deadline_ts: float) -> dict:
    """BASELINE.md row: tensor_trainer MNIST CNN epoch time (tracked)."""
    from nnstreamer_tpu.trainer.jax_trainer import mnist_epoch_benchmark

    secs, acc = mnist_epoch_benchmark(
        dtype=dtype, timeout_s=max(60.0, deadline_ts - time.time() - 30.0)
    )
    return {
        "metric": METRICS["mnist_trainer"][0],
        "value": round(secs, 2),
        "unit": "s",
        "vs_baseline": None,
        "train_accuracy": round(acc, 4),
    }


def child_main() -> None:
    """Run the actual measurement; print the result row on the last line.

    Runs inside a killable subprocess (see main): accelerator ops dispatch
    into C calls that no in-process alarm can interrupt when the device
    tunnel wedges mid-run, so the deadline lives in the parent.
    """
    which = os.environ.get("BENCH_MODEL", "mobilenet")
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    n_frames = int(os.environ.get("BENCH_FRAMES", "4096"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    host_frames = os.environ.get("BENCH_HOST", "0").lower() in (
        "1", "true", "yes",
    )
    # BENCH_FUSE -> pipeline layer (read at Pipeline construction)
    os.environ["NNS_FUSE"] = "1" if bench_fuse() else "0"
    if (os.environ.get("BENCH_PLATFORM") == "cpu"
            or which in ("overhead", "generate")):
        import jax

        jax.config.update("jax_platforms", "cpu")
    # absolute deadline anchored at process start (_T0, module import),
    # so import/build/compile time shrinks later windows instead of
    # racing the parent's kill
    deadline_ts = _T0 + float(os.environ.get("BENCH_DEADLINE", "420"))
    if which == "mnist_trainer":
        row = trainer_row(dtype, deadline_ts)
    elif which == "overhead":
        row = overhead_row(deadline_ts)
    elif which == "generate":
        row = generate_row(deadline_ts)
    else:
        row = pipeline_row(
            which, batch, n_frames, dtype, host_frames, deadline_ts
        )
    print("BENCHROW " + json.dumps(row), flush=True)


def run_child(deadline_s: float) -> tuple:
    """(row|None, error_string).

    Child stderr is inherited (diagnostics stream through live); stdout is
    captured for the BENCHROW line.  The kill deadline gets a grace margin
    over the child's own budget so a self-reporting child always wins the
    race — the kill only fires when the child is truly wedged (tunnel hang
    inside a C call).
    """
    import tempfile

    with tempfile.TemporaryFile(mode="w+t") as out:
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                stdout=out, timeout=deadline_s + 60.0,
            )
            rc = r.returncode
        except subprocess.TimeoutExpired:
            out.seek(0)
            tail = out.read().strip().splitlines()
            return None, (
                f"bench run exceeded {deadline_s + 60:.0f}s deadline; "
                f"last output: {tail[-1] if tail else 'none'}"
            )
        out.seek(0)
        lines = out.read().splitlines()
    for line in reversed(lines):
        if line.startswith("BENCHROW "):
            return json.loads(line[len("BENCHROW "):]), ""
    return None, (
        f"bench child rc={rc}: {lines[-1] if lines else 'no stdout'}"
    )


def _try_cpu_proxy() -> dict:
    """Labeled CPU-proxy evidence attached to a failure row (the stale
    TPU evidence stays banked, never overwritten — these measures are
    live numbers for THIS code).  BENCH_PROXY=0 disables; failures
    degrade to no extra fields rather than masking the real error."""
    if os.environ.get("BENCH_PROXY", "1").lower() in ("0", "false", "no"):
        return {}
    try:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")  # never probe again
        return {"cpu_proxy": cpu_proxy_measures()}
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        sys.stderr.write(f"[bench] cpu proxy failed: {e}\n")
        return {}


def main() -> None:
    which = os.environ.get("BENCH_MODEL", "mobilenet")
    if which not in METRICS:
        emit({
            "metric": "invalid", "value": None, "unit": None,
            "vs_baseline": None,
            "error": f"unknown BENCH_MODEL {which!r}; "
                     f"expected one of {sorted(METRICS)}",
        })
        return
    metric, unit = METRICS[which]
    host_frames = os.environ.get("BENCH_HOST", "0").lower() in (
        "1", "true", "yes",
    )
    # the overhead row never touches an accelerator: CPU-safe by
    # construction, so the backend probe (and stale fallback) are skipped
    force_cpu = (os.environ.get("BENCH_PLATFORM") == "cpu"
                 or which in ("overhead", "generate"))
    meta = {
        "model": which,
        "batch": int(os.environ.get("BENCH_BATCH", "128")),
        "dtype": os.environ.get("BENCH_DTYPE", "bfloat16"),
        "quantize": "int8" if quant_applied(which) else None,
        "dispatch_depth": int(os.environ.get("BENCH_DEPTH", "4")),
        "ingest": (
            "block" if os.environ.get("BENCH_INGEST", "") == "block"
            else "frame"
        ),
        "sink_split": os.environ.get("BENCH_SINK_SPLIT", "1") not in (
            "0", "false"
        ),
        "batch_timeout_ms": int(os.environ.get(
            "BENCH_BATCH_TIMEOUT", BATCH_TIMEOUT_DEFAULT_MS
        )),
        "fuse": 1 if bench_fuse() else 0,
        "ingest_lane": os.environ.get("BENCH_INGEST_LANE", "auto"),
        "input": "host" if host_frames else "device",
        # continuous-batching axis: rows from non-generation models (and
        # every pre-slot banked row, via _SIG_DEFAULTS) carry slots=0 —
        # request-serial evidence can never stand in for slotted runs
        "slots": (int(os.environ.get("BENCH_SLOTS", "4"))
                  if which == "generate" else 0),
        # mesh-sharded serving axis: canonical spec string, or 0 (every
        # pre-mesh banked row, via _SIG_DEFAULTS) — single-device
        # evidence can never stand in for a sharded run
        "mesh": bench_mesh(),
        # shared-prefix KV cache axis: 1 only when the generate row
        # measured warm-prefix TTFT (BENCH_PREFIX_CACHE=1); every banked
        # row predating the axis carries 0 via _SIG_DEFAULTS
        "prefix_cache": (1 if which == "generate" and bench_prefix_cache()
                         else 0),
        "platform": "cpu" if force_cpu else os.environ.get(
            "JAX_PLATFORMS", "default"
        ),
    }

    if not force_cpu:
        # worst case ~4.5 min (2 x 120s + backoff): the fail-soft JSON row
        # must land well inside the driver's own kill window — a healthy
        # tunnel probes in 10-30s, so 120s also covers "slow but alive"
        err, probed_platform = probe_backend(
            tries=int(os.environ.get("BENCH_PROBE_TRIES", "2")),
            timeout_s=float(os.environ.get("BENCH_PROBE_TIMEOUT", "120")),
        )
        if err:
            emit_failure(
                metric, unit, meta,
                f"accelerator backend unavailable: {err}",
                extra=_try_cpu_proxy(),
            )
            return
        if probed_platform:
            # the label the row (and its evidence-cache entry) carries is
            # what the probe SAW, not what the env claimed
            meta["platform"] = probed_platform

    deadline = float(os.environ.get("BENCH_DEADLINE", "420"))
    tries = int(os.environ.get("BENCH_TRIES", "2"))
    err = "no attempts"
    for attempt in range(1, tries + 1):
        row, err = run_child(deadline)
        if row is not None:
            merged = {**row, **meta}
            bank_row(merged)
            emit(merged)
            return
        sys.stderr.write(
            f"[bench] attempt {attempt}/{tries} failed: {err}\n"
        )
    # discriminate WHY the child failed before reaching for banked
    # evidence: a tunnel that wedged mid-run (probe now fails too — the
    # r4 host-row scenario) justifies the stale fallback; a backend that
    # still answers means the bench itself regressed, and masking a code
    # bug with yesterday's headline would be fabrication.
    if not force_cpu:
        recheck_err, _ = probe_backend(
            tries=1,
            timeout_s=min(
                60.0,
                float(os.environ.get("BENCH_PROBE_TIMEOUT", "120")),
            ),
        )
        if not recheck_err:
            # backend still answers -> the bench itself regressed
            emit({
                "metric": metric, "value": None, "unit": unit,
                "vs_baseline": None,
                "error": f"{err} (backend healthy: not a tunnel outage)",
                **meta,
            })
            return
        err = f"{err}; re-probe: {recheck_err}"
    emit_failure(metric, unit, meta, err, extra=_try_cpu_proxy())


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child_main()
    else:
        main()
