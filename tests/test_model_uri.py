"""model:// URI resolution (≙ mlagent_parse_uri_string, ml_agent.c)."""

import os

import numpy as np
import pytest

from nnstreamer_tpu.core.model_uri import resolve_model_uri


@pytest.fixture
def repo(tmp_path, monkeypatch):
    monkeypatch.setenv("NNS_TPU_MODEL_REPO", str(tmp_path))
    return tmp_path


class TestResolve:
    def test_plain_and_file_scheme(self):
        assert resolve_model_uri("/a/b.msgpack") == "/a/b.msgpack"
        assert resolve_model_uri("file:///a/b.py") == "/a/b.py"

    def test_named_version(self, repo):
        d = repo / "scaler" / "2"
        d.mkdir(parents=True)
        (d / "scaler.py").write_text("# model")
        assert resolve_model_uri("model://scaler/2") == str(d / "scaler.py")

    def test_latest_picks_highest(self, repo):
        for v in ("1", "3", "2"):
            d = repo / "m" / v
            d.mkdir(parents=True)
            (d / f"m{v}.bin").write_text(v)
        assert resolve_model_uri("model://m").endswith("3/m3.bin")
        assert resolve_model_uri("model://m/latest").endswith("3/m3.bin")

    def test_multi_file_version_returns_dir(self, repo):
        d = repo / "ck" / "1"
        d.mkdir(parents=True)
        (d / "a").write_text("x")
        (d / "b").write_text("y")
        assert resolve_model_uri("model://ck/1") == str(d)

    def test_missing_raises(self, repo):
        with pytest.raises(FileNotFoundError):
            resolve_model_uri("model://nope")

    def test_filter_resolves_uri(self, repo):
        # a python3-backend model via model:// in a pipeline
        d = repo / "pysq" / "1"
        d.mkdir(parents=True)
        (d / "sq.py").write_text(
            "def invoke(inputs):\n"
            "    return [inputs[0] * inputs[0]]\n"
        )
        from nnstreamer_tpu.pipeline import parse_pipeline

        pipe = parse_pipeline(
            "appsrc name=a ! tensor_filter framework=python3 "
            "model=model://pysq ! tensor_sink name=out"
        )
        pipe.start()
        pipe["a"].push(np.float32([3.0]))
        pipe["a"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        assert float(pipe["out"].frames[0].tensors[0][0]) == 9.0
