"""Documentation/examples.md stays honest: every nnstreamer_tpu pipeline
in it must parse (reference gst-launch blocks are skipped)."""

import os
import re

import pytest

from nnstreamer_tpu.pipeline import parse_pipeline

DOC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "Documentation", "examples.md",
)


def _our_pipelines():
    text = open(DOC).read()
    out = []
    for block in re.findall(r"```\n(.*?)```", text, re.S):
        if "gst-launch-1.0" in block:
            continue  # reference side of the comparison
        # strip comments, join backslash continuations
        block = re.sub(r"^#.*$", "", block, flags=re.M)
        block = block.replace("\\\n", " ")
        for line in block.splitlines():
            line = line.strip()
            # "..." marks elided fragments in the prose, not runnable text
            if line and "!" in line and "..." not in line:
                out.append(line)
    return out


PIPELINES = _our_pipelines()


def test_doc_has_pipelines():
    assert len(PIPELINES) >= 8


@pytest.mark.parametrize("text", PIPELINES)
def test_pipeline_parses(text):
    # parse only (files referenced by the docs don't exist here); parser
    # errors = the doc drifted from the element/property registry
    pipe = parse_pipeline(text)
    assert pipe.elements
