"""Documentation/examples.md stays honest: every nnstreamer_tpu pipeline
in it must parse (reference gst-launch blocks are skipped)."""

import os
import re

import pytest

from nnstreamer_tpu.pipeline import parse_pipeline

DOC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "Documentation", "examples.md",
)


def _fenced_blocks(text):
    """Line-based fence parser: (language, body) pairs.  A regex over
    the whole file mis-pairs fences as soon as language-tagged blocks
    (```python) interleave with plain ones."""
    blocks, cur, lang = [], None, None
    for line in text.splitlines():
        if line.startswith("```"):
            if cur is None:
                lang, cur = line[3:].strip(), []
            else:
                blocks.append((lang, "\n".join(cur)))
                cur = None
        elif cur is not None:
            cur.append(line)
    assert cur is None, "unclosed ``` fence in examples.md"
    return blocks


def _our_pipelines():
    text = open(DOC).read()
    out = []
    for lang, block in _fenced_blocks(text):
        if lang or "gst-launch-1.0" in block:
            continue  # python snippets / reference side of the comparison
        # strip comments, join backslash continuations
        block = re.sub(r"^#.*$", "", block, flags=re.M)
        block = block.replace("\\\n", " ")
        for line in block.splitlines():
            line = line.strip()
            # "..." marks elided fragments in the prose, not runnable text
            if line and "!" in line and "..." not in line:
                out.append(line)
    return out


PIPELINES = _our_pipelines()


def test_doc_has_pipelines():
    assert len(PIPELINES) >= 8


@pytest.mark.parametrize("text", PIPELINES)
def test_pipeline_parses(text):
    # parse only (files referenced by the docs don't exist here); parser
    # errors = the doc drifted from the element/property registry
    pipe = parse_pipeline(text)
    assert pipe.elements
