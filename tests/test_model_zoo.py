"""Detection/pose model families wired to their decoders (BASELINE rows:
SSD-MobileNet + bounding-box decode, YOLOv5s, PoseNet + pose decode)."""

import numpy as np
import pytest

from nnstreamer_tpu.models import available, build


def _img(size, seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (size, size, 3), np.uint8
    )


class TestZoo:
    def test_families_registered(self):
        names = available()
        for want in ("mobilenet_v2", "ssd_mobilenet_v2", "yolov5s",
                     "posenet", "mnist_cnn", "transformer"):
            assert want in names


class TestSSD:
    @pytest.mark.slow  # tier-1 budget: ~24s SSD build+decode; the
    # decoder truth tables keep bounding-box decode covered
    def test_shapes_and_decode(self, tmp_path):
        from nnstreamer_tpu.decoders.bounding_box import BoundingBoxes
        from nnstreamer_tpu.models.ssd_mobilenet import (
            num_priors, write_box_priors,
        )
        from nnstreamer_tpu.core.buffer import TensorFrame

        fn, params, in_spec, out_spec = build(
            "ssd_mobilenet_v2", {"dtype": "float32", "classes": "11"}
        )
        loc, scores = fn(params, [_img(300)])
        P = num_priors()
        assert loc.shape == (P, 4)
        assert scores.shape == (P, 11)
        priors = write_box_priors(str(tmp_path / "box-priors.txt"))
        labels = tmp_path / "labels.txt"
        labels.write_text("\n".join(f"c{i}" for i in range(11)))
        dec = BoundingBoxes()
        dec.set_options(
            ["mobilenet-ssd", str(labels), priors, "300:300", "300:300"]
        )
        out = dec.decode(
            TensorFrame([np.asarray(loc), np.asarray(scores)]), in_spec
        )
        # random weights: just require a valid RGBA video frame out
        assert out.tensors[0].shape == (300, 300, 4)


class TestYolo:
    @pytest.mark.slow  # tier-1 budget: ~31s yolov5 build+decode; the
    # in-graph NMS unit + decoder truth tables stay in tier-1
    def test_shapes_and_decode(self, tmp_path):
        from nnstreamer_tpu.decoders.bounding_box import BoundingBoxes
        from nnstreamer_tpu.models.yolov5 import num_candidates
        from nnstreamer_tpu.core.buffer import TensorFrame

        size = 320
        fn, params, in_spec, out_spec = build(
            "yolov5s", {"dtype": "float32", "size": str(size), "classes": "5"}
        )
        pred = np.asarray(fn(params, [_img(size)])[0])
        assert pred.shape == (num_candidates(size), 10)
        # decoded boxes are normalized * size: all finite, obj/cls in [0,1]
        assert np.isfinite(pred).all()
        assert (pred[:, 4:] >= 0).all() and (pred[:, 4:] <= 1).all()
        labels = tmp_path / "labels.txt"
        labels.write_text("\n".join(f"c{i}" for i in range(5)))
        dec = BoundingBoxes()
        dec.set_options(
            ["yolov5", str(labels), "", f"{size}:{size}", f"{size}:{size}"]
        )
        out = dec.decode(TensorFrame([pred]), in_spec)
        assert out.tensors[0].shape == (size, size, 4)

    def test_size_must_be_multiple_of_32(self):
        with pytest.raises(ValueError):
            build("yolov5s", {"size": "100"})


class TestPoseNet:
    @pytest.mark.slow  # tier-1 budget: ~20s posenet build+decode; zoo
    # breadth, not a serving-dataplane contract — full suite keeps it
    def test_shapes_and_decode(self):
        from nnstreamer_tpu.decoders.pose import PoseEstimation
        from nnstreamer_tpu.core.buffer import TensorFrame

        fn, params, in_spec, out_spec = build(
            "posenet", {"dtype": "float32", "size": "129", "keypoints": "7"}
        )
        heat, off = fn(params, [_img(129)])
        gh = (129 + 15) // 16
        assert heat.shape == (gh, gh, 7)
        assert off.shape == (gh, gh, 14)
        dec = PoseEstimation()
        dec.set_options(["129:129", "129:129", "", "heatmap-offset"])
        out = dec.decode(
            TensorFrame([np.asarray(heat), np.asarray(off)]), in_spec
        )
        assert out.tensors[0].shape[-1] == 4  # RGBA overlay
