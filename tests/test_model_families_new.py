"""New zoo families: DeepLab-lite segmentation and keyword-spotting CNN.

Each runs as a full pipeline with its natural decoder — segmentation pairs
with image_segment (``tensordec-imagesegment.c`` contract), KWS consumes
real .wav audio through the media ingest path.
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends.jax_xla import register_jax_model, unregister_jax_model
from nnstreamer_tpu.media.wav import write_wav
from nnstreamer_tpu.models import build
from nnstreamer_tpu.pipeline import parse_pipeline


class TestDeepLab:
    @pytest.mark.slow  # tier-1 budget: ~19s deeplab build; zoo-breadth
    # family, full correctness stays in the full suite
    def test_build_shapes(self):
        fn, params, in_spec, out_spec = build(
            "deeplab", {"dtype": "float32", "size": "65", "classes": "5"}
        )
        img = np.random.default_rng(0).integers(0, 255, (65, 65, 3), np.uint8)
        out = fn(params, [img])[0]
        assert out.shape == (65, 65, 5)
        assert np.isfinite(np.asarray(out)).all()

    @pytest.mark.slow  # tier-1 budget: ~29s compile-bound CNN e2e; zoo
    # breadth, not a serving-dataplane contract — full suite keeps it
    def test_pipeline_with_segment_decoder(self):
        fn, params, in_spec, out_spec = build(
            "deeplab", {"dtype": "float32", "size": "33", "classes": "5"}
        )
        register_jax_model("seg_t", fn, params, in_spec, out_spec)
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_filter framework=jax-xla "
                "model=seg_t ! tensor_decoder mode=image_segment "
                "option1=tflite-deeplab option2=5 ! tensor_sink name=out"
            )
            pipe.start()
            img = np.random.default_rng(1).integers(0, 255, (33, 33, 3), np.uint8)
            pipe["src"].push(img)
            pipe["src"].end_of_stream()
            pipe.wait(timeout=120)
            frames = pipe["out"].frames
            pipe.stop()
            assert frames[0].tensors[0].shape == (33, 33, 4)  # RGBA overlay
            assert frames[0].tensors[0].dtype == np.uint8
        finally:
            unregister_jax_model("seg_t")


class TestKwsCNN:
    def test_build_and_logits(self):
        fn, params, in_spec, out_spec = build(
            "kws_cnn", {"dtype": "float32", "samples": "4000", "classes": "4"}
        )
        pcm = (np.sin(np.arange(4000) / 5.0) * 10000).astype(np.int16)[:, None]
        out = np.asarray(fn(params, [pcm])[0])
        assert out.shape == (4,)
        assert np.isfinite(out).all()

    def test_wav_to_keyword_pipeline(self, tmp_path):
        """audiofilesrc -> converter -> KWS filter: real file, end to end."""
        rate, samples = 16000, 4000
        t = np.arange(rate, dtype=np.float32)
        pcm = (np.sin(t / 8.0) * 9000).astype(np.int16)
        path = str(tmp_path / "kw.wav")
        write_wav(path, pcm, rate=rate)

        fn, params, in_spec, out_spec = build(
            "kws_cnn",
            {"dtype": "float32", "samples": str(samples), "classes": "4",
             "rate": str(rate)},
        )
        register_jax_model("kws_t", fn, params, in_spec, out_spec)
        try:
            pipe = parse_pipeline(
                f"audiofilesrc location={path} samples-per-buffer={samples} ! "
                "tensor_converter ! tensor_filter framework=jax-xla "
                "model=kws_t ! tensor_sink name=out"
            )
            pipe.start()
            pipe.wait(timeout=120)
            frames = pipe["out"].frames
            pipe.stop()
            assert len(frames) == rate // samples  # 4 clips
            for f in frames:
                logits = np.asarray(f.tensors[0])
                assert logits.shape == (4,) and np.isfinite(logits).all()
        finally:
            unregister_jax_model("kws_t")

    def test_frontend_is_traced_not_host(self):
        """The mel front-end must live inside the jitted program (no host
        numpy on the data path) — jit with tracers would fail otherwise."""
        import jax

        fn, params, _, _ = build(
            "kws_cnn", {"dtype": "float32", "samples": "2000", "classes": "3"}
        )
        jf = jax.jit(lambda p, x: fn(p, [x])[0])
        out = jf(params, np.zeros((2000, 1), np.int16))
        assert out.shape == (3,)
