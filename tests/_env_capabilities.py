"""Environment-capability probes for explicit skipif guards.

The tier-1 suite must report REAL regressions only: tests whose failure
is a property of the environment (jax version capabilities, the
reference checkout, real devices) carry explicit ``skipif`` guards built
from these probes instead of failing forever.  Every probe is cheap,
cached, and names the genuine capability the test needs — a newer jax /
a mounted reference tree flips the guard off with no code change.
"""

import functools
import os

#: the reference NNStreamer checkout (prop-parity audit, reference
#: .tflite test models) — absent on CI boxes without the mount
REFERENCE_TREE = "/root/reference"


@functools.lru_cache(maxsize=None)
def has_reference_tree() -> bool:
    return os.path.isdir(REFERENCE_TREE)


@functools.lru_cache(maxsize=None)
def spmd_stack_ok() -> bool:
    """PROBE-AND-RUN: True when a tiny shard_map program actually runs
    on this process's multi-device CPU mesh through the repo's own
    compat shim (``parallel.ring_attention.shard_map_compat`` maps the
    strictness knob to ``check_vma``/``check_rep``/nothing per jax
    generation, and ``vary_over`` degrades to the identity pre-vma).
    The old guard keyed on jax-0.8-era API names (check_vma/pvary) and
    skipped the whole manual-SPMD suite on any older jax even though
    the stack runs there — now the capability is the EXECUTION, so the
    suite runs wherever >= 2 devices exist and the shim holds."""
    import jax

    try:
        if len(jax.devices()) < 2:
            return False  # a mesh program needs a mesh
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from nnstreamer_tpu.parallel.mesh import make_mesh
        from nnstreamer_tpu.parallel.ring_attention import (
            shard_map_compat,
            vary_over,
        )

        mesh = make_mesh({"sp": 2}, devices=jax.devices()[:2])

        def body(x):
            acc = vary_over(jnp.zeros(x.shape, x.dtype), ("sp",))
            rolled = jax.lax.ppermute(x, "sp", [(0, 1), (1, 0)])
            return acc + x + rolled

        fn = shard_map_compat(
            body, mesh, in_specs=(P("sp"),), out_specs=P("sp"))
        out = fn(jnp.arange(4, dtype=jnp.float32))
        return float(out.sum()) == 12.0
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def multihost_cpu_ok() -> bool:
    """PROBE-AND-RUN: True when this box can actually host a localhost
    multi-process "multi-host" gang.  The old guard keyed on
    ``jax_num_cpu_devices`` existing; ``parallel.multihost.initialize``
    now falls back to ``XLA_FLAGS=--xla_force_host_platform_device_
    count`` (workers are FRESH processes, so the flag lands before
    their backend initializes) and selects the gloo CPU collectives, so
    the jax version no longer gates these tests.  What still does is
    the HARDWARE: a 2-4 process gang, each with 4 virtual devices,
    starves gloo barriers into timeouts on a single-core box under
    tier-1 load — the one genuine "needs a real multi-host runtime"
    residue, probed as cores >= 2."""
    import jax

    try:
        return hasattr(jax, "distributed") and (os.cpu_count() or 1) >= 2
    except Exception:
        return False
