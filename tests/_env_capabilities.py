"""Environment-capability probes for explicit skipif guards.

The tier-1 suite must report REAL regressions only: tests whose failure
is a property of the environment (jax version capabilities, the
reference checkout, real devices) carry explicit ``skipif`` guards built
from these probes instead of failing forever.  Every probe is cheap,
cached, and names the genuine capability the test needs — a newer jax /
a mounted reference tree flips the guard off with no code change.
"""

import functools
import os

#: the reference NNStreamer checkout (prop-parity audit, reference
#: .tflite test models) — absent on CI boxes without the mount
REFERENCE_TREE = "/root/reference"


@functools.lru_cache(maxsize=None)
def has_reference_tree() -> bool:
    return os.path.isdir(REFERENCE_TREE)


@functools.lru_cache(maxsize=None)
def spmd_stack_ok() -> bool:
    """True when jax carries the shard_map feature set the manual-SPMD
    stack (ring/flash attention on a mesh, pipeline-parallel transformer)
    is written against: ``check_vma``/varying-manual-axes handling
    (``jax.lax.pvary``) and the pallas_call replication rule that ships
    with it.  jax 0.4.x lacks all three — the kernels still run
    single-device (interpret mode), but any shard_map-wrapped use
    fails with version errors, not correctness ones."""
    import inspect

    import jax

    try:
        try:
            from jax import shard_map  # newer spelling
        except ImportError:
            from jax.experimental.shard_map import shard_map
        return (
            hasattr(jax.lax, "pvary")
            and "check_vma" in inspect.signature(shard_map).parameters
        )
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def multihost_cpu_ok() -> bool:
    """True when jax supports per-process virtual CPU device counts
    (``jax_num_cpu_devices``), which the localhost multi-process
    "multi-host" tests need to build their 2x4 hybrid mesh."""
    import jax

    try:
        return hasattr(jax.config, "jax_num_cpu_devices")
    except Exception:
        return False
