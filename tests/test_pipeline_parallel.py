"""5-axis (dp/tp/sp/pp/ep) manual-SPMD transformer training step.

Runs on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8).  Correctness oracle: the
unsharded reference_loss over the same param pytree.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import _env_capabilities

pytestmark = pytest.mark.skipif(
    not _env_capabilities.spmd_stack_ok(),
    reason="jax lacks the shard_map feature set (check_vma/pvary) the "
    "5-axis manual-SPMD transformer needs",
)

from nnstreamer_tpu.parallel.mesh import make_mesh
from nnstreamer_tpu.parallel.pipeline_transformer import (
    PipelineConfig,
    init_params,
    make_pipeline_train_step,
    reference_loss,
)


def _tokens(cfg, batch, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, cfg.vocab, (batch, cfg.max_seq)), jnp.int32
    )


def _run_and_compare(mesh_axes, cfg, batch):
    import math

    n = math.prod(mesh_axes.values())
    mesh = make_mesh(mesh_axes, devices=jax.devices()[:n])
    step, params, opt, data_sh = make_pipeline_train_step(mesh, cfg)
    toks = jax.device_put(_tokens(cfg, batch), data_sh)
    p2, opt2, loss = step(params, opt, toks)
    ref = reference_loss(init_params(cfg), _tokens(cfg, batch), cfg)
    np.testing.assert_allclose(float(loss), float(ref), rtol=2e-4)
    # second step must also run (exercises donated buffers + updated params)
    _, _, loss2 = step(p2, opt2, toks)
    assert np.isfinite(float(loss2))
    return float(loss), float(loss2)


class TestPipelineParallel:
    @pytest.mark.slow  # tier-1 budget: ~22s compile-bound axis combo;
    # test_moe_capacity_drop_runs keeps the pp train-step compile in tier-1
    def test_pp_sp_tp(self):
        cfg = PipelineConfig(n_layers=2, n_experts=0, n_microbatches=2)
        l1, l2 = _run_and_compare(
            {"dp": 1, "pp": 2, "sp": 2, "tp": 2, "ep": 1}, cfg, batch=4
        )
        assert l2 < l1  # one adamw step reduces loss on the same batch

    @pytest.mark.slow  # tier-1 budget: ~19s compile-bound axis combo vs the
    # same loss oracle; tier-1 keeps the cheaper capacity-drop moe compile
    def test_dp_pp_ep_moe(self):
        # capacity_factor high enough that no token drops => exact oracle
        cfg = PipelineConfig(
            n_layers=2, n_experts=4, n_microbatches=2, capacity_factor=8.0
        )
        _run_and_compare(
            {"dp": 2, "pp": 2, "sp": 1, "tp": 1, "ep": 2}, cfg, batch=4
        )

    @pytest.mark.slow  # tier-1 budget: ~19s; degenerate all-1 mesh of the
    # same oracle comparison — pure compile cost, no extra coverage vs above
    def test_all_axes_single_device(self):
        cfg = PipelineConfig(n_layers=2, n_experts=2, n_microbatches=2,
                             capacity_factor=8.0)
        _run_and_compare(
            {"dp": 1, "pp": 1, "sp": 1, "tp": 1, "ep": 1}, cfg, batch=2
        )

    def test_moe_capacity_drop_runs(self):
        # tight capacity: tokens drop (not oracle-exact) but must stay finite
        cfg = PipelineConfig(n_layers=2, n_experts=4, n_microbatches=1,
                             capacity_factor=1.0)
        mesh = make_mesh({"dp": 1, "pp": 2, "sp": 2, "tp": 1, "ep": 2})
        step, params, opt, data_sh = make_pipeline_train_step(mesh, cfg)
        toks = jax.device_put(_tokens(cfg, 2), data_sh)
        _, _, loss = step(params, opt, toks)
        assert np.isfinite(float(loss))


class TestElasticResume:
    @pytest.mark.slow  # tier-1 budget: ~42s (two full train-step compiles);
    # serving-side resume bit-parity stays tier-1 via test_prefix_cache
    # cache_cold_resume + the chaos rolling-restart smokes
    def test_resume_is_bit_identical(self, tmp_path):
        """Preemption recovery: save after step 2, restore into a FRESH
        train step on the same mesh, continue — losses must match the
        uninterrupted run exactly."""
        from nnstreamer_tpu.parallel.mesh import make_mesh
        from nnstreamer_tpu.parallel.pipeline_transformer import (
            PipelineConfig,
            make_pipeline_train_step,
            restore_train_state,
            save_train_state,
        )

        cfg = PipelineConfig(
            vocab=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
            n_experts=2, max_seq=16, n_microbatches=2, dtype=jnp.float32,
        )
        mesh = make_mesh({"dp": 2, "pp": 2, "sp": 2, "tp": 1, "ep": 1})
        step, params, opt, data_sh = make_pipeline_train_step(mesh, cfg)
        batch = 2 * 2 * cfg.n_microbatches
        toks = [
            jax.device_put(
                jax.random.randint(
                    jax.random.PRNGKey(i), (batch, cfg.max_seq), 0, cfg.vocab
                ),
                data_sh,
            )
            for i in range(3)
        ]

        # uninterrupted run (the step donates its inputs, so this consumes
        # params/opt — the interrupted run rebuilds identical state from
        # the deterministic seed)
        p, o = params, opt
        losses = []
        for t in toks:
            p, o, loss = step(p, o, t)
            losses.append(float(loss))

        # interrupted run: 2 steps, checkpoint, fresh state, restore, step 3
        step_b, p, o, _ = make_pipeline_train_step(mesh, cfg)
        for t in toks[:2]:
            p, o, _ = step_b(p, o, t)
        save_train_state(str(tmp_path / "ck"), 2, p, o)

        step2, p_t, o_t, _ = make_pipeline_train_step(mesh, cfg)
        p_r, o_r = restore_train_state(str(tmp_path / "ck"), 2, p_t, o_t)
        _, _, loss3 = step2(p_r, o_r, toks[2])
        assert float(loss3) == losses[2]  # bit-identical resume

        # restored leaves carry their mesh shardings
        leaf = jax.tree_util.tree_leaves(p_r)[0]
        assert leaf.sharding.mesh.shape == mesh.shape
