"""Chaos/soak: sustained streaming through broker death, gRPC peer death,
and live model hot-reload.

The reference inherits this robustness from GStreamer's maturity (its
elements survive peer restarts because paho/gRPC reconnect underneath);
here the framework must prove the same end-to-end: frames keep flowing
across every injected failure, outputs switch cleanly on reload, both
pipelines reach EOS, the publisher ends with zero unacked QoS-1 messages,
and no worker threads or native buffers leak.

Failure injections (one continuous run each):
  * MQTT broker kill + rebind on the same port mid-stream
    (≙ gst/mqtt reconnect contract)
  * model hot-reload while frames are in flight
    (≙ tensor_filter RELOAD_MODEL, tests/nnstreamer_filter_reload)
  * gRPC server pipeline kill + restart mid-stream
    (≙ grpc element reconnect, nnstreamer_grpc_common.cc)
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.backends.jax_xla import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.distributed.mqtt import MiniBroker
from nnstreamer_tpu.pipeline import parse_pipeline


def _alive_threads():
    return {t.ident for t in threading.enumerate() if t.is_alive()}


def _restart_broker(port, timeout=8.0):
    deadline = time.time() + timeout
    while True:
        try:
            return MiniBroker(port=port, retransmit_s=0.2)
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)


@pytest.fixture
def chaos_models():
    def scale(w):
        def fn(params, xs):
            return [xs[0] * params["w"]]

        return fn

    register_jax_model("chaos_m1", scale(2.0), {"w": np.float32(2.0)})
    register_jax_model("chaos_m2", scale(3.0), {"w": np.float32(3.0)})
    yield
    unregister_jax_model("chaos_m1")
    unregister_jax_model("chaos_m2")


class TestChaosSoak:
    def test_stream_survives_broker_death_and_model_reload(
        self, chaos_models
    ):
        """One continuous load: push frames at a steady rate while the
        broker is killed+rebound and the model is hot-reloaded; assert
        per-frame continuity (every pushed index arrives, correct value
        for whichever model weight was live) and clean shutdown."""
        from nnstreamer_tpu.core.buffer import CustomEvent

        baseline_threads = _alive_threads()
        b1 = MiniBroker(retransmit_s=0.2)
        port = b1.port

        rx = parse_pipeline(
            f"mqttsrc host=127.0.0.1 port={port} sub-topic=chaos/t "
            "client-id=chaos-rx clean-session=false qos=1 "
            "sub-timeout=20000 ! tensor_sink name=out"
        )
        rx.start()
        tx = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter name=f framework=jax-xla model=chaos_m1 "
            "is-updatable=true ! "
            f"mqttsink name=snk host=127.0.0.1 port={port} "
            "pub-topic=chaos/t qos=1 client-id=chaos-tx"
        )
        tx.start()
        # event-driven readiness: the broker REPORTS the live subscription
        # (no sleep margin to outrun on a loaded box)
        assert b1.wait_subscriber("chaos/t", 10), "subscription never landed"

        # event-driven delivery tracking: the sink wakes this when every
        # DISTINCT frame index has arrived (QoS-1 duplicates are legal and
        # must not satisfy the count early)
        all_delivered = threading.Event()
        n_total = 60
        seen_idx = set()

        def _on_frame(f):
            if f.pts is not None:
                seen_idx.add(int(round(f.pts)))
            if len(seen_idx) >= n_total:
                all_delivered.set()

        rx["out"].connect_new_data(_on_frame)

        reload_at = 40  # model switch point (weight 2.0 -> 3.0)
        broker = b1
        try:
            for i in range(n_total):
                if i == 20:
                    broker.close()  # chaos: broker dies under load
                if i == 28:
                    broker = _restart_broker(port)
                if i == reload_at:
                    # chaos: live weight swap while frames are in flight;
                    # barrier first so in-flight frames finish under m1
                    # and the value contract below stays exact
                    deadline = time.time() + 10
                    while (len(rx["out"].frames) < reload_at
                           and time.time() < deadline):
                        time.sleep(0.05)
                    tx["src"].push_event(
                        CustomEvent("reload-model", {"model": "chaos_m2"})
                    )
                    # the reload now STAGES chaos_m2 on a second backend
                    # (validate + JIT warmup off the hot path) and the
                    # swap lands at the next frame boundary — barrier on
                    # staging completing, so frame 40's invoke applies
                    # the swap first and the value contract stays exact
                    def _staged():
                        h = tx.health()["f"]
                        return (h.get("swap_state") == "staged"
                                or h["swaps"] >= 1)

                    deadline = time.time() + 15
                    while not _staged() and time.time() < deadline:
                        time.sleep(0.05)
                    assert _staged(), tx.health()["f"]
                tx["src"].push(np.full((4,), float(i), np.float32),
                               pts=float(i))
                time.sleep(0.02)  # ~50 fps sustained

            tx["src"].end_of_stream()
            tx.wait(timeout=60)
            # publisher must end clean: all QoS-1 publishes acknowledged.
            # Every wait here is event-driven (returns the instant the
            # condition lands); the bounds are pathology caps only, so
            # generous values cost nothing on success and cannot flake a
            # loaded box (the 41419f3 lesson)
            if tx["snk"]._client is not None:
                assert tx["snk"]._client.drain(20.0) == 0
            tx.stop()

            all_delivered.wait(timeout=40)
            frames = list(rx["out"].frames)
            rx.stop()
        finally:
            broker.close()

        # continuity: every frame index arrived at least once (QoS 1 =
        # at-least-once; duplicates legal, loss not), each with the value
        # of the model that was live when it was pushed
        by_idx = {}
        for f in frames:
            arr = np.asarray(f.tensors[0])
            idx = int(round(f.pts)) if f.pts is not None else -1
            by_idx.setdefault(idx, arr)
        missing = [i for i in range(n_total) if i not in by_idx]
        assert not missing, f"lost frames: {missing}"
        for i, arr in by_idx.items():
            w = 2.0 if i < reload_at else 3.0
            np.testing.assert_allclose(arr, np.full((4,), i * w), rtol=1e-5)

        # no leaked workers: thread population returns to baseline
        # (early-exit poll; the cap is a pathology bound, not a margin)
        deadline = time.time() + 30
        while time.time() < deadline:
            leaked = [
                t for t in threading.enumerate()
                if t.is_alive() and t.ident not in baseline_threads
            ]
            if not leaked:
                break
            time.sleep(0.2)
        assert not leaked, f"leaked: {[(t.name, t.daemon) for t in leaked]}"

    def test_stream_survives_grpc_server_restart_under_load(self):
        """gRPC leg: client-side sink streams into a server-side src
        pipeline; the server pipeline is killed and a fresh one bound on
        the same port mid-stream.  The client reconnects and the stream
        completes; both servers' frames decode cleanly."""
        rx1 = parse_pipeline(
            "tensor_src_grpc name=src server=true port=0 num-buffers=-1 "
            "timeout=4000 ! tensor_sink name=out"
        )
        rx1.start()
        port = rx1["src"].bound_port

        tx = parse_pipeline(
            f"appsrc name=a ! tensor_sink_grpc server=false port={port} "
            "retry-timeout=15"
        )
        tx.start()
        got = []
        n_total, kill_at = 40, 15
        rx2 = None
        try:
            for i in range(n_total):
                if i == kill_at:
                    # wait for phase-1 delivery, then kill the server
                    deadline = time.time() + 10
                    while (len(rx1["out"].frames) < kill_at
                           and time.time() < deadline):
                        time.sleep(0.05)
                    got.extend(rx1["out"].frames)
                    rx1.stop()
                    deadline = time.time() + 8
                    while time.time() < deadline:
                        try:
                            rx2 = parse_pipeline(
                                f"tensor_src_grpc name=src server=true "
                                f"port={port} num-buffers=-1 timeout=4000 "
                                "! tensor_sink name=out"
                            )
                            rx2.start()
                            break
                        except Exception:
                            time.sleep(0.2)
                    assert rx2 is not None
                    # no settling sleep: the sink's retry-timeout covers
                    # the reconnect window; mid-kill drops are legal and
                    # the post-restart resume is verified event-bound below
                tx["a"].push(np.full((3,), float(i), np.float32))
                time.sleep(0.02)
            deadline = time.time() + 15
            while (len(rx2["out"].frames) < 5  # post-restart flow resumed
                   and time.time() < deadline):
                time.sleep(0.1)
            tx["a"].end_of_stream()
            tx.wait(timeout=15)
            tx.stop()
            rx2.wait(timeout=10)  # idle timeout EOS
            got.extend(rx2["out"].frames)
            rx2.stop()
        finally:
            for p in (rx1, rx2):
                try:
                    if p is not None:
                        p.stop()
                except Exception:
                    pass

        # frames from before the kill and after the restart all decoded;
        # the mid-kill window may drop (gRPC has no at-least-once layer —
        # that's the MQTT leg's job) but the stream must RESUME
        vals = sorted({int(np.asarray(f.tensors[0])[0]) for f in got})
        assert vals[:kill_at] == list(range(kill_at)), "pre-kill loss"
        assert any(v >= kill_at + 5 for v in vals), "stream never resumed"

    def test_native_pool_balance_under_churn(self):
        """The native allocator stays balanced through a realistic
        acquire/release storm with concurrent churn (the leak probe the
        soak story needs: outstanding() must return to zero)."""
        rt = pytest.importorskip("nnstreamer_tpu.native.runtime")
        if not rt.available():
            pytest.skip("native core not built")
        pool = rt.BufferPool(block_size=4096, prealloc=8)
        stop = threading.Event()
        errors = []

        def churn():
            try:
                while not stop.is_set():
                    grabbed = [pool.acquire() for _ in range(16)]
                    for ptr, mv in grabbed:
                        mv[:8] = b"chaosrun"
                        pool.release(ptr)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=churn) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errors
        assert pool.outstanding == 0  # every block returned
        pool.destroy()
