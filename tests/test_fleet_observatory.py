"""Fleet observatory: discovery-plane telemetry digests, fleet-wide
rollup exactness, per-stream SLO accounting, and the chaos acceptance
(Documentation/observability.md "Fleet observatory" / "SLO accounting").

Contracts pinned here:

* DigestPublisher — fake-clock cadence, seq monotonicity, bounded
  serialized size (tenant-map truncation is loud), tokens/s EWMA.
* FleetObservatory — rollups EXACTLY equal hand-built per-server sums
  (retired servers included), TTL age-out retires stale rows, tombstones
  retire counters, duplicate/stale seqs ignored, table bounded.
* SLO burn-rate math — the met/warn/burned truth table, deterministic
  bucket-grain violation counts, availability burn.
* Engine + client accounting — classification truth (good/expired/
  evicted), fused/unfused parity of TTFT/goodput accounting.
* Trace continuity — a resumed/migrated stream keeps ONE trace id
  end-to-end (the resume request re-stamps, never re-mints) and every
  chunk's server-span decomposition sums exactly on both sides of the
  handoff.
* The chaos acceptance: rolling restart + hot-tenant burst + crash with
  exact observatory-vs-ledger cross-checks and /metrics visibility.
"""

import json
import sys
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.fleet import (
    DIGEST_MAX_BYTES,
    DIGEST_MAX_TENANTS,
    DigestPublisher,
    FleetObservatory,
    hint_from_announce,
    pipeline_digest_stats,
)
from nnstreamer_tpu.core.slots import SimSlotModel, SlotEngine
from nnstreamer_tpu.core.telemetry import (
    SRV_SPAN_META,
    TRACE_ID_META,
    Log2Histogram,
    SloTracker,
    slo_status,
)
from nnstreamer_tpu.pipeline import parse_pipeline


# ---------------------------------------------------------------------------
# Digest publisher (fake clock)
# ---------------------------------------------------------------------------
class TestDigestPublisher:
    def _pub(self, stats, interval=2.0):
        t = [0.0]
        published = []
        pub = DigestPublisher(
            lambda: stats, published.append, interval_s=interval,
            clock=lambda: t[0])
        return t, published, pub

    def test_cadence_and_seq_monotonic(self):
        stats = {"inflight": 1}
        t, published, pub = self._pub(stats)
        assert pub.poll() is not None          # first poll publishes
        assert pub.poll() is None              # inside the interval
        t[0] = 1.99
        assert pub.poll() is None
        t[0] = 2.0
        assert pub.poll() is not None
        t[0] = 2.5
        forced = pub.poll(force=True)          # force beats the interval
        assert forced is not None
        seqs = [d["seq"] for d in published]
        assert seqs == sorted(seqs) == list(range(1, len(seqs) + 1))
        assert published[-1]["age_s"] == 2.5   # publisher monotonic age
        assert pub.published == 3

    def test_tokens_per_s_ewma_from_counter_deltas(self):
        stats = {"tokens": 0}
        t, published, pub = self._pub(stats, interval=1.0)
        pub.poll()
        assert published[-1]["tokens_per_s"] == 0.0
        stats["tokens"] = 100
        t[0] = 1.0
        pub.poll()
        assert published[-1]["tokens_per_s"] == 100.0
        stats["tokens"] = 100          # stalled: rate decays toward 0
        t[0] = 2.0
        pub.poll()
        assert 0.0 < published[-1]["tokens_per_s"] < 100.0

    def test_bounded_tenant_map_is_loud(self):
        stats = {"tenants": {
            f"t{i}": {"admitted": i, "shed": 0} for i in range(40)
        }}
        _, published, pub = self._pub(stats)
        d = pub.poll()
        assert len(d["tenants"]) == DIGEST_MAX_TENANTS
        assert d["tenants_dropped"] == 40 - DIGEST_MAX_TENANTS
        # busiest tenants won the bound
        assert "t39" in d["tenants"] and "t0" not in d["tenants"]

    def test_oversize_digest_truncates_loudly(self):
        stats = {"tenants": {
            ("x" * 400 + str(i)): {"admitted": 1, "shed": 0}
            for i in range(12)
        }}
        _, _, pub = self._pub(stats)
        d = pub.poll()
        assert len(json.dumps(d)) <= DIGEST_MAX_BYTES
        assert d.get("truncated") is True
        assert "tenants" not in d

    def test_publish_failure_counted_never_raises(self):
        t = [0.0]

        def boom(d):
            raise OSError("broker gone")

        pub = DigestPublisher(lambda: {}, boom, interval_s=1.0,
                              clock=lambda: t[0])
        assert pub.poll() is None
        assert pub.publish_failures == 1


# ---------------------------------------------------------------------------
# Observatory rollup exactness (hand-built tables)
# ---------------------------------------------------------------------------
def _digest(seq=1, ttl=10.0, **kw):
    d = {"v": 1, "seq": seq, "age_s": 0.0, "interval_s": 1.0,
         "ttl_s": ttl, "draining": False, "degraded": False,
         "swap": "idle", "inflight": 0, "admitted": 0, "shed": 0,
         "tokens_per_s": 0.0}
    d.update(kw)
    return d


def _announce(digest, host="h", port=1):
    return {"host": host, "port": port, "digest": digest}


class TestObservatoryRollups:
    def test_rollup_exactly_equals_hand_built_table(self):
        t = [0.0]
        obs = FleetObservatory(topic="x", clock=lambda: t[0])
        obs.ingest("a", _announce(_digest(
            seq=3, inflight=2, admitted=10, shed=1, tokens=100,
            slots=4, occupied=3, waiting=1, tokens_per_s=50.0,
            mem_headroom_bytes=1000,
            tenants={"A": {"admitted": 6, "shed": 1}}), port=1))
        obs.ingest("b", _announce(_digest(
            seq=7, inflight=1, admitted=20, shed=2, tokens=200,
            slots=4, occupied=1, tokens_per_s=25.0, draining=True,
            mem_headroom_bytes=500, slo_burn={"A": 1.5},
            tenants={"A": {"admitted": 15, "shed": 2},
                     "B": {"admitted": 5, "shed": 0}}), port=2))
        # memory-pressured server: its free slots are NOT admittable
        obs.ingest("c", _announce(_digest(
            seq=1, admitted=5, shed=0, tokens=50, slots=4, occupied=1,
            mem_pressure=1, degraded=True, swap="staging",
            slo_burn={"A": 0.5, "B": 2.5}), port=3))
        r = obs.rollup()
        assert r["servers"] == 3
        assert r["draining"] == 1 and r["degraded"] == 1
        assert r["swapping"] == 1 and r["mem_pressured"] == 1
        assert r["inflight"] == 3
        assert r["slots"] == 12 and r["occupied"] == 5
        assert r["occupancy"] == round(5 / 12, 4)
        assert r["tokens_per_s"] == 75.0
        # a (4-3) + b (4-1) admittable; c pressured -> 0
        assert r["slot_headroom"] == 1 + 3
        assert r["mem_headroom_bytes"] == 1500
        assert r["tokens"] == 350
        assert r["admitted"] == 35 and r["shed"] == 3
        assert r["tenants"] == {
            "A": {"admitted": 21, "shed": 3},
            "B": {"admitted": 5, "shed": 0},
        }
        # worst burn per tenant across live servers
        assert r["slo_burn"] == {"A": 1.5, "B": 2.5}
        assert r["servers_seen"] == 3 and r["digests"] == 3

    def test_ttl_age_out_retires_counters_exactly(self):
        t = [0.0]
        obs = FleetObservatory(topic="x", clock=lambda: t[0])
        obs.ingest("a", _announce(_digest(
            seq=1, ttl=5.0, tokens=100, admitted=7, shed=2,
            tenants={"A": {"admitted": 7, "shed": 2}})))
        t[0] = 4.9
        assert obs.rollup()["servers"] == 1
        t[0] = 5.1
        r = obs.rollup()
        assert r["servers"] == 0
        assert r["stale_evicted"] == 1 and r["retired"] == 0
        # the stale row's counters RETIRED, not lost (exactness across
        # crashes that never tombstone their announce)
        assert r["tokens"] == 100 and r["admitted"] == 7
        assert r["tenants"] == {"A": {"admitted": 7, "shed": 2}}

    def test_tombstone_retires_and_restart_reaccumulates(self):
        t = [0.0]
        obs = FleetObservatory(topic="x", clock=lambda: t[0])
        obs.ingest("a", _announce(_digest(seq=5, tokens=100,
                                          admitted=10)))
        obs.note_tombstone("a")
        r = obs.rollup()
        assert r["servers"] == 0 and r["retired"] == 1
        assert r["tokens"] == 100
        # the restarted instance (new topic) counts from zero — totals
        # keep both generations
        obs.ingest("a2", _announce(_digest(seq=1, tokens=30, admitted=3)))
        r = obs.rollup()
        assert r["tokens"] == 130 and r["admitted"] == 13
        assert r["servers_seen"] == 2

    def test_duplicate_and_stale_seq_ignored(self):
        t = [0.0]
        obs = FleetObservatory(topic="x", clock=lambda: t[0])
        assert obs.ingest("a", _announce(_digest(seq=3, tokens=10)))
        assert not obs.ingest("a", _announce(_digest(seq=3, tokens=99)))
        assert not obs.ingest("a", _announce(_digest(seq=2, tokens=99)))
        assert obs.rollup()["tokens"] == 10
        assert obs.ingest("a", _announce(_digest(seq=4, tokens=11)))
        assert obs.rollup()["tokens"] == 11

    def test_non_digest_and_foreign_version_announces_skipped(self):
        obs = FleetObservatory(topic="x")
        assert not obs.ingest("a", {"host": "h", "port": 1})
        assert not obs.ingest("a", _announce({"v": 99, "seq": 1}))
        assert obs.rollup()["servers"] == 0

    def test_table_bound_retires_oldest(self):
        t = [0.0]
        obs = FleetObservatory(topic="x", max_servers=3,
                               clock=lambda: t[0])
        for i in range(5):
            t[0] = float(i)
            obs.ingest(f"s{i}", _announce(
                _digest(seq=1, ttl=100.0, tokens=1), port=i))
        r = obs.rollup()
        assert r["servers"] == 3
        assert r["stale_evicted"] == 2
        assert r["tokens"] == 5  # evicted rows retired, not lost

    def test_resurrected_row_never_double_counts(self):
        """A row TTL-evicted while its server was merely slow, then
        re-ingested from the SAME instance topic, must reverse its
        retired contribution — cumulative counters may count once."""
        t = [0.0]
        obs = FleetObservatory(topic="x", clock=lambda: t[0])
        obs.ingest("a", _announce(_digest(
            seq=1, ttl=5.0, tokens=100, admitted=7, shed=1,
            tenants={"A": {"admitted": 7, "shed": 1}})))
        t[0] = 6.0  # transient staleness: evicted + retired
        assert obs.rollup()["stale_evicted"] == 1
        # the same instance comes back with HIGHER cumulative counters
        obs.ingest("a", _announce(_digest(
            seq=2, ttl=5.0, tokens=140, admitted=9, shed=1,
            tenants={"A": {"admitted": 9, "shed": 1}})))
        r = obs.rollup()
        assert r["servers"] == 1
        assert r["tokens"] == 140          # once, not 100 + 140
        assert r["admitted"] == 9
        assert r["tenants"] == {"A": {"admitted": 9, "shed": 1}}
        assert r["servers_seen"] == 1      # same instance, not a new one
        assert obs.resurrected == 1
        # a LATER eviction retires the fresh counters exactly once
        t[0] = 12.0
        r = obs.rollup()
        assert r["servers"] == 0 and r["tokens"] == 140

    def test_empty_topic_subscribes_to_every_announce(self):
        """FleetObservatory(topic=\"\") must see servers announcing
        under ANY topic (MQTT matches level-by-level: the pattern has
        to be nns/query/#, never nns/query//#)."""
        from nnstreamer_tpu.distributed.mqtt import MiniBroker, MqttClient

        broker = MiniBroker()
        obs = FleetObservatory(topic="", default_ttl_s=30.0)
        obs.start("127.0.0.1", broker.port)
        pub = MqttClient("127.0.0.1", broker.port)
        try:
            pub.publish(
                "nns/query/prod/inst1",
                json.dumps(_announce(_digest(seq=1, tokens=5))).encode(),
                retain=True, qos=1)
            deadline = time.monotonic() + 10
            while (obs.rollup()["servers"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert obs.rollup()["servers"] == 1
        finally:
            pub.close()
            obs.stop()
            broker.close()

    def test_hint_unification_digest_wins_legacy_accepted(self):
        # digest fields are the ONE capture path when present...
        info = _announce(_digest(draining=True, degraded=False))
        info.update(draining=False, degraded=True)  # stale legacy keys
        assert hint_from_announce(info) == {
            "draining": True, "degraded": False}
        # ...and pre-digest announces (mixed fleets) keep working
        assert hint_from_announce(
            {"host": "h", "port": 1, "draining": True}) == {
            "draining": True, "degraded": False}


# ---------------------------------------------------------------------------
# SLO burn-rate math
# ---------------------------------------------------------------------------
class TestSloMath:
    def test_status_truth_table(self):
        assert slo_status(None) == "met"
        assert slo_status(0.0) == "met"
        assert slo_status(1.0) == "met"      # exactly on budget
        assert slo_status(1.001) == "warn"
        assert slo_status(1.999) == "warn"
        assert slo_status(2.0) == "burned"
        assert slo_status(50.0) == "burned"

    def test_count_over_is_bucket_deterministic(self):
        h = Log2Histogram()
        for v in (0.001, 0.002, 0.004, 0.1, 1.0):
            h.record(v)
        assert h.count_over(0.01) == 2     # 0.1 and 1.0
        assert h.count_over(10.0) == 0
        assert h.count_over(1e-9) == 5

    def test_ttft_burn_met_warn_burned(self):
        # objective: p95 under 0.1s -> 5% violation budget
        slo = SloTracker(ttft_p95_s=0.1)
        for _ in range(99):
            slo.note_ttft("t", 0.01)
        slo.note_ttft("t", 1.0)            # 1% over -> burn 0.2: met
        snap = slo.snapshot()["t"]
        assert snap["ttft_burn"] == pytest.approx(0.2)
        assert snap["status"] == 0
        for _ in range(4):
            slo.note_ttft("t", 1.0)        # 5/104 over -> burn ~0.96
        assert slo.snapshot()["t"]["status"] == 0
        for _ in range(8):
            slo.note_ttft("t", 1.0)        # 13/112 over -> burn ~2.3
        snap = slo.snapshot()["t"]
        assert snap["ttft_burn"] > 2.0
        assert snap["status"] == 2
        # warn band: between 1x and 2x the budget
        slo2 = SloTracker(ttft_p95_s=0.1)
        for _ in range(93):
            slo2.note_ttft("t", 0.01)
        for _ in range(7):
            slo2.note_ttft("t", 1.0)       # 7% over -> burn 1.4
        snap2 = slo2.snapshot()["t"]
        assert 1.0 < snap2["ttft_burn"] < 2.0
        assert snap2["status"] == 1

    def test_availability_burn_and_goodput_classification(self):
        slo = SloTracker(availability=0.99)
        for _ in range(98):
            slo.note_stream("t", "good")
        slo.note_stream("t", "shed")
        slo.note_stream("t", "expired")
        snap = slo.snapshot()["t"]
        assert snap["good"] == 98 and snap["shed"] == 1
        assert snap["expired"] == 1
        assert snap["availability"] == pytest.approx(0.98)
        assert snap["availability_burn"] == pytest.approx(2.0)
        assert snap["status"] == 2

    def test_unarmed_objectives_never_burn(self):
        slo = SloTracker()
        assert not slo.armed
        slo2 = SloTracker(token_p99_s=0.01)
        slo2.note_stream("t", "error")     # availability NOT armed
        snap = slo2.snapshot()["t"]
        assert "availability_burn" not in snap
        assert snap["status"] == 0         # no armed objective violated

    def test_invalid_availability_objective_refused(self):
        with pytest.raises(ValueError):
            SloTracker(availability=1.0)
        with pytest.raises(ValueError):
            SloTracker(availability=-0.1)

    def test_token_record_n_bulk_counts(self):
        slo = SloTracker(token_p99_s=0.01)
        slo.note_tokens("t", 0.8, 8)       # 8 tokens at 100ms each
        slo.note_tokens("t", 0.008, 8)     # 8 tokens at 1ms each
        snap = slo.snapshot()["t"]
        # 8/16 over the 10ms bound -> burn 50x, and counts are exact
        assert snap["token_burn"] == pytest.approx(50.0)
        row_counts = {
            name: h.count for name, h, lbl in slo.hist_rows()
        }
        assert row_counts["nns.slo.token_seconds"] == 16


# ---------------------------------------------------------------------------
# Engine-side accounting: classification truth + fused/unfused parity
# ---------------------------------------------------------------------------
class TestEngineSloAccounting:
    def test_engine_classification_good_expired_evicted(self):
        slo = SloTracker(ttft_p95_s=10.0, availability=0.5)
        eng = SlotEngine(SimSlotModel(2, vocab=97, step_base_ms=0.2),
                         None, max_seq=1 << 20, chunk=4, slo=slo)
        eng.start()
        try:
            prompt = np.arange(4, dtype=np.int32)[None]
            # good: completes
            eng.submit(TensorFrame([prompt]), prompt, 8, 4, tenant="A")
            deadline = time.monotonic() + 20
            done = []
            while time.monotonic() < deadline:
                done.extend(f for _, f in eng.pop_ready())
                if done and done[-1].meta.get("final"):
                    break
                time.sleep(0.002)
            assert done and done[-1].meta.get("final")
            # expired: deadline already blown at submit
            eng.submit(TensorFrame([prompt]), prompt, 8, 4, tenant="A",
                       deadline_ts=time.monotonic() - 1.0)
            deadline = time.monotonic() + 10
            while (eng.snapshot()["gen_evicted"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.002)
            # evicted: consumer gone
            s = eng.submit(TensorFrame([prompt]), prompt, 64, 4,
                           tenant="A")
            eng.cancel(sid=s.sid)
            deadline = time.monotonic() + 10
            while (eng.snapshot()["gen_cancelled"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.002)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                row = slo.snapshot().get("A", {})
                if (row.get("good") == 1 and row.get("expired") == 1
                        and row.get("evicted") == 1):
                    break
                time.sleep(0.01)
            row = slo.snapshot()["A"]
            assert row["good"] == 1
            assert row["expired"] == 1
            assert row["evicted"] == 1
            # TTFT recorded exactly once (the completed stream; the
            # pre-expired and cancelled ones may or may not have decoded)
            assert row["ttft_p95_ms"] > 0
        finally:
            eng.stop()

    @staticmethod
    def _run_gen_pipeline(fuse: bool, streams: int = 4,
                          max_new: int = 12):
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_generator name=gen slots=4 "
            "custom=sim:1,sim_step_ms:0.2,vocab:997 "
            f"max-new={max_new} chunk=4 "
            "slo-ttft-p95=30 slo-token-p99=5 slo-availability=0.9 ! "
            "tensor_sink name=out",
            fuse=fuse, name=f"slo-parity-{fuse}")
        pipe.start()
        try:
            for i in range(streams):
                prompt = (np.arange(4, dtype=np.int32)[None] + i) % 997
                pipe["src"].push(TensorFrame([prompt]))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                finals = sum(
                    1 for f in pipe["out"].frames
                    if f.meta.get("final"))
                if finals >= streams:
                    break
                time.sleep(0.005)
            assert finals >= streams, "streams never finished"
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            slo_row = pipe.health()["gen"]["slo"][""]
            hist_counts = {
                name: h.count
                for name, h, lbl in pipe["gen"].histograms_info()
            }
            return slo_row, hist_counts
        finally:
            pipe.stop()

    def test_ttft_goodput_parity_fused_vs_unfused(self):
        """The PR's parity satellite: identical classification counters
        and histogram OBSERVATION counts on both dataplanes (bucket
        values are timing, counts are structure)."""
        streams, max_new = 4, 12
        row_f, hists_f = self._run_gen_pipeline(True, streams, max_new)
        row_u, hists_u = self._run_gen_pipeline(False, streams, max_new)
        for row in (row_f, row_u):
            assert row["good"] == streams
            assert row["shed"] == row["evicted"] == row["expired"] == 0
            assert row["errors"] == 0
            assert row["availability"] == 1.0
            assert row["status"] == 0
        # exact observation counts: one TTFT per fresh stream, one
        # inter-arrival observation per decoded token after token 1
        assert hists_f["nns.slo.ttft_seconds"] == streams
        assert hists_f["nns.slo.token_seconds"] == streams * (max_new - 1)
        assert hists_f == hists_u
        deterministic = {
            k: v for k, v in row_f.items()
            if not k.endswith("_ms")  # quantiles are timing, not structure
        }
        assert deterministic == {
            k: v for k, v in row_u.items() if not k.endswith("_ms")}


# ---------------------------------------------------------------------------
# Trace continuity across resume/migration (satellite pin)
# ---------------------------------------------------------------------------
class TestTraceContinuity:
    def test_resume_frame_restamps_never_remints(self):
        """The RESUME request must carry the ORIGINAL stream's trace id
        — a re-mint would split one logical stream across two traces."""
        from nnstreamer_tpu.core.continuity import (
            RESUME_META,
            StreamContinuity,
            prompt_digest,
        )

        prompt = np.arange(4, dtype=np.int32)[None]
        frame = TensorFrame([prompt])
        frame.meta[TRACE_ID_META] = "trace-origin-1"
        cont = StreamContinuity(frame)
        chunk = frame.with_tensors([np.int32([[5, 6, 7, 8]])])
        chunk.meta.update(stream_seq=1, chunk_index=0, tokens_done=4,
                          final=False)
        chunk.meta[RESUME_META] = {
            "v": 1, "sig": "S", "digest": prompt_digest(prompt),
            "chunk": 4}
        cont.accept(chunk)
        resume = cont.build_resume_frame()
        assert resume.meta[TRACE_ID_META] == "trace-origin-1"

    def test_one_trace_id_and_exact_spans_across_migration(self):
        """Drain-migration e2e: every chunk the client delivers — from
        BOTH servers — carries the one original trace id, and each
        chunk's server-side span decomposition sums exactly
        (queue + dispatch + compute == total) on both sides of the
        handoff."""
        def gen_server(sid, name):
            pipe = parse_pipeline(
                f"tensor_query_serversrc name=ssrc id={sid} port=0 "
                "connect-type=tcp ! "
                "tensor_generator name=gen slots=4 "
                "custom=sim:1,sim_step_ms:3.0,vocab:997 "
                "max-new=48 chunk=4 ! "
                f"tensor_query_serversink id={sid}", name=name)
            pipe.start()
            return pipe

        s1 = gen_server(10051, "trace-s1")
        s2 = gen_server(10052, "trace-s2")
        p1 = s1["ssrc"].props["port"]
        p2 = s2["ssrc"].props["port"]
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q "
            f"connect-type=tcp hosts=localhost:{p1},localhost:{p2} "
            "stream=true timeout=60 retry-backoff=0.01 ! "
            "tensor_sink name=out", name="cli-trace")
        client.start()
        try:
            prompt = np.arange(5, dtype=np.int32)[None]
            req = TensorFrame([prompt])
            req.meta[TRACE_ID_META] = "trace-mig-7"
            client["src"].push(req)
            deadline = time.monotonic() + 30
            while (not client["out"].frames
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert client["out"].frames, "no chunk before the drain"
            res = s1.drain(timeout=15)
            assert res["dropped"] == 0
            client["src"].end_of_stream()
            client.wait(timeout=60)
            frames = list(client["out"].frames)
            assert client.health()["q"]["stream_migrations"] == 1
            # ONE trace id across the whole migrated stream
            assert all(
                f.meta.get(TRACE_ID_META) == "trace-mig-7"
                for f in frames), [f.meta.get(TRACE_ID_META)
                                   for f in frames]
            # both servers served chunks of this one trace
            assert s2.health()["gen"]["gen_resumes"] == 1
            # span decomposition sums EXACTLY per chunk, pre- and
            # post-handoff alike (the server-span additivity contract)
            spans = [f.meta.get(SRV_SPAN_META) for f in frames]
            spans = [s for s in spans if s]
            assert spans, "no server spans on delivered chunks"
            for s in spans:
                assert (s["queue"] + s["dispatch"] + s["compute"]
                        == pytest.approx(s["total"], abs=1e-9))
        finally:
            client.stop()
            s1.stop()
            s2.stop()


# ---------------------------------------------------------------------------
# Discovery-plane wiring: digests on the announce, hints, health
# ---------------------------------------------------------------------------
class TestDigestOnDiscoveryPlane:
    def test_serversrc_digests_reach_observatory_and_hints(self):
        """One server announcing with digests armed: the observatory
        ingests them (seq advances on the sweeper cadence), the client's
        endpoint hints read the digest's state fields, and
        health()/metrics expose digests_published."""
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        broker = MiniBroker()
        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=10060 connect-type=tcp "
            "topic=obstest dest-host=127.0.0.1 "
            f"dest-port={broker.port} digest-interval=0.1 ! "
            "tensor_generator name=gen slots=2 "
            "custom=sim:1,sim_step_ms:0.5,vocab:997 max-new=8 chunk=4 ! "
            "tensor_query_serversink id=10060", name="obsw-srv")
        server.start()
        obs = FleetObservatory(topic="obstest", default_ttl_s=10.0)
        obs.start("127.0.0.1", broker.port)
        client = None
        try:
            deadline = time.monotonic() + 15
            while (obs.rollup()["servers"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            rows = obs.servers()
            assert len(rows) == 1
            first_seq = rows[0]["seq"]
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                rows = obs.servers()
                if rows and rows[0]["seq"] > first_seq:
                    break
                time.sleep(0.02)
            assert rows[0]["seq"] > first_seq, "digest seq never advanced"
            assert server.health()["ssrc"]["digests_published"] >= 2
            # the client's ONE capture path reads the digest state
            client = parse_pipeline(
                "appsrc name=src ! tensor_query_client name=q "
                "connect-type=tcp topic=obstest dest-host=127.0.0.1 "
                f"dest-port={broker.port} discovery-timeout=10 ! "
                "tensor_sink name=out", name="obsw-cli")
            client.start()
            # healthy server: no hint row kept (absent = healthy)
            assert client["q"]._endpoint_hints == {}
            # a degraded DIGEST becomes a degraded hint on rediscovery
            # (the ONE capture path: the hint is read from the digest's
            # state fields, which note_degraded force-publishes)
            server["ssrc"].note_degraded("test")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                q = client["q"]
                q._last_discovery_ts = float("-inf")
                q._rediscover(q._pstate)
                if any(h.get("degraded")
                       for h in q._endpoint_hints.values()):
                    break
                time.sleep(0.05)
            assert any(h.get("degraded")
                       for h in client["q"]._endpoint_hints.values())
            # the observatory reads the same fact from the same digest
            deadline = time.monotonic() + 10
            while (obs.rollup()["degraded"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert obs.rollup()["degraded"] == 1
        finally:
            if client is not None:
                client.stop()
            server.stop()
            obs.stop()
            broker.close()

    def test_stopped_server_digest_stays_draining(self):
        """After a drain completes (_lc_state == \"stopped\") the
        pipeline's sweeper may still tick: a periodic digest must NEVER
        flip the retained announce back to draining=false while the
        listeners are closed (clients would dial a dead port)."""
        from nnstreamer_tpu.elements.query import TensorQueryServerSrc

        src = TensorQueryServerSrc("ssrc")
        for state, want in (("serving", False), ("draining", True),
                            ("stopped", True)):
            src._lc_state = state
            assert src._digest_stats()["draining"] is want, state

    def test_pipeline_digest_stats_scans_health(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_generator name=gen slots=2 "
            "custom=sim:1,sim_step_ms:0.2,vocab:997 max-new=8 chunk=4 "
            "slo-ttft-p95=10 ! tensor_sink name=out", name="pds")
        pipe.start()
        try:
            prompt = np.arange(4, dtype=np.int32)[None]
            pipe["src"].push(TensorFrame([prompt]))
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if any(f.meta.get("final") for f in pipe["out"].frames):
                    break
                time.sleep(0.005)
            stats = pipeline_digest_stats(pipe)
            assert stats["slots"] == 2
            assert stats["tokens"] == 8
            assert stats["swap"] == "idle"
            assert "slo_burn" in stats  # armed objectives surface burns
        finally:
            pipe.stop()


# ---------------------------------------------------------------------------
# fleet_top rendering (pure function of a snapshot)
# ---------------------------------------------------------------------------
def test_fleet_top_render_unit():
    from tools.fleet_top import render

    snapshot = {
        "rollup": {
            "servers": 2, "draining": 1, "degraded": 0, "retired": 1,
            "stale_evicted": 0, "tokens_per_s": 123.4,
            "occupancy": 0.5, "occupied": 4, "slots": 8,
            "slot_headroom": 4, "mem_headroom_bytes": 2 << 30,
            "inflight": 3, "tokens": 1000, "admitted": 50, "shed": 2,
            "tenants": {"A": {"admitted": 40, "shed": 1}},
            "slo_burn": {"A": 1.25},
        },
        "servers": [
            {"addr": "127.0.0.1:9000", "seq": 12, "seen_s": 0.4,
             "inflight": 2, "slots": 4, "occupied": 3,
             "tokens_per_s": 100.0, "shed": 1,
             "mem_headroom_bytes": 1 << 30},
            {"addr": "127.0.0.1:9001", "seq": 9, "seen_s": 1.0,
             "draining": True, "inflight": 1, "slots": 4,
             "occupied": 1, "tokens_per_s": 23.4, "shed": 1},
        ],
    }
    out = render(snapshot, "prod")
    assert "127.0.0.1:9000" in out and "127.0.0.1:9001" in out
    assert "draining" in out
    assert "123.4" in out           # rollup tokens/s
    assert "A: 40/1" in out         # tenant admitted/shed
    assert "A: 1.25" in out         # slo burn
    # empty fleet renders a hint, not a crash
    empty = render({"rollup": {
        "servers": 0, "draining": 0, "degraded": 0, "retired": 0,
        "stale_evicted": 0, "tokens_per_s": 0.0, "occupancy": 0.0,
        "occupied": 0, "slots": 0, "slot_headroom": 0,
        "mem_headroom_bytes": 0, "inflight": 0, "tokens": 0,
        "admitted": 0, "shed": 0, "tenants": {}, "slo_burn": {},
    }, "servers": []}, "")
    assert "no live digests" in empty


# ---------------------------------------------------------------------------
# The chaos acceptance (tier-1, chaos-marked)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_fleet_observatory_chaos_smoke():
    """The acceptance contract: under generate-mode rolling-restart and
    hot-tenant-burst (plus a tombstone-less crash), the observatory's
    fleet rollups are EXACTLY the sum of per-server ledgers including
    retired servers, digests were observed from every server, the stale
    digest was TTL-evicted, and the per-tenant SLO burn gauges are
    visible in /metrics — with zero lost streams and zero breaker
    trips."""
    from tools.chaos_fleet import run_observatory_script

    v = run_observatory_script(servers=3, streams=8)
    assert v["ok"], v
    # the contract, spelled out
    assert v["mismatched"] == 0
    assert v["crosscheck_pre_crash"]["exact"]
    assert v["crosscheck_post_crash"]["exact"]
    cc = v["crosscheck_post_crash"]
    assert cc["rollup_tokens"] == cc["ledger_tokens"]
    assert cc["rollup_tenants"] == cc["ledger_tenants"]
    assert cc["servers_seen"] == cc["server_starts"]
    assert cc["stale_evicted"] >= 1
    assert v["burst_shed_B"] > 0
    assert v["metrics_endpoint_ok"]
    assert v["rolling_restart"]["drain_dropped"] == 0
    assert v["breaker_trips"] == 0
