"""Training subsystem tests: datarepo round trip, deterministic shuffle,
index ranges, and the full in-pipeline MNIST training flow (reference
canonical config: datareposrc -> tensor_trainer, SURVEY §3.4)."""

import json
import os

import numpy as np
import pytest

from nnstreamer_tpu.pipeline import parse_pipeline


def write_dataset(tmp_path, n=20, seed=0):
    """Synthetic 'MNIST-like' set: class = brightest quadrant (learnable)."""
    rng = np.random.default_rng(seed)
    data_path = str(tmp_path / "data.bin")
    json_path = str(tmp_path / "data.json")
    pipe = parse_pipeline(
        f"appsrc name=src ! datareposink location={data_path} json={json_path}"
    )
    pipe.start()
    for i in range(n):
        label = i % 4
        img = rng.normal(0.2, 0.05, (28, 28, 1)).astype(np.float32)
        qy, qx = divmod(label, 2)
        img[qy * 14 : (qy + 1) * 14, qx * 14 : (qx + 1) * 14] += 0.8
        pipe["src"].push([img, np.int64([label])])
    pipe["src"].end_of_stream()
    pipe.wait(timeout=15)
    pipe.stop()
    return data_path, json_path


class TestDataRepo:
    def test_roundtrip(self, tmp_path):
        data, meta = write_dataset(tmp_path, n=6)
        m = json.load(open(meta))
        assert m["total_samples"] == 6
        assert m["tensors"][0].startswith("float32")
        pipe = parse_pipeline(
            f"datareposrc location={data} json={meta} ! tensor_sink name=out"
        )
        pipe.run(timeout=15)
        assert len(pipe["out"].frames) == 6
        f = pipe["out"].frames[0]
        assert f.tensors[0].shape == (28, 28, 1)
        assert f.tensors[1].shape == (1,)

    def test_index_range_and_epochs(self, tmp_path):
        data, meta = write_dataset(tmp_path, n=10)
        pipe = parse_pipeline(
            f"datareposrc location={data} json={meta} start-sample-index=2 "
            "stop-sample-index=4 epochs=3 ! tensor_sink name=out"
        )
        pipe.run(timeout=15)
        frames = pipe["out"].frames
        assert len(frames) == 9  # 3 samples × 3 epochs
        assert [f.meta["sample_index"] for f in frames[:3]] == [2, 3, 4]

    def test_shuffle_deterministic(self, tmp_path):
        data, meta = write_dataset(tmp_path, n=8)
        orders = []
        for _ in range(2):
            pipe = parse_pipeline(
                f"datareposrc location={data} json={meta} is-shuffle=true "
                "shuffle-seed=42 ! tensor_sink name=out"
            )
            pipe.run(timeout=15)
            orders.append([f.meta["sample_index"] for f in pipe["out"].frames])
        assert orders[0] == orders[1]  # resume-deterministic
        assert orders[0] != sorted(orders[0])  # actually shuffled

    def test_tensors_sequence_reorder(self, tmp_path):
        data, meta = write_dataset(tmp_path, n=2)
        pipe = parse_pipeline(
            f"datareposrc location={data} json={meta} tensors-sequence=1,0 ! "
            "tensor_sink name=out"
        )
        pipe.run(timeout=15)
        f = pipe["out"].frames[0]
        assert f.tensors[0].shape == (1,)  # label first now

    def test_missing_meta_n(self, tmp_path):
        pipe = parse_pipeline(
            f"datareposrc location={tmp_path}/none.bin json={tmp_path}/none.json ! "
            "tensor_sink name=out"
        )
        with pytest.raises(Exception):
            pipe.start()
        pipe.stop()


class TestTrainerPipeline:
    def test_mnist_cnn_trains(self, tmp_path):
        n_train, n_valid, epochs = 16, 4, 3
        data, meta = write_dataset(tmp_path, n=n_train + n_valid)
        cfg = {
            "arch": "mnist_cnn",
            "arch_props": {"dtype": "float32", "classes": "4"},
            "optimizer": "adam",
            "learning_rate": 5e-3,
            "batch_size": 8,
        }
        cfg_path = str(tmp_path / "cfg.json")
        json.dump(cfg, open(cfg_path, "w"))
        save_path = str(tmp_path / "model.msgpack")

        pipe = parse_pipeline(
            f"datareposrc location={data} json={meta} epochs={epochs} ! "
            f"tensor_trainer name=t framework=jax model-config={cfg_path} "
            f"model-save-path={save_path} num-inputs=1 num-labels=1 "
            f"num-training-samples={n_train} num-validation-samples={n_valid} "
            f"epochs={epochs} ! tensor_sink name=out"
        )
        pipe.run(timeout=120)

        stats_frames = pipe["out"].frames
        assert len(stats_frames) == epochs  # one stats frame per epoch
        first, last = stats_frames[0].tensors[0], stats_frames[-1].tensors[0]
        assert last[0] == epochs  # epoch counter
        assert last[1] < first[1]  # training loss decreased
        assert os.path.exists(save_path)  # model saved on completion
        # the saved model must actually classify (guards against losses
        # that "converge" on degenerate targets): reload and predict
        from flax import serialization

        from nnstreamer_tpu.models import build

        fn, template, _, _ = build(
            "mnist_cnn", {"dtype": "float32", "classes": "4"}
        )
        restored = serialization.from_bytes(
            template, open(save_path, "rb").read()
        )
        rng = np.random.default_rng(0)  # same generator as write_dataset
        correct = 0
        for i in range(12):
            label = i % 4
            img = rng.normal(0.2, 0.05, (28, 28, 1)).astype(np.float32)
            qy, qx = divmod(label, 2)
            img[qy * 14 : (qy + 1) * 14, qx * 14 : (qx + 1) * 14] += 0.8
            pred = int(np.argmax(np.asarray(fn(restored, [img])[0])))
            correct += int(pred == label)
        assert correct >= 9, f"trained model only got {correct}/12"
        # bus carried epoch events
        events = []
        while (m := pipe.pop_message()) is not None:
            if m.kind == "element" and m.source == "t":
                events.extend(m.data.keys())
        assert "epoch-completion" in events
        assert "training-completion" in events

    def test_warm_start_load(self, tmp_path):
        # train 1 epoch, save; retrain loading the saved model
        data, meta = write_dataset(tmp_path, n=8)
        cfg = {"arch": "mnist_cnn", "arch_props": {"dtype": "float32", "classes": "4"},
               "batch_size": 8}
        cfg_path = str(tmp_path / "cfg.json")
        json.dump(cfg, open(cfg_path, "w"))
        save1 = str(tmp_path / "m1.msgpack")
        for load, save in ((None, save1), (save1, str(tmp_path / "m2.msgpack"))):
            load_opt = f"model-load-path={load} " if load else ""
            pipe = parse_pipeline(
                f"datareposrc location={data} json={meta} ! "
                f"tensor_trainer framework=jax model-config={cfg_path} "
                f"model-save-path={save} {load_opt}"
                "num-inputs=1 num-labels=1 num-training-samples=8 "
                "num-validation-samples=0 epochs=1"
            )
            pipe.run(timeout=120)
            assert os.path.exists(save)
