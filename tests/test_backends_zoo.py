"""Backend-zoo + converter-subplugin tests.

Mirrors the reference's parameterized filter-subplugin template
(``tests/nnstreamer_filter_extensions_common/unittest_tizen_template.cc.in``:
checkExistence, openClose_n, invoke, setDimension...) for the python3,
torch, custom-native, and tflite(gated) backends, plus the converter
subplugins that invert the serialize decoders.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

from nnstreamer_tpu.backends import find_backend
from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.types import ANY, FORMAT_STATIC, StreamSpec, TensorSpec
from nnstreamer_tpu.pipeline import parse_pipeline
import nnstreamer_tpu.converters  # noqa: F401


# -- python3 backend ----------------------------------------------------------

SCALER_SCRIPT = """
import numpy as np

class CustomFilter:
    def set_options(self, custom):
        self.mult = float(custom.get("mult", 2.0))
    def invoke(self, inputs):
        return [np.asarray(a, np.float32) * self.mult for a in inputs]
"""


@pytest.fixture
def py_scaler(tmp_path):
    p = tmp_path / "scaler.py"
    p.write_text(SCALER_SCRIPT)
    return str(p)


def test_python3_backend_existence():
    assert find_backend("python3") is not None


def test_python3_backend_invoke(py_scaler):
    be = find_backend("python3")()
    be.open(py_scaler, {"custom": "mult:3"})
    out = be.invoke([np.ones((2, 2), np.float32)])
    np.testing.assert_allclose(out[0], 3.0)
    be.close()


def test_python3_backend_set_input_info(py_scaler):
    be = find_backend("python3")()
    be.open(py_scaler, {})
    spec = StreamSpec((TensorSpec((4, 4), np.float32),), FORMAT_STATIC)
    out_spec = be.set_input_info(spec)
    assert out_spec.tensors[0].shape == (4, 4)
    be.close()


def test_python3_backend_open_missing_n():
    be = find_backend("python3")()
    with pytest.raises(FileNotFoundError):
        be.open("/nonexistent/f.py", {})


def test_python3_backend_in_pipeline(py_scaler):
    pipe = parse_pipeline(
        "appsrc name=src ! "
        f"tensor_filter framework=python3 model={py_scaler} custom=mult:4 ! "
        "tensor_sink name=out"
    )
    pipe.start()
    pipe["src"].push([np.full((3,), 2.0, np.float32)])
    pipe["src"].end_of_stream()
    pipe.wait(timeout=10)
    pipe.stop()
    np.testing.assert_allclose(pipe["out"].frames[0].tensors[0], 8.0)


def test_python3_auto_detect(py_scaler):
    # framework=auto + .py extension resolves to python3
    pipe = parse_pipeline(
        f"appsrc name=src ! tensor_filter model={py_scaler} ! tensor_sink name=out"
    )
    pipe.start()
    pipe["src"].push([np.ones((2,), np.float32)])
    pipe["src"].end_of_stream()
    pipe.wait(timeout=10)
    pipe.stop()
    np.testing.assert_allclose(pipe["out"].frames[0].tensors[0], 2.0)


# -- torch backend ------------------------------------------------------------

torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def torchscript_model(tmp_path_factory):
    class AddOne(torch.nn.Module):
        def forward(self, x):
            return x + 1.0

    path = tmp_path_factory.mktemp("torch") / "addone.pt"
    torch.jit.script(AddOne()).save(str(path))
    return str(path)


def test_torch_backend_invoke(torchscript_model):
    be = find_backend("torch")()
    be.open(torchscript_model, {})
    out = be.invoke([np.zeros((2, 3), np.float32)])
    np.testing.assert_allclose(out[0], 1.0)
    be.close()


def test_torch_backend_set_input_info(torchscript_model):
    be = find_backend("torch")()
    be.open(torchscript_model, {})
    out_spec = be.set_input_info(
        StreamSpec((TensorSpec((5,), np.float32),), FORMAT_STATIC))
    assert out_spec.tensors[0].shape == (5,)
    assert out_spec.tensors[0].dtype == np.float32
    be.close()


def test_torch_backend_in_pipeline_auto(torchscript_model):
    pipe = parse_pipeline(
        f"appsrc name=src ! tensor_filter model={torchscript_model} ! "
        "tensor_sink name=out"
    )
    pipe.start()
    pipe["src"].push([np.full((4,), 2.0, np.float32)])
    pipe["src"].end_of_stream()
    pipe.wait(timeout=10)
    pipe.stop()
    np.testing.assert_allclose(pipe["out"].frames[0].tensors[0], 3.0)


# -- tflite backend (real importer since round 4) -----------------------------

def test_tflite_backend_rejects_non_tflite():
    from nnstreamer_tpu.backends.tflite_import import TFLiteBackend
    from nnstreamer_tpu.importers.tflite_reader import TFLiteParseError
    be = TFLiteBackend()
    with pytest.raises((TFLiteParseError, FileNotFoundError, ValueError)):
        be.open(__file__, {})  # a .py file is not a tflite flatbuffer


# -- custom native (.so over the C ABI) --------------------------------------

_EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "nnstreamer_tpu", "native", "examples")


@pytest.fixture(scope="module")
def scaler_so(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    build = tmp_path_factory.mktemp("native")
    so = build / "libscaler.so"
    inc = os.path.join(os.path.dirname(_EXAMPLES), "include")
    subprocess.run(
        ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", f"-I{inc}",
         os.path.join(_EXAMPLES, "scaler_custom.cc"), "-o", str(so)],
        check=True)
    return str(so)


def test_custom_native_invoke(scaler_so):
    be = find_backend("custom")()
    be.open(scaler_so, {"custom": "mult:2.5"})
    spec = StreamSpec((TensorSpec((8,), np.float32),), FORMAT_STATIC)
    out_spec = be.set_input_info(spec)
    assert out_spec.tensors[0].shape == (8,)
    out = be.invoke([np.full((8,), 2.0, np.float32)])
    np.testing.assert_allclose(out[0], 5.0)
    be.close()


def test_custom_native_non_float_passthrough(scaler_so):
    be = find_backend("custom")()
    be.open(scaler_so, {"custom": "mult:3"})
    spec = StreamSpec((TensorSpec((4,), np.int32),), FORMAT_STATIC)
    be.set_input_info(spec)
    data = np.arange(4, dtype=np.int32)
    out = be.invoke([data])
    np.testing.assert_array_equal(out[0], data)
    be.close()


def test_custom_native_in_pipeline_auto(scaler_so):
    # .so extension auto-detects the custom backend
    pipe = parse_pipeline(
        f"appsrc name=src ! tensor_filter model={scaler_so} custom=mult:10 ! "
        "tensor_sink name=out"
    )
    pipe.start()
    pipe["src"].push([np.ones((2, 2), np.float32)])
    pipe["src"].end_of_stream()
    pipe.wait(timeout=10)
    pipe.stop()
    np.testing.assert_allclose(pipe["out"].frames[0].tensors[0], 10.0)


def test_custom_native_missing_so_n():
    be = find_backend("custom")()
    with pytest.raises(FileNotFoundError):
        be.open("/nonexistent/lib.so", {})


# -- converter subplugins -----------------------------------------------------

@pytest.mark.parametrize("mode", ["flexbuf", "flatbuf", "protobuf"])
def test_serialize_deserialize_pipeline_roundtrip(mode):
    """decoder(serialize) ! converter(deserialize) recovers the stream."""
    t = np.random.default_rng(1).normal(size=(2, 3)).astype(np.float32)
    pipe = parse_pipeline(
        "appsrc name=src ! "
        f"tensor_decoder mode={mode} ! "
        f"tensor_converter mode=custom:{mode} ! "
        "tensor_sink name=out"
    )
    pipe.start()
    pipe["src"].push([t])
    pipe["src"].end_of_stream()
    pipe.wait(timeout=10)
    pipe.stop()
    got = pipe["out"].frames[0].tensors[0]
    np.testing.assert_array_equal(np.asarray(got), t)


def test_python3_converter_script(tmp_path):
    script = tmp_path / "conv.py"
    script.write_text(
        "import numpy as np\n"
        "def convert(payload):\n"
        "    return [np.asarray(payload, np.float32).reshape(2, -1)]\n"
    )
    pipe = parse_pipeline(
        "appsrc name=src ! "
        f"tensor_converter mode=custom-script:{script} ! "
        "tensor_sink name=out"
    )
    pipe.start()
    pipe["src"].push([np.arange(6, dtype=np.float32)])
    pipe["src"].end_of_stream()
    pipe.wait(timeout=10)
    pipe.stop()
    assert pipe["out"].frames[0].tensors[0].shape == (2, 3)


def test_converter_unknown_subplugin_n():
    pipe = parse_pipeline(
        "appsrc name=src ! tensor_converter mode=custom:nope ! tensor_sink")
    with pytest.raises(Exception, match="unknown converter subplugin"):
        pipe.start()
    pipe.stop()


# -- torch backend against the reference repo's own .pt artifacts -------------

_REF_MODELS = "/root/reference/tests/test_models/models"


@pytest.mark.skipif(not os.path.isdir(_REF_MODELS),
                    reason="reference test models not present")
class TestTorchReferenceArtifacts:
    """The reference's own TorchScript files run unmodified
    (≙ tests/nnstreamer_filter_pytorch/runTest.sh)."""

    def test_lenet5(self):
        from nnstreamer_tpu.backends.torch_cpu import TorchBackend

        be = TorchBackend()
        be.open(os.path.join(_REF_MODELS, "pytorch_lenet5.pt"), {})
        try:
            # NHWC, as the reference pipeline feeds raw frames
            # (the module permutes internally)
            img = np.zeros((1, 28, 28, 1), np.float32)
            (out,) = be.invoke([img])
            assert out.shape == (1, 10)  # digit logits
        finally:
            be.close()

    def test_two_input_two_output(self):
        from nnstreamer_tpu.backends.torch_cpu import TorchBackend

        be = TorchBackend()
        be.open(os.path.join(
            _REF_MODELS, "sample_3x4_two_input_two_output.pt"), {})
        try:
            a = np.ones((3, 4), np.float32)
            b = np.full((3, 4), 2.0, np.float32)
            outs = be.invoke([a, b])
            assert len(outs) == 2
            assert all(o.shape == (3, 4) for o in outs)
        finally:
            be.close()

    def test_lenet5_pipeline_auto(self):
        model = os.path.join(_REF_MODELS, "pytorch_lenet5.pt")
        from nnstreamer_tpu.elements.filter import detect_framework

        assert detect_framework(model) == "torch"
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_filter framework=auto model={model} "
            "! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(np.zeros((1, 28, 28, 1), np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=60)
        frames = pipe["out"].frames
        pipe.stop()
        assert np.asarray(frames[0].tensors[0]).shape == (1, 10)
