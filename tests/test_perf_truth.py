"""Perf-truth layer tests (tools/perf_truth.py + PERF_BASELINE.json).

Everything here is deterministic — tolerance MATH, baseline-file
contracts, trend-report stale labeling, and the conftest perf-block
contiguity pin.  The timing half (a live fast-subset check against the
committed baseline) lives in tests/test_perf_smoke.py under the perf
marker, inside the load-shielded perf block.
"""

import json
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _load_perf_truth():
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import perf_truth
    finally:
        sys.path.pop(0)
    return perf_truth


def _load_bench():
    """One loader for bench.py (repo root is not a package)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_for_truth", str(REPO / "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


REQUIRED_AXES = {
    "fuse_speedup", "dispatch_overlap", "ingest_overlap",
    "pipeline_vs_raw", "slot_multiplex", "crc_bandwidth_mb_s",
}


class TestBaselineContract:
    def test_baseline_committed_with_required_axes(self):
        """Acceptance: PERF_BASELINE.json is committed with >= 6 axes
        covering fuse speedup, dispatch overlap, ingest overlap,
        pipeline_vs_raw, slot multiplex, and CRC bandwidth — each a
        median+MAD distribution from the shared bench harnesses."""
        pt = _load_perf_truth()
        base = pt.load_baseline()
        axes = base["axes"]
        assert REQUIRED_AXES <= set(axes), (
            f"baseline missing axes: {REQUIRED_AXES - set(axes)}")
        assert len(axes) >= 6
        for name, e in axes.items():
            assert e["median"] > 0, name
            assert e["mad"] >= 0, name
            assert len(e["samples"]) == e["k"] >= 2, name
            assert e["unit"], name
            # the committed floor field matches the live tolerance math
            assert e["floor"] == pytest.approx(
                pt.regression_floor(e), abs=1e-3), name
            # every harness is a shared bench.py / bench_wire.py entry
            assert e["harness"].split(".")[0] in ("bench", "bench_wire")

    def test_axis_catalog_matches_baseline(self):
        """Every committed axis still has a live harness (a renamed or
        dropped harness must regenerate the baseline, not silently stop
        being checked)."""
        pt = _load_perf_truth()
        base = pt.load_baseline()
        catalog = pt._axes()
        missing = set(base["axes"]) - set(catalog)
        assert not missing, f"baseline axes without a harness: {missing}"
        fast = {n for n, a in catalog.items() if a.fast}
        assert fast & set(base["axes"]), "no fast axis in the baseline"


class TestToleranceMath:
    def test_self_test_25pct_regression_detectable(self):
        """Acceptance: on the COMMITTED baseline, a value 25% below any
        axis median classifies as a regression and the median itself
        passes — the --self-test contract, pure math, no clocks."""
        pt = _load_perf_truth()
        problems = pt.self_test()
        assert not problems, "\n".join(problems)

    def test_tolerance_clamps(self):
        pt = _load_perf_truth()
        # huge MAD: capped at REL_MAX so 25% drops always trip
        assert pt.tolerance(10.0, 100.0) == pytest.approx(2.0)
        # zero MAD: floored at REL_MIN so jitter alone can't flake
        assert pt.tolerance(10.0, 0.0) == pytest.approx(0.8)
        # in-band MAD: the 4*MAD noise envelope governs
        assert pt.tolerance(10.0, 0.3) == pytest.approx(1.2)

    def test_injected_regression_fails_check(self, monkeypatch):
        """check() with a 30% handicap on a synthetic zero-variance
        baseline reports the regression; without the handicap it
        passes (and early-exits after one run)."""
        pt = _load_perf_truth()
        calls = {"n": 0}

        def fake_measure():
            calls["n"] += 1
            return 100.0

        fake_axis = pt.Axis("fuse_speedup", "bench.fake", "x",
                            True, 3, 3, fake_measure)
        monkeypatch.setattr(pt, "_axes",
                            lambda: {"fuse_speedup": fake_axis})
        monkeypatch.setattr(pt, "_force_cpu", lambda: None)
        baseline = {
            "captured_at": "2026-08-04T00:00:00Z",
            "axes": {"fuse_speedup": {
                "unit": "x", "harness": "bench.fake", "fast": True,
                "k": 3, "samples": [100.0] * 3, "median": 100.0,
                "mad": 0.0,
            }},
        }
        ok = pt.check(baseline=baseline, handicap=1.0, verbose=False)
        assert ok["ok"] and ok["axes"]["fuse_speedup"]["verdict"] == "ok"
        assert len(ok["axes"]["fuse_speedup"]["runs"]) == 1  # early exit
        calls["n"] = 0
        bad = pt.check(baseline=baseline, handicap=0.70, verbose=False)
        assert not bad["ok"]
        assert bad["axes"]["fuse_speedup"]["verdict"] == "regression"
        assert calls["n"] == 3  # all k runs consumed before reporting


class TestTrendReport:
    def test_stale_chip_rows_loudly_labeled(self, tmp_path):
        """A banked chip row older than the staleness threshold is
        labeled STALE with its age; a fresh cpu row is not."""
        pt = _load_perf_truth()
        old = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                            time.gmtime(time.time() - 5 * 86400))
        fresh = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        (tmp_path / "BENCH_EVIDENCE.json").write_text(json.dumps({
            "sig1": {"captured_at": old, "row": {
                "metric": "mobilenet_fps", "value": 1821.0,
                "unit": "fps", "platform": "axon"}},
        }))
        (tmp_path / "BENCH_CPU.json").write_text(json.dumps([
            {"metric": "overhead_fps", "value": 40000.0, "unit": "fps",
             "platform": "cpu", "captured_at": fresh},
        ]))
        rep = pt.trend_report(root=str(tmp_path),
                              baseline_path=str(tmp_path / "missing.json"))
        by_metric = {h["metric"]: h for h in rep["history"]}
        chip = by_metric["mobilenet_fps"]
        assert chip["status"].startswith("STALE")
        assert chip["age_days"] == pytest.approx(5.0, abs=0.1)
        assert "5.0d" in chip["status"]
        assert not by_metric["overhead_fps"]["status"].startswith("STALE")
        md = pt.render_markdown(rep)
        assert "STALE chip row(s)" in md
        assert "mobilenet_fps" in md

    def test_report_runs_on_real_repo(self):
        """The ledger walks the repo's actual BENCH_* history (which
        holds axon rows stale since the 2026-07-31 tunnel outage) and
        renders without error."""
        pt = _load_perf_truth()
        rep = pt.trend_report()
        assert rep["history"], "no bench history found in the repo"
        assert any(h["platform"] not in (None, "cpu")
                   for h in rep["history"])
        md = pt.render_markdown(rep)
        assert "PERF_BASELINE.json" in md
        # the known-stale axon evidence is loudly labeled
        assert "STALE" in md


class TestBenchHygiene:
    def test_stale_served_rows_carry_age_days(self, tmp_path, capsys,
                                              monkeypatch):
        """Satellite pin: emit_failure serving banked evidence stamps an
        explicit age_days next to stale_since."""
        bench = _load_bench()
        since = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                              time.gmtime(time.time() - 2 * 86400))
        meta = {"model": "m", "batch": 1, "dtype": "bf16",
                "quantize": None, "dispatch_depth": 1, "ingest": "frame",
                "sink_split": True, "batch_timeout_ms": 20, "fuse": 1,
                "ingest_lane": "off", "slots": 0, "input": "device",
                "platform": "axon"}
        row = {**meta, "metric": "m_fps", "value": 123.0, "unit": "fps"}
        ev = tmp_path / "ev.json"
        ev.write_text(json.dumps(
            {bench._sig(row): {"captured_at": since, "row": row}}))
        monkeypatch.setattr(bench, "EVIDENCE_PATH", str(ev))
        monkeypatch.setattr(bench, "ROWS_PATH",
                            str(tmp_path / "rows.json"))
        bench.emit_failure("m_fps", "fps", meta, "probe timed out")
        out = json.loads(capsys.readouterr().out.strip())
        assert out["stale"] is True and out["value"] == 123.0
        assert out["age_days"] == pytest.approx(2.0, abs=0.1)

    def test_age_days_parses_and_rejects(self):
        bench = _load_bench()
        now = time.time()
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                              time.gmtime(now - 86400))
        assert bench.age_days(stamp, now=now) == pytest.approx(1.0,
                                                              abs=0.05)
        assert bench.age_days("unknown") is None
        assert bench.age_days("") is None

    def test_cpu_proxy_carries_git_rev(self):
        """Satellite pin: cpu_proxy rows align with commits via the
        harness git revision (a real checkout here, so non-None)."""
        rev = _load_bench().git_rev()
        assert rev and len(rev) >= 7


# ---------------------------------------------------------------------------
# Perf-block contiguity (PR-8 caveat pinned): the conftest load shield
# must keep perf-marked items in ONE contiguous block after any plugin
# (pytest-randomly included) reorders collection.
# ---------------------------------------------------------------------------
class _FakeItem:
    def __init__(self, name, perf):
        self.name = name
        self._perf = perf

    def get_closest_marker(self, name):
        return object() if (name == "perf" and self._perf) else None


def _drive_hookwrapper(items):
    import conftest

    gen = conftest.pytest_collection_modifyitems(None, items)
    next(gen)  # the pre-yield half (other plugins would reorder here)
    with pytest.raises(StopIteration):
        next(gen)


def test_perf_block_stays_contiguous():
    """Simulated post-shuffle order: perf items scattered through the
    list are gathered into one contiguous block at the first perf
    item's position, non-perf relative order preserved."""
    items = [
        _FakeItem("a", False), _FakeItem("p1", True), _FakeItem("b", False),
        _FakeItem("p2", True), _FakeItem("c", False), _FakeItem("p3", True),
    ]
    _drive_hookwrapper(items)
    names = [it.name for it in items]
    assert names == ["a", "p1", "p2", "p3", "b", "c"]
    # idempotent: re-running the shield does not move the block
    _drive_hookwrapper(items)
    assert [it.name for it in items] == names
    # degenerate cases: all-perf and no-perf lists stay untouched
    all_perf = [_FakeItem("x", True), _FakeItem("y", True)]
    _drive_hookwrapper(all_perf)
    assert [it.name for it in all_perf] == ["x", "y"]


def test_perf_block_contiguous_in_real_session(request):
    """The REAL collected session (whatever pytest-randomly did this
    run) holds its perf items contiguously."""
    items = request.session.items
    perf_idx = [
        i for i, it in enumerate(items)
        if it.get_closest_marker("perf") is not None
    ]
    if len(perf_idx) < 2:
        pytest.skip("fewer than 2 perf items collected in this run")
    assert perf_idx == list(range(perf_idx[0], perf_idx[0] + len(perf_idx))), (
        "perf-marked items are not contiguous — the conftest load "
        "shield regressed")
