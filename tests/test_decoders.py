"""Decoder-subplugin suite tests.

Mirrors the reference test strategy (SURVEY.md §4): each decoder mode gets
synthetic tensors with a known answer, decoded output is checked for both the
rendered overlay and the machine-readable meta.  Reference analogs:
``tests/nnstreamer_decoder*/runTest.sh`` + decoder gtest cases.
"""


import numpy as np
import pytest

from nnstreamer_tpu.core import registry
from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.types import ANY
import nnstreamer_tpu.decoders  # noqa: F401 — registers decoder modes
from nnstreamer_tpu.decoders import util


def get_decoder(name):
    cls = registry.get(registry.KIND_DECODER, name)
    return cls()


def frame(*tensors, **meta):
    f = TensorFrame(tensors=[np.asarray(t) for t in tensors], pts=0.0)
    f.meta.update(meta)
    return f


# -- util ---------------------------------------------------------------------

def test_nms_suppresses_same_class_overlap():
    dets = np.array([
        [0, 0, 10, 10, 0.9, 1],
        [1, 1, 11, 11, 0.8, 1],   # overlaps first, same class -> dropped
        [1, 1, 11, 11, 0.7, 2],   # same box, other class -> kept
        [50, 50, 60, 60, 0.6, 1], # far away -> kept
    ])
    out = util.nms(dets, iou_threshold=0.5)
    assert out.shape[0] == 3
    assert out[0, 4] == pytest.approx(0.9)


def test_nms_empty():
    assert util.nms(np.zeros((0, 6))).shape == (0, 6)


def test_parse_wh():
    assert util.parse_wh("640:480", (1, 1)) == (640, 480)
    assert util.parse_wh("", (320, 240)) == (320, 240)
    assert util.parse_wh(":480", (320, 240)) == (320, 480)


def test_draw_rect_bounds():
    c = util.blank_canvas(20, 10)
    util.draw_rect(c, -5, -5, 30, 30, (255, 0, 0, 255))
    assert c[0, 0, 0] == 255 and c[9, 19, 0] == 255


# -- bounding_boxes -----------------------------------------------------------

def _ssd_fixture(tmp_path, priors=4):
    """Priors file + loc/score tensors putting one box at a known spot."""
    pri = np.zeros((4, priors))
    pri[0] = 0.5   # yc
    pri[1] = 0.5   # xc
    pri[2] = 0.4   # h
    pri[3] = 0.4   # w
    path = tmp_path / "priors.txt"
    path.write_text("\n".join(" ".join(str(v) for v in row) for row in pri))
    loc = np.zeros((priors, 4), np.float32)
    scores = np.full((priors, 3), -10.0, np.float32)
    scores[2, 1] = 10.0  # prior 2, class 1 confident
    return str(path), loc, scores


def test_bbox_mobilenet_ssd(tmp_path):
    path, loc, scores = _ssd_fixture(tmp_path)
    dec = get_decoder("bounding_boxes")
    dec.set_options(["mobilenet-ssd", "", path, "600:600", "300:300",
                     "", "", "", ""])
    out = dec.decode(frame(loc, scores), ANY)
    assert out.tensors[0].shape == (600, 600, 4)
    boxes = out.meta["boxes"]
    assert len(boxes) == 1
    b = boxes[0]
    assert b["class"] == 1
    # prior box centered at (.5,.5) size .4 -> scaled x2: x=180 w=240
    assert b["x"] == pytest.approx(180, abs=2)
    assert b["w"] == pytest.approx(240, abs=3)


def test_bbox_requires_priors():
    dec = get_decoder("bounding_boxes")
    dec.set_options(["mobilenet-ssd"] + [""] * 8)
    with pytest.raises(ValueError):
        dec.decode(frame(np.zeros((4, 4)), np.zeros((4, 2))), ANY)


def test_bbox_unknown_mode_rejected():
    dec = get_decoder("bounding_boxes")
    with pytest.raises(ValueError):
        dec.set_options(["not-a-mode"] + [""] * 8)


def test_bbox_postprocess_mode():
    boxes = np.array([[0.1, 0.2, 0.5, 0.6]], np.float32)  # ymin,xmin,ymax,xmax
    classes = np.array([3.0], np.float32)
    scores = np.array([0.9], np.float32)
    count = np.array([1.0], np.float32)
    dec = get_decoder("bounding_boxes")
    dec.set_options(["mobilenet-ssd-postprocess", "", "", "100:100",
                     "100:100", "", "", "", ""])
    out = dec.decode(frame(boxes, classes, scores, count), ANY)
    b = out.meta["boxes"][0]
    assert b["class"] == 3
    assert b["x"] == pytest.approx(20, abs=1)
    assert b["y"] == pytest.approx(10, abs=1)
    assert b["w"] == pytest.approx(40, abs=2)


def test_bbox_yolov5():
    # one row: cx,cy,w,h (normalized), objectness, 2 class scores
    pred = np.array([[0.5, 0.5, 0.2, 0.2, 0.99, 0.1, 0.95],
                     [0.1, 0.1, 0.05, 0.05, 0.01, 0.5, 0.5]], np.float32)
    dec = get_decoder("bounding_boxes")
    dec.set_options(["yolov5", "", "0:0.5:0.5", "320:320", "320:320",
                     "", "", "", ""])
    out = dec.decode(frame(pred), ANY)
    boxes = out.meta["boxes"]
    assert len(boxes) == 1
    assert boxes[0]["class"] == 1
    assert boxes[0]["x"] == pytest.approx(0.4 * 320, abs=1)


def test_bbox_yolov8_transposed():
    # yolov8 layout [4+C, N] without objectness
    n = 10
    pred = np.zeros((6, n), np.float32)
    pred[:, 1] = [0.5, 0.5, 0.3, 0.3, 0.05, 0.9]  # col 1 is a strong det
    dec = get_decoder("bounding_boxes")
    dec.set_options(["yolov8", "", "0:0.5:0.5", "100:100", "100:100",
                     "", "", "", ""])
    out = dec.decode(frame(pred), ANY)
    assert len(out.meta["boxes"]) == 1
    assert out.meta["boxes"][0]["class"] == 1


def test_bbox_openvino():
    rows = np.array([[0, 1, 0.9, 0.1, 0.1, 0.3, 0.3],
                     [-1, 0, 0.0, 0, 0, 0, 0]], np.float32).reshape(1, 1, 2, 7)
    dec = get_decoder("bounding_boxes")
    dec.set_options(["ov-person-detection", "", "", "200:200", "100:100",
                     "", "", "", ""])
    out = dec.decode(frame(rows), ANY)
    assert len(out.meta["boxes"]) == 1
    assert out.meta["boxes"][0]["x"] == pytest.approx(20, abs=1)


def test_bbox_mp_palm():
    dec = get_decoder("bounding_boxes")
    dec.set_options(["mp-palm-detection", "", "0.5", "192:192", "192:192",
                     "", "", "", ""])
    anchors = dec._anchors = None  # force regeneration on decode
    n = 2016  # 192/8=24^2*2 + 3 layers of 12^2*2... use whatever count
    raw = np.zeros((8, 18), np.float32)
    raw[0, :4] = [0.0, 0.0, 38.4, 38.4]  # w,h = 0.2 of input
    scores = np.full((8,), -10.0, np.float32)
    scores[0] = 5.0
    out = dec.decode(frame(raw, scores), ANY)
    assert len(out.meta["boxes"]) == 1
    assert out.meta["boxes"][0]["score"] > 0.9


def test_bbox_labels(tmp_path):
    lf = tmp_path / "labels.txt"
    lf.write_text("zero\none\ntwo\n")
    boxes = np.array([[0.1, 0.1, 0.5, 0.5]], np.float32)
    dec = get_decoder("bounding_boxes")
    dec.set_options(["mobilenet-ssd-postprocess", str(lf), "", "100:100",
                     "100:100", "", "", "", ""])
    out = dec.decode(frame(boxes, np.array([2.0]), np.array([0.8]),
                           np.array([1.0])), ANY)
    assert out.meta["boxes"][0]["label"] == "two"


# -- pose ---------------------------------------------------------------------

def test_pose_heatmap_only():
    k = 17
    heat = np.full((9, 9, k), -10.0, np.float32)
    for i in range(k):
        heat[i % 9, (i * 2) % 9, i] = 10.0
    dec = get_decoder("pose_estimation")
    dec.set_options(["90:90", "90:90", "", "", "", "", "", "", ""])
    out = dec.decode(frame(heat), ANY)
    assert out.tensors[0].shape == (90, 90, 4)
    kps = out.meta["keypoints"]
    assert len(kps) == k
    # keypoint 0 at grid (0,0) -> center of cell 0 = 5px
    assert kps[0][0] == pytest.approx(5, abs=1)
    assert all(s > 0.9 for _, _, s in kps)


def test_pose_heatmap_offset():
    k = 3
    heat = np.full((5, 5, k), -10.0, np.float32)
    heat[2, 2, :] = 10.0
    off = np.zeros((5, 5, 2 * k), np.float32)
    off[2, 2, :k] = 7.0   # y offsets
    off[2, 2, k:] = -3.0  # x offsets
    dec = get_decoder("pose_estimation")
    dec.set_options(["100:100", "100:100", "", "heatmap-offset",
                     "", "", "", "", ""])
    out = dec.decode(frame(heat, off), ANY)
    x, y, s = out.meta["keypoints"][0]
    assert y == pytest.approx(2 / 4 * 100 + 7.0, abs=1)
    assert x == pytest.approx(2 / 4 * 100 - 3.0, abs=1)


def test_pose_bad_mode():
    dec = get_decoder("pose_estimation")
    with pytest.raises(ValueError):
        dec.set_options(["", "", "", "nope", "", "", "", "", ""])


# -- segment ------------------------------------------------------------------

def test_segment_deeplab_argmax():
    grid = np.zeros((4, 4, 3), np.float32)
    grid[:2, :, 1] = 5.0  # top half class 1
    grid[2:, :, 2] = 5.0  # bottom half class 2
    dec = get_decoder("image_segment")
    dec.set_options(["tflite-deeplab", "", "", "", "", "", "", "", ""])
    out = dec.decode(frame(grid), ANY)
    rgba = out.tensors[0]
    assert rgba.shape == (4, 4, 4)
    assert set(out.meta["classes_present"]) == {1, 2}
    assert not np.array_equal(rgba[0, 0], rgba[3, 0])
    assert rgba[0, 0, 3] == 160  # overlay alpha


def test_segment_snpe_depth():
    depth = np.linspace(0, 10, 16, dtype=np.float32).reshape(4, 4)
    dec = get_decoder("image_segment")
    dec.set_options(["snpe-depth", "", "", "", "", "", "", "", ""])
    out = dec.decode(frame(depth), ANY)
    rgba = out.tensors[0]
    assert rgba[0, 0, 0] == 0 and rgba[3, 3, 0] == 255
    assert out.meta["depth_range"] == [0.0, 10.0]


# -- tensor_region ------------------------------------------------------------

def test_tensor_region_pairs_with_crop(tmp_path):
    path, loc, scores = _ssd_fixture(tmp_path)
    dec = get_decoder("tensor_region")
    dec.set_options(["2", "", path, "", "300:300", "", "", "", ""])
    out = dec.decode(frame(loc, scores), ANY)
    regions = out.tensors[0]
    assert regions.dtype == np.int32
    assert regions.shape[1] == 4
    x, y, w, h = regions[0]
    assert w > 0 and h > 0

    # feed it into tensor_crop's math: crop region within bounds
    img = np.zeros((300, 300, 3), np.uint8)
    assert 0 <= x < 300 and 0 <= y < 300


# -- octet / serialize / python3 ----------------------------------------------

def test_octet_stream_concat():
    a = np.arange(4, dtype=np.uint8)
    b = np.arange(2, dtype=np.int16)
    dec = get_decoder("octet_stream")
    out = dec.decode(frame(a, b), ANY)
    assert out.tensors[0].dtype == np.uint8
    assert out.tensors[0].nbytes == a.nbytes + b.nbytes
    assert bytes(out.tensors[0][:4]) == a.tobytes()


@pytest.mark.parametrize("mode,media", [
    ("flexbuf", "other/flexbuf"),
    ("flatbuf", "other/flatbuf"),
    ("protobuf", "other/protobuf-tensor"),
])
def test_serialize_roundtrip(mode, media):
    # round-trip through the matching converter subplugin (protobuf mode
    # speaks the public nns_tensors.proto; flatbuf the reference's actual
    # nnstreamer.fbs; flexbuf the canonical NNSQ framing — either way
    # decoder+converter must be exact inverses)
    import nnstreamer_tpu.converters  # noqa: F401 — registers subplugins
    from nnstreamer_tpu.core.registry import KIND_CONVERTER, get
    t = np.random.default_rng(0).normal(size=(3, 5)).astype(np.float32)
    dec = get_decoder(mode)
    out = dec.decode(frame(t), ANY)
    assert out.meta["media_type"] == media
    conv = get(KIND_CONVERTER, mode)()
    back = conv.convert(out)
    np.testing.assert_array_equal(np.asarray(back.tensors[0]), t)
    if mode == "protobuf":
        # the payload must ALSO parse with the public proto codec alone —
        # the decoder/converter pair agreeing is not the interop contract
        from nnstreamer_tpu.distributed import protobuf_codec

        ext = protobuf_codec.decode_frame(bytes(out.tensors[0]))
        np.testing.assert_array_equal(np.asarray(ext.tensors[0]), t)
    if mode == "flatbuf":
        # same interop bar for flatbuf: the payload is a real
        # nnstreamer.fbs buffer, parseable by the schema codec alone
        from nnstreamer_tpu.distributed import flatbuf_codec

        ext = flatbuf_codec.decode_frame(bytes(out.tensors[0]))
        np.testing.assert_array_equal(np.asarray(ext.tensors[0]), t)


def test_python3_decoder(tmp_path):
    script = tmp_path / "dec.py"
    script.write_text(
        "import numpy as np\n"
        "class CustomDecoder:\n"
        "    def decode(self, tensors, meta):\n"
        "        return [tensors[0] * 2]\n"
    )
    dec = get_decoder("python3")
    dec.set_options([str(script)] + [""] * 8)
    out = dec.decode(frame(np.ones((2, 2), np.float32)), ANY)
    np.testing.assert_array_equal(out.tensors[0], np.full((2, 2), 2.0))


def test_python3_decoder_function_form(tmp_path):
    script = tmp_path / "decfn.py"
    script.write_text("def decode(tensors):\n    return [t + 1 for t in tensors]\n")
    dec = get_decoder("python3")
    dec.set_options([str(script)] + [""] * 8)
    out = dec.decode(frame(np.zeros(3, np.int32)), ANY)
    np.testing.assert_array_equal(out.tensors[0], np.ones(3, np.int32))


def test_python3_decoder_missing_script():
    dec = get_decoder("python3")
    with pytest.raises((FileNotFoundError, ValueError)):
        dec.set_options(["/nonexistent/x.py"] + [""] * 8)


# -- pipeline integration -----------------------------------------------------

def test_decoder_element_bounding_boxes_in_pipeline(tmp_path):
    """Full pipeline: appsrc -> tensor_decoder mode=bounding_boxes."""
    from nnstreamer_tpu.pipeline import parse_pipeline

    boxes = np.array([[0.0, 0.0, 0.5, 0.5]], np.float32)
    pipe = parse_pipeline(
        "appsrc name=src ! "
        "tensor_decoder mode=bounding_boxes option1=mobilenet-ssd-postprocess "
        "option4=64:64 option5=64:64 ! "
        "tensor_sink name=out"
    )
    pipe.start()
    pipe["src"].push([boxes, np.array([1.0], np.float32),
                      np.array([0.9], np.float32), np.array([1.0], np.float32)])
    pipe["src"].end_of_stream()
    pipe.wait(timeout=10)
    pipe.stop()
    got = pipe["out"].frames
    assert len(got) == 1
    assert got[0].tensors[0].shape == (64, 64, 4)
    assert got[0].meta["boxes"][0]["class"] == 1
