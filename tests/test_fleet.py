"""Fleet overload resilience: load-aware routing, consistent-hash
affinity, per-tenant admission, discovery-plane health, and the scripted
fleet chaos e2e (the acceptance contract of the fleet arc).

Covers, per Documentation/resilience.md "Fleet overload & tenancy":

* routing policy ranking (`rotate` | `least-inflight` | `ewma`) with the
  selection-side breaker guard: an OPEN-breaker remote is NEVER ranked
  ahead of a closed-breaker alternative, no matter how good its load
  signal looks — and EWMA rows evicted by `_rediscover` are never
  consulted again (both PR-7-era gaps, pinned here);
* rendezvous-hash affinity: fairness within ±25% of uniform across 8
  servers, and provably-minimal remapping on join/leave;
* the per-tenant shed truth table: quota, priority ordering, retry-after
  pacing, breaker-immunity of tenant-quota BUSY;
* discovery-plane health propagation (draining announce -> client
  deprioritization before any GOAWAY round trip);
* sustained tenant-quota shed -> rate-limited flight-recorder incident;
* the chaos e2e: 3 tcp servers under continuous 2-tenant load survive
  scripted kill + rolling restart + server join with zero lost or
  duplicated frames, exact per-tenant accounting, zero breaker trips
  from drains, bounded affinity remaps, and a hot-tenant burst that
  sheds ONLY the hot tenant.
"""

import math
import os
import sys
import time
from collections import Counter
from pathlib import Path

import numpy as np
import pytest

from nnstreamer_tpu.core import routing
from nnstreamer_tpu.core.continuity import PREFIX_GRAIN, prefix_route_key
from nnstreamer_tpu.core.liveness import (
    ServerBusyError,
    TenantAdmissionController,
    parse_tenant_quotas,
)
from nnstreamer_tpu.core.resilience import (
    CircuitBreaker,
    is_remote_application_error,
)
from nnstreamer_tpu.pipeline.element import make_element
from nnstreamer_tpu.pipeline.parser import parse_pipeline

sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))


# ---------------------------------------------------------------------------
# Rendezvous-hash affinity: fairness + minimal remapping (property-style)
# ---------------------------------------------------------------------------
class TestRendezvousAffinity:
    KEYS = [f"sess-{i}" for i in range(2000)]
    FLEET8 = [(f"10.0.0.{i}", 7000 + i) for i in range(8)]

    def test_deterministic(self):
        t = self.FLEET8
        assert [routing.rendezvous_owner(k, t) for k in self.KEYS[:50]] == [
            routing.rendezvous_owner(k, t) for k in self.KEYS[:50]
        ]

    def test_fairness_within_25pct_of_uniform_across_8_servers(self):
        owners = Counter(
            routing.rendezvous_owner(k, self.FLEET8) for k in self.KEYS)
        ideal = len(self.KEYS) / len(self.FLEET8)
        assert set(owners) == set(range(8)), "every server owns keys"
        for i, n in owners.items():
            assert 0.75 * ideal <= n <= 1.25 * ideal, (
                f"server {i} owns {n} keys (ideal {ideal:.0f} +/- 25%)")

    def test_join_remaps_only_what_the_newcomer_wins(self):
        """Adding one server moves EXACTLY the keys the newcomer now
        owns — every other key keeps its owner (minimal remapping), and
        the moved fraction is ~1/N (within the fairness tolerance)."""
        before = routing.ownership_map(self.KEYS, self.FLEET8)
        grown = self.FLEET8 + [("10.0.0.8", 7008)]
        after = routing.ownership_map(self.KEYS, grown)
        moved = [k for k in self.KEYS if before[k] != after[k]]
        for k in moved:
            assert grown[after[k]] == ("10.0.0.8", 7008), (
                "a key may only move TO the joining server")
        assert len(moved) <= math.ceil(1.25 * len(self.KEYS) / len(grown))

    def test_leave_remaps_only_the_departed_servers_keys(self):
        before = routing.ownership_map(self.KEYS, self.FLEET8)
        survivors = self.FLEET8[:3] + self.FLEET8[4:]  # drop index 3
        after = routing.ownership_map(self.KEYS, survivors)
        for k in self.KEYS:
            if before[k] != 3:
                # survivors' keys keep their owner (compare by endpoint,
                # indices shift after the removal)
                assert self.FLEET8[before[k]] == survivors[after[k]]
        departed = [k for k in self.KEYS if before[k] == 3]
        moved = [
            k for k in self.KEYS
            if self.FLEET8[before[k]] != survivors[after[k]]
        ]
        assert sorted(moved) == sorted(departed)
        assert len(moved) <= math.ceil(
            1.25 * len(self.KEYS) / len(self.FLEET8))


# ---------------------------------------------------------------------------
# Prefix-affinity routing (PR 18): the remap math over REAL prefix
# digests, and the tier discipline for a draining/degraded prefix owner
# ---------------------------------------------------------------------------
class TestPrefixAffinityRouting:
    FLEET8 = [(f"10.0.0.{i}", 7000 + i) for i in range(8)]

    @staticmethod
    def _digest_keys(n=600, seed=5):
        """Route keys as the query client computes them: grain-aligned
        chain digests of synthetic prompts (not opaque session strings —
        the remap math must hold over the ACTUAL key distribution)."""
        rng = np.random.default_rng(seed)
        return [
            prefix_route_key(
                rng.integers(0, 997, (1, PREFIX_GRAIN + 17)).astype(
                    np.int32))
            for _ in range(n)
        ]

    def test_shared_prefix_maps_to_one_owner_distinct_prefixes_spread(
            self):
        """The tentpole's routing premise: clients sharing a prompt
        prefix compute the SAME route key (suffix divergence past the
        first grain is invisible to it) and so land on the one server
        whose prefix KV pages are warm, while distinct prefixes spread
        across the fleet."""
        rng = np.random.default_rng(7)
        base = rng.integers(0, 997, (1, PREFIX_GRAIN + 40)).astype(
            np.int32)
        fork = base.copy()
        fork[0, PREFIX_GRAIN + 5] ^= 1  # diverge AFTER the first grain
        assert prefix_route_key(base) == prefix_route_key(fork)
        other = base.copy()
        other[0, 3] ^= 1                # diverge INSIDE the prefix
        assert prefix_route_key(base) != prefix_route_key(other)
        owners = Counter(
            routing.rendezvous_owner(k, self.FLEET8)
            for k in self._digest_keys())
        assert set(owners) == set(range(8)), (
            "distinct prefixes must spread over every server")

    def test_join_steals_only_the_prefix_digests_the_newcomer_wins(self):
        """Minimal remap over prefix digests: a scale-up invalidates
        ONLY the warm prefix pages for keys the newcomer now owns
        (~1/N of them) — every other key keeps its warm server."""
        keys = self._digest_keys()
        before = routing.ownership_map(keys, self.FLEET8)
        grown = self.FLEET8 + [("10.0.0.8", 7008)]
        after = routing.ownership_map(keys, grown)
        moved = [k for k in keys if before[k] != after[k]]
        for k in moved:
            assert grown[after[k]] == ("10.0.0.8", 7008), (
                "a prefix digest may only move TO the joining server")
        assert len(moved) <= math.ceil(1.35 * len(keys) / len(grown))

    def test_leave_moves_exactly_the_departed_servers_digests(self):
        """A scale-down re-homes EXACTLY the departed server's prefix
        digests; every surviving server keeps its warm set bit-for-bit
        (compare by endpoint — indices shift after the removal)."""
        keys = self._digest_keys()
        before = routing.ownership_map(keys, self.FLEET8)
        survivors = self.FLEET8[:3] + self.FLEET8[4:]  # drop index 3
        after = routing.ownership_map(keys, survivors)
        departed = [k for k in keys if before[k] == 3]
        moved = [
            k for k in keys
            if self.FLEET8[before[k]] != survivors[after[k]]
        ]
        assert sorted(moved) == sorted(departed)

    def test_draining_owner_fails_over_in_tier_without_remap_thrash(
            self):
        """A draining (then degraded) prefix owner's traffic fails over
        to healthy remotes WITHOUT counting affinity remaps: the owner
        assignment is a pure function of the endpoint set, so tier
        demotion — a routing-order concern — must not thrash the
        `affinity_remaps` ledger, and the owner still outranks remotes
        in worse tiers (pages are warm there; it is wounded, not
        gone)."""
        el = _client_with_pool(3, **{"affinity-key": "prefix"})
        from nnstreamer_tpu.core.buffer import TensorFrame

        rng = np.random.default_rng(11)
        prompt = rng.integers(0, 997, (1, PREFIX_GRAIN + 8)).astype(
            np.int32)
        f = TensorFrame([prompt])
        key = prefix_route_key(prompt)
        owner = routing.rendezvous_owner(key, el._pstate.targets)
        addr = "{}:{}".format(*el._pstate.targets[owner])
        # healthy owner: promoted to the very front, zero remaps
        for first in range(3):
            assert el._route_order(el._pstate, f, first)[0] == owner
        assert el._affinity_remaps == 0
        for hint in ({"draining": True}, {"degraded": True}):
            with el._breakers_lock:
                el._endpoint_hints = {addr: hint}
                el._hints_ts = time.monotonic()
            for first in range(3):
                order = el._route_order(el._pstate, f, first)
                assert order[-1] == owner, (
                    f"{hint}: owner must yield to healthy remotes")
                assert set(order[:2]) == {i for i in range(3)
                                          if i != owner}
        # repeated failover routing counted ZERO owner changes
        assert el._affinity_remaps == 0
        # ...and a frame declaring a longer shared prefix still routes
        # deterministically (meta prefix_tokens -> deeper chain digest)
        f2 = TensorFrame([prompt], meta={"prefix_tokens": PREFIX_GRAIN})
        el._route_order(el._pstate, f2, 0)
        assert el._affinity_remaps == 0


# ---------------------------------------------------------------------------
# Routing policy ranking (pure units over core/routing.py)
# ---------------------------------------------------------------------------
class TestRoutingRanking:
    def test_rotate_is_rotation_order(self):
        tiers = {i: routing.TIER_OK for i in range(4)}
        assert routing.order_remotes("rotate", tiers, 2, 4) == [2, 3, 0, 1]

    def test_least_inflight_prefers_idle_with_rotation_tiebreak(self):
        tiers = {i: routing.TIER_OK for i in range(4)}
        infl = {0: 3, 1: 0, 2: 1, 3: 0}
        assert routing.order_remotes(
            "least-inflight", tiers, 3, 4, inflight=infl) == [3, 1, 2, 0]

    def test_ewma_prefers_fast_remote_inflight_tiebreak(self):
        tiers = {i: routing.TIER_OK for i in range(3)}
        scores = {0: 40.0, 1: 5.0, 2: 5.0}
        infl = {0: 0, 1: 2, 2: 0}
        assert routing.order_remotes(
            "ewma", tiers, 0, 3, inflight=infl, scores=scores) == [2, 1, 0]

    def test_unknown_endpoint_scores_neutral_mean(self):
        """A just-joined server (no EWMA row yet) is neither flooded nor
        starved: it ranks at the mean of the known rows."""
        addrs = ["a:1", "b:2", "c:3"]
        spans = {
            "a:1": {"e2e_ms": 10.0, "requests": 5},
            "b:2": {"e2e_ms": 30.0, "requests": 5},
        }
        scores = routing.ewma_scores(range(3), addrs, spans)
        assert scores[0] == 10.0 and scores[1] == 30.0
        assert scores[2] == pytest.approx(20.0)
        # a row that never completed a request carries no signal
        spans["c:3"] = {"e2e_ms": None, "requests": 0}
        assert routing.ewma_scores(
            range(3), addrs, spans)[2] == pytest.approx(20.0)

    @pytest.mark.parametrize("policy", routing.ROUTING_POLICIES)
    def test_down_tier_never_outranks_ok_tier(self, policy):
        """The selection-side guard: a breaker-open/cooled remote is
        never ranked ahead of ANY healthy one, even with the best load
        signal of the pool."""
        tiers = {0: routing.TIER_OK, 1: routing.TIER_DOWN,
                 2: routing.TIER_OK}
        infl = {0: 9, 1: 0, 2: 7}          # the down one looks idle...
        scores = {0: 90.0, 1: 0.1, 2: 70.0}  # ...and fast
        order = routing.order_remotes(
            policy, tiers, 1, 3, inflight=infl, scores=scores)
        assert order[-1] == 1
        assert set(order[:2]) == {0, 2}

    @pytest.mark.parametrize("policy", routing.ROUTING_POLICIES)
    def test_draining_ranks_between_ok_and_down(self, policy):
        tiers = {0: routing.TIER_DOWN, 1: routing.TIER_DRAINING,
                 2: routing.TIER_OK}
        order = routing.order_remotes(policy, tiers, 0, 3,
                                      inflight={}, scores={})
        assert order == [2, 1, 0]

    def test_affinity_owner_promoted_within_its_tier_only(self):
        tiers = {0: routing.TIER_OK, 1: routing.TIER_OK,
                 2: routing.TIER_DOWN}
        # healthy owner: jumps to the very front
        assert routing.order_remotes(
            "rotate", tiers, 0, 3, affinity_owner=1)[:2] == [1, 0]
        # down owner: stickiness must NOT pin a session to a dead host
        order = routing.order_remotes(
            "rotate", tiers, 0, 3, affinity_owner=2)
        assert order == [0, 1, 2]


# ---------------------------------------------------------------------------
# Element-level routing: the two bugfix pins + draining hints
# ---------------------------------------------------------------------------
def _client_with_pool(n=3, **props):
    """An unstarted query client with a synthetic pool (no sockets)."""
    from nnstreamer_tpu.elements.query import _PoolState

    el = make_element("tensor_query_client", "q")
    for k, v in props.items():
        el.props[k] = v
    targets = [("127.9.9.9", 7100 + i) for i in range(n)]
    el._pstate = _PoolState([object() for _ in range(n)], targets, 0)
    return el


def _trip_breaker(el, target):
    b = el._breaker_for(target)
    for _ in range(int(el.props["breaker-threshold"])):
        b.record_failure()
    assert b.state == CircuitBreaker.OPEN
    return b


class TestClientRouting:
    @pytest.mark.parametrize("policy",
                             ["rotate", "least-inflight", "ewma"])
    def test_open_breaker_never_selected_over_closed_alternative(
            self, policy):
        """BUGFIX PIN: whatever the policy and however attractive its
        load signal, a remote with an OPEN breaker is ordered after
        every closed-breaker alternative — so the failover loop can
        never dial it while a healthy remote exists."""
        el = _client_with_pool(3, routing=policy)
        _trip_breaker(el, el._pstate.targets[0])
        # make the tripped remote maximally attractive to the policies
        with el._breakers_lock:
            el._remote_inflight["127.9.9.9:7101"] = 5
            el._remote_inflight["127.9.9.9:7102"] = 7
            el._remote_spans["127.9.9.9:7100"] = {
                "e2e_ms": 0.1, "requests": 100}
            el._remote_spans["127.9.9.9:7101"] = {
                "e2e_ms": 80.0, "requests": 100}
            el._remote_spans["127.9.9.9:7102"] = {
                "e2e_ms": 90.0, "requests": 100}
        for first in range(3):
            order = el._route_order(el._pstate, None, first)
            assert order[-1] == 0, (
                f"open-breaker remote ranked {order} (policy={policy}, "
                f"first={first})")

    def test_evicted_ewma_rows_are_never_consulted(self):
        """BUGFIX PIN: after `_rediscover` evicts a vanished endpoint,
        its (frozen, possibly absurdly-good) EWMA row must not influence
        routing.  Lookup is by CURRENT target, so a stale row is
        unreachable; the live endpoints rank on their own signals."""
        el = _client_with_pool(2, routing="ewma")
        with el._breakers_lock:
            # vanished endpoint left a frozen "fastest ever" row behind
            el._remote_spans["10.66.66.66:9999"] = {
                "e2e_ms": 0.001, "requests": 10_000}
            el._remote_spans["127.9.9.9:7100"] = {
                "e2e_ms": 50.0, "requests": 10}
            el._remote_spans["127.9.9.9:7101"] = {
                "e2e_ms": 5.0, "requests": 10}
        order = el._route_order(el._pstate, None, 0)
        assert order == [1, 0]
        # and the real _rediscover eviction removes such rows outright
        # (pinned in PR 7; re-checked here against the routing path)
        with el._breakers_lock:
            keep = {f"{h}:{p}" for h, p in el._pstate.targets}
            for key in [k for k in el._remote_spans if k not in keep]:
                del el._remote_spans[key]
            assert set(el._remote_spans) == keep

    def test_draining_hint_deprioritizes_before_any_dial(self):
        """Discovery-plane health: a host that ANNOUNCED it is draining
        ranks below every serving host — the client never pays the
        GOAWAY round trip to learn what the broker already told it."""
        el = _client_with_pool(3, routing="rotate")
        with el._breakers_lock:
            el._endpoint_hints = {"127.9.9.9:7100": {"draining": True}}
            el._hints_ts = time.monotonic()
        for first in range(3):
            order = el._route_order(el._pstate, None, first)
            assert order[-1] == 0
        # ...but still above a breaker-open host
        _trip_breaker(el, el._pstate.targets[1])
        order = el._route_order(el._pstate, None, 0)
        assert order == [2, 0, 1]

    def test_stale_draining_hint_decays(self):
        """A hints generation older than the TTL stops deprioritizing:
        a drained-then-restarted host must regain traffic even when no
        failure ever triggers a rediscovery."""
        el = _client_with_pool(2, routing="rotate")
        with el._breakers_lock:
            el._endpoint_hints = {"127.9.9.9:7100": {"draining": True}}
            el._hints_ts = time.monotonic() - el._HINT_TTL_S - 1.0
        assert el._route_order(el._pstate, None, 0) == [0, 1]

    def test_no_duplicate_registry_samples_per_scrape(self):
        """affinity_remaps / remote_inflight export through exactly ONE
        collector path — duplicate series would be invalid Prometheus
        exposition and double-count on aggregation."""
        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=986 connect-type=tcp ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=986")
        server.start()
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q "
            "connect-type=tcp host=localhost "
            f"port={server['ssrc'].props['port']} affinity-key=sess ! "
            "tensor_sink name=out")
        client.start()
        try:
            from nnstreamer_tpu.core.buffer import TensorFrame

            client["src"].push(TensorFrame(
                [np.float32([1])], meta={"sess": "k"}))
            client["src"].end_of_stream()
            client.wait(timeout=30)
            snap = client.metrics_snapshot()
            by_key = Counter(
                (s.name, tuple(sorted(s.labels.items())))
                for s in snap.samples)
            dupes = {k: n for k, n in by_key.items() if n > 1}
            assert not dupes, f"duplicate series in one scrape: {dupes}"
        finally:
            client.stop()
            server.stop()

    def test_affinity_remap_counting(self):
        """A remap is an OWNER change for a known key — re-routing the
        same key to its unchanged owner counts nothing."""
        el = _client_with_pool(2, **{"affinity-key": "sess"})
        from nnstreamer_tpu.core.buffer import TensorFrame

        f = TensorFrame([np.float32([1])], meta={"sess": "k1"})
        el._route_order(el._pstate, f, 0)
        el._route_order(el._pstate, f, 1)
        assert el._affinity_remaps == 0
        owner = routing.rendezvous_owner("k1", el._pstate.targets)
        # shrink the fleet so k1's owner changes iff it owned it
        from nnstreamer_tpu.elements.query import _PoolState

        survivors = [t for i, t in enumerate(el._pstate.targets)
                     if i != owner]
        el._pstate = _PoolState([object()], survivors, 1)
        el._route_order(el._pstate, f, 0)
        assert el._affinity_remaps == 1

    def test_affinity_batch_uses_first_frame_key(self):
        el = _client_with_pool(3, **{"affinity-key": "sess"})
        from nnstreamer_tpu.core.buffer import TensorFrame

        f = TensorFrame([np.float32([1])], meta={"sess": "sticky"})
        owner = routing.rendezvous_owner("sticky", el._pstate.targets)
        for first in range(3):
            assert el._route_order(el._pstate, [f, f], first)[0] == owner


# ---------------------------------------------------------------------------
# Per-tenant admission truth table (core/liveness.py)
# ---------------------------------------------------------------------------
class TestTenantAdmission:
    def test_quota_shed_is_per_tenant_and_exactly_counted(self):
        a = TenantAdmissionController(quotas={"hot": 2})
        a.admit(tenant="hot")
        a.admit(tenant="hot")
        with pytest.raises(ServerBusyError) as ei:
            a.admit(tenant="hot")
        assert ei.value.reason == "quota" and ei.value.tenant == "hot"
        # other tenants are untouched by hot's quota
        a.admit(tenant="cold")
        a.admit(tenant="")  # unnamed: never quota-bound
        snap = a.snapshot()["tenants"]
        assert snap["hot"] == {
            "inflight": 2, "admitted": 2, "shed": 1, "quota": 2}
        assert snap["cold"]["shed"] == 0
        # release frees the quota slot
        a.release(tenant="hot")
        a.admit(tenant="hot")

    def test_retry_after_paces_with_shed_streak_and_resets(self):
        a = TenantAdmissionController(quotas={"t": 1},
                                      clock=lambda: 0.0)
        a.admit(tenant="t")
        afters = []
        for _ in range(10):
            with pytest.raises(ServerBusyError) as ei:
                a.admit(tenant="t", retry_after=0.05)
            afters.append(ei.value.retry_after)
        assert afters[0] == pytest.approx(0.05)
        assert afters[1] == pytest.approx(0.10)
        assert max(afters) == pytest.approx(
            0.05 * TenantAdmissionController.RETRY_AFTER_CAP)
        assert afters == sorted(afters)
        # an admit resets the pacing
        a.release(tenant="t")
        a.admit(tenant="t")
        a.release(tenant="t")
        a.admit(tenant="t")
        with pytest.raises(ServerBusyError) as ei:
            a.admit(tenant="t", retry_after=0.05)
        assert ei.value.retry_after == pytest.approx(0.05)

    def test_priority_classes_shed_low_first(self):
        """high=8, low=2 -> ceilings [2, 4, 6, 8]: under pressure the
        low classes hit their ceiling while priority 3 still has
        headroom (the weighted-shed order)."""
        a = TenantAdmissionController(high=8, low=2)
        for _ in range(6):
            a.admit(priority=3)
        for p in (0, 1, 2):
            with pytest.raises(ServerBusyError) as ei:
                a.admit(priority=p)
            assert ei.value.reason == "priority"
        a.admit(priority=3)  # 7/8: the top class is still admitted
        a.admit(priority=3)  # 8/8
        with pytest.raises(ServerBusyError) as ei:
            a.admit(priority=3)
        assert ei.value.reason == "load"

    def test_priority3_semantics_identical_to_base_watermark(self):
        """Requests without a priority class (= priority 3) see the
        EXACT pre-tenancy high/low hysteresis behavior."""
        a = TenantAdmissionController(high=4, low=1)
        for _ in range(4):
            a.admit()
        with pytest.raises(ServerBusyError):
            a.admit()
        a.release()
        a.release()  # inflight 2 > low 1: still shedding
        with pytest.raises(ServerBusyError):
            a.admit()
        a.release()  # inflight 1 <= low: band clears
        a.admit()

    def test_quota_checked_before_priority_and_load(self):
        a = TenantAdmissionController(high=8, low=2, quotas={"t": 1})
        a.admit(tenant="t", priority=0)
        with pytest.raises(ServerBusyError) as ei:
            a.admit(tenant="t", priority=0)
        assert ei.value.reason == "quota"

    def test_tenant_quota_busy_is_breaker_immune(self):
        a = TenantAdmissionController(quotas={"t": 1})
        a.admit(tenant="t")
        with pytest.raises(ServerBusyError) as ei:
            a.admit(tenant="t")
        assert is_remote_application_error(ei.value), (
            "tenant-quota BUSY must never count against the remote's "
            "breaker")

    def test_sustained_quota_shed_fires_rate_limited_incident(self):
        now = [0.0]
        fired = []
        a = TenantAdmissionController(
            quotas={"t": 1}, shed_window_s=5.0,
            on_sustained_shed=fired.append, clock=lambda: now[0])
        a.admit(tenant="t")
        for t in (0.0, 1.0, 4.9):
            now[0] = t
            with pytest.raises(ServerBusyError):
                a.admit(tenant="t")
        assert fired == []  # window not yet exceeded
        now[0] = 5.0
        with pytest.raises(ServerBusyError):
            a.admit(tenant="t")
        assert fired == ["t"]
        now[0] = 7.0  # rate limit: once per window
        with pytest.raises(ServerBusyError):
            a.admit(tenant="t")
        assert fired == ["t"]
        now[0] = 10.0
        with pytest.raises(ServerBusyError):
            a.admit(tenant="t")
        assert fired == ["t", "t"]
        # an admit ends the episode entirely
        a.release(tenant="t")
        now[0] = 20.0
        a.admit(tenant="t")
        a.release(tenant="t")
        assert a.snapshot()["tenants"]["t"]["shed"] == 6

    def test_load_and_priority_sheds_keep_flat_retry_after(self):
        """Streak-scaled pacing is a QUOTA property: global watermark /
        priority sheds keep the flat pre-tenancy retry-after, so
        unnamed clients sharing the \"\" ledger never couple each
        other's backoff."""
        a = TenantAdmissionController(high=2, low=0)
        a.admit()
        a.admit()
        for _ in range(10):
            with pytest.raises(ServerBusyError) as ei:
                a.admit(retry_after=0.05)
            assert ei.value.reason == "load"
            assert ei.value.retry_after == pytest.approx(0.05)

    def test_tenant_table_is_bounded_with_loud_eviction(self):
        """The tenant name is client-controlled wire input: the ledger
        table caps at TENANT_MAP_MAX, evicting only IDLE
        least-recently-active rows, and counts evictions."""
        a = TenantAdmissionController()
        held = [f"held-{i}" for i in range(4)]
        for t in held:
            a.admit(tenant=t)  # in flight: must never be evicted
        for i in range(TenantAdmissionController.TENANT_MAP_MAX * 2):
            a.admit(tenant=f"churn-{i}")
            a.release(tenant=f"churn-{i}")
        snap = a.snapshot()
        assert len(snap["tenants"]) <= (
            TenantAdmissionController.TENANT_MAP_MAX)
        assert snap["tenants_evicted"] > 0
        for t in held:
            assert snap["tenants"][t]["inflight"] == 1
        # aggregate history survives eviction
        assert snap["admitted"] == (
            len(held) + TenantAdmissionController.TENANT_MAP_MAX * 2)

    def test_parse_tenant_quotas(self):
        assert parse_tenant_quotas("a:8, b:4") == {"a": 8, "b": 4}
        assert parse_tenant_quotas("") == {}
        with pytest.raises(ValueError):
            parse_tenant_quotas("a:-1")
        with pytest.raises(ValueError):
            parse_tenant_quotas("nocolon")


# ---------------------------------------------------------------------------
# Tenant admission over the wire (both shapes of BUSY, exact accounting)
# ---------------------------------------------------------------------------
class TestTenantAdmissionE2E:
    def _server(self, sid, quotas, sleep=0.05, max_inflight=16):
        pipe = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} connect-type=tcp "
            f"max-inflight={max_inflight} tenant-quotas={quotas} ! "
            f"identity sleep={sleep} ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            f"tensor_query_serversink id={sid}")
        pipe.start()
        return pipe, pipe["ssrc"].props["port"]

    def test_hot_tenant_sheds_and_recovers_without_breaker_trips(self):
        """A tenant over its quota is shed with BUSY (carried per-tenant
        retry-after), retries deliver everything eventually, the
        breaker never trips, and the server's per-tenant ledger is
        exact."""
        sp, port = self._server(981, "hot:1")
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"host=localhost port={port} tenant=hot busy-retries=40 "
            "retry-backoff=0.01 max-in-flight=4 timeout=5 ! "
            "tensor_sink name=out")
        client.start()
        try:
            n = 8
            for i in range(n):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=60)
            vals = sorted(
                float(f.tensors[0][0]) for f in client["out"].frames)
            assert vals == [i * 2.0 for i in range(n)]
            hq = client.health()["q"]
            assert hq["busy_replies"] > 0, "the quota actually bound"
            for snap in hq["breakers"].values():
                assert snap["trips"] == 0 and snap["state"] == "closed"
            tenants = sp.health()["ssrc"]["tenants"]
            assert tenants["hot"]["admitted"] == n
            assert tenants["hot"]["shed"] == hq["busy_replies"]
            assert tenants["hot"]["quota"] == 1
        finally:
            client.stop()
            sp.stop()

    def test_tenant_meta_crosses_grpc_too(self):
        pipe = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=983 connect-type=grpc "
            "max-inflight=16 tenant-quotas=g:2 ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=983")
        pipe.start()
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q "
            f"connect-type=grpc host=localhost "
            f"port={pipe['ssrc'].props['port']} tenant=g "
            "busy-retries=20 retry-backoff=0.01 max-in-flight=2 ! "
            "tensor_sink name=out")
        client.start()
        try:
            for i in range(4):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=30)
            assert sorted(
                float(f.tensors[0][0]) for f in client["out"].frames
            ) == [0.0, 2.0, 4.0, 6.0]
            assert pipe.health()["ssrc"]["tenants"]["g"]["admitted"] == 4
        finally:
            client.stop()
            pipe.stop()


# ---------------------------------------------------------------------------
# Sustained shed -> flight-recorder incident (e2e)
# ---------------------------------------------------------------------------
class TestSustainedShedIncident:
    def test_incident_dump_names_the_tenant(self, tmp_path):
        pipe = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=984 connect-type=tcp "
            "max-inflight=16 tenant-quotas=drowning:1 shed-window=0.15 ! "
            "identity sleep=0.4 ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=984")
        pipe.enable_flight_recorder(dump_dir=str(tmp_path))
        pipe.start()
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"host=localhost port={pipe['ssrc'].props['port']} "
            "tenant=drowning busy-retries=60 retry-backoff=0.01 "
            "max-in-flight=4 timeout=10 ! tensor_sink name=out")
        client.start()
        try:
            for i in range(3):
                client["src"].push(np.float32([i]))
            deadline = time.monotonic() + 15
            dumps = []
            while time.monotonic() < deadline and not dumps:
                dumps = [p for p in os.listdir(tmp_path)
                         if "tenant_shed" in p]
                time.sleep(0.05)
            assert dumps, "sustained quota shed produced no incident dump"
            client["src"].end_of_stream()
            client.wait(timeout=60)
        finally:
            client.stop()
            pipe.stop()


# ---------------------------------------------------------------------------
# Discovery-plane health propagation (broker-level)
# ---------------------------------------------------------------------------
class TestDiscoveryHealth:
    def test_announce_update_is_visible_to_discoverers(self):
        from nnstreamer_tpu.distributed.hybrid import (
            Announcement,
            discover_endpoints,
        )
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        broker = MiniBroker()
        try:
            ann = Announcement(
                "127.0.0.1", broker.port, "nns/query/ft/one",
                {"host": "127.0.0.1", "port": 7199, "draining": False})
            seen = {}

            def validate(topic, info):
                seen[topic] = dict(info)
                return True

            discover_endpoints(
                "127.0.0.1", broker.port, "nns/query/ft/#",
                timeout_s=5.0, validate=validate)
            assert seen["nns/query/ft/one"]["draining"] is False
            ann.update({"draining": True, "inflight": 3})
            seen.clear()
            discover_endpoints(
                "127.0.0.1", broker.port, "nns/query/ft/#",
                timeout_s=5.0, validate=validate)
            assert seen["nns/query/ft/one"]["draining"] is True
            assert seen["nns/query/ft/one"]["inflight"] == 3
            assert seen["nns/query/ft/one"]["port"] == 7199
            ann.clear()
        finally:
            broker.close()

    def test_fresh_healthy_announce_overrides_stale_draining_hint(self):
        """A restarted server announces healthy on a NEW instance topic
        but the SAME host:port — its announce must override the dead
        instance's retained draining=true, or the healthy replacement
        would sit in TIER_DRAINING for a whole hint TTL."""
        import socket

        from nnstreamer_tpu.distributed.hybrid import Announcement
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        broker = MiniBroker()
        ls = socket.socket()
        ls.bind(("127.0.0.1", 0))
        ls.listen(1)  # probe_endpoint needs a live listener
        port = ls.getsockname()[1]
        try:
            old = Announcement(
                "127.0.0.1", broker.port, "nns/query/hint/old",
                {"host": "127.0.0.1", "port": port,
                 "connect_type": "tcp", "draining": True})
            new = Announcement(
                "127.0.0.1", broker.port, "nns/query/hint/new",
                {"host": "127.0.0.1", "port": port,
                 "connect_type": "tcp", "draining": False})
            el = make_element("tensor_query_client", "q")
            el.props["topic"] = "hint"
            el.props["dest-port"] = broker.port
            el.props["connect-type"] = "tcp"
            el.props["discovery-timeout"] = 10.0
            targets = el._discover_targets()
            assert targets == [("127.0.0.1", port)]
            assert el._endpoint_hints == {}, (
                "stale draining hint survived a fresh healthy announce: "
                f"{el._endpoint_hints}")
            old.clear()
            new.clear()
        finally:
            ls.close()
            broker.close()

    def test_serversrc_announces_draining_on_drain(self):
        from nnstreamer_tpu.distributed.hybrid import discover_endpoints
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        broker = MiniBroker()
        server = client = None
        try:
            server = parse_pipeline(
                "tensor_query_serversrc name=ssrc id=985 connect-type=tcp "
                "topic=drainft dest-host=127.0.0.1 "
                f"dest-port={broker.port} drain-deadline=5 ! "
                "identity sleep=0.5 ! "
                "tensor_filter framework=scaler custom=factor:2 ! "
                "tensor_query_serversink id=985")
            server.start()
            port = server["ssrc"].props["port"]
            # hold one request in flight so the drain STAYS draining
            client = parse_pipeline(
                "appsrc name=src ! tensor_query_client name=q "
                f"connect-type=tcp host=localhost port={port} timeout=10 "
                "! tensor_sink name=out")
            client.start()
            client["src"].push(np.float32([7]))
            deadline = time.monotonic() + 5
            core = server["ssrc"]._core
            while (core.admission.inflight == 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            server["ssrc"].request_drain()
            deadline = time.monotonic() + 5
            state = {}
            while time.monotonic() < deadline:
                found = {}

                def validate(topic, info, _found=found):
                    _found[topic] = dict(info)
                    return True

                discover_endpoints(
                    "127.0.0.1", broker.port, "nns/query/drainft/#",
                    timeout_s=2.0, validate=validate)
                state = next(iter(found.values()), {})
                if state.get("draining"):
                    break
                time.sleep(0.05)
            assert state.get("draining") is True, (
                f"drain not propagated to the broker: {state}")
            client["src"].end_of_stream()
            client.wait(timeout=30)
        finally:
            if client is not None:
                client.stop()
            if server is not None:
                server.stop()
            broker.close()


# ---------------------------------------------------------------------------
# The fleet chaos e2e (acceptance)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
class TestFleetChaos:
    """3 tcp servers under continuous 2-tenant load survive scripted
    kill + rolling restart + server join with zero lost/duplicated
    frames, exact per-tenant accounting, zero breaker trips from
    drains, and bounded affinity remaps; a hot-tenant burst at 2x quota
    sheds ONLY the hot tenant while the victim keeps >= 90% of its
    no-burst throughput."""

    KEYS = 300

    def test_fleet_survives_scripted_churn(self):
        from chaos_fleet import FleetHarness

        h = FleetHarness(tenant_quotas="A:6,B:2", server_sleep=0.01,
                         max_inflight=32, shed_window_s=30.0)
        try:
            self._run(h)
        finally:
            h.stop_all()

    def _run(self, h):
        for i in range(3):
            h.start_server(i)
        ca = h.make_client("A", tenant="A", routing="least-inflight",
                           busy_retries=12)
        cb = h.make_client("B", tenant="B", routing="ewma",
                           max_in_flight=2, busy_retries=12)
        ck = h.make_client("K", affinity=True, routing="rotate",
                           max_in_flight=8)
        keys = [f"sess-{k}" for k in range(self.KEYS)]
        seq = iter(range(10**6))

        def tenant_wave(n=16):
            for _ in range(n):
                ca.push(next(seq))
                cb.push(next(seq))
            ca.settle()
            cb.settle()

        def key_wave():
            for k in keys:
                ck.push(next(seq), key=k)
            ck.settle()

        # -- phase 1: baseline --------------------------------------------
        tenant_wave()
        key_wave()
        remaps0 = ck.health()["affinity_remaps"]

        # -- phase 2: rolling restart under load (GOAWAY, zero loss) ------
        for _ in range(24):
            ca.push(next(seq))
        roll = h.rolling_restart(0)
        assert roll["drain"]["dropped"] == 0
        ca.settle()
        tenant_wave()
        # same port came back: no membership change, no affinity remap
        key_wave()
        assert ck.health()["affinity_remaps"] == remaps0
        goaways = (roll["health"]["goaway_sent"]
                   + sum(c.health()["goaway_replies"]
                         for c in (ca, cb, ck)))
        assert goaways >= 1, "the roll was never observed as GOAWAY"

        # -- phase 3: server join (bounded remap) -------------------------
        h.add_server()
        assert h.refresh_client(ck), "join must swap the affinity pool"
        key_wave()
        remap_join = ck.health()["affinity_remaps"] - remaps0
        bound = math.ceil(self.KEYS / 3)
        assert 0 < remap_join <= bound, (
            f"join remapped {remap_join} keys (bound ceil(K/N) = {bound})")

        # -- phase 4: hard kill mid-load (zero loss, bounded remap) -------
        for _ in range(16):
            ca.push(next(seq))
            cb.push(next(seq))
        h.kill_server(2)
        ca.settle(timeout=60)
        cb.settle(timeout=60)
        for c in (ca, cb, ck):
            h.refresh_client(c)
        remaps_prekill = ck.health()["affinity_remaps"]
        tenant_wave()
        key_wave()
        remap_kill = ck.health()["affinity_remaps"] - remaps_prekill
        assert remap_kill <= math.ceil(self.KEYS / 3)

        # -- phase 5: hot-tenant burst at 2x quota ------------------------
        # baseline: the victim tenant alone
        a0 = len(ca.values())
        for _ in range(30):
            ca.push(next(seq))
        ca.settle(timeout=60)
        baseline_delivered = len(ca.values()) - a0
        assert baseline_delivered == 30
        # burst: B floods at ~2x its fleet quota (3 live servers x
        # quota 2 = 6 slots; 8+ concurrent singles, no retries) while
        # A keeps pushing its normal load
        tenants_before = h.fleet_tenants()
        burst = h.make_client(
            "Bburst", tenant="B", routing="least-inflight",
            max_in_flight=12, retries=0, busy_retries=0,
            degrade="skip", static_hosts=True)
        a1 = len(ca.values())
        for i in range(60):
            burst.push(next(seq))
            if i % 2 == 0:
                ca.push(next(seq))
        ca.settle(timeout=60)
        burst.settle(timeout=60)
        tenants_after = h.fleet_tenants()
        burst_delivered = len(ca.values()) - a1
        # victim keeps >= 90% of its no-burst baseline (count-based:
        # same 30-frame load, quota guarantees the slots)
        assert burst_delivered >= 0.9 * baseline_delivered, (
            f"victim tenant degraded: {burst_delivered}/30 delivered "
            f"under burst vs {baseline_delivered}/30 baseline")
        # the hot tenant absorbed ALL the shedding, exactly accounted
        shed_a = (tenants_after["A"]["shed"]
                  - tenants_before["A"]["shed"])
        shed_b = (tenants_after["B"]["shed"]
                  - tenants_before["B"]["shed"])
        bh = burst.health()
        assert shed_a == 0
        assert shed_b == bh["busy_replies"] > 0
        assert bh["busy_replies"] == bh["degraded_frames"]
        adm_b = (tenants_after["B"]["admitted"]
                 - tenants_before["B"]["admitted"])
        assert adm_b == len(burst.values())
        assert len(burst.values()) + bh["degraded_frames"] == 60

        # -- final verdict -------------------------------------------------
        for c in (ca, cb, ck, burst):
            c.finish()
        v = h.verdict()
        assert v["lost"] == 0 and v["duplicated"] == 0, v
        assert v["breaker_trips"] == 0, v
        # bounded per-tenant p50 skew (loose CI bound: paced busy
        # retries inflate the hot tenant, but never unboundedly)
        p50 = v["p50_ms"]
        if p50["A"] > 0 and p50["B"] > 0:
            assert p50["B"] <= 30 * max(p50["A"], 1.0), p50
        # per-tenant ledgers stayed internally consistent fleet-wide
        tenants = v["tenants"]
        assert tenants["A"]["shed"] == 0
        assert tenants["B"]["shed"] >= shed_b


# ---------------------------------------------------------------------------
# The device-loss chaos e2e (acceptance — degrade, don't die)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
class TestDeviceLossChaos:
    """A mesh member dies mid-decode under concurrent slotted
    generation load: every live stream hands off with resume state and
    lands bit-exact to the oracle, the engine re-meshes atomically onto
    the survivors (``gen_device_lost == 1`` / ``gen_remeshes == 1``,
    migrations exactly equal handoffs), the wounded server announces
    ``degraded:true`` on the discovery plane (observed client-side
    after one rediscovery and reflected in health), and ZERO breakers
    trip anywhere — the chip died, no server did."""

    def test_device_loss_survived_fleet_wide(self):
        from chaos_fleet import run_device_loss_script

        v = run_device_loss_script(servers=3, streams=4, seed=0)
        assert v["ok"], v
        assert v["exact"] == 4 and v["mismatched"] == 0, v
        assert v["gen"]["gen_device_lost"] == 1, v
        assert v["gen"]["gen_remeshes"] == 1, v
        assert v["handed_off"] >= 1, v
        assert v["resumes"]["stream_migrations"] == v["handed_off"], v
        assert v["degraded_announce_seen"] and v["victim_degraded_health"], v
        assert v["breaker_trips"] == 0, v
