"""Liveness layer: stall watchdog (fake clock), deadline QoS truth
table, overload admission control, BUSY client backpressure, latency
fault injection, and the chaos acceptance runs.

All tier-1 fast: fake clocks for the watchdog/deadline units, real
timeouts capped at fractions of a second for the e2e chaos runs.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.liveness import (
    DEADLINE_META,
    AdmissionController,
    ServerBusyError,
    StallError,
    Watchdog,
    deadline_remaining,
    is_expired,
    stamp_deadline,
)
from nnstreamer_tpu.core.resilience import (
    FAULTS,
    is_remote_application_error,
    is_transient,
)
from nnstreamer_tpu.elements.basic import AppSrc, TensorSink
from nnstreamer_tpu.pipeline import parse_pipeline
from nnstreamer_tpu.pipeline.element import TransformElement
from nnstreamer_tpu.pipeline.pipeline import Pipeline


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def frame(v=0.0, pts=None):
    return TensorFrame([np.float32([v])], pts=pts)


# ---------------------------------------------------------------------------
# deadline helpers (fake clock) — the drop-vs-deliver truth table
# ---------------------------------------------------------------------------
class TestDeadlineTruthTable:
    def test_wall_anchored_stamp_and_remaining(self):
        clk = FakeClock(100.0)
        f = stamp_deadline(frame(), 0.5, clock=clk)
        assert f.meta[DEADLINE_META] == 100.5
        assert deadline_remaining(f, clock=clk) == 0.5
        clk.t = 100.4
        assert deadline_remaining(f, clock=clk) == pytest.approx(0.1)

    def test_pts_anchored_stamp(self):
        clk = FakeClock(100.0)
        f = stamp_deadline(frame(pts=2.0), 0.5, clock=clk, anchor=90.0)
        assert f.meta[DEADLINE_META] == 92.5  # anchor + pts + budget

    def test_no_deadline_never_expires(self):
        f = frame()
        assert deadline_remaining(f) is None
        assert not is_expired(f, now=1e12)

    def test_boundary_drop_vs_deliver(self):
        # the pinned boundary contract: delivered strictly BEFORE the
        # deadline; dropped from the instant now >= deadline (zero
        # remaining budget cannot pay for any downstream work)
        clk = FakeClock(0.0)
        f = stamp_deadline(frame(), 1.0, clock=clk)
        assert not is_expired(f, now=0.999999)   # deliver
        assert is_expired(f, now=1.0)            # drop AT the boundary
        assert is_expired(f, now=1.5)            # drop past it

    def test_scheduler_drops_expired_with_accounting(self):
        # a frame whose budget died while queued is dropped before the
        # element runs, counted exactly, and warned on the bus
        pipe = Pipeline("dl")
        src, sink = AppSrc("src"), TensorSink("out")
        pipe.chain(src, sink)
        warnings = []
        pipe.add_bus_watcher(
            lambda m: warnings.append(m) if m.kind == "warning" else None)
        pipe.start()
        expired = stamp_deadline(frame(1.0), -0.1)   # already dead
        alive = stamp_deadline(frame(2.0), 60.0)
        src.push(expired)
        src.push(alive)
        src.push(frame(3.0))                          # no deadline
        src.end_of_stream()
        pipe.wait(timeout=20)
        vals = [float(f.tensors[0][0]) for f in sink.frames]
        assert vals == [2.0, 3.0]
        assert pipe.health()["out"]["deadline_drops"] == 1
        assert [m for m in warnings if m.data.get("qos") == "deadline"]
        pipe.stop()

    def test_late_policy_deliver_processes_expired(self):
        pipe = Pipeline("dl2")
        src, sink = AppSrc("src"), TensorSink("out")
        sink.set_property("late-policy", "deliver")
        pipe.chain(src, sink)
        pipe.start()
        src.push(stamp_deadline(frame(1.0), -0.1))
        src.end_of_stream()
        pipe.wait(timeout=20)
        assert len(sink.frames) == 1
        assert pipe.health()["out"]["deadline_drops"] == 0
        pipe.stop()

    def test_source_deadline_s_stamps_frames(self):
        pipe = parse_pipeline(
            "appsrc name=src deadline-s=60 ! tensor_sink name=out")
        pipe.start()
        pipe["src"].push(np.float32([1]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=20)
        out = pipe["out"].frames[0]
        rem = deadline_remaining(out)
        assert rem is not None and 0 < rem <= 60
        pipe.stop()


# ---------------------------------------------------------------------------
# tensor_rate QoS feedback
# ---------------------------------------------------------------------------
class TestTensorRateQos:
    def test_note_qos_sheds_up_to_late_pts(self):
        from nnstreamer_tpu.elements.flow import TensorRate

        r = TensorRate("r")
        r.start()
        r.note_qos(pts=0.5, lateness=0.25)  # shed everything <= 0.75
        assert r.transform(frame(1.0, pts=0.6)) is None
        assert r.transform(frame(2.0, pts=0.75)) is None
        out = r.transform(frame(3.0, pts=0.76))
        assert out is not None
        assert r.qos_dropped == 2 and r.dropped == 2
        assert r.get_property("qos-dropped") == 2

    def test_qos_false_ignores_feedback(self):
        from nnstreamer_tpu.elements.flow import TensorRate

        r = TensorRate("r")
        r.set_property("qos", False)
        r.start()
        r.note_qos(pts=0.5, lateness=0.25)
        assert r.transform(frame(1.0, pts=0.6)) is not None
        assert r.qos_dropped == 0

    def test_pipeline_routes_deadline_miss_to_upstream_rate(self):
        # a frame that expires DOWNSTREAM of tensor_rate (between the
        # slow element and the sink) must feed back to the rate sitting
        # upstream (≙ GStreamer QoS events travelling upstream)
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_rate name=rate ! "
            "identity sleep=0.1 ! tensor_sink name=out")
        pipe.start()
        f = stamp_deadline(frame(1.0, pts=0.0), 0.05)  # dies mid-pipeline
        pipe["src"].push(f)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=20)
        assert pipe.health()["out"]["deadline_drops"] == 1
        # the miss reached the throttle: it now sheds around pts 0
        assert pipe["rate"]._qos_until > 0.0
        pipe.stop()


# ---------------------------------------------------------------------------
# FaultInjector delay= / hang=
# ---------------------------------------------------------------------------
class TestLatencyFaults:
    def test_delay_fault_injects_latency_then_proceeds(self):
        FAULTS.arm("t.delay", delay=0.08, times=1)
        t0 = time.monotonic()
        FAULTS.check("t.delay")  # must NOT raise
        assert time.monotonic() - t0 >= 0.07
        t0 = time.monotonic()
        FAULTS.check("t.delay")  # times=1: second call is free
        assert time.monotonic() - t0 < 0.05
        assert FAULTS.stats("t.delay") == {"calls": 2, "fired": 1}

    def test_hang_fault_interrupted_raises_stall(self):
        FAULTS.arm("t.hang", hang=True, times=1)
        flag = threading.Event()
        t = threading.Timer(0.05, flag.set)
        t.start()
        t0 = time.monotonic()
        with pytest.raises(StallError):
            FAULTS.check("t.hang", interrupt=flag.is_set)
        assert time.monotonic() - t0 >= 0.04
        t.cancel()

    def test_reset_releases_a_hanging_check(self):
        FAULTS.arm("t.hang2", hang=True)
        errs = []

        def hung():
            try:
                FAULTS.check("t.hang2")
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        th = threading.Thread(target=hung, daemon=True)
        th.start()
        time.sleep(0.05)
        assert th.is_alive()  # wedged, as designed
        FAULTS.reset()        # teardown valve
        th.join(timeout=2)
        assert not th.is_alive()
        assert len(errs) == 1 and isinstance(errs[0], StallError)

    def test_stall_error_is_transient(self):
        assert is_transient(StallError("x"))  # restart can cure a stall


# ---------------------------------------------------------------------------
# Watchdog (fake clock)
# ---------------------------------------------------------------------------
class TestWatchdogUnit:
    def test_overrun_flagged_once_per_episode(self):
        clk = FakeClock()
        wd = Watchdog(clock=clk)
        events = []
        w = wd.register("f", frame_deadline=1.0,
                        on_event=lambda w, k, e: events.append((k, e)))
        wd.begin(w)
        clk.t = 0.5
        assert wd.check() == []          # inside the budget
        clk.t = 1.2
        assert wd.check() == [("f", "overrun", pytest.approx(1.2))]
        assert wd.check() == []          # same episode: no re-flag
        wd.done(w)
        assert w.overruns == 1 and w.frames_done == 1
        wd.begin(w)                      # new episode
        clk.t = 2.5
        assert len(wd.check()) == 1
        assert events and events[0][0] == "overrun"

    def test_stall_needs_queued_input_and_no_progress(self):
        clk = FakeClock()
        wd = Watchdog(clock=clk)
        depth = [0]
        w = wd.register("f", stall_timeout=2.0, qsize=lambda: depth[0])
        clk.t = 3.0
        assert wd.check() == []          # idle + empty queue: healthy
        depth[0] = 4
        assert wd.check() == [("f", "stall", pytest.approx(3.0))]
        assert w.stalls == 1
        clk.t = 4.0
        assert wd.check() == []          # re-flag only every stall_timeout
        clk.t = 5.0
        assert len(wd.check()) == 1
        wd.begin(w)
        wd.done(w)                       # progress resets the clock
        clk.t = 6.0
        assert wd.check() == []

    def test_stall_timeout_alone_detects_in_call_hang(self):
        # an element hung INSIDE handle_frame must be detectable with
        # only stall-timeout armed (frame-deadline is the per-call
        # refinement, not a prerequisite) — the in-flight call counts
        # as pending work even with an empty mailbox
        clk = FakeClock()
        wd = Watchdog(clock=clk)
        w = wd.register("f", stall_timeout=1.0, qsize=lambda: 0)
        wd.begin(w)
        clk.t = 0.5
        assert wd.check() == []
        clk.t = 1.5
        assert wd.check() == [("f", "stall", pytest.approx(1.5))]
        assert w.stalls == 1

    def test_overrun_wins_the_tie_over_stall(self):
        # both armed, hung in-call: the first sweep reports the overrun;
        # the stall only fires on LATER sweeps (once per stall_timeout)
        clk = FakeClock()
        wd = Watchdog(clock=clk)
        w = wd.register("f", stall_timeout=1.0, frame_deadline=1.0,
                        qsize=lambda: 0)
        wd.begin(w)
        clk.t = 1.5
        assert wd.check() == [("f", "overrun", pytest.approx(1.5))]
        clk.t = 2.5
        assert wd.check() == [("f", "stall", pytest.approx(2.5))]

    def test_policy_validated(self):
        wd = Watchdog()
        with pytest.raises(ValueError):
            wd.register("f", policy="reboot")

    def test_min_interval_quarter_of_tightest_bound(self):
        wd = Watchdog()
        assert wd.min_interval() == 0.5  # nothing armed
        wd.register("a", frame_deadline=0.4)
        wd.register("b", stall_timeout=2.0)
        assert wd.min_interval() == pytest.approx(0.1)

    def test_snapshot(self):
        wd = Watchdog(clock=FakeClock())
        w = wd.register("f", frame_deadline=1.0)
        wd.begin(w)
        snap = wd.snapshot()["f"]
        assert snap["busy"] and snap["frames_done"] == 0


# ---------------------------------------------------------------------------
# AdmissionController
# ---------------------------------------------------------------------------
class TestAdmissionController:
    def test_high_watermark_sheds(self):
        a = AdmissionController(high=2, low=1)
        assert a.try_admit() and a.try_admit()
        assert not a.try_admit()           # at high: shed
        snap = a.snapshot()
        assert snap["shed"] == 1 and snap["admitted"] == 2

    def test_hysteresis_holds_until_low_watermark(self):
        a = AdmissionController(high=4, low=2)
        for _ in range(4):
            assert a.try_admit()
        assert not a.try_admit()           # shedding begins
        a.release()                        # inflight 3 — still > low
        assert not a.try_admit()
        a.release()                        # inflight 2 == low: band clears
        assert a.try_admit()

    def test_unlimited_when_high_zero(self):
        a = AdmissionController(0)
        for _ in range(1000):
            assert a.try_admit()
        assert a.snapshot()["shed"] == 0

    def test_low_must_be_below_high(self):
        with pytest.raises(ValueError):
            AdmissionController(high=4, low=4)

    def test_negative_low_rejected(self):
        # a negative low could never clear the shedding band — the first
        # overload would brick the server into BUSY forever
        with pytest.raises(ValueError):
            AdmissionController(high=2, low=-1)

    def test_default_low_is_half(self):
        a = AdmissionController(high=8)
        assert a.low == 4

    def test_high_of_one_is_legal(self):
        # low defaults to 0: drain fully before re-admitting
        a = AdmissionController(high=1)
        assert a.low == 0
        assert a.try_admit() and not a.try_admit()
        a.release()
        assert a.try_admit()

    def test_explicit_low_of_zero_honored(self):
        # an explicit 0 means drain FULLY — it must not silently coerce
        # to the high//2 default
        a = AdmissionController(high=4, low=0)
        assert a.low == 0
        for _ in range(4):
            assert a.try_admit()
        assert not a.try_admit()
        for _ in range(3):
            a.release()
            assert not a.try_admit()  # still draining (inflight > 0)
        a.release()
        assert a.try_admit()

    def test_busy_error_is_backpressure_not_ill_health(self):
        e = ServerBusyError(retry_after=0.2)
        assert is_remote_application_error(e)  # never trips a breaker
        assert is_transient(e)                 # retry may succeed
        assert e.retry_after == 0.2


def test_tcp_pipeline_timeout_is_a_health_signal():
    # transport parity: a server pipeline that produces no answer in
    # time must surface as TimeoutError on raw TCP (≙ gRPC
    # DEADLINE_EXCEEDED) — NOT as a RemoteApplicationError, which would
    # immunize a wedged server against breakers/cooldowns
    from nnstreamer_tpu.distributed.tcp_query import (
        TcpQueryConnection,
        TcpQueryServer,
    )

    class StuckCore:
        def check_caps(self, caps):
            return caps

        def process(self, frames, timeout):
            raise TimeoutError("server pipeline produced no answer in time")

    srv = TcpQueryServer(StuckCore(), port=0)
    srv.start()
    conn = TcpQueryConnection("localhost", srv.port, timeout=5.0)
    try:
        with pytest.raises(TimeoutError) as ei:
            conn.invoke(frame())
        assert not is_remote_application_error(ei.value)
        assert is_transient(ei.value)  # retries/failover still apply
    finally:
        conn.close()
        srv.stop()


# ---------------------------------------------------------------------------
# BUSY-reply client behavior (unit: fake connection)
# ---------------------------------------------------------------------------
class TestBusyClient:
    def make_client(self, busy_retries=3, breaker_threshold=2):
        from nnstreamer_tpu.elements.query import TensorQueryClient, _PoolState

        q = TensorQueryClient("q")
        q.set_property("busy-retries", busy_retries)
        q.set_property("breaker-threshold", breaker_threshold)
        q.set_property("retries", 0)
        q.set_property("retry-backoff", 0.0)
        return q, _PoolState

    def test_busy_retried_on_own_budget_without_breaker_trip(self):
        q, _PoolState = self.make_client(busy_retries=3)

        class BusyTwice:
            addr = "fake:1"
            calls = 0

            def invoke(self, frame, timeout):
                type(self).calls += 1
                if type(self).calls <= 2:
                    raise ServerBusyError(retry_after=0.0)
                return frame

        q._pstate = _PoolState((BusyTwice(),), (("fake", 1),), 0)
        q._stopped = False
        f = frame(7.0)
        # retries=0 (single failover attempt) — yet BUSY gets its own
        # paced budget and the request ultimately succeeds
        assert q._invoke_failover(f, 0) is f
        assert BusyTwice.calls == 3
        info = q.health_info()
        assert info["busy_replies"] == 2
        snap = info["breakers"]["fake:1"]
        assert snap["state"] == "closed" and snap["trips"] == 0

    def test_busy_budget_exhausted_surfaces_error(self):
        q, _PoolState = self.make_client(busy_retries=1)

        class AlwaysBusy:
            addr = "fake:1"

            def invoke(self, frame, timeout):
                raise ServerBusyError(retry_after=0.0)

        q._pstate = _PoolState((AlwaysBusy(),), (("fake", 1),), 0)
        q._stopped = False
        with pytest.raises(ServerBusyError):
            q._invoke_failover(frame(), 0)
        snap = q.health_info()["breakers"]["fake:1"]
        assert snap["state"] == "closed" and snap["trips"] == 0

    def test_expired_request_counts_once_even_in_topic_mode_shape(self):
        # deadline expiry is TERMINAL: no rediscovery, no recursive
        # re-invoke, exactly one deadline_expired count per request
        q, _PoolState = self.make_client()
        q.set_property("retries", 3)  # would make resends "safe"

        class Slow:
            addr = "fake:1"

            def invoke(self, frame, timeout):
                raise ConnectionResetError("down")

        q._pstate = _PoolState((Slow(),), (("fake", 1),), 0)
        q._stopped = False
        f = stamp_deadline(frame(), -1.0)
        with pytest.raises(TimeoutError):
            q._invoke_failover(f, 0)
        assert q.health_info()["deadline_expired"] == 1

    def test_expired_request_stops_retrying(self):
        q, _PoolState = self.make_client()

        class NeverReached:
            addr = "fake:1"
            calls = 0

            def invoke(self, frame, timeout):
                type(self).calls += 1
                return frame

        q._pstate = _PoolState((NeverReached(),), (("fake", 1),), 0)
        q._stopped = False
        f = stamp_deadline(frame(), -1.0)  # budget already dead
        with pytest.raises(TimeoutError):
            q._invoke_failover(f, 0)
        assert NeverReached.calls == 0  # never even sent
        assert q.health_info()["deadline_expired"] == 1

    def test_request_timeout_propagates_remaining_budget(self):
        q, _ = self.make_client()
        f = stamp_deadline(frame(), 0.5)
        t, expired = q._request_timeout(f, 10.0)
        assert not expired and 0 < t <= 0.5
        t2, _ = q._request_timeout(frame(), 10.0)
        assert t2 == 10.0  # no deadline: configured timeout

    def test_answers_inherit_request_deadline(self):
        from nnstreamer_tpu.elements.query import TensorQueryClient

        req = stamp_deadline(frame(1.0), 9.0)
        ans = frame(2.0)
        TensorQueryClient._carry_deadline(req, ans)
        assert ans.meta[DEADLINE_META] == req.meta[DEADLINE_META]
        reqs = [stamp_deadline(frame(), 1.0), stamp_deadline(frame(), 2.0)]
        answers = [frame(), frame()]
        TensorQueryClient._carry_deadline(reqs, answers)
        assert [a.meta[DEADLINE_META] for a in answers] == [
            r.meta[DEADLINE_META] for r in reqs]


# ---------------------------------------------------------------------------
# watchdog in a live pipeline
# ---------------------------------------------------------------------------
class Pass(TransformElement):
    FACTORY_NAME = "pass"

    def transform(self, frame):
        return frame


class TestWatchdogPipeline:
    def test_overrun_warn_policy_counts_without_restart(self):
        # delay= fault overruns the frame-deadline; warn policy observes
        # (bus + health) but never interferes with the stream
        FAULTS.arm("element.mid.handle_frame", delay=0.3, times=1)
        pipe = Pipeline("wwarn")
        src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
        mid.set_property("frame-deadline", 0.1)
        mid.set_property("stall-policy", "warn")
        pipe.chain(src, mid, sink)
        warnings = []
        pipe.add_bus_watcher(
            lambda m: warnings.append(m) if m.kind == "warning" else None)
        pipe.start()
        for i in range(4):
            src.push(np.float32([i]))
        src.end_of_stream()
        pipe.wait(timeout=20)
        h = pipe.health()["mid"]
        assert len(sink.frames) == 4           # nothing lost
        assert h["overruns"] == 1 and h["restarts"] == 0
        assert [m for m in warnings if m.data.get("liveness") == "overrun"]
        pipe.stop()

    def test_hung_element_restarted_zero_loss(self):
        # the acceptance core: a hang is detected as an overrun, the
        # watchdog interrupts it, restart machinery retries the frame
        FAULTS.arm("element.mid.handle_frame", every=3, times=1, hang=True)
        pipe = Pipeline("wrestart")
        src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
        mid.set_property("frame-deadline", 0.12)
        mid.set_property("stall-policy", "restart")
        mid.set_property("restart-backoff", 0.01)
        pipe.chain(src, mid, sink)
        pipe.start()
        n = 8
        for i in range(n):
            src.push(np.float32([i]))
        src.end_of_stream()
        pipe.wait(timeout=30)
        h = pipe.health()["mid"]
        vals = [float(f.tensors[0][0]) for f in sink.frames]
        assert vals == [float(i) for i in range(n)]   # zero loss, in order
        assert h["restarts"] == 1 and h["overruns"] == 1
        assert h["state"] == "finished"
        pipe.stop()

    def test_stale_interrupt_does_not_spuriously_restart(self):
        # an escalation whose flagged call completes on its own leaves
        # the interrupt flag set; the NEXT healthy call must consume it
        # silently instead of raising a spurious StallError
        pipe = Pipeline("wstale")
        src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
        mid.set_property("stall-policy", "restart")
        mid.set_property("frame-deadline", 5.0)  # watchdog armed, quiet
        pipe.chain(src, mid, sink)
        pipe.start()
        mid._interrupted.set()  # simulate the race: flag set, call done
        FAULTS.arm("element.mid.handle_frame", hang=True, times=1)
        # were the stale flag leaked into the fault's interrupt predicate,
        # this frame would insta-StallError and burn a restart
        src.push(np.float32([1]))
        time.sleep(0.2)
        FAULTS.reset()  # release the (expected, genuine) hang
        src.push(np.float32([2]))
        src.end_of_stream()
        pipe.wait(timeout=20)
        h = pipe.health()["mid"]
        assert len(sink.frames) == 2
        assert h["restarts"] <= 1  # at most the genuine-hang recovery
        pipe.stop()

    def test_stall_policy_fail_tears_down(self):
        FAULTS.arm("element.mid.handle_frame", hang=True, times=1)
        pipe = Pipeline("wfail")
        src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
        mid.set_property("frame-deadline", 0.1)
        mid.set_property("stall-policy", "fail")
        pipe.chain(src, mid, sink)
        pipe.start()
        src.push(np.float32([1]))
        with pytest.raises(StallError):
            pipe.wait(timeout=20)
        pipe.stop()

    def test_hung_source_detected_and_restarted(self):
        # sources are monitored too: the busy window wraps each next()
        # on frames(), so a stalled producer (camera/publisher) is
        # flagged and stall-policy=restart re-opens it
        FAULTS.arm("element.cam.frames", every=4, times=1, hang=True)
        pipe = parse_pipeline(
            "videotestsrc name=cam num-buffers=10 width=4 height=4 "
            "frame-deadline=0.15 stall-policy=restart "
            "restart-backoff=0.01 ! tensor_sink name=out")
        pipe.start()
        pipe.wait(timeout=30)
        h = pipe.health()["cam"]
        assert h["restarts"] == 1 and h["overruns"] == 1
        assert len(pipe["out"].frames) == 10
        pipe.stop()

    def test_restart_budget_still_applies_to_stalls(self):
        # a permanently hanging element degrades after max-restarts
        # instead of restart-looping forever
        FAULTS.arm("element.mid.handle_frame", hang=True)
        pipe = Pipeline("wbudget")
        src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
        mid.set_property("frame-deadline", 0.08)
        mid.set_property("stall-policy", "restart")
        mid.set_property("restart-backoff", 0.0)
        mid.set_property("max-restarts", 2)
        mid.set_property("restart-window", 0.0)
        pipe.chain(src, mid, sink)
        pipe.start()
        src.push(np.float32([1]))
        with pytest.raises(StallError):
            pipe.wait(timeout=30)
        assert pipe.health()["mid"]["restarts"] == 2
        pipe.stop()
        FAULTS.reset()


# ---------------------------------------------------------------------------
# chaos acceptance: hang + deadline + overload, exact accounting
# ---------------------------------------------------------------------------
def _live_named_threads(names):
    return [t.name for t in threading.enumerate()
            if t.is_alive() and t.name in names]


@pytest.mark.chaos
class TestChaosLiveness:
    def test_hung_filter_detected_restarted_stream_completes(self):
        """Acceptance: a filter hung via an injected hang= fault is
        detected by the watchdog within its deadline, restarted under
        stall-policy=restart, and the stream reaches EOS with every
        frame accounted for (delivered + dead-lettered + deadline-
        dropped == pushed) and no leaked worker threads."""
        FAULTS.arm("filter.invoke", every=11, times=1, hang=True)
        pipe = parse_pipeline(
            "appsrc name=src deadline-s=20 ! "
            "tensor_filter name=f framework=scaler custom=factor:2 "
            "frame-deadline=0.15 stall-policy=restart restart-backoff=0.01 ! "
            "tensor_sink name=out")
        pipe.start()
        n = 30
        for i in range(n):
            pipe["src"].push(np.float32([i]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=60)
        h = pipe.health()
        hf = h["f"]
        vals = sorted(float(f.tensors[0][0]) for f in pipe["out"].frames)
        delivered = len(vals)
        dead_lettered = hf["dead_letters"]
        deadline_dropped = sum(e["deadline_drops"] for e in h.values())
        # exact accounting: no frame unaccounted, no dupes
        assert delivered + dead_lettered + deadline_dropped == n
        assert len(set(vals)) == delivered
        assert set(vals) <= {i * 2.0 for i in range(n)}
        # the hang was detected and cured by a restart
        assert hf["overruns"] == 1 and hf["restarts"] == 1
        assert hf["state"] == "finished"
        pipe.stop()
        deadline = time.monotonic() + 3
        while (_live_named_threads({"src", "f", "out"})
               and time.monotonic() < deadline):
            time.sleep(0.01)
        leaked = _live_named_threads({"src", "f", "out"})
        assert not leaked, f"leaked worker threads: {leaked}"

    def test_overloaded_server_sheds_busy_client_completes(self):
        """Acceptance: an overloaded query server sheds with BUSY instead
        of timing out; the client treats BUSY as paced backpressure and
        completes (degrade accounts any residue), the breaker stays
        closed, and shed counts are visible in Pipeline.health()."""
        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=981 port=0 "
            "connect-type=tcp max-inflight=2 low-watermark=1 "
            "retry-after=0.02 ! "
            "identity sleep=0.03 ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=981")
        server.start()
        port = server["ssrc"].props["port"]
        client = parse_pipeline(
            f"appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"host=localhost port={port} retries=0 busy-retries=10 "
            "retry-backoff=0.01 breaker-threshold=3 degrade=skip timeout=5 "
            "max-in-flight=8 ! tensor_sink name=out")
        client.start()
        try:
            n = 20
            for i in range(n):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=60)
            hq = client.health()["q"]
            hs = server.health()["ssrc"]
            vals = sorted(
                float(f.tensors[0][0]) for f in client["out"].frames)
            # the stream completed, degraded at worst — exact accounting
            assert len(vals) + hq["degraded_frames"] == n
            assert len(set(vals)) == len(vals)
            # shedding actually happened and is visible in health()
            assert hq["busy_replies"] > 0
            assert hs["load_shed"] > 0 and hs["admitted"] >= len(vals)
            # BUSY is backpressure, not ill-health: breaker stays closed
            for snap in hq["breakers"].values():
                assert snap["state"] == "closed" and snap["trips"] == 0
        finally:
            client.stop()
            server.stop()

    def test_server_side_expiry_before_invoke(self):
        """The wire deadline is honored END-TO-END: the server re-stamps
        each request with the client's remaining budget, and a frame
        whose budget dies inside the server pipeline is expired BEFORE
        the expensive invoke (visible in the server's health)."""
        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=982 port=0 "
            "connect-type=tcp ! "
            "identity name=slow sleep=0.3 ! "
            "tensor_filter name=sf framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=982")
        server.start()
        port = server["ssrc"].props["port"]
        client = parse_pipeline(
            f"appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"host=localhost port={port} retries=0 busy-retries=0 "
            "retry-backoff=0 breaker-threshold=0 degrade=skip timeout=0.2 ! "
            "tensor_sink name=out")
        client.start()
        try:
            for i in range(2):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=30)
            assert len(client["out"].frames) == 0  # all too slow
            # the filter never invoked those frames: each expired at the
            # door of whichever server element it had reached (the budget
            # re-stamped from the wire deadline_s governs them all)
            def server_drops():
                return sum(
                    e["deadline_drops"] for e in server.health().values())

            deadline = time.monotonic() + 5
            while server_drops() < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert server_drops() >= 2
            assert server.health()["sf"]["state"] == "running"
        finally:
            client.stop()
            server.stop()


# ---------------------------------------------------------------------------
# lint gate: no unbounded blocking calls in the I/O layers
# ---------------------------------------------------------------------------
def test_no_unbounded_blocking():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    try:
        import check_blocking_timeouts
    finally:
        sys.path.pop(0)
    bad = check_blocking_timeouts.scan()
    assert not bad, f"unbounded blocking calls: {bad}"


def test_both_lint_gates_run_clean():
    # CI contract: BOTH failure-handling gates run inside tier-1
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    try:
        import check_blocking_timeouts
        import check_no_bare_except
    finally:
        sys.path.pop(0)
    assert not check_no_bare_except.scan()
    assert not check_blocking_timeouts.scan()
