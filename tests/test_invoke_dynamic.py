"""invoke-dynamic end-to-end: per-buffer-varying output schemas flow as
format=flexible frames through decoder and sink.

Reference: ``tensor_filter.c:856-930`` — a subplugin with invoke_dynamic
produces outputs whose dimensions differ per buffer; the element wraps
them as flexible tensors so downstream caps stay valid.
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends import register_custom_easy, unregister_custom_easy
from nnstreamer_tpu.core.types import FORMAT_FLEXIBLE
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture
def nonzero_model():
    # output shape = number of nonzero elements -> varies per buffer
    register_custom_easy(
        "nonzeros", lambda xs: [np.asarray(xs[0])[np.asarray(xs[0]) != 0]]
    )
    yield "nonzeros"
    unregister_custom_easy("nonzeros")


class TestInvokeDynamic:
    def test_two_shapes_one_run(self, nonzero_model):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=custom-easy "
            f"model={nonzero_model} invoke-dynamic=true ! tensor_sink name=out"
        )
        pipe.start()
        # flexible advertised downstream before data flows
        assert pipe["f"].srcpads[0].spec.fmt == FORMAT_FLEXIBLE
        pipe["src"].push(np.float32([1, 0, 2, 0, 3]))  # -> shape (3,)
        pipe["src"].push(np.float32([0, 7, 0, 0, 0]))  # -> shape (1,)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert [f.tensors[0].shape for f in frames] == [(3,), (1,)]
        np.testing.assert_array_equal(frames[0].tensors[0], [1, 2, 3])
        np.testing.assert_array_equal(frames[1].tensors[0], [7])

    def test_through_decoder(self, nonzero_model):
        # flexible frames decode per-buffer (octet decoder concatenates
        # whatever bytes arrive — size varies run to run)
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=custom-easy "
            f"model={nonzero_model} invoke-dynamic=true ! "
            "tensor_decoder mode=octet_stream ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(np.uint8([5, 0, 6]))
        pipe["src"].push(np.uint8([0, 0, 9]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert bytes(frames[0].tensors[0]) == bytes([5, 6])
        assert bytes(frames[1].tensors[0]) == bytes([9])

    def test_batching_rejected(self, nonzero_model):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=custom-easy "
            f"model={nonzero_model} invoke-dynamic=true max-batch=8 ! "
            "tensor_sink name=out"
        )
        with pytest.raises(Exception, match="invoke-dynamic is per-frame"):
            pipe.start()
        pipe.stop()

    def test_jax_backend_dynamic_via_shape_buckets(self):
        """jax-xla handles per-buffer-varying INPUT shapes through its
        shape-bucketed jit cache; with invoke-dynamic the varying output
        schema flows as flexible frames."""
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model("idy", lambda p, xs: [xs[0] * 2])
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_filter framework=jax-xla "
                "model=idy invoke-dynamic=true ! tensor_sink name=out"
            )
            pipe.start()
            pipe["src"].push(np.float32([1, 2]))
            pipe["src"].push(np.float32([1, 2, 3, 4]))  # different shape
            pipe["src"].end_of_stream()
            pipe.wait(timeout=60)
            frames = pipe["out"].frames
            pipe.stop()
            assert [f.tensors[0].shape for f in frames] == [(2,), (4,)]
            np.testing.assert_array_equal(frames[1].tensors[0], [2, 4, 6, 8])
        finally:
            unregister_jax_model("idy")
