"""Block ingest: N logical frames travel as ONE pre-batched stream item.

≙ the reference converter's ``frames-per-tensor`` batching
(gsttensor_converter.c: frames-per-tensor property batches N media frames
into one tensor buffer).  TPU-first rationale: per-frame Python ingest and
per-frame stacking cap pipeline throughput far below the chip's rate; a
block pays those costs once per micro-batch (bench.py BENCH_INGEST=block).
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends.jax_xla import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.core.buffer import BatchFrame
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture(autouse=True)
def _affine_model():
    register_jax_model("blk_affine", lambda p, xs: [xs[0] * 3.0 - 1.0], None)
    yield
    unregister_jax_model("blk_affine")


def _run(push, n, extra="", timeout=30):
    pipe = parse_pipeline(
        "appsrc name=src ! tensor_filter name=f framework=jax-xla "
        f"model=blk_affine max-batch=8 {extra} ! tensor_sink name=out"
    )
    pipe.start()
    push(pipe["src"])
    pipe["src"].end_of_stream()
    pipe.wait(timeout=timeout)
    frames = pipe["out"].frames
    pipe.stop()
    assert len(frames) == n, f"expected {n} frames, got {len(frames)}"
    return frames


def _expect(frames, values, pts=None):
    got = [float(f.tensors[0][0]) for f in frames]
    assert got == pytest.approx([3.0 * v - 1.0 for v in values])
    if pts is not None:
        assert [f.pts for f in frames] == pytest.approx(pts)


class TestBlockIngest:
    def test_blocks_split_back_to_logical_frames(self):
        """3 blocks x 8 frames -> 24 per-frame outputs, in order, with
        per-logical pts carried through the batch."""
        def push(src):
            for b in range(3):
                block = np.arange(b * 8, b * 8 + 8, dtype=np.float32)
                src.push_block(
                    block[:, None], pts=[0.1 * i for i in range(b * 8, b * 8 + 8)]
                )
        frames = _run(push, 24)
        _expect(frames, list(range(24)), pts=[0.1 * i for i in range(24)])

    def test_block_equals_per_frame_results(self):
        vals = list(range(16))

        def push_frames(src):
            for i in vals:
                src.push(np.float32([i]), pts=i * 0.01)

        def push_blocks(src):
            src.push_block(
                np.float32(vals)[:, None], pts=[i * 0.01 for i in vals]
            )

        per_frame = _run(push_frames, 16)
        per_block = _run(push_blocks, 16)
        for a, b in zip(per_frame, per_block):
            np.testing.assert_allclose(a.tensors[0], b.tensors[0])
            assert a.pts == pytest.approx(b.pts)

    def test_mixed_blocks_and_plain_frames_keep_order(self):
        """A block arriving between plain frames must neither reorder nor
        drop anything (mixed concat path in _handle_prebatched)."""
        def push(src):
            src.push(np.float32([100.0]), pts=0.0)
            src.push_block(np.float32([[0.0], [1.0], [2.0]]),
                           pts=[0.1, 0.2, 0.3])
            src.push(np.float32([200.0]), pts=0.4)
            src.push_block(np.float32([[3.0], [4.0]]), pts=[0.5, 0.6])

        frames = _run(push, 7)
        _expect(frames, [100.0, 0.0, 1.0, 2.0, 200.0, 3.0, 4.0],
                pts=[0.0, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6])

    def test_block_larger_than_max_batch(self):
        """A 20-frame block with max-batch=8: the scheduler never splits a
        queue item, but the filter chunks the invoke to honor max-batch
        (traced batch axes stay <= 8) — all frames come back once, in
        order."""
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model, unregister_jax_model)

        sizes = set()

        def fn(p, xs):
            sizes.add(int(xs[0].shape[0]))
            return [xs[0] * 3.0 - 1.0]

        register_jax_model("blk_chunk", fn, None)
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_filter framework=jax-xla "
                "model=blk_chunk max-batch=8 ! tensor_sink name=out"
            )
            pipe.start()
            pipe["src"].push_block(
                np.arange(20, dtype=np.float32)[:, None],
                pts=[float(i) for i in range(20)],
            )
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            frames = pipe["out"].frames
            pipe.stop()
            assert len(frames) == 20
            _expect(frames, list(range(20)), pts=[float(i) for i in range(20)])
            assert all(s <= 8 for s in sizes), f"max-batch exceeded: {sizes}"
        finally:
            unregister_jax_model("blk_chunk")

    def test_empty_block_is_a_noop(self):
        def push(src):
            src.push_block(np.zeros((0, 1), np.float32))
            src.push_block(np.float32([[1.0], [2.0]]), pts=[0.0, 0.1])
        frames = _run(push, 2)
        _expect(frames, [1.0, 2.0], pts=[0.0, 0.1])

    def test_outputs_only_combination_with_blocks(self):
        """output-combination=o0 (no input refs) must still apply to block
        rows — and must not need the input block on host."""
        def push(src):
            src.push_block(
                np.arange(4, dtype=np.float32)[:, None],
                pts=[float(i) for i in range(4)],
            )
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=jax-xla "
            "model=blk_affine max-batch=8 dispatch-depth=1 "
            "output-combination=o0 ! tensor_sink name=out"
        )
        pipe.start()
        push(pipe["src"])
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert len(frames) == 4
        _expect(frames, list(range(4)))

    def test_depth_window_drains_blocks_on_eos(self):
        """Parked pre-batched windows (dispatch-depth > 1) must fully drain
        at EOS in order."""
        def push(src):
            for b in range(6):
                src.push_block(
                    np.arange(b * 4, b * 4 + 4, dtype=np.float32)[:, None]
                )
        frames = _run(push, 24, extra="dispatch-depth=4")
        _expect(frames, list(range(24)))

    def test_depth_1_synchronous_blocks(self):
        def push(src):
            for b in range(4):
                src.push_block(
                    np.arange(b * 4, b * 4 + 4, dtype=np.float32)[:, None]
                )
        frames = _run(push, 16, extra="dispatch-depth=1")
        _expect(frames, list(range(16)))

    def test_push_block_framerate_stamps_logical_pts(self):
        """Without explicit pts, push_block stamps per-logical-frame pts
        from the framerate prop, continuing across blocks."""
        pipe = parse_pipeline(
            "appsrc name=src framerate=10/1 ! tensor_filter framework=jax-xla "
            "model=blk_affine max-batch=8 ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push_block(np.zeros((4, 1), np.float32))
        pipe["src"].push_block(np.zeros((4, 1), np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert [f.pts for f in frames] == pytest.approx(
            [i * 0.1 for i in range(8)]
        )

    def test_output_combination_with_blocks(self):
        """output-combination needs per-logical input rows: the emit path
        slices the block's inputs (materialized once per block)."""
        def push(src):
            src.push_block(
                np.arange(6, dtype=np.float32)[:, None],
                pts=[float(i) for i in range(6)],
            )
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=jax-xla "
            "model=blk_affine max-batch=8 dispatch-depth=1 "
            "output-combination=i0,o0 ! tensor_sink name=out"
        )
        pipe.start()
        push(pipe["src"])
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert len(frames) == 6
        for i, f in enumerate(frames):
            assert len(f.tensors) == 2
            np.testing.assert_allclose(f.tensors[0], np.float32([i]))
            np.testing.assert_allclose(f.tensors[1], np.float32([3.0 * i - 1.0]))

    def test_input_combination_falls_back(self):
        """input-combination is incompatible with skipping per-frame views:
        blocks take the per-item transform path and results stay correct."""
        def push(src):
            src.push_block(
                np.arange(5, dtype=np.float32)[:, None],
                pts=[float(i) for i in range(5)],
            )
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=jax-xla "
            "model=blk_affine max-batch=8 input-combination=0 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        push(pipe["src"])
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        # the solo-BatchFrame transform path emits the block whole; the
        # sink fans it back out to logical frames
        assert len(frames) == 5
        _expect(frames, list(range(5)))

    def test_fused_decoder_consumes_blocks(self):
        """Device-fused decode (filter + image_labeling compiled into one
        XLA program) must accept pre-batched input and still deliver
        per-logical-frame labels."""
        import tempfile

        register_jax_model("blk_logits", lambda p, xs: [xs[0]], None)
        try:
            with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                             delete=False) as f:
                f.write("\n".join(f"label{i}" for i in range(5)))
                labels = f.name
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_filter name=f framework=jax-xla "
                "model=blk_logits max-batch=8 ! tensor_decoder "
                f"mode=image_labeling option1={labels} ! tensor_sink name=out"
            )
            pipe.start()
            rows = np.float32(
                [np.eye(5, dtype=np.float32)[i % 5] for i in range(12)]
            )
            pipe["src"].push_block(rows)
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            frames = pipe["out"].frames
            pipe.stop()
            assert len(frames) == 12
            assert [f.meta.get("label") for f in frames] == [
                f"label{i % 5}" for i in range(12)
            ]
            assert [int(f.tensors[0][0]) for f in frames] == [
                i % 5 for i in range(12)
            ]
        finally:
            unregister_jax_model("blk_logits")


class TestBlockIngestGuards:
    def test_push_block_rejects_mismatched_pts(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_sink name=out"
        )
        pipe.start()
        with pytest.raises(ValueError, match="pts"):
            pipe["src"].push_block(
                np.zeros((4, 1), np.float32), pts=[0.0, 0.1]
            )
        pipe["src"].end_of_stream()
        pipe.wait(timeout=10)
        pipe.stop()

    def test_push_block_rejects_mismatched_frame_axes(self):
        pipe = parse_pipeline("appsrc name=src ! tensor_sink name=out")
        pipe.start()
        with pytest.raises(ValueError, match="frame axis"):
            pipe["src"].push_block(
                [np.zeros((4, 1), np.float32), np.zeros((3, 1), np.float32)]
            )
        pipe["src"].end_of_stream()
        pipe.wait(timeout=10)
        pipe.stop()

    def test_scheduler_bounds_logical_batch(self):
        """Flooding the queue with blocks must not produce invokes beyond
        max-batch (+ at most one block's worth): traced batch-axis sizes
        stay in {8, 16}, never a whole-queue mega-batch."""
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model, unregister_jax_model)

        sizes = set()

        def fn(p, xs):
            sizes.add(int(xs[0].shape[0]))  # trace-time: one per compile
            return [xs[0] * 2.0]

        register_jax_model("blk_sizes", fn, None)
        try:
            pipe = parse_pipeline(
                "appsrc name=src max-buffers=64 ! tensor_filter "
                "framework=jax-xla model=blk_sizes max-batch=16 ! "
                "tensor_sink name=out"
            )
            pipe.start()
            for b in range(40):
                pipe["src"].push_block(
                    np.full((8, 1), float(b), np.float32)
                )
            pipe["src"].end_of_stream()
            pipe.wait(timeout=60)
            frames = pipe["out"].frames
            pipe.stop()
            assert len(frames) == 320
            assert sizes <= {8, 16}, f"unbounded micro-batch: {sizes}"
        finally:
            unregister_jax_model("blk_sizes")

    def test_block_through_max_batch_1_path(self):
        """max-batch=1 routes blocks through transform(): the batch axis
        must still mean batch (invoke_batch), not one frame's shape."""
        def push(src):
            src.push_block(
                np.arange(6, dtype=np.float32)[:, None],
                pts=[float(i) for i in range(6)],
            )
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=jax-xla "
            "model=blk_affine max-batch=1 ! tensor_sink name=out"
        )
        pipe.start()
        push(pipe["src"])
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert len(frames) == 6
        _expect(frames, list(range(6)), pts=[float(i) for i in range(6)])


class TestConverterEmitBlocks:
    """tensor_converter emit-blocks=true: frames-per-tensor batching that
    emits a transparent BatchFrame (per-frame schema/pts preserved) instead
    of the reference's shape-changed stacked tensor — block ingest from
    pipeline text alone, no appsrc API needed."""

    def test_media_pipeline_blocks_end_to_end(self):
        pipe = parse_pipeline(
            "videotestsrc num-buffers=12 pattern=solid width=8 height=8 "
            "framerate=10/1 ! tensor_converter frames-per-tensor=4 "
            "emit-blocks=true ! tensor_filter framework=jax-xla "
            "model=blk_img max-batch=4 ! tensor_sink name=out"
        )
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model, unregister_jax_model)
        # batch-polymorphic like the zoo models: (H,W,C) -> (1,) per frame,
        # (B,H,W,C) -> (B,1) per block (schema negotiates UNBATCHED)
        register_jax_model(
            "blk_img", lambda p, xs: [xs[0].astype("float32").mean(
                axis=(-3, -2, -1))[..., None]], None)
        try:
            pipe.start()
            pipe.wait(timeout=30)
            frames = pipe["out"].frames
            pipe.stop()
            # all 12 logical frames come back, at the SOURCE framerate
            assert len(frames) == 12
            assert [f.pts for f in frames] == pytest.approx(
                [i * 0.1 for i in range(12)]
            )
            # solid pattern: frame i has value (i*8)%256 everywhere
            got = [float(f.tensors[0][0]) for f in frames]
            assert got == pytest.approx([(i * 8) % 256 for i in range(12)])
        finally:
            unregister_jax_model("blk_img")

    def test_partial_tail_block_is_emitted_not_dropped(self):
        """10 frames at frames-per-tensor=4 -> blocks of 4,4,2: the tail
        block flushes at EOS (no schema change, so no reason to drop —
        documented divergence from the reference's stacking mode)."""
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_converter frames-per-tensor=4 "
            "emit-blocks=true ! tensor_filter framework=jax-xla "
            "model=blk_affine max-batch=4 ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(10):
            pipe["src"].push(np.float32([i]), pts=i * 0.1)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert len(frames) == 10
        _expect(frames, list(range(10)),
                pts=[i * 0.1 for i in range(10)])

    def test_stacking_mode_unchanged_without_emit_blocks(self):
        """Reference semantics intact: fpt=4 without emit-blocks emits
        shape-changed frames and drops the partial tail."""
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_converter frames-per-tensor=4 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        for i in range(10):
            pipe["src"].push(np.float32([i]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert len(frames) == 2  # 4+4, tail of 2 dropped
        assert frames[0].tensors[0].shape == (4, 1)


class TestWholeBlockDelivery:
    """decoder/sink split-batches=false: blocks stay whole through the
    fused decode (vectorized decode_fused_batch) and arrive at callbacks
    as BatchFrames — the per-frame fan-out disappears from the hot path."""

    def _pipe(self, labels, sink_split):
        from nnstreamer_tpu.backends.jax_xla import register_jax_model
        register_jax_model("blk_pass", lambda p, xs: [xs[0]], None)
        extra = "" if sink_split else " split-batches=false"
        return parse_pipeline(
            "appsrc name=src ! tensor_filter framework=jax-xla "
            "model=blk_pass max-batch=8 ! "
            f"tensor_decoder mode=image_labeling option1={labels}{extra} ! "
            f"tensor_sink name=out{extra}"
        )

    def test_blocks_survive_to_callbacks_with_labels(self):
        import tempfile

        from nnstreamer_tpu.backends.jax_xla import unregister_jax_model
        with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                         delete=False) as f:
            f.write("\n".join(f"L{i}" for i in range(5)))
            labels = f.name
        try:
            pipe = self._pipe(labels, sink_split=False)
            got = []
            pipe["out"].connect_new_data(got.append)
            pipe.start()
            rows = np.float32(
                [np.eye(5, dtype=np.float32)[i % 5] for i in range(16)]
            )
            pipe["src"].push_block(rows[:8], pts=[float(i) for i in range(8)])
            pipe["src"].push_block(rows[8:], pts=[float(i) for i in range(8, 16)])
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            pipe.stop()
            # callbacks received whole blocks...
            assert all(isinstance(f, BatchFrame) for f in got)
            assert sum(f.batch_size for f in got) == 16
            # ...with per-logical labels/pts in frames_info
            flat = [
                (p, m.get("label"))
                for f in got for (p, d, m) in f.frames_info
            ]
            assert flat == [(float(i), f"L{i % 5}") for i in range(16)]
        finally:
            unregister_jax_model("blk_pass")

    def test_split_results_identical_to_block_delivery(self):
        import tempfile

        from nnstreamer_tpu.backends.jax_xla import unregister_jax_model
        with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                         delete=False) as f:
            f.write("\n".join(f"L{i}" for i in range(5)))
            labels = f.name
        rows = np.float32(
            [np.eye(5, dtype=np.float32)[(3 * i) % 5] for i in range(12)]
        )
        try:
            results = {}
            for split in (True, False):
                pipe = self._pipe(labels, sink_split=split)
                pipe.start()
                pipe["src"].push_block(
                    rows, pts=[float(i) for i in range(12)]
                )
                pipe["src"].end_of_stream()
                pipe.wait(timeout=30)
                frames = pipe["out"].frames
                pipe.stop()
                if split:
                    results[split] = [
                        (f.pts, f.meta.get("label"), int(f.tensors[0][0]))
                        for f in frames
                    ]
                else:
                    results[split] = [
                        (p, m.get("label"), int(f.tensors[0][j, 0]))
                        for f in frames
                        for j, (p, d, m) in enumerate(f.frames_info)
                    ]
            assert results[True] == results[False]
        finally:
            unregister_jax_model("blk_pass")


class TestBatchAwareSafetyNet:
    """Non-batch-aware elements must see LOGICAL frames: the scheduler
    splits blocks before per-frame elements (transform/if/...), so a block
    upstream can never smuggle a surprise batch axis into per-frame
    semantics (Element.BATCH_AWARE opt-in)."""

    def test_transform_sees_logical_frames(self):
        """mode=transpose on (2,3) frames would corrupt on a (B,2,3) batch
        axis; with the safety net, blocks and per-frame pushes agree."""
        def run(push):
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_transform mode=transpose "
                "option=1:0 ! tensor_sink name=out"
            )
            pipe.start()
            push(pipe["src"])
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            frames = pipe["out"].frames
            pipe.stop()
            return [np.asarray(f.tensors[0]) for f in frames]

        data = np.arange(24, dtype=np.float32).reshape(4, 2, 3)
        per_frame = run(lambda s: [s.push(d) for d in data])
        per_block = run(lambda s: s.push_block(data))
        assert len(per_block) == 4
        for a, b in zip(per_frame, per_block):
            assert a.shape == (3, 2)
            np.testing.assert_array_equal(a, b)

    def test_tensor_if_routes_per_logical_frame(self):
        """Data-dependent routing must evaluate each logical frame, not
        the whole block once."""
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_if name=cond compared-value=A_VALUE "
            "compared-value-option=0:0 supplied-value=10 operator=GE "
            "then=PASSTHROUGH else=SKIP ! tensor_sink name=out"
        )
        pipe.start()
        vals = np.float32([[3.0], [15.0], [7.0], [22.0]])
        pipe["src"].push_block(vals)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        got = [float(f.tensors[0][0]) for f in frames]
        assert got == [15.0, 22.0]


class TestBatchFrameUnit:
    def test_batchframe_through_push_roundtrip(self):
        """AppSrc.push accepts a hand-built BatchFrame (it IS a
        TensorFrame) — push_block is sugar, not a requirement."""
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=jax-xla "
            "model=blk_affine max-batch=4 ! tensor_sink name=out"
        )
        pipe.start()
        bf = BatchFrame(
            tensors=[np.float32([[1.0], [2.0]])],
            pts=0.0,
            frames_info=[(0.0, None, {}), (0.1, None, {})],
        )
        pipe["src"].push(bf)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert len(frames) == 2
        _expect(frames, [1.0, 2.0], pts=[0.0, 0.1])
