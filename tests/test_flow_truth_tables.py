"""tensor_if / tensor_rate truth tables — the full reference option matrix
(``gsttensor_if.h:42-91`` enums: 6 compared-value modes x 10 operators x 8
then/else behaviors; ``gsttensor_rate.c:81-88`` in/out/dup/drop counters).
"""

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.elements.flow import TensorIf, TensorRate
from nnstreamer_tpu.pipeline import parse_pipeline


def make_if(**props):
    el = TensorIf("tif")
    for k, v in props.items():
        el.props[k.replace("_", "-")] = v
    el.srcpad(0)
    el.start()
    return el


def run_if(el, frame):
    out = el.handle_frame(0, frame)
    return out[0][1] if out else None


class TestOperators:
    # (operator, supplied, value, expected) — truth table for all 10
    TABLE = [
        ("eq", "5", 5.0, True), ("eq", "5", 4.0, False),
        ("ne", "5", 4.0, True), ("ne", "5", 5.0, False),
        ("gt", "5", 6.0, True), ("gt", "5", 5.0, False),
        ("ge", "5", 5.0, True), ("ge", "5", 4.9, False),
        ("lt", "5", 4.0, True), ("lt", "5", 5.0, False),
        ("le", "5", 5.0, True), ("le", "5", 5.1, False),
        ("range_inclusive", "2,5", 2.0, True),
        ("range_inclusive", "2,5", 5.0, True),
        ("range_inclusive", "2,5", 5.5, False),
        ("range_exclusive", "2,5", 2.0, False),
        ("range_exclusive", "2,5", 3.0, True),
        ("range_exclusive", "2,5", 5.0, False),
        ("not_in_range_inclusive", "2,5", 2.0, False),
        ("not_in_range_inclusive", "2,5", 1.0, True),
        ("not_in_range_exclusive", "2,5", 2.0, True),
        ("not_in_range_exclusive", "2,5", 3.0, False),
    ]

    @pytest.mark.parametrize("op,supplied,value,expect", TABLE)
    def test_operator_truth_table(self, op, supplied, value, expect):
        el = make_if(operator=op, supplied_value=supplied,
                     then="passthrough", **{"else": "skip"})
        out = run_if(el, TensorFrame([np.float64([value])]))
        if expect:
            assert out is not None and out.meta["tensor_if"] == "then"
        else:
            assert out is None


class TestComparedValues:
    def test_a_value_coordinate(self):
        # innermost-first dims "1:0" -> numpy [0, 1] of tensor 1
        arr0 = np.zeros((2, 2), np.float32)
        arr1 = np.float32([[0, 9], [0, 0]])
        el = make_if(compared_value="a_value", compared_value_option="1:0,1",
                     operator="eq", supplied_value="9")
        assert run_if(el, TensorFrame([arr0, arr1])) is not None

    def test_tensor_total_and_average(self):
        frame = TensorFrame([np.float32([1, 2, 3]), np.float32([10, 20])])
        el = make_if(compared_value="tensor_total_value",
                     compared_value_option="1", operator="eq",
                     supplied_value="30")
        assert run_if(el, frame) is not None
        el = make_if(compared_value="tensor_average_value",
                     compared_value_option="0", operator="eq",
                     supplied_value="2")
        assert run_if(el, frame) is not None

    def test_all_tensors_total_and_average(self):
        frame = TensorFrame([np.float32([1, 2, 3]), np.float32([10, 20])])
        el = make_if(compared_value="all_tensors_total_value",
                     operator="eq", supplied_value="36")
        assert run_if(el, frame) is not None
        # subset list: tensors 0 only
        el = make_if(compared_value="all_tensors_total_value",
                     compared_value_option="0", operator="eq",
                     supplied_value="6")
        assert run_if(el, frame) is not None
        el = make_if(compared_value="all_tensors_average_value",
                     operator="eq", supplied_value="7.2")  # 36/5
        assert run_if(el, frame) is not None

    def test_custom_callback(self):
        from nnstreamer_tpu.elements.flow import (
            register_if_custom,
            unregister_if_custom,
        )

        register_if_custom("odd_sum", lambda f: int(np.asarray(f.tensors[0]).sum()) % 2 == 1)
        try:
            el = make_if(compared_value="custom", compared_value_option="odd_sum",
                         operator="eq", supplied_value="1")
            assert run_if(el, TensorFrame([np.int32([1, 2])])) is not None
            assert run_if(el, TensorFrame([np.int32([1, 3])])) is None
        finally:
            unregister_if_custom("odd_sum")


class TestBehaviors:
    def _frame(self, fill=7):
        return TensorFrame(
            [np.full((2, 2), fill, np.int32), np.full((3,), fill, np.uint8)]
        )

    def test_fill_zero(self):
        el = make_if(operator="gt", supplied_value="0", then="fill_zero")
        out = run_if(el, self._frame())
        assert (out.tensors[0] == 0).all() and (out.tensors[1] == 0).all()
        assert out.tensors[0].dtype == np.int32

    def test_fill_values_per_tensor_and_broadcast(self):
        el = make_if(operator="gt", supplied_value="0", then="fill_values",
                     then_option="3,250")
        out = run_if(el, self._frame())
        assert (out.tensors[0] == 3).all()
        assert (out.tensors[1] == 250).all()
        # single value broadcasts to every tensor
        el = make_if(operator="gt", supplied_value="0", then="fill_values",
                     then_option="9")
        out = run_if(el, self._frame())
        assert (out.tensors[0] == 9).all() and (out.tensors[1] == 9).all()

    def test_fill_with_file_pads_zero(self, tmp_path):
        path = tmp_path / "fill.raw"
        path.write_bytes(np.int32([11, 22]).tobytes())  # 8 bytes < 16+3
        el = make_if(operator="gt", supplied_value="0", then="fill_with_file",
                     then_option=str(path))
        out = run_if(el, self._frame())
        np.testing.assert_array_equal(
            out.tensors[0].reshape(-1), np.int32([11, 22, 0, 0])
        )
        assert (out.tensors[1] == 0).all()  # file exhausted -> zeros

    def test_fill_with_file_rpt_cycles(self, tmp_path):
        path = tmp_path / "fill.raw"
        path.write_bytes(bytes([1, 2]))
        el = make_if(operator="gt", supplied_value="0",
                     then="fill_with_file_rpt", then_option=str(path))
        out = run_if(el, self._frame())
        flat0 = out.tensors[0].view(np.uint8).reshape(-1)
        np.testing.assert_array_equal(flat0, np.tile([1, 2], 8))
        # the second tensor continues the cycle from byte offset 16
        np.testing.assert_array_equal(out.tensors[1], [1, 2, 1])

    def test_repeat_previous_frame_first_is_zero(self):
        el = make_if(operator="gt", supplied_value="0",
                     then="repeat_previous_frame")
        first = run_if(el, self._frame(5))
        assert (first.tensors[0] == 0).all()  # first on the pad: zeros
        second = run_if(el, self._frame(6))
        assert (second.tensors[0] == 0).all()  # resends previous output

    def test_repeat_resends_last_passthrough_on_shared_pad(self):
        # then=passthrough else=repeat, single pad: 'previous output
        # frame' = the last frame that left this pad (the passthrough) —
        # the hold-last-good-frame use case
        el = make_if(operator="gt", supplied_value="10", then="passthrough",
                     **{"else": "repeat_previous_frame"})
        out1 = run_if(el, self._frame(20))  # then: passthrough 20s
        assert (out1.tensors[0] == 20).all()
        out2 = run_if(el, self._frame(1))  # else: re-sends the 20s frame
        assert (out2.tensors[0] == 20).all()
        out3 = run_if(el, self._frame(30))  # passthrough updates the cache
        out4 = run_if(el, self._frame(2))
        assert (out4.tensors[0] == 30).all()

    def test_tensorpick_subset(self):
        el = make_if(operator="gt", supplied_value="0", then="tensorpick",
                     then_option="1")
        out = run_if(el, self._frame())
        assert len(out.tensors) == 1 and out.tensors[0].shape == (3,)

    def test_unknown_behavior_rejected_at_start(self):
        el = TensorIf("bad")
        el.props["then"] = "explode"
        el.srcpad(0)
        with pytest.raises(Exception, match="unknown behavior"):
            el.start()

    def test_caches_reset_on_restart(self):
        el = make_if(operator="gt", supplied_value="0",
                     then="repeat_previous_frame")
        run_if(el, self._frame(5))
        run_if(el, self._frame(6))
        el.start()  # restart
        again = run_if(el, self._frame(7))
        assert (again.tensors[0] == 0).all()  # pad cache cleared -> zeros


class TestRateCounters:
    def _push(self, el, pts, val=1.0):
        return el.handle_frame(0, TensorFrame([np.float32([val])], pts=pts))

    def test_drop_counters(self):
        el = TensorRate("r")
        el.props["framerate"] = "1/1"
        el.props["throttle"] = True
        el.start()
        # 4 frames at 2 fps -> 2 out, 2 dropped
        for i in range(4):
            self._push(el, i * 0.5)
        assert (el.in_frames, el.out_frames) == (4, 2)
        assert (el.dropped, el.duplicated) == (2, 0)

    def test_duplicate_counters(self):
        el = TensorRate("r")
        el.props["framerate"] = "2/1"
        el.props["throttle"] = False
        el.start()
        # 1 fps in -> 2 fps out: each gap filled with one duplicate
        outs = []
        for i in range(3):
            outs.extend(self._push(el, float(i)))
        assert el.in_frames == 3
        assert el.duplicated == 2
        assert el.out_frames == len(outs) == 5
        assert el.dropped == 0

    def test_counters_reset_on_restart(self):
        el = TensorRate("r")
        el.props["framerate"] = "1/1"
        el.start()
        for i in range(3):
            self._push(el, i * 0.5)
        el.start()
        assert (el.in_frames, el.out_frames, el.dropped, el.duplicated) == (0, 0, 0, 0)


class TestPipelineIntegration:
    def test_if_fill_values_in_pipeline(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_if compared-value=tensor_average_value "
            "compared-value-option=0 operator=ge supplied-value=100 "
            "then=fill_values then-option=255 else=passthrough ! "
            "tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(np.full((2, 2), 200, np.uint8))  # bright -> filled
        pipe["src"].push(np.full((2, 2), 3, np.uint8))  # dark -> passthrough
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        assert (np.asarray(frames[0].tensors[0]) == 255).all()
        assert (np.asarray(frames[1].tensors[0]) == 3).all()
