"""Pipeline runtime tests: threading, backpressure, events, parser.

Modeled on the reference's pipeline-level SSAT suites (launch a pipeline,
collect sink output, byte-compare) but as in-process pytest.
"""


import numpy as np
import pytest

from nnstreamer_tpu.pipeline import (
    ElementError,
    ParseError,
    Pipeline,
    TransformElement,
    element,
    make_element,
    parse_pipeline,
)
from nnstreamer_tpu.elements.basic import AppSrc, TensorSink


class TestProgrammatic:
    def test_linear_chain(self):
        pipe = Pipeline("t")
        src = make_element("videotestsrc", **{"num-buffers": 5, "width": 8, "height": 8})
        sink = make_element("tensor_sink")
        pipe.chain(src, make_element("identity"), sink)
        pipe.run(timeout=10)
        assert len(sink.frames) == 5
        assert sink.frames[0].tensors[0].shape == (8, 8, 3)
        # pts stamped from framerate
        assert sink.frames[1].pts == pytest.approx(1 / 30)

    def test_appsrc_push(self):
        pipe = Pipeline("t")
        src = AppSrc()
        sink = TensorSink()
        pipe.chain(src, sink)
        pipe.start()
        for i in range(3):
            src.push(np.full((4,), i, np.int32))
        src.end_of_stream()
        pipe.wait(timeout=10)
        pipe.stop()
        assert [int(f.tensors[0][0]) for f in sink.frames] == [0, 1, 2]

    def test_tee_fanout(self):
        pipe = Pipeline("t")
        src = make_element("videotestsrc", **{"num-buffers": 4, "width": 4, "height": 4})
        tee = make_element("tee")
        s1, s2 = TensorSink("s1"), TensorSink("s2")
        pipe.add(src, tee, s1, s2)
        src.link(tee)
        tee.link(s1, src_pad=0)
        tee.link(s2, src_pad=1)
        pipe.run(timeout=10)
        assert len(s1.frames) == 4 and len(s2.frames) == 4

    def test_error_propagates(self):
        @element("_exploder")
        class Exploder(TransformElement):
            def transform(self, frame):
                raise RuntimeError("boom")

        pipe = Pipeline("t")
        pipe.chain(
            make_element("videotestsrc", **{"num-buffers": 2}),
            make_element("_exploder"),
            TensorSink(),
        )
        pipe.start()
        with pytest.raises(RuntimeError, match="boom"):
            pipe.wait(timeout=10)
        pipe.stop()
        msgs = []
        while (m := pipe.pop_message()) is not None:
            msgs.append(m)
        assert any(m.kind == "error" for m in msgs)

    def test_backpressure_bounded(self):
        # a slow sink must throttle a fast source via bounded mailboxes
        pipe = Pipeline("t", default_queue_size=2)
        src = AppSrc()
        slow = make_element("identity", sleep=0.01)
        sink = TensorSink()
        pipe.chain(src, slow, sink)
        pipe.start()
        for i in range(30):
            src.push(np.int32([i]))
        src.end_of_stream()
        pipe.wait(timeout=30)
        pipe.stop()
        assert len(sink.frames) == 30  # nothing dropped

    def test_caps_negotiation_failure(self):
        pipe = Pipeline("t")
        src = make_element("videotestsrc", width=8, height=8)
        cf = make_element("capsfilter", caps="tensors,format=static,num=1,dimensions=3:16:16,types=uint8")
        pipe.chain(src, cf, TensorSink())
        with pytest.raises(ElementError, match="does not satisfy"):
            pipe.start()
        pipe.stop()


class TestParser:
    def test_parse_linear(self):
        pipe = parse_pipeline(
            "videotestsrc num-buffers=3 width=16 height=16 ! queue ! tensor_sink name=out"
        )
        pipe.run(timeout=10)
        assert len(pipe["out"].frames) == 3

    def test_parse_tee_branches(self):
        pipe = parse_pipeline(
            "videotestsrc num-buffers=2 width=4 height=4 ! tee name=t "
            "t. ! queue ! tensor_sink name=a  t. ! queue ! tensor_sink name=b"
        )
        pipe.run(timeout=10)
        assert len(pipe["a"].frames) == 2
        assert len(pipe["b"].frames) == 2

    def test_parse_capsfilter(self):
        pipe = parse_pipeline(
            "videotestsrc num-buffers=2 width=8 height=8 ! "
            "tensors,format=static,num=1,dimensions=3:8:8,types=uint8 ! tensor_sink name=out"
        )
        pipe.run(timeout=10)
        assert len(pipe["out"].frames) == 2

    def test_parse_errors(self):
        with pytest.raises(ParseError):
            parse_pipeline("videotestsrc !")
        with pytest.raises(ParseError):
            parse_pipeline("! tensor_sink")
        with pytest.raises(ParseError):
            parse_pipeline("nonexistent_element_xyz")
        with pytest.raises(ParseError):
            parse_pipeline("")
        with pytest.raises(ParseError):
            parse_pipeline("videotestsrc ! nosuch. ! tensor_sink")

    def test_unknown_property(self):
        with pytest.raises(ElementError, match="unknown property"):
            parse_pipeline("videotestsrc bogus-prop=3 ! tensor_sink")

    def test_join_first_come(self):
        pipe = parse_pipeline(
            "appsrc name=a ! join name=j  appsrc name=b ! j.  j. ! tensor_sink name=out"
        )
        # "j. ! sink" after feeding INTO j: j's src chain
        pipe.start()
        pipe["a"].push(np.int32([1]))
        pipe["b"].push(np.int32([2]))
        pipe["a"].end_of_stream()
        pipe["b"].end_of_stream()
        pipe.wait(timeout=10)
        pipe.stop()
        vals = sorted(int(f.tensors[0][0]) for f in pipe["out"].frames)
        assert vals == [1, 2]


class TestDotExport:
    def test_to_dot_structure(self):
        pipe = parse_pipeline(
            "videotestsrc num-buffers=1 width=4 height=4 ! tee name=t "
            "t. ! queue ! tensor_sink name=a  t. ! queue ! tensor_sink name=b"
        )
        dot = pipe.to_dot()
        assert dot.startswith("digraph pipeline {")
        for name in ("t", "a", "b"):
            assert f'"{name}"' in dot
        # tee fans to two queues: two edges out of t
        assert dot.count('"t" ->') == 2
        # sinks render as house shapes, sources inverted
        assert "shape=house" in dot and "shape=invhouse" in dot

    def test_launch_dot_flag(self, tmp_path):
        import jax

        jax.config.update("jax_platforms", "cpu")
        from nnstreamer_tpu.cli.launch import main

        out = tmp_path / "g.dot"
        assert main([
            "videotestsrc num-buffers=1 width=4 height=4 ! tensor_sink",
            "--dot", str(out), "--timeout", "20", "-q",
        ]) == 0
        assert out.read_text().startswith("digraph pipeline {")
