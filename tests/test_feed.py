"""Unit tests for core/feed.py — the completion-driven dispatch window
and the host->device staging lane, exercised directly (no element, no
pipeline) so the threading contracts are pinned at the primitive level:
FIFO completion, error placement, Flush/close semantics, buffer-pool
cycling, and job abandonment.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import DeviceBufferPool
from nnstreamer_tpu.core.feed import CompletionWindow, HostStagingLane


class GateMaterializer:
    """materialize() blocks until the test releases that entry; entries
    release in any order the test chooses (the window must still emit
    FIFO).  A payload of Exception type raises instead."""

    def __init__(self):
        self.events = {}
        self.lock = threading.Lock()

    def release(self, token):
        with self.lock:
            ev = self.events.setdefault(token, threading.Event())
        ev.set()

    def __call__(self, out_b):
        token = out_b[0]
        with self.lock:
            ev = self.events.setdefault(token, threading.Event())
        ev.wait(timeout=10)
        if isinstance(token, type) and issubclass(token, BaseException):
            raise token("materialization failed")
        return [np.float32([token])]


class TestCompletionWindow:
    def test_pop_ready_is_fifo_and_nonblocking(self):
        gate = GateMaterializer()
        win = CompletionWindow("t", materialize=gate)
        try:
            for i in range(3):
                win.park([i], payload=i)
            assert win.pop_ready() == []  # nothing completed: no block
            gate.release(1)  # out-of-order completion...
            time.sleep(0.05)
            assert win.pop_ready() == []  # ...must NOT emit 1 before 0
            gate.release(0)
            deadline = time.monotonic() + 5
            got = []
            while len(got) < 2 and time.monotonic() < deadline:
                got += win.pop_ready()
            assert [p for _, p in got] == [0, 1]  # FIFO restored
            assert [float(m[0][0]) for m, _ in got] == [0.0, 1.0]
            gate.release(2)
            assert win.wait_oldest(timeout=5)
            assert [p for _, p in win.pop_ready()] == [2]
        finally:
            win.close()

    def test_error_entry_raises_after_good_prefix(self):
        gate = GateMaterializer()
        win = CompletionWindow("t", materialize=gate)
        try:
            win.park([7], payload="ok")
            win.park([RuntimeError], payload="bad")
            gate.release(7)
            gate.release(RuntimeError)
            deadline = time.monotonic() + 5
            while win.reaped < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            # first call hands out the completed prefix...
            assert [p for _, p in win.pop_ready()] == ["ok"]
            # ...the NEXT call raises the parked error (dispatch thread)
            with pytest.raises(RuntimeError, match="materialization"):
                win.pop_ready()
            assert len(win) == 0  # the errored entry was consumed
        finally:
            win.close()

    def test_clear_discards_and_reaper_survives(self):
        gate = GateMaterializer()
        win = CompletionWindow("t", materialize=gate)
        try:
            win.park([0], payload="a")
            win.park([1], payload="b")
            assert win.clear() == ["a", "b"]
            assert len(win) == 0
            gate.release(0)  # reaper mid-sync finishes harmlessly
            gate.release(1)
            win.park([2], payload="c")  # window still usable
            gate.release(2)
            assert win.wait_oldest(timeout=5)
            assert [p for _, p in win.pop_ready()] == ["c"]
        finally:
            win.close()

    def test_close_stops_reaper_and_park_reopens(self):
        win = CompletionWindow("t", materialize=lambda o: [np.float32(o)])
        win.park([1.0], payload="x")
        deadline = time.monotonic() + 5
        while not win.oldest_ready() and time.monotonic() < deadline:
            time.sleep(0.01)
        reaper = win._reaper
        win.close()
        assert reaper is not None and not reaper.is_alive()
        win.park([2.0], payload="y")  # transparent reopen
        assert win.wait_oldest(timeout=5)
        assert [p for _, p in win.pop_ready()] == ["y"]
        win.close()

    def test_wait_oldest_counts_backpressure(self):
        gate = GateMaterializer()
        win = CompletionWindow("t", materialize=gate)
        try:
            win.park([0], payload="a")
            assert not win.wait_oldest(timeout=0.05)  # bounded, times out
            assert win.dispatch_waits == 1
            gate.release(0)
            assert win.wait_oldest(timeout=5)
        finally:
            win.close()


class TestHostStagingLane:
    def test_stacks_and_places_through_pool(self):
        pool = DeviceBufferPool(max_per_key=4)
        seen = []

        def to_dev(arrs):
            seen.append([a.copy() for a in arrs])
            return [np.array(a) for a in arrs]

        lane = HostStagingLane(to_dev, pool=pool, name="t")
        try:
            frames = [
                [np.full((2,), i, np.float32)] for i in range(4)
            ]
            dev = lane.submit(frames).result()
            assert len(dev) == 1 and dev[0].shape == (4, 2)
            np.testing.assert_array_equal(
                dev[0], np.repeat([[0.0], [1.0], [2.0], [3.0]], 2, axis=1))
            # second batch reuses the released staging buffer
            lane.submit(frames).result()
            assert pool.reused >= 1 and pool.allocated <= 2
        finally:
            lane.close()

    def test_discard_drops_device_refs(self):
        lane = HostStagingLane(
            lambda arrs: [np.array(a) for a in arrs], name="t")
        try:
            job = lane.submit([[np.zeros((2,), np.float32)]])
            job.discard()
            assert job.wait(timeout=5)
            assert job._dev is None  # refs dropped even though staged
        finally:
            lane.close()

    def test_staging_error_reaches_collector(self):
        def bad(arrs):
            raise ValueError("no device")

        lane = HostStagingLane(bad, name="t")
        try:
            job = lane.submit([[np.zeros((2,), np.float32)]])
            assert job.wait(timeout=5)
            with pytest.raises(ValueError, match="no device"):
                job.result()
        finally:
            lane.close()

    def test_close_abandons_queued_jobs_loudly(self):
        release = threading.Event()

        def slow(arrs):
            release.wait(timeout=10)
            return [np.array(a) for a in arrs]

        lane = HostStagingLane(slow, name="t")
        first = lane.submit([[np.zeros((2,), np.float32)]])
        queued = lane.submit([[np.zeros((2,), np.float32)]])
        # the worker is held inside to_device (release unset), so `queued`
        # is still in the lane's queue when close() runs: it must resolve
        # with an error — never strand a waiter
        lane.close()
        assert queued.wait(timeout=5)
        with pytest.raises(RuntimeError, match="closed"):
            queued.result()
        release.set()  # let the in-service job finish into its handle
        assert first.wait(timeout=5)
