"""Scheduler-overhead smoke gates (`pytest -m perf`).

Real clocks, no fake time, generous margins: every threshold here sits at
~half of what the dataplane measures on a loaded CI box, so a pass means
"the tentpole optimizations still exist", not "the machine was fast
today".  All tests finish in seconds — they run inside the tier-1 budget.
"""

import time
import tracemalloc

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import FRAME_POOL, TensorFrame
from nnstreamer_tpu.pipeline import parse_pipeline

pytestmark = pytest.mark.perf

CHAIN = (
    "appsrc name=src max-buffers=256 ! identity ! identity ! identity ! "
    "tensor_sink name=out max-stored=1"
)


def _passthrough_fps(fuse: bool, n_frames: int = 2500) -> float:
    pipe = parse_pipeline(CHAIN, name="perf", fuse=fuse)
    pipe.start()
    src, sink = pipe["src"], pipe["out"]
    done = {"n": 0}
    sink.connect_new_data(lambda f: done.__setitem__("n", done["n"] + 1))
    pool = [np.zeros((64,), np.float32) for _ in range(16)]
    for i in range(128):  # warmup: settle thread scheduling
        src.push(pool[i % 16])
    t_w = time.time()
    while done["n"] < 128 and time.time() - t_w < 30:
        time.sleep(0.005)
    assert done["n"] >= 128, "warmup stalled"
    done["n"] = 0
    t0 = time.perf_counter()
    for i in range(n_frames):
        src.push(pool[i % 16])
    while done["n"] < n_frames and time.perf_counter() - t0 < 60:
        time.sleep(0.002)
    dt = time.perf_counter() - t0
    fps = done["n"] / dt
    src.end_of_stream()
    pipe.wait(timeout=30)
    pipe.stop()
    assert done["n"] == n_frames, "frames lost in passthrough"
    return fps


def test_fusion_speedup_and_absolute_floor():
    """Tentpole gate: the fused 5-element identity chain must beat the
    unfused seed dataplane by >= 2x (measured 4-10x; threshold at the
    acceptance floor with the rest as CI-noise margin), and clear an
    absolute 4000 fps floor (measured 12-25k on this container)."""
    fused = _passthrough_fps(True)
    unfused = _passthrough_fps(False)
    assert fused >= 2.0 * unfused, (
        f"fusion speedup regressed: fused {fused:.0f} fps vs "
        f"unfused {unfused:.0f} fps ({fused / unfused:.2f}x < 2x)"
    )
    assert fused >= 4000


def test_hot_path_allocation_budget():
    """tracemalloc gate: the fused dispatch loop must not RETAIN
    allocations per frame in steady state (frame-pool regression, a
    per-frame cache that never evicts, stash leaks...).  Budget: <= 5
    retained allocations and <= 2 KiB retained bytes per frame, measured
    over 300 frames after warmup — actual steady state is ~0.1/frame, so
    the margin is >10x."""
    pipe = parse_pipeline(CHAIN, name="alloc", fuse=True)
    pipe.start()
    src, sink = pipe["src"], pipe["out"]
    done = {"n": 0}
    sink.connect_new_data(lambda f: done.__setitem__("n", done["n"] + 1))
    arr = np.zeros((64,), np.float32)
    for _ in range(200):  # warmup: pool/jit/thread steady state
        src.push(TensorFrame([arr]))
    t_w = time.time()
    while done["n"] < 200 and time.time() - t_w < 30:
        time.sleep(0.005)
    n = 300
    # frames pre-created OUTSIDE the traced window: the budget pins the
    # dispatch loop, not the application's ingest allocations
    frames = [TensorFrame([arr]) for _ in range(n)]
    done["n"] = 0
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for f in frames:
        src.push(f)
    t0 = time.time()
    while done["n"] < n and time.time() - t0 < 30:
        time.sleep(0.002)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    src.end_of_stream()
    pipe.wait(timeout=30)
    pipe.stop()
    assert done["n"] == n
    diff = after.compare_to(before, "filename")
    count = sum(max(0, d.count_diff) for d in diff)
    size = sum(max(0, d.size_diff) for d in diff)
    assert count / n <= 5, f"retained {count / n:.1f} allocations/frame"
    assert size / n <= 2048, f"retained {size / n:.0f} bytes/frame"


def test_frame_pool_reuses_carcasses():
    """The free-list actually cycles: a capped sink evicting frames feeds
    the pool, and BatchFrame.split / filter emission draw from it."""
    reused_before = FRAME_POOL.reused
    recycled_before = FRAME_POOL.recycled
    from nnstreamer_tpu.core.buffer import BatchFrame

    block = BatchFrame(
        tensors=[np.zeros((8, 4), np.float32)],
        frames_info=[(float(i), None, {}) for i in range(8)],
    )
    for _ in range(10):
        lfs = block.split()
        while lfs:
            # recycle() demands the caller hold the LAST reference: pop
            # the frame out of the list before handing it over
            f = lfs.pop()
            assert FRAME_POOL.recycle(f)
    assert FRAME_POOL.recycled >= recycled_before + 80
    assert FRAME_POOL.reused >= reused_before + 72  # rounds 2-10 reuse


def test_block_handoff_single_queue_op():
    """_push_outs delivers a run of outputs bound for one destination as
    one bulk mailbox operation, preserving order and events."""
    from nnstreamer_tpu.pipeline.pipeline import _LeakyMailbox

    box = _LeakyMailbox(8, "upstream")
    items = [(0, TensorFrame([np.zeros(2)])) for _ in range(5)]
    n = box.put_many(items, timeout=0.0)
    assert n == 5 and box.qsize() == 5
    # order preserved
    out = [box.get(timeout=0.1) for _ in range(5)]
    assert out == items
    # leaky policy under one lock: 10 frames into depth 8 drops 2
    n = box.put_many(
        [(0, TensorFrame([np.zeros(2)])) for _ in range(10)], timeout=0.0
    )
    assert n == 10 and box.qsize() == 8
