"""Scheduler-overhead smoke gates (`pytest -m perf`).

Real clocks, no fake time, generous margins: every threshold here sits at
~half of what the dataplane measures on a loaded CI box, so a pass means
"the tentpole optimizations still exist", not "the machine was fast
today".  All tests finish in seconds — they run inside the tier-1 budget.
"""

import time
import tracemalloc

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import FRAME_POOL, TensorFrame
from nnstreamer_tpu.pipeline import parse_pipeline

pytestmark = pytest.mark.perf

CHAIN = (
    "appsrc name=src max-buffers=256 ! identity ! identity ! identity ! "
    "tensor_sink name=out max-stored=1"
)


def _passthrough_fps(fuse: bool, n_frames: int = 2500) -> float:
    pipe = parse_pipeline(CHAIN, name="perf", fuse=fuse)
    pipe.start()
    src, sink = pipe["src"], pipe["out"]
    done = {"n": 0}
    sink.connect_new_data(lambda f: done.__setitem__("n", done["n"] + 1))
    pool = [np.zeros((64,), np.float32) for _ in range(16)]
    for i in range(128):  # warmup: settle thread scheduling
        src.push(pool[i % 16])
    t_w = time.time()
    while done["n"] < 128 and time.time() - t_w < 30:
        time.sleep(0.005)
    assert done["n"] >= 128, "warmup stalled"
    done["n"] = 0
    t0 = time.perf_counter()
    for i in range(n_frames):
        src.push(pool[i % 16])
    while done["n"] < n_frames and time.perf_counter() - t0 < 60:
        time.sleep(0.002)
    dt = time.perf_counter() - t0
    fps = done["n"] / dt
    src.end_of_stream()
    pipe.wait(timeout=30)
    pipe.stop()
    assert done["n"] == n_frames, "frames lost in passthrough"
    return fps


def test_fusion_speedup_and_absolute_floor():
    """Tentpole gate: the fused 5-element identity chain must beat the
    unfused seed dataplane by >= 2x (measured 4-10x; threshold at the
    acceptance floor with the rest as CI-noise margin), and clear an
    absolute 4000 fps floor (measured 12-25k on this container)."""
    fused = _passthrough_fps(True)
    unfused = _passthrough_fps(False)
    assert fused >= 2.0 * unfused, (
        f"fusion speedup regressed: fused {fused:.0f} fps vs "
        f"unfused {unfused:.0f} fps ({fused / unfused:.2f}x < 2x)"
    )
    assert fused >= 4000


def test_histograms_armed_identity_floor():
    """PR-11 pin: with the ALWAYS-ON log2 latency histograms armed (a
    tracer attached records per-element handle latency per call plus a
    mailbox queue-wait stamp per crossing), the fused identity chain
    still clears the PR-3/PR-6 absolute 4000 fps floor — the lock-free
    array-increment record path is cheap enough to leave on in
    production."""
    from nnstreamer_tpu.pipeline import parse_pipeline as parse

    n = 2500
    pipe = parse(CHAIN, name="histperf", fuse=True)
    tracer = pipe.enable_tracing()
    pipe.start()
    src, sink = pipe["src"], pipe["out"]
    done = {"n": 0}
    sink.connect_new_data(lambda f: done.__setitem__("n", done["n"] + 1))
    pool = [np.zeros((64,), np.float32) for _ in range(16)]
    for i in range(128):
        src.push(pool[i % 16])
    t_w = time.time()
    while done["n"] < 128 and time.time() - t_w < 30:
        time.sleep(0.005)
    assert done["n"] >= 128, "warmup stalled"
    done["n"] = 0
    t0 = time.perf_counter()
    for i in range(n):
        src.push(pool[i % 16])
    while done["n"] < n and time.perf_counter() - t0 < 60:
        time.sleep(0.002)
    fps = done["n"] / (time.perf_counter() - t0)
    src.end_of_stream()
    pipe.wait(timeout=30)
    hists = {
        (el, name): h for el, name, h in tracer.latency_histograms()
    }
    snap = pipe.metrics_snapshot()
    pipe.stop()
    assert done["n"] == n, "frames lost with histograms armed"
    assert fps >= 4000, (
        f"histogram-armed dataplane regressed: {fps:.0f} fps < 4000"
    )
    # the instruments really recorded: every element's handle histogram
    # holds one observation per call, and the percentiles surface in the
    # snapshot under their stable names
    h_out = hists[("out", "nns.element.handle_seconds")]
    assert h_out.count == n + 128
    assert snap.get("nns.element.handle_p99_us", element="out") > 0
    assert snap.sum("nns.element.handle_seconds_count", element="out") == (
        n + 128)


def test_perf_truth_fast_check_against_committed_baseline():
    """The per-PR perf-truth gate (tier-1, next to the three lint
    gates): the FAST axis subset must land inside the committed
    PERF_BASELINE.json distribution — median beyond ``median - tol``
    counts as a regression (tolerance math pinned by
    tests/test_perf_truth.py; best-of-k with early exit absorbs ambient
    load).  This replaces hand-picked binary floors with the committed
    distribution for every PR, chip or no chip."""
    import importlib.util
    import os

    pt_path = os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "tools",
        "perf_truth.py")
    spec = importlib.util.spec_from_file_location("perf_truth_gate", pt_path)
    pt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pt)
    report = pt.check(fast=True, k=3, verbose=False)
    bad = {
        name: ax for name, ax in report["axes"].items()
        if ax["verdict"] != "ok"
    }
    assert report["ok"], (
        "perf-truth regression vs committed baseline "
        f"(PERF_BASELINE.json, captured {report['baseline_captured_at']}"
        f"): {bad}"
    )


def test_telemetry_disabled_per_frame_overhead():
    """PR-7 pin: with the telemetry layer present but DISABLED (the
    default — no tracer, no flight recorder, no exposition endpoint),
    per-frame cost stays the tracer's single `is not None` branch, so
    the fused identity chain still clears the PR-3/PR-6 absolute floor.
    Structural half of the pin: a started pipeline holds no tracer or
    recorder object at all (registry collection is scrape-time only),
    so that branch IS the telemetry integration's entire hot-path
    footprint."""
    from nnstreamer_tpu.core import telemetry

    pipe = parse_pipeline(CHAIN, name="teloff", fuse=True)
    pipe.start()
    try:
        assert pipe.tracer is None
        assert pipe.flight_recorder is None
        assert telemetry.live_server_count() == 0
        pipe["src"].end_of_stream()
        pipe.wait(timeout=10)
    finally:
        pipe.stop()
    fps = _passthrough_fps(True)
    assert fps >= 4000, (
        f"telemetry-disabled dataplane regressed: {fps:.0f} fps < 4000"
    )


def test_memory_monitor_armed_identity_floor():
    """PR-14 pin: with the memory-pressure watermark monitor ARMED
    (sweeper-thread polling of real device/host memory stats), the
    fused identity chain still clears the PR-3/PR-6 absolute 4000 fps
    floor — the monitor touches NO per-frame path; its entire cost is
    a rate-limited poll on the sweeper cadence plus one bool read per
    ADMISSION (and this chain has no admission at all).  Structural
    half: a pipeline without enable_memory_monitor holds no monitor
    object, so the disabled dataplane is byte-identical to PR-13's."""
    pipe = parse_pipeline(CHAIN, name="memperf", fuse=True)
    mon = pipe.enable_memory_monitor(min_poll_s=0.01)
    pipe.start()
    src, sink = pipe["src"], pipe["out"]
    done = {"n": 0}
    sink.connect_new_data(lambda f: done.__setitem__("n", done["n"] + 1))
    pool = [np.zeros((64,), np.float32) for _ in range(16)]
    for i in range(128):
        src.push(pool[i % 16])
    t_w = time.time()
    while done["n"] < 128 and time.time() - t_w < 30:
        time.sleep(0.005)
    assert done["n"] >= 128, "warmup stalled"
    done["n"] = 0
    n = 2500
    t0 = time.perf_counter()
    for i in range(n):
        src.push(pool[i % 16])
    while done["n"] < n and time.perf_counter() - t0 < 60:
        time.sleep(0.002)
    fps = done["n"] / (time.perf_counter() - t0)
    src.end_of_stream()
    pipe.wait(timeout=30)
    pipe.stop()
    assert done["n"] == n, "frames lost with the memory monitor armed"
    assert fps >= 4000, (
        f"memory-monitor-armed dataplane regressed: {fps:.0f} fps < 4000"
    )
    # the monitor really ran on the sweeper (not on the frame path)
    assert mon.polls > 0
    # structural: a default pipeline holds no monitor at all
    off = parse_pipeline(CHAIN, name="memoff", fuse=True)
    assert off.memory_monitor is None


def test_fleet_observatory_armed_identity_floor():
    """PR-15 pin: with the FLEET OBSERVATORY fully armed in-process — a
    digest publisher polling on the sweeper cadence, a live
    FleetObservatory ingesting every digest, its ``nns.fleet.*``
    registry collector registered, and SLO instruments holding
    observations — the fused identity chain still clears the absolute
    4000 fps floor.  The whole plane is sweeper- and scrape-time-only:
    an armed-but-idle observatory costs ZERO on the per-frame path."""
    from nnstreamer_tpu.core.fleet import (
        DigestPublisher,
        FleetObservatory,
        pipeline_digest_stats,
    )
    from nnstreamer_tpu.core.telemetry import REGISTRY, SloTracker

    pipe = parse_pipeline(CHAIN, name="fleetperf", fuse=True)
    obs = FleetObservatory(topic="perf", default_ttl_s=60.0)
    REGISTRY.register_collector(obs._collect)
    slo = SloTracker(ttft_p95_s=0.5, token_p99_s=0.01, availability=0.99)
    slo.note_ttft("perf", 0.01)
    slo.note_tokens("perf", 0.02, 8)
    slo.note_stream("perf", "good")
    pub = DigestPublisher(
        lambda: {**pipeline_digest_stats(pipe), "inflight": 0,
                 "slo_burn": {t: r.get("ttft_burn", 0.0)
                              for t, r in slo.snapshot().items()}},
        lambda d: obs.ingest(
            "nns/query/perf/a", {"host": "x", "port": 1, "digest": d}),
        interval_s=0.02, name="perf")
    pipe.register_sweep(pub.poll, 0.02)
    try:
        pipe.start()
        src, sink = pipe["src"], pipe["out"]
        done = {"n": 0}
        sink.connect_new_data(lambda f: done.__setitem__("n", done["n"] + 1))
        pool = [np.zeros((64,), np.float32) for _ in range(16)]
        for i in range(128):
            src.push(pool[i % 16])
        t_w = time.time()
        while done["n"] < 128 and time.time() - t_w < 30:
            time.sleep(0.005)
        assert done["n"] >= 128, "warmup stalled"
        done["n"] = 0
        n = 2500
        t0 = time.perf_counter()
        for i in range(n):
            src.push(pool[i % 16])
        while done["n"] < n and time.perf_counter() - t0 < 60:
            time.sleep(0.002)
        fps = done["n"] / (time.perf_counter() - t0)
        src.end_of_stream()
        pipe.wait(timeout=30)
        pipe.stop()
        assert done["n"] == n, "frames lost with the observatory armed"
        assert fps >= 4000, (
            f"observatory-armed dataplane regressed: {fps:.0f} fps < 4000"
        )
        # the digest plane really ran on the sweeper, not the frame path
        assert pub.published > 0
        assert obs.rollup()["digests"] > 0
    finally:
        REGISTRY.unregister_collector(obs._collect)


def test_autoscale_controller_armed_identity_floor():
    """PR-16 pin: with the AUTOSCALE CONTROLLER fully armed in-process —
    a FleetController ticking on the sweeper cadence over a live
    observatory (one healthy idle server ingested, so the envelope is
    satisfied and every tick runs the full reap/snapshot/feed/plan
    path), its ``nns.autoscale.*`` collector registered — the fused
    identity chain still clears the absolute 4000 fps floor.  The loop
    is sweeper- and scrape-time-only: an armed-but-calm controller
    makes ZERO decisions and costs ZERO on the per-frame path."""
    from nnstreamer_tpu.core.autoscale import FleetController, NullActuator
    from nnstreamer_tpu.core.fleet import FleetObservatory

    pipe = parse_pipeline(CHAIN, name="autoscaleperf", fuse=True)
    obs = FleetObservatory(topic="perf", default_ttl_s=60.0)
    # one healthy idle server: without it the envelope floor would spawn
    obs.ingest("nns/query/perf/a", {"host": "x", "port": 1, "digest": {
        "v": 1, "seq": 1, "age_s": 0.0, "interval_s": 1.0, "ttl_s": 60.0,
        "draining": False, "degraded": False, "swap": "idle",
        "inflight": 0, "admitted": 0, "shed": 0, "tokens_per_s": 0.0,
        "slots": 4, "occupied": 0}})
    actuator = NullActuator()
    ctrl = FleetController(obs, actuator).attach(pipe, interval_s=0.02)
    try:
        pipe.start()
        src, sink = pipe["src"], pipe["out"]
        done = {"n": 0}
        sink.connect_new_data(lambda f: done.__setitem__("n", done["n"] + 1))
        pool = [np.zeros((64,), np.float32) for _ in range(16)]
        for i in range(128):
            src.push(pool[i % 16])
        t_w = time.time()
        while done["n"] < 128 and time.time() - t_w < 30:
            time.sleep(0.005)
        assert done["n"] >= 128, "warmup stalled"
        done["n"] = 0
        n = 2500
        t0 = time.perf_counter()
        for i in range(n):
            src.push(pool[i % 16])
        while done["n"] < n and time.perf_counter() - t0 < 60:
            time.sleep(0.002)
        fps = done["n"] / (time.perf_counter() - t0)
        src.end_of_stream()
        pipe.wait(timeout=30)
        pipe.stop()
        assert done["n"] == n, "frames lost with the controller armed"
        assert fps >= 4000, (
            f"controller-armed dataplane regressed: {fps:.0f} fps < 4000"
        )
        # the loop really ran on the sweeper and stayed calm: ticks
        # accumulated, zero decisions, zero actuation
        assert ctrl.ticks > 0
        assert ctrl.state.decisions == 0
        assert actuator.calls == []
    finally:
        ctrl.stop()


def test_oom_retry_accounting_parity_fused_vs_unfused():
    """PR-14 satellite: the OOM shrink-retry ladder produces IDENTICAL
    outputs and identical ``oom_retries``/``oom_shrinks`` accounting
    fused and unfused — recovery must not depend on the threading
    topology."""
    def run(fuse: bool):
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter name=f framework=async-sim custom=oom_at:0 "
            "max-batch=8 ! tensor_sink name=out max-stored=64",
            name=f"oomparity{fuse}", fuse=fuse)
        pipe.start()
        got = []
        pipe["out"].connect_new_data(
            lambda f: got.append(float(np.asarray(f.tensors[0])[0])))
        pipe["src"].push_block(
            np.arange(8, dtype=np.float32).reshape(8, 1))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        h = pipe.health()["f"]
        pipe.stop()
        # oom_evictions excluded from the parity tuple: it counts
        # whatever the PROCESS-WIDE staging pool happened to hold when
        # the trim fired, which earlier tests legitimately vary
        return got, (h["oom_retries"], h["oom_shrinks"],
                     h["dead_letters"], h["restarts"])
    got_f, acc_f = run(True)
    got_u, acc_u = run(False)
    assert got_f == got_u == [v * 2.0 + 1.0 for v in range(8)]
    assert acc_f == acc_u == (1, 1, 0, 0)


def test_hot_path_allocation_budget():
    """tracemalloc gate: the fused dispatch loop must not RETAIN
    allocations per frame in steady state (frame-pool regression, a
    per-frame cache that never evicts, stash leaks...).  Budget: <= 5
    retained allocations and <= 2 KiB retained bytes per frame, measured
    over 300 frames after warmup — actual steady state is ~0.1/frame, so
    the margin is >10x."""
    pipe = parse_pipeline(CHAIN, name="alloc", fuse=True)
    pipe.start()
    src, sink = pipe["src"], pipe["out"]
    done = {"n": 0}
    sink.connect_new_data(lambda f: done.__setitem__("n", done["n"] + 1))
    arr = np.zeros((64,), np.float32)
    for _ in range(200):  # warmup: pool/jit/thread steady state
        src.push(TensorFrame([arr]))
    t_w = time.time()
    while done["n"] < 200 and time.time() - t_w < 30:
        time.sleep(0.005)
    n = 300
    # frames pre-created OUTSIDE the traced window: the budget pins the
    # dispatch loop, not the application's ingest allocations
    frames = [TensorFrame([arr]) for _ in range(n)]
    done["n"] = 0
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for f in frames:
        src.push(f)
    t0 = time.time()
    while done["n"] < n and time.time() - t0 < 30:
        time.sleep(0.002)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    src.end_of_stream()
    pipe.wait(timeout=30)
    pipe.stop()
    assert done["n"] == n
    diff = after.compare_to(before, "filename")
    count = sum(max(0, d.count_diff) for d in diff)
    size = sum(max(0, d.size_diff) for d in diff)
    assert count / n <= 5, f"retained {count / n:.1f} allocations/frame"
    assert size / n <= 2048, f"retained {size / n:.0f} bytes/frame"


def test_frame_pool_reuses_carcasses():
    """The free-list actually cycles: a capped sink evicting frames feeds
    the pool, and BatchFrame.split / filter emission draw from it."""
    reused_before = FRAME_POOL.reused
    recycled_before = FRAME_POOL.recycled
    from nnstreamer_tpu.core.buffer import BatchFrame

    block = BatchFrame(
        tensors=[np.zeros((8, 4), np.float32)],
        frames_info=[(float(i), None, {}) for i in range(8)],
    )
    for _ in range(10):
        lfs = block.split()
        while lfs:
            # recycle() demands the caller hold the LAST reference: pop
            # the frame out of the list before handing it over
            f = lfs.pop()
            assert FRAME_POOL.recycle(f)
    assert FRAME_POOL.recycled >= recycled_before + 80
    assert FRAME_POOL.reused >= reused_before + 72  # rounds 2-10 reuse


def test_block_handoff_single_queue_op():
    """_push_outs delivers a run of outputs bound for one destination as
    one bulk mailbox operation, preserving order and events."""
    from nnstreamer_tpu.pipeline.pipeline import _LeakyMailbox

    box = _LeakyMailbox(8, "upstream")
    items = [(0, TensorFrame([np.zeros(2)])) for _ in range(5)]
    n = box.put_many(items, timeout=0.0)
    assert n == 5 and box.qsize() == 5
    # order preserved
    out = [box.get(timeout=0.1) for _ in range(5)]
    assert out == items
    # leaky policy under one lock: 10 frames into depth 8 drops 2
    n = box.put_many(
        [(0, TensorFrame([np.zeros(2)])) for _ in range(10)], timeout=0.0
    )
    assert n == 10 and box.qsize() == 8


# ---------------------------------------------------------------------------
# Async device feed gates (PR-6): the pipeline-vs-raw gap can only shrink
# between chip windows — CPU-proxy floors for the window, the donated
# buffer ring, and the staging lane (ROADMAP item 5, first slice).
# ---------------------------------------------------------------------------
def test_dispatch_window_nonblocking_tracks_backend():
    """Acceptance gate: at dispatch-depth 8 over a slow single-server
    fake device, pipeline throughput tracks BACKEND throughput within
    10% — the device is busy >= 90% of wall time because stacking,
    dispatch, and the device->host sync all hide behind compute (the
    pre-async design was bounded by serial block-on-oldest: compute +
    transfer + dispatch per batch, ~55% busy at these costs).  And the
    structural claim behind the number: the dispatch thread is NEVER
    observed inside a device_get-style blocking sync — the window's
    reaper thread owns every pre-completion wait."""
    from nnstreamer_tpu.pipeline import parse_pipeline as parse

    compute_ms, mb, nbatches = 8.0, 8, 60
    pipe = parse(
        "appsrc name=src max-buffers=512 ! tensor_filter name=f "
        "framework=async-sim "
        f"custom=compute_ms:{compute_ms},transfer_ms:4,dispatch_ms:1 "
        f"max-batch={mb} dispatch-depth=8 ingest-lane=off ! "
        "tensor_sink name=out max-stored=1",
        name="awperf",
    )
    pipe.start()
    done = {"n": 0}
    pipe["out"].connect_new_data(
        lambda f: done.__setitem__("n", done["n"] + 1))
    be = pipe["f"].backend
    arr = np.zeros((64,), np.float32)
    for _ in range(mb * 4):  # warmup: fill the window, settle batching
        pipe["src"].push(arr)
    t_w = time.time()
    while done["n"] < mb * 4 and time.time() - t_w < 30:
        time.sleep(0.005)
    assert done["n"] >= mb * 4, "warmup stalled"
    done["n"] = 0
    b0 = be.busy_s
    n = mb * nbatches
    t0 = time.perf_counter()
    for _ in range(n):
        pipe["src"].push(arr)
    while done["n"] < n and time.perf_counter() - t0 < 60:
        time.sleep(0.002)
    elapsed = time.perf_counter() - t0
    busy_s = be.busy_s - b0
    foreign_syncs = [
        t for t in be.blocking_syncs if not t.endswith("-reaper")
    ]
    pipe["src"].end_of_stream()
    pipe.wait(timeout=30)
    pipe.stop()
    assert done["n"] == n, "frames lost in the async window"
    # device-busy fraction: the single server's ACTUAL service seconds
    # over wall time; overlap means wall time barely exceeds service.
    # Steady state measures >= 0.95; the serial block-on-oldest design
    # measures compute/(compute+transfer+dispatch) ~= 0.62 at these
    # costs — 0.85 keeps CI-scheduling headroom while separating the
    # two structures by a wide margin.
    busy = busy_s / elapsed
    assert busy >= 0.85, (
        f"dispatch window no longer hides framework cost: device busy "
        f"{busy:.2f} < 0.85 ({busy_s * 1000:.0f}ms service in "
        f"{elapsed * 1000:.0f}ms wall)"
    )
    assert foreign_syncs == [], (
        f"dispatch thread blocked in device_get: {foreign_syncs}"
    )


def _load_bench():
    import importlib.util
    import os

    bench_path = os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "bench.py")
    spec = importlib.util.spec_from_file_location("bench_for_perf", bench_path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_pipeline_vs_raw_proxy_floor():
    """ROADMAP items 1+5 gate: the full dataplane must deliver >= 60% of
    the bare backend's throughput when both run the same async-sim
    device costs with the same depth-8 window structure (measured
    ~0.9-1.0x — the async feed hides framework cost behind compute; the
    pre-async serial design measured ~0.6x).  SAME harness bench.py
    publishes as `pipeline_vs_raw` in its cpu_proxy evidence, so the
    gate and the evidence cannot drift — the PR-6 gains can only shrink
    loudly."""
    bench = _load_bench()
    best = (0.0, 0.0, 0.0)
    for _attempt in range(2):  # best-of-2: CI scheduling noise, not code
        raw_fps, pipe_fps = bench.measure_pipeline_vs_raw(nbatches=24)
        assert raw_fps > 0 and pipe_fps > 0
        ratio = pipe_fps / raw_fps
        if ratio > best[0]:
            best = (ratio, raw_fps, pipe_fps)
        if best[0] >= 0.6:
            break
    ratio, raw_fps, pipe_fps = best
    assert ratio >= 0.6, (
        f"pipeline_vs_raw proxy regressed: pipeline {pipe_fps:.0f} fps vs "
        f"raw {raw_fps:.0f} fps ({ratio:.2f}x < 0.6x; steady state "
        "measures ~0.75-0.9x)"
    )


def test_host_ingest_overlap_speedup():
    """Acceptance gate: the double-buffered staging lane beats serialized
    stack+transfer+compute by >= 1.3x on equal costs (measured ~1.8x at
    4ms/4ms; the lane hides the whole transfer behind compute).  Runs
    the SAME harness bench.py publishes as `ingest_overlap_speedup` in
    its cpu_proxy evidence — the gate and the evidence cannot drift."""
    bench = _load_bench()

    t_serial, t_lane = bench.measure_ingest_overlap(nb=16)
    speedup = t_serial / t_lane
    assert speedup >= 1.3, (
        f"staging lane overlap regressed: {speedup:.2f}x < 1.3x "
        f"(serial {t_serial * 1000:.0f}ms vs lane {t_lane * 1000:.0f}ms)"
    )


def test_device_buffer_pool_reuse_rate():
    """Acceptance gate: steady-state staging performs zero per-batch
    buffer allocations — the lane's double-buffered ring settles on <= 3
    buffers per (shape, dtype) and every later batch reuses one
    (reuse rate >= 0.8 over 20 batches)."""
    from nnstreamer_tpu.core.buffer import DeviceBufferPool
    from nnstreamer_tpu.core.feed import HostStagingLane

    pool = DeviceBufferPool(max_per_key=8)
    lane = HostStagingLane(
        lambda arrs: [np.array(a) for a in arrs], pool=pool, name="pool")
    frames = [[np.zeros((128,), np.float32)] for _ in range(8)]
    try:
        prev = None
        for _ in range(20):
            job = lane.submit(frames)
            if prev is not None:
                prev.result()
            prev = job
        prev.result()
    finally:
        lane.close()
    assert pool.allocated <= 3, (
        f"staging ring allocates per batch: {pool.allocated} allocations"
    )
    assert pool.reuse_rate >= 0.8, (
        f"staging-buffer reuse regressed: {pool.reuse_rate:.2f} < 0.8 "
        f"({pool.reused} reused / {pool.allocated} allocated)"
    )


def test_ingest_lane_end_to_end_zero_alloc_steady_state():
    """The lane wired through the element: a host-ingest pipeline with
    ingest-lane=on stages every micro-batch through the pool (global
    DEVICE_POOL counters grow, reuse dominates) and loses nothing."""
    from nnstreamer_tpu.core.buffer import DEVICE_POOL
    from nnstreamer_tpu.pipeline import parse_pipeline as parse

    pipe = parse(
        "appsrc name=src max-buffers=512 ! tensor_filter name=f "
        "framework=async-sim custom=compute_ms:3 max-batch=8 "
        "dispatch-depth=4 ingest-lane=on ! tensor_sink name=out",
        name="laneperf",
    )
    pipe.start()
    reused0, alloc0 = DEVICE_POOL.reused, DEVICE_POOL.allocated
    n = 8 * 16
    for i in range(n):
        pipe["src"].push(np.float32([i]))
    pipe["src"].end_of_stream()
    lane = pipe["f"]._lane
    pipe.wait(timeout=30)
    staged = lane.staged
    pipe.stop()
    outs = [float(f.tensors[0][0]) for f in pipe["out"].frames]
    assert outs == [2.0 * i + 1.0 for i in range(n)]  # FIFO, zero loss
    assert staged >= 8  # the lane really carried the ingest
    reused = DEVICE_POOL.reused - reused0
    allocated = DEVICE_POOL.allocated - alloc0
    # every staged batch acquired its buffer from the pool (one tensor
    # per frame here, so acquires == staged); ragged scheduler batching
    # mints a few distinct (n, 1) shape keys, each allowed its small
    # double-buffer ring — a pool bypass (acquires == 0) or a broken
    # release (allocated == staged) both fail loudly
    assert reused + allocated == staged, (
        f"pool bypass on the lane path: {reused} reused + "
        f"{allocated} allocated != {staged} staged batches"
    )
    assert allocated <= 10, (
        f"staging ring allocates per batch: {allocated} allocations "
        f"over {staged} staged batches"
    )


def test_routing_decision_overhead_floor():
    """Fleet-routing gate: choosing a remote with least-inflight or
    ewma costs <= 2 us/request MORE than blind rotation on the CPU
    proxy harness (measured ~0.3-0.8 us of policy delta on a 3-remote
    pool; the tier partition + breaker peek is paid by every policy,
    rotation included).  A routing layer that shows up on the RPC hot
    path has failed its design contract."""
    from nnstreamer_tpu.elements.query import _PoolState
    from nnstreamer_tpu.pipeline.element import make_element

    el = make_element("tensor_query_client", "q")
    targets = [("127.0.0.1", 7310 + i) for i in range(3)]
    ps = _PoolState([object()] * 3, targets, 0)
    el._pstate = ps
    # realistic signal state: live EWMA rows + in-flight counts
    with el._breakers_lock:
        for i, (h, p) in enumerate(targets):
            el._remote_spans[f"{h}:{p}"] = {
                "e2e_ms": 10.0 * (i + 1), "requests": 100}
            el._remote_inflight[f"{h}:{p}"] = i
    for t in targets:
        el._breaker_for(t)  # pre-create (steady-state shape)

    def per_call(policy: str, iters: int = 5_000) -> float:
        el.props["routing"] = policy
        t0 = time.perf_counter()
        for i in range(iters):
            el._route_order(ps, None, i)
        return (time.perf_counter() - t0) / iters

    for policy in ("rotate", "least-inflight", "ewma"):
        per_call(policy, 1_000)  # warm every path
    for policy in ("least-inflight", "ewma"):
        # interleaved rounds, min-of-deltas: each delta compares two
        # ADJACENT-in-time loops so ambient box load cancels instead of
        # being attributed to the policy
        deltas = [per_call(policy) - per_call("rotate") for _ in range(8)]
        delta = min(deltas)
        assert delta <= 2e-6, (
            f"routing={policy} adds {delta * 1e6:.2f} us/request over "
            "rotate (floor 2 us)"
        )


def test_continuous_batching_multiplex_floor():
    """Continuous-batching gate (ROADMAP item 2): >= 4 concurrent
    generation streams through shared slots must sustain >= 2x the
    aggregate token throughput of the same requests served one at a
    time, at bounded p50 per-token latency (measured ~2.5-3x on the
    async-sim proxy, whose simulated decode step pays the batch-
    independent weight-streaming cost real accelerator decode pays;
    threshold at the acceptance floor with the rest as CI-noise
    margin).  SAME harness bench.py publishes as `sim_speedup`, so the
    banked evidence and this gate cannot drift."""
    import bench

    res = bench.measure_slot_multiplex_speedup(
        slots=4, streams=4, max_new=64, chunk=8)
    assert res["sim_speedup"] >= 2.0, (
        f"slotted vs request-serial generation: {res['sim_speedup']}x "
        f"aggregate tokens/s (floor 2x; measured ~2.5-3x): {res}"
    )
    # bounded per-token latency: the roofline per-token cost is
    # ~1.2ms (base 1.0 + 4 slots x 0.05); 10ms means the scheduler,
    # not the device, is pacing tokens
    assert res["sim_p50_ms_per_token"] <= 10.0, res
    # slots are genuinely multiplexed, not serialized
    assert res["sim_slot_occupancy"] >= 0.5, res


@pytest.mark.slow  # tier-1 budget: ~17s live zoo re-measurement; the banked
# prefix_ttft axis is still gated every tier-1 run by
# test_perf_truth_fast_check_against_committed_baseline above
def test_prefix_ttft_floor():
    """Shared-prefix KV cache gate (ROADMAP item 4 arc): at 256 shared
    prefix tokens on the CPU-proxy zoo transformer, warm-hit TTFT must
    be <= 0.5x cold TTFT (ratio >= 2.0; measured ~3-3.4x — the
    remainder is CI-noise margin).  SAME harness bench.py publishes
    (BENCH_PREFIX_CACHE=1) and the perf-truth `prefix_ttft_speedup`
    axis trend-gates, so the banked evidence, the trend floor, and this
    product gate cannot measure different things.  The harness asserts
    the hit/miss ledger internally — a silently-cold cache fails loudly
    instead of publishing a 1.0x ratio."""
    import bench

    res = bench.measure_prefix_ttft(trials=3)
    assert res["prefix_ttft_speedup"] >= 2.0, (
        f"warm-prefix TTFT not <= 0.5x cold: "
        f"{res['prefix_ttft_speedup']}x (floor 2x; measured ~3x): {res}"
    )


def test_prefix_cache_armed_cold_identity_floor():
    """Tentpole zero-cost pin: with a prefix-cache=on (armed but COLD)
    slotted generator pipeline live in the process AND the memory
    monitor armed on the identity pipeline — so the PR-14 trim ladder's
    new first rung (prefix trim) is wired — the fused identity chain
    still clears the absolute 4000 fps floor.  The pool does no work
    until a prompt arrives and the trim rung runs on the watchdog
    cadence only: arming the cache must cost the dataplane nothing."""
    gen_pipe = parse_pipeline(
        "appsrc name=src ! tensor_generator slots=2 custom=sim:1 "
        "max-new=4 prefix-cache=on prefix-grain=32 prefill-chunk=4 ! "
        "tensor_sink name=out", name="prefixidle")
    gen_pipe.start()
    gen_pipe.enable_memory_monitor(high=0.99, low=0.9)
    try:
        assert gen_pipe["out"] is not None  # armed, idle, cold
        fps = _passthrough_fps(True)
    finally:
        gen_pipe["src"].end_of_stream()
        gen_pipe.wait(timeout=30)
        gen_pipe.stop()
    assert fps >= 4000, (
        f"armed-but-cold prefix cache dented the dataplane: "
        f"{fps:.0f} fps < 4000"
    )


@pytest.mark.slow  # tier-1 budget: ~12s live sharded re-measurement; the
# banked sharded_overhead axis is still gated every tier-1 run by
# test_perf_truth_fast_check_against_committed_baseline
def test_sharded_serving_floors():
    """The two mesh-sharded dataplane gates (ROADMAP item 4), both over
    the ONE bench.measure_sharded_overhead harness the cpu_proxy
    evidence and the perf-truth `sharded_overhead` axis publish:

    * dispatch overhead <= 15% on a single-device-equivalent mesh —
      jax-xla invoke_batch through the FULL sharded machinery
      (mesh=dp:1: NamedSharding in/out specs, scatter path, mesh-keyed
      pooling) must reach >= 0.85x the unsharded fps (measured ~1.0:
      the plumbing is free; interleaved rounds cancel ambient load);
    * >= 1.5x dp:2 aggregate throughput — the full pipeline over the
      async-sim mesh twin (2 concurrent shard servers, compute-bound
      knobs; measured ~1.9x).  The device layer is simulated because a
      single-core box cannot exhibit real XLA-CPU dp parallelism (both
      virtual devices share the one core) — what this floor pins is
      the sharded FEED structure: even scatter, all-shards readiness,
      no per-shard serialization anywhere in the dataplane.
    """
    import bench

    res = bench.measure_sharded_overhead()
    assert res["sharded_ratio"] >= 0.85, (
        f"single-device-equivalent mesh costs more than 15% dispatch "
        f"overhead: sharded/unsharded fps = {res['sharded_ratio']} "
        f"(floor 0.85; measured ~1.0): {res}"
    )
    assert res["dp2_speedup"] >= 1.5, (
        f"dp:2 aggregate throughput only {res['dp2_speedup']}x the "
        f"single-server dataplane (floor 1.5x; measured ~1.9x): {res}"
    )


def test_control_plane_armed_identity_floor():
    """PR-17 pin: with the WHOLE control plane armed and healthy — a
    live broker, a leader-elected lease renewing over its retained
    topic, a broker-backed observatory ingesting digests, and a ticking
    controller running the fail-static plane assessment — the fused
    identity chain still clears the absolute 4000 fps floor.  Lease
    renewal, plane grading, and freeze bookkeeping all live on the
    controller's slow cadence and broker reader threads: none of it may
    show up on the per-frame hot path."""
    import threading

    from nnstreamer_tpu.core.autoscale import (
        FleetController, FleetPolicy, LeaderLease, LeaseChannel,
        NullActuator)
    from nnstreamer_tpu.core.fleet import FleetObservatory
    from nnstreamer_tpu.distributed.mqtt import MiniBroker

    broker = MiniBroker()
    obs = FleetObservatory(topic="perfcp", default_ttl_s=5.0)
    chan = None
    stop = threading.Event()
    try:
        obs.start("127.0.0.1", broker.port)
        lease = LeaderLease("perf-ctl", ttl_s=1.0)
        chan = LeaseChannel("127.0.0.1", broker.port, "perfcp", lease)
        ctrl = FleetController(obs, NullActuator(),
                               policy=FleetPolicy(min_servers=0),
                               lease=lease)
        t0 = time.monotonic()
        while not lease.held and time.monotonic() - t0 < 10.0:
            ctrl.tick()          # vacancy watch, then acquire
            time.sleep(0.02)
        assert lease.held, "lease never acquired against a live broker"

        def churn():
            while not stop.is_set():
                ctrl.tick()      # renew + assess_plane every 20ms
                time.sleep(0.02)

        th = threading.Thread(target=churn, daemon=True)
        th.start()
        fps = _passthrough_fps(True)
        stop.set()
        th.join(timeout=5.0)
        assert lease.held and lease.self_fences == 0
        assert fps >= 4000, (
            f"armed control plane invaded the dataplane: {fps:.0f} fps "
            "< 4000"
        )
    finally:
        stop.set()
        if chan is not None:
            chan.close()
        obs.stop()
        broker.close()
