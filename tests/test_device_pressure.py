"""Device-resource resilience: HBM pressure and device loss (degrade,
don't die — Documentation/resilience.md "Resource pressure & device
loss").

Covers the four seams of the ladder:

1. **Typed taxonomy** — ``classify_device_error`` maps raw XLA runtime
   errors to :class:`DeviceOomError` / :class:`DeviceLostError`; both
   are transient (a shrink/re-mesh cures them, never a restart burn).
2. **Adaptive OOM recovery on the hot path** — the filter retries once
   at the next-smaller batch bucket with exact ``oom_retries`` /
   ``oom_shrinks`` / ``oom_evictions`` accounting (fused/unfused
   parity), and the slot engine sheds its lowest-priority slot as a
   RESUMABLE continuity chunk.
3. **Memory watermarks** — ``MemoryPressureMonitor`` hysteresis, trim
   hooks, rate-limited incidents, and the admission coupling that sheds
   BUSY (reason="memory") *before* the chip OOMs.
4. **Degraded-mesh re-shard** — a jax-xla mesh backend that loses a
   device rebuilds on the survivors via the ``shrink_axes`` ladder,
   the slot engine hands live streams off with resume state, and the
   serving plane announces degraded.

Every path runs chip-free: deterministic injection via the ``device.*``
fault sites, the AsyncSim ``oom_at``/``lost_at`` knobs, and the
SimSlotModel ``fail_next`` twin.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import (
    BatchFrame,
    DeviceBufferPool,
    FramePool,
    TensorFrame,
)
from nnstreamer_tpu.core.continuity import GOAWAY_META, RESUME_META
from nnstreamer_tpu.core.liveness import (
    MemoryPressureMonitor,
    ServerBusyError,
    TenantAdmissionController,
)
from nnstreamer_tpu.core.resilience import (
    FAULTS,
    DeviceLostError,
    DeviceOomError,
    classify_device_error,
    is_transient,
)
from nnstreamer_tpu.core.slots import SimSlotModel, SlotEngine
from nnstreamer_tpu.parallel.mesh import remesh_after_loss, shrink_axes
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# ---------------------------------------------------------------------------
# 1. Typed device-error classification
# ---------------------------------------------------------------------------
# a stand-in whose TYPE NAME matches the jax runtime's (classification
# keys on name/module, never on an import of jaxlib)
XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})


class TestClassification:
    def test_resource_exhausted_maps_to_oom(self):
        raw = XlaRuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
            "4096 bytes")
        typed = classify_device_error(raw)
        assert isinstance(typed, DeviceOomError)
        assert typed.__cause__ is raw

    def test_device_death_maps_to_lost(self):
        typed = classify_device_error(
            XlaRuntimeError("INTERNAL: device is lost (chip reset?)"))
        assert isinstance(typed, DeviceLostError)

    def test_unrelated_runtime_error_is_not_classified(self):
        assert classify_device_error(
            XlaRuntimeError("INVALID_ARGUMENT: shapes differ")) is None
        assert classify_device_error(ValueError("nope")) is None

    def test_already_typed_pass_through(self):
        e = DeviceOomError("x")
        assert classify_device_error(e) is e
        lost = DeviceLostError("y", device_ids=(3,))
        assert classify_device_error(lost) is lost
        assert lost.device_ids == (3,)

    def test_both_are_transient(self):
        # the recovery ladders cure them; supervision must never treat
        # them as poison frames (restart-budget burn / dead-letter)
        assert is_transient(DeviceOomError("o"))
        assert is_transient(DeviceLostError("l"))


class TestShrinkLadder:
    """parallel/mesh.shrink_axes: dp gives way first, then tp halves,
    then unsharded."""

    @pytest.mark.parametrize("axes,n,want", [
        ({"dp": 2, "tp": 2}, 3, {"dp": 1, "tp": 2}),
        ({"dp": 4, "tp": 2}, 6, {"dp": 3, "tp": 2}),
        ({"dp": 4}, 2, {"dp": 2}),
        ({"tp": 4}, 2, {"tp": 2}),
        ({"tp": 2}, 1, {}),
        ({"dp": 2, "tp": 2}, 1, {}),
        ({}, 4, {}),
    ])
    def test_ladder(self, axes, n, want):
        assert shrink_axes(axes, n) == want


class TestRemeshAfterLoss:
    """parallel/mesh.remesh_after_loss: dead-member identification
    order (reported > probed > guessed-last), the probe's
    cannot-probe (``None``) vs all-alive (``()``) disambiguation, and
    the exclusion contract — shared by the jax-xla backend and the
    slotted generator so both re-shard identically."""

    def test_reported_ids_win_and_probe_is_skipped(self):
        probed = []
        dead, axes, spec = remesh_after_loss(
            [0, 1, 2, 3], {"dp": 2, "tp": 2}, (1,),
            probe=lambda ids: probed.append(ids) or (0,))
        assert dead == (1,) and probed == []
        assert axes == {"dp": 1, "tp": 2} and spec == "dp:1,tp:2"

    def test_unnamed_loss_probes_for_the_dead_member(self):
        """Real XLA status strings rarely name the chip: with empty
        ``lost_ids`` the ladder PROBES instead of guessing, so
        ordinal-first claiming cannot hand the rebuilt backend the
        dead chip back (chip 0 dead + a last-member guess would have
        re-placed tp:2 on devices[:2] = {0, 1})."""
        dead, axes, spec = remesh_after_loss(
            [0, 1, 2, 3], {"tp": 4}, (), probe=lambda ids: (0,))
        assert dead == (0,)
        assert axes == {"tp": 2} and spec == "tp:2"

    def test_all_alive_probe_condemns_nobody(self):
        """A probe that reaches EVERY member means the loss did not
        reproduce: dead comes back empty with axes UNCHANGED — callers
        escalate to supervision (a plain retry may cure a transient)
        instead of shrinking the mesh around a healthy chip."""
        dead, axes, spec = remesh_after_loss(
            [0, 1], {"tp": 2}, (), probe=lambda ids: ())
        assert dead == ()
        assert axes == {"tp": 2} and spec == "tp:2"

    def test_unavailable_probe_falls_back_to_last_member_guess(self):
        """``None`` from the probe = could not even enumerate devices
        (a wedged runtime): only THEN does the conservative last-member
        guess apply."""
        dead, axes, spec = remesh_after_loss(
            [0, 1], {"tp": 2}, (), probe=lambda ids: None)
        assert dead == (1,)
        assert axes == {} and spec == ""

    def test_no_probe_falls_back_to_last_member_guess(self):
        dead, axes, spec = remesh_after_loss([0, 1], {"tp": 2}, ())
        assert dead == (1,)
        assert axes == {} and spec == ""


class TestUnshardedSurvivorPlacement:
    """The BOTTOM rung of the re-mesh ladder (spec ``""`` = rebuild
    unsharded) must still avoid the dead ordinals: the default device
    pick would otherwise hand the rebuilt backend the very chip that
    died, crash-looping a server with a healthy survivor."""

    def test_unsharded_open_avoids_excluded_ordinal(self):
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 proxy devices")
        from nnstreamer_tpu.backends.jax_xla import (
            JaxXla,
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model("excl_ident", lambda p, xs: list(xs))
        try:
            first = int(jax.devices()[0].id)
            be = JaxXla()
            be.open("excl_ident", {
                "accelerators": ["cpu"], "mesh": "",
                "mesh_remesh_override": True,
                "mesh_exclude_ids": [first],
            })
            try:
                assert int(be._device.id) != first
            finally:
                be.close()
        finally:
            unregister_jax_model("excl_ident")

    def test_override_replaces_legacy_mesh_custom_props(self):
        """A survivor spec must REPLACE legacy ``mesh_*`` custom props,
        not merge over them — re-merged axes the survivors cannot
        satisfy would refuse every restart."""
        from nnstreamer_tpu.backends.jax_xla import JaxXla

        be = JaxXla()
        be.custom_props = {"mesh_dp": "2"}
        assert be._mesh_axes_from_props({"mesh": ""}) == {"dp": 2}
        assert be._mesh_axes_from_props(
            {"mesh": "", "mesh_remesh_override": True}) == {}
        assert be._mesh_axes_from_props(
            {"mesh": "tp:2", "mesh_remesh_override": True}) == {"tp": 2}


# ---------------------------------------------------------------------------
# Satellite: DeviceBufferPool key-space LRU + trims
# ---------------------------------------------------------------------------
class TestPoolBounds:
    def test_key_space_is_lru_bounded(self):
        pool = DeviceBufferPool(max_per_key=2)
        sweep = pool.MAX_KEYS + 8
        for i in range(sweep):
            # a (shape, dtype, placement) sweep used to grow rings
            # forever — the jit-cache leak class
            pool.release(np.empty((i + 1, 4), np.float32))
        assert len(pool._free) <= pool.MAX_KEYS
        assert pool.rings_evicted >= 8

    def test_lru_keeps_the_hot_ring(self):
        pool = DeviceBufferPool(max_per_key=2)
        hot = pool._key((2, 2), np.float32, None)
        pool.release(np.empty((2, 2), np.float32))
        for i in range(pool.MAX_KEYS + 4):
            pool.release(np.empty((i + 3, 3), np.float32))
            pool.acquire((2, 2), np.float32)  # touch = keep
        assert hot in pool._free

    def test_trim_frees_everything_but_keeps_pooling(self):
        pool = DeviceBufferPool(max_per_key=4)
        for _ in range(3):
            pool.release(np.empty((4, 4), np.float32))
        assert pool.trim() == 3
        assert not pool._free and pool.trims == 1
        buf = pool.acquire((4, 4), np.float32)
        assert pool.release(buf)  # ring rebuilt on demand

    def test_frame_pool_trim(self):
        fp = FramePool(maxsize=16)
        f = fp.acquire([np.zeros(2)])
        fp.recycle(f)
        assert fp.trim() >= 1
        assert fp.acquire([np.zeros(2)]) is not None


# ---------------------------------------------------------------------------
# 3. Memory watermarks
# ---------------------------------------------------------------------------
class FakeMem:
    def __init__(self, frac=0.0, limit=1000):
        self.frac = frac
        self.limit = limit

    def __call__(self):
        return int(self.frac * self.limit), self.limit, 123


class TestMemoryPressureMonitor:
    def _mon(self, mem, clk, **kw):
        kw.setdefault("high", 0.9)
        kw.setdefault("low", 0.7)
        kw.setdefault("min_poll_s", 0.0)
        return MemoryPressureMonitor(
            sample=mem, clock=lambda: clk["t"], **kw)

    def test_hysteresis_and_trim_on_entry(self):
        mem, clk = FakeMem(0.5), {"t": 0.0}
        trims = {"n": 0}
        mon = self._mon(mem, clk)
        mon.add_trim_hook(lambda: trims.__setitem__("n", trims["n"] + 1) or 7)
        assert mon.poll() is False
        mem.frac = 0.95
        clk["t"] = 1.0
        assert mon.poll() is True and trims["n"] == 1
        assert mon.trimmed_entries == 7
        # inside the hysteresis band: still pressured, no re-trim
        mem.frac = 0.8
        clk["t"] = 1.1
        assert mon.poll() is True and trims["n"] == 1
        mem.frac = 0.6
        clk["t"] = 1.2
        assert mon.poll() is False
        snap = mon.snapshot()
        assert snap["mem_pressure"] == 0 and snap["mem_trims"] == 1
        assert snap["mem_host_rss"] == 123

    def test_sustained_pressure_incident_is_rate_limited(self):
        mem, clk = FakeMem(0.95), {"t": 0.0}
        hits = []
        mon = self._mon(mem, clk, sustain_s=1.0, incident_interval_s=10.0,
                        on_pressure=hits.append)
        mon.poll()
        assert not hits  # entered, not yet sustained
        clk["t"] = 1.5
        mon.poll()
        assert len(hits) == 1 and hits[0]["mem_pressure"] == 1
        clk["t"] = 2.0
        mon.poll()
        assert len(hits) == 1  # rate-limited
        clk["t"] = 12.0
        mon.poll()
        assert len(hits) == 2

    def test_poll_rate_limit(self):
        mem, clk = FakeMem(0.0), {"t": 0.0}
        mon = self._mon(mem, clk, min_poll_s=0.25)
        mon.poll()
        clk["t"] = 0.1
        mon.poll()  # inside the window: no sample
        assert mon.polls == 1
        clk["t"] = 0.3
        mon.poll()
        assert mon.polls == 2

    def test_host_rss_watermark_fallback(self):
        # no device stats: the host-RSS/host-limit fraction drives it
        clk = {"t": 0.0}
        mon = MemoryPressureMonitor(
            high=0.9, low=0.5, min_poll_s=0.0, host_limit_bytes=100,
            sample=lambda: (0, 0, 95), clock=lambda: clk["t"])
        assert mon.poll() is True

    def test_armed_monitor_never_inert_without_limits(self):
        """Stats-less platform + no explicit host limit: the fraction
        defaults to RSS over physical RAM — an armed watermark must
        watch SOMETHING, never sit at 0.0 while the process OOMs."""
        mon = MemoryPressureMonitor(
            high=0.9, low=0.5, min_poll_s=0.0,
            sample=lambda: (0, 0, 123 << 20), clock=lambda: 0.0)
        mon.poll()
        assert mon.fraction > 0.0

    def test_bad_watermarks_refused(self):
        with pytest.raises(ValueError):
            MemoryPressureMonitor(high=0.5, low=0.8)


class TestAdmissionMemoryCoupling:
    def test_pressure_sheds_with_memory_reason(self):
        flag = {"on": False}
        adm = TenantAdmissionController(high=8)
        adm.pressure = lambda: flag["on"]
        adm.admit(tenant="a")
        flag["on"] = True
        with pytest.raises(ServerBusyError) as ei:
            adm.admit(tenant="a")
        assert ei.value.reason == "memory"
        assert adm.memory_shed == 1
        assert adm.snapshot()["memory_shed"] == 1
        flag["on"] = False
        adm.admit(tenant="a")  # clears with the watermark
        adm.release(tenant="a")
        adm.release(tenant="a")

    def test_memory_shed_covers_every_priority_class(self):
        adm = TenantAdmissionController(high=8)
        adm.pressure = lambda: True
        # HBM exhaustion takes the whole chip down: even priority-3
        # traffic sheds while the watermark is crossed
        for prio in (0, 3):
            with pytest.raises(ServerBusyError) as ei:
                adm.admit(tenant="x", priority=prio)
            assert ei.value.reason == "memory"
        assert adm.memory_shed == 2


# ---------------------------------------------------------------------------
# 2a. Slot engine: OOM shed + device-loss handoff
# ---------------------------------------------------------------------------
def _engine(model, resume_sig="testsig", **kw):
    eng = SlotEngine(model, None, max_seq=1 << 20, chunk=4,
                     prefill_chunk=32, resume_sig=resume_sig, **kw)
    eng.start()
    return eng


def _submit(eng, base=1, priority=3):
    prompt = (np.arange(4, dtype=np.int32)[None] + base)
    frame = TensorFrame([prompt])
    return eng.submit(frame, prompt, max_new=24, chunk=4,
                      priority=priority), prompt


def _drain_until(eng, pred, timeout=20.0, out=None):
    out = [] if out is None else out
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out.extend(eng.pop_ready())
        if pred(out):
            return out
        time.sleep(0.005)
    raise TimeoutError(f"engine never satisfied predicate; got {len(out)}")


def _oracle(model, prompt, n):
    t = int(prompt.sum()) % model.vocab
    toks = [t]
    for _ in range(n - 1):
        t = model.step_token(t)
        toks.append(t)
    return toks


def _stream_tokens(frames, sid):
    toks = []
    for _, f in frames:
        if f.meta.get("stream_seq") == sid and f.tensors:
            toks.extend(int(t) for t in np.asarray(f.tensors[0])[0])
    return toks


class TestSlotEngineOom:
    def test_oom_sheds_lowest_priority_resumably(self):
        model = SimSlotModel(2, step_base_ms=0.5)
        eng = _engine(model)
        try:
            s_lo, p_lo = _submit(eng, base=1, priority=0)
            s_hi, p_hi = _submit(eng, base=9, priority=3)
            # both decoding: wait for tokens from each, then blow HBM
            out = _drain_until(eng, lambda o: (
                _stream_tokens(o, s_lo.frame.seq)
                and _stream_tokens(o, s_hi.frame.seq)))
            model.fail_next("oom")
            out = _drain_until(
                eng, lambda o: any(
                    f.meta.get("evicted") == "oom" for _, f in o),
                out=out)
            shed = [f for _, f in out if f.meta.get("evicted") == "oom"]
            assert len(shed) == 1
            # the LOWEST priority stream was chosen, and its chunk is a
            # resumable migration (goaway marker + resume state), never
            # a deadline-style failure
            assert shed[0].meta.get("stream_seq") == s_lo.frame.seq
            assert shed[0].meta.get(GOAWAY_META) is True
            assert RESUME_META in shed[0].meta
            assert "deadline_expired" not in shed[0].meta
            assert eng.oom_retries == 1 and eng.oom_sheds == 1
            # the survivor finishes bit-exact: the retried step lost no
            # tokens and duplicated none
            out = _drain_until(
                eng, lambda o: any(
                    f.meta.get("final") and not f.meta.get("evicted")
                    and f.meta.get("stream_seq") == s_hi.frame.seq
                    for _, f in o),
                out=out)
            assert _stream_tokens(out, s_hi.frame.seq) == _oracle(
                model, p_hi, 24)
            assert eng.snapshot()["gen_oom_sheds"] == 1
        finally:
            eng.stop()

    def test_donated_cache_death_hands_off_all_streams_resumably(self):
        """Real-chip donation semantics: the decode/prefill jits donate
        the KV cache, and donation invalidates at DISPATCH, not at
        success — a step that OOMs after dispatch leaves ``_cache``
        deleted.  Retrying on the deleted buffers would raise an
        UNTYPED "Array has been deleted" and kill the pump; instead the
        engine hands EVERY live stream off as a resumable continuity
        chunk and re-inits the cache clean (streams re-prefill on
        resume — bit-exact), then keeps serving."""

        class _DeadLeaf:
            def is_deleted(self):
                return True

        model = SimSlotModel(2, step_base_ms=0.5)
        eng = _engine(model)
        try:
            s1, _p1 = _submit(eng, base=1, priority=0)
            s2, _p2 = _submit(eng, base=9, priority=3)
            out = _drain_until(eng, lambda o: (
                _stream_tokens(o, s1.frame.seq)
                and _stream_tokens(o, s2.frame.seq)))
            # the OOMing step also consumed the donated cache
            orig = eng._handle_oom

            def oom_and_kill_cache():
                orig()
                eng._cache = {"k": _DeadLeaf()}

            eng._handle_oom = oom_and_kill_cache
            model.fail_next("oom")
            out = _drain_until(eng, lambda o: sum(
                1 for _, f in o
                if f.meta.get("evicted") == "oom") >= 2, out=out)
            shed = [f for _, f in out if f.meta.get("evicted") == "oom"]
            # the priority victim AND the survivor whose KV died: both
            # resumable migrations, never a poisoned frame
            assert len(shed) == 2
            for f in shed:
                assert f.meta.get(GOAWAY_META) is True
                assert RESUME_META in f.meta
            assert eng.oom_retries == 1 and eng.oom_sheds == 2
            # the pump SURVIVED with a fresh cache: a new stream
            # decodes to the exact oracle
            s3, p3 = _submit(eng, base=42)
            out = _drain_until(eng, lambda o: any(
                f.meta.get("final") and not f.meta.get("evicted")
                and f.meta.get("stream_seq") == s3.frame.seq
                for _, f in o))
            assert _stream_tokens(out, s3.frame.seq) == _oracle(
                model, p3, 24)
        finally:
            eng.stop()

    def test_single_occupant_oom_is_shed_resumably(self):
        model = SimSlotModel(1, step_base_ms=0.5, oom_at_step=0)
        eng = _engine(model)
        try:
            s, _ = _submit(eng)
            out = _drain_until(
                eng, lambda o: any(f.meta.get("final") for _, f in o))
            # single occupant: it IS the lowest-priority slot, so it is
            # shed resumably (token 1 from the prefill survives in the
            # handoff chunk) — never silently dropped, never restarted
            shed = [f for _, f in out if f.meta.get("evicted") == "oom"]
            assert len(shed) == 1
            assert shed[0].meta.get(GOAWAY_META) is True
            assert shed[0].meta.get("tokens_done") == 1
            assert eng.oom_retries == 1 and eng.oom_sheds == 1
        finally:
            eng.stop()


class TestSlotEngineDeviceLost:
    def test_loss_hands_off_all_streams_and_remeshes(self):
        model = SimSlotModel(4, step_base_ms=0.5)
        calls = []

        def hook(err):
            calls.append(err)
            return None  # sim twin: recovered in place

        eng = _engine(model, on_device_lost=hook)
        try:
            streams = [_submit(eng, base=i * 7 + 1) for i in range(3)]
            _drain_until(eng, lambda out: all(
                _stream_tokens(out, s.frame.seq) for s, _ in streams))
            model.fail_next("lost")
            out = _drain_until(eng, lambda o: sum(
                1 for _, f in o
                if f.meta.get("evicted") == "device_lost") >= 3)
            handed = [f for _, f in out
                      if f.meta.get("evicted") == "device_lost"]
            assert len(handed) == 3
            for f in handed:
                assert f.meta.get(GOAWAY_META) is True  # resumable
                assert RESUME_META in f.meta
            assert len(calls) == 1
            assert isinstance(calls[0], DeviceLostError)
            snap = eng.snapshot()
            assert snap["gen_device_lost"] == 1
            assert snap["gen_device_lost_evicted"] == 3
            assert snap["gen_remeshes"] == 1
            # the engine keeps serving on the "survivors": a fresh
            # stream decodes to the exact oracle
            s2, p2 = _submit(eng, base=42)
            out = _drain_until(eng, lambda o: any(
                f.meta.get("final")
                and f.meta.get("stream_seq") == s2.frame.seq
                for _, f in o))
            assert _stream_tokens(out, s2.frame.seq) == _oracle(
                model, p2, 24)
        finally:
            eng.stop()

    def test_loss_without_hook_is_sticky(self):
        model = SimSlotModel(1, step_base_ms=0.5)
        eng = _engine(model, on_device_lost=None)
        try:
            _submit(eng)
            model.fail_next("lost")
            deadline = time.monotonic() + 10
            with pytest.raises(DeviceLostError):
                while time.monotonic() < deadline:
                    eng.pop_ready()
                    time.sleep(0.01)
                raise TimeoutError("engine error never surfaced")
        finally:
            eng.stop()

    def test_legacy_engine_handoff_is_typed_but_not_resumable(self):
        model = SimSlotModel(1, step_base_ms=0.5)
        eng = _engine(model, resume_sig=None, on_device_lost=lambda e: None)
        try:
            s, _ = _submit(eng)
            _drain_until(eng, lambda out: _stream_tokens(out, s.frame.seq))
            model.fail_next("lost")
            out = _drain_until(eng, lambda o: any(
                f.meta.get("evicted") == "device_lost" for _, f in o))
            f = next(f for _, f in out
                     if f.meta.get("evicted") == "device_lost")
            # no resume state to offer: the truncation is LOUD (typed
            # final chunk), never a goaway a client would wait on
            assert GOAWAY_META not in f.meta
            assert RESUME_META not in f.meta
            assert f.meta.get("final") is True
        finally:
            eng.stop()


class TestGeneratorDeviceLost:
    """The slotted generator's ``on_device_lost`` hook for REAL
    (non-sim) models: an unsharded model escalates to supervision
    instead of "recovering" onto the dead device forever, and a
    re-shard leaves a survivor config that later restarts keep."""

    CUSTOM = ("dtype:float32,vocab:61,d_model:32,heads:2,layers:1,"
              "d_ff:64,seq:32,seed:11")

    def test_unsharded_real_model_loss_escalates(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_generator name=g slots=2 "
            f"custom={self.CUSTOM} max-new=4 ! tensor_sink name=out",
            name="genloss", fuse=False)
        pipe.start()
        try:
            with pytest.raises(DeviceLostError):
                pipe["g"]._rebuild_on_device_loss(
                    DeviceLostError("chip gone"))
        finally:
            pipe.stop()

    def test_restart_keeps_the_survivor_config(self):
        """A supervision restart after a re-shard must claim the SHRUNK
        config: re-claiming the original spec against the exclusion
        list would refuse to start once the survivors no longer fit
        it (the dead stay dead across restarts)."""
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 proxy devices")
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_generator name=g slots=2 "
            f"custom={self.CUSTOM} mesh=tp:2 max-new=4 ! "
            "tensor_sink name=out", name="genremesh", fuse=False)
        pipe.start()
        g = pipe["g"]
        assert g._mesh is not None
        pipe.stop()
        # the state a device-loss rebuild leaves behind: the FIRST
        # member (the default pick!) dead, survivor config "" (unsharded)
        dead = int(jax.devices()[0].id)
        g._mesh_exclude = (dead,)
        g._mesh_override = ""
        g.start()  # the supervision-restart path re-enters start()
        try:
            assert g._mesh is None  # serving unsharded on the survivor
            leaf = jax.tree_util.tree_leaves(g._params)[0]
            assert dead not in {int(d.id) for d in leaf.devices()}
        finally:
            g.stop()


# ---------------------------------------------------------------------------
# 2b. Filter hot path: OOM shrink-retry (fused/unfused parity) + loss
# ---------------------------------------------------------------------------
def _run_block_through_filter(fuse: bool, custom: str,
                              n: int = 8) -> tuple:
    pipe = parse_pipeline(
        "appsrc name=src ! "
        f"tensor_filter name=f framework=async-sim custom={custom} "
        "max-batch=8 ! tensor_sink name=out max-stored=64",
        name=f"oomf-{fuse}", fuse=fuse)
    pipe.start()
    got = []
    pipe["out"].connect_new_data(
        lambda f: got.append(float(np.asarray(f.tensors[0])[0])))
    block = np.arange(n * 1, dtype=np.float32).reshape(n, 1)
    pipe["src"].push_block(block)
    pipe["src"].end_of_stream()
    pipe.wait(timeout=30)
    health = pipe.health()["f"]
    pipe.stop()
    return got, health


class TestFilterOomShrinkRetry:
    @pytest.mark.parametrize("fuse", [True, False])
    def test_injected_oom_burst_delivers_every_frame(self, fuse):
        """Acceptance pin: an OOM on a full 8-row micro-batch delivers
        ALL frames via two half-bucket invokes — zero dead-letters,
        zero restart-budget burn, exact counters; identical fused and
        unfused (the parity satellite)."""
        got, health = _run_block_through_filter(fuse, "oom_at:0")
        assert sorted(got) == [v * 2.0 + 1.0 for v in range(8)]
        assert health["oom_retries"] == 1
        assert health["oom_shrinks"] == 1
        assert health["device_lost"] == 0
        assert health["dead_letters"] == 0
        assert health["restarts"] == 0
        assert health["degraded"] == 0

    def test_unrecovered_second_oom_escalates(self):
        """The retry is ONCE: a second OOM on the shrunken halves
        surfaces to supervision (typed, transient) instead of looping."""
        # every attempt from 0 on faults: attempt 0 (full batch) and
        # attempt 1 (first half) both OOM
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter name=f framework=async-sim "
            "custom=oom_at:0,oom_every:0 max-batch=8 ! "
            "tensor_sink name=out", name="oomhard", fuse=False)
        # arm the process-wide site as well: the half-batch retry hits it
        FAULTS.arm("device.oom", exc=DeviceOomError, times=2, after=1)
        pipe.start()
        pipe["src"].push_block(np.ones((8, 1), np.float32))
        pipe["src"].end_of_stream()
        with pytest.raises(DeviceOomError):
            pipe.wait(timeout=20)
        pipe.stop()

    def test_per_frame_oom_trims_and_retries_once(self):
        """max-batch=1 path (nothing to split): trim + one bare retry."""
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model("oom_ident", lambda p, xs: list(xs))
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! "
                "tensor_filter name=f framework=jax-xla model=oom_ident ! "
                "tensor_sink name=out max-stored=8",
                name="oomframe", fuse=False)
            pipe.start()
            got = []
            pipe["out"].connect_new_data(
                lambda f: got.append(np.asarray(f.tensors[0]).copy()))
            FAULTS.arm("device.oom", exc=DeviceOomError, times=1, after=1)
            for i in range(4):
                pipe["src"].push(np.full((3,), float(i), np.float32))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=60)
            health = pipe.health()["f"]
            pipe.stop()
            assert len(got) == 4
            assert health["oom_retries"] == 1
            assert health["oom_shrinks"] == 0
            assert health["dead_letters"] == 0
        finally:
            unregister_jax_model("oom_ident")

    def test_donated_inputs_deleted_by_the_failed_attempt_escalate(self):
        """A donated invoke invalidates its inputs at DISPATCH, not at
        success: when the OOM lands after donation there is nothing
        left to slice — the typed transient error must surface to
        supervision, never a crash on a deleted array (and never a
        phantom ``oom_retries`` count for a retry that cannot run)."""
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter name=f framework=async-sim max-batch=8 ! "
            "tensor_sink name=out", name="oomdel", fuse=False)
        pipe.start()
        try:
            f = pipe["f"]

            def _oom(inputs, private=False):
                raise DeviceOomError("post-donation OOM")

            f._backend_invoke_batch = _oom

            class DeletedArray:
                shape = (8, 1)

                def is_deleted(self):
                    return True

                def __getitem__(self, s):
                    raise RuntimeError("Array has been deleted.")

            with pytest.raises(DeviceOomError):
                f._resilient_invoke_batch([DeletedArray()], private=True)
            assert f._oom_retries == 0
            assert f._oom_shrinks == 0
        finally:
            pipe.stop()


# ---------------------------------------------------------------------------
# Satellite: RESOURCE_EXHAUSTED inside staged-reload warmup
# ---------------------------------------------------------------------------
class TestWarmupOom:
    def test_warmup_oom_counts_swap_failure_and_keeps_serving(self):
        """An OOM-typed error raised inside the staged-reload WARMUP
        (the new model's probe invoke blowing HBM) must land as a
        ``swap_failures`` with the old backend serving — never a
        restart, never a half-swapped backend."""
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model("warm_a", lambda p, xs: [xs[0] * 2.0])
        register_jax_model("warm_b", lambda p, xs: [xs[0] * 3.0])
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! "
                "tensor_filter name=f framework=jax-xla model=warm_a "
                "is-updatable=true ! tensor_sink name=out max-stored=8",
                name="warmoom", fuse=False)
            pipe.start()
            got = []
            pipe["out"].connect_new_data(
                lambda f: got.append(float(np.asarray(f.tensors[0])[0])))
            pipe["src"].push(np.float32([1.0]))
            _wait(lambda: len(got) == 1)
            FAULTS.arm(
                "filter.reload.warmup",
                exc=DeviceOomError("RESOURCE_EXHAUSTED in staged warmup"),
                times=1)
            ticket = pipe["f"].request_reload("warm_b")
            assert ticket.wait_staged(timeout=20)
            assert not ticket.ok
            assert isinstance(ticket.error, DeviceOomError)
            # the OLD model keeps serving, accounted as a swap failure
            pipe["src"].push(np.float32([2.0]))
            _wait(lambda: len(got) == 2)
            assert got == [2.0, 4.0]  # still *2, never *3
            health = pipe.health()["f"]
            assert health["swap_failures"] == 1
            assert health["swaps"] == 0
            assert health["restarts"] == 0
            pipe["src"].end_of_stream()
            pipe.wait(timeout=20)
            pipe.stop()
        finally:
            unregister_jax_model("warm_a")
            unregister_jax_model("warm_b")


def _wait(pred, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise TimeoutError("condition never held")


# ---------------------------------------------------------------------------
# 4. Degraded-mesh re-shard (real jax-xla CPU proxy mesh)
# ---------------------------------------------------------------------------
class TestFilterDeviceLostRemesh:
    def test_mesh_member_loss_reshards_and_redelivers(self):
        """dp:2,tp:2 filter loses device 3 mid-serving: the element
        stages a dp:1,tp:2 backend on the survivors, swaps atomically,
        retries the failed batch (zero frame loss), reports exact
        ``device_lost``/``remeshes`` counters, and marks itself
        degraded."""
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs >= 4 proxy devices")
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model("remesh_ident", lambda p, xs: [xs[0] * 2.0])
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! "
                "tensor_filter name=f framework=jax-xla "
                "model=remesh_ident mesh=dp:2,tp:2 max-batch=4 ! "
                "tensor_sink name=out max-stored=64",
                name="remesh", fuse=False)
            pipe.start()
            got = []
            pipe["out"].connect_new_data(
                lambda f: got.append(float(np.asarray(f.tensors[0])[0])))
            pipe["src"].push_block(
                np.arange(4, dtype=np.float32).reshape(4, 1))
            _wait(lambda: len(got) == 4)
            assert pipe.health()["f"]["mesh_devices"] == 4
            # device 3 dies under the NEXT batch (exactly once)
            FAULTS.arm("device.lost", callback=lambda i: (
                DeviceLostError("injected chip death", device_ids=(3,))
                if i == 0 else None))
            pipe["src"].push_block(
                np.arange(4, 8, dtype=np.float32).reshape(4, 1))
            _wait(lambda: len(got) == 8)
            pipe["src"].push_block(
                np.arange(8, 12, dtype=np.float32).reshape(4, 1))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=60)
            health = pipe.health()["f"]
            pipe.stop()
            # zero frame loss, bit-exact through the re-shard
            assert sorted(got) == [v * 2.0 for v in range(12)]
            assert health["device_lost"] == 1
            assert health["remeshes"] == 1
            assert health["degraded"] == 1
            # the survivors' mesh: dp halved, tp kept, dead chip excluded
            assert health["mesh_devices"] == 2
            assert health["mesh_dp"] == 1 and health["mesh_tp"] == 2
            assert health["dead_letters"] == 0
            assert health["restarts"] == 0
        finally:
            unregister_jax_model("remesh_ident")

    def test_unsharded_loss_falls_through_to_supervision(self):
        """No mesh = no re-mesh story: the typed loss reaches the
        supervisor (error-policy owns it), pinned so the ladder never
        silently swallows a loss it cannot cure."""
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model("flat_ident", lambda p, xs: list(xs))
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! "
                "tensor_filter name=f framework=jax-xla model=flat_ident ! "
                "tensor_sink name=out", name="flatloss", fuse=False)
            pipe.start()
            FAULTS.arm("device.lost", exc=DeviceLostError, times=1)
            pipe["src"].push(np.float32([1.0]))
            pipe["src"].end_of_stream()
            with pytest.raises(DeviceLostError):
                pipe.wait(timeout=20)
            pipe.stop()
        finally:
            unregister_jax_model("flat_ident")

    def test_unsharded_loss_excludes_dead_chip_for_restart(self):
        """An UNSHARDED loss has no re-mesh story, but the reported
        dead ordinal must still land on the exclusion list — without
        it the supervision restart deterministically re-picks the very
        chip that died (pick_device is ordinal-first) and crash-loops
        until the restart budget burns.  With it, open()'s survivor
        placement moves serving to a live device and every frame
        delivers."""
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 proxy devices")
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model("uloss_double", lambda p, xs: [xs[0] * 2.0])
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! "
                "tensor_filter name=f framework=jax-xla "
                "model=uloss_double error-policy=restart "
                "max-restarts=2 ! tensor_sink name=out max-stored=64",
                name="ulossex", fuse=False)
            pipe.start()
            got = []
            pipe["out"].connect_new_data(
                lambda f: got.append(float(np.asarray(f.tensors[0])[0])))
            own = int(pipe["f"].backend._device.id)
            FAULTS.arm("device.lost", callback=lambda i: (
                DeviceLostError("chip reset", device_ids=(own,))
                if i == 0 else None))
            pipe["src"].push(np.float32([1.0]))
            pipe["src"].push(np.float32([2.0]))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            health = pipe.health()["f"]
            moved_to = int(pipe["f"].backend._device.id)
            pipe.stop()
            assert sorted(got) == [2.0, 4.0]
            assert health["restarts"] == 1
            assert health["device_lost"] == 1
            assert health["dead_letters"] == 0
            assert moved_to != own  # restarted on a SURVIVOR
        finally:
            unregister_jax_model("uloss_double")


# ---------------------------------------------------------------------------
# 3b. Watermark -> BUSY coupling, end to end over the query wire
# ---------------------------------------------------------------------------
class TestWatermarkProps:
    def test_serversrc_prop_arms_the_pipeline_monitor(self):
        """Pipeline-text configuration parity: ``mem-high-watermark=``
        on the serversrc arms the same pipeline monitor as
        ``enable_memory_monitor()`` (sweeper-polled, admission-coupled,
        default real sampler)."""
        pipe = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=9472 port=0 "
            "connect-type=tcp mem-high-watermark=0.9 ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=9472", name="memprop")
        pipe.start()
        try:
            mon = pipe.memory_monitor
            assert mon is not None
            assert mon.high == 0.9 and abs(mon.low - 0.72) < 1e-9
            _wait(lambda: mon.polls > 0)  # the sweeper picked it up
            assert pipe.health()["ssrc"]["mem_polls"] >= 1
        finally:
            pipe.stop()


class TestWatermarkShedsBeforeOom:
    def test_server_sheds_busy_at_the_watermark_then_recovers(self):
        """Acceptance pin: sustained watermark pressure sheds BUSY at
        admission (reason=memory, exact ``memory_shed`` count, breaker-
        immune) and serving resumes once pressure clears — every frame
        delivered exactly once."""
        mem = FakeMem(0.1)
        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=9471 port=0 "
            "connect-type=tcp max-inflight=8 ! "
            "tensor_filter name=f framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=9471", name="memsrv")
        mon = server.enable_memory_monitor(
            high=0.9, low=0.5, sustain_s=0.05, min_poll_s=0.01,
            sample=mem)
        server.start()
        port = server["ssrc"].props["port"]
        client = parse_pipeline(
            "appsrc name=src ! "
            f"tensor_query_client name=q hosts=localhost:{port} "
            "connect-type=tcp busy-retries=200 retry-backoff=0.02 "
            "timeout=30 ! tensor_sink name=out max-stored=64",
            name="memcli")
        client.start()
        got = []
        client["out"].connect_new_data(
            lambda f: got.append(float(np.asarray(f.tensors[0])[0])))
        try:
            client["src"].push(np.float32([1.0]))
            _wait(lambda: len(got) == 1)
            # cross the watermark; the sweeper poll cadence picks it up
            mem.frac = 0.95
            _wait(lambda: mon.pressured)
            client["src"].push(np.float32([2.0]))
            client["src"].push(np.float32([3.0]))
            # the server provably refused at admission while pressured
            _wait(lambda: server.health()["ssrc"]["memory_shed"] >= 1)
            assert server.health()["ssrc"]["mem_pressure"] == 1
            # pressure clears -> the paced client retries land
            mem.frac = 0.1
            _wait(lambda: not mon.pressured)
            _wait(lambda: len(got) == 3, timeout=30)
            assert sorted(got) == [2.0, 4.0, 6.0]
            h = server.health()["ssrc"]
            assert h["memory_shed"] >= 1
            assert h["mem_polls"] > 0
            # the shed PREEMPTED the OOM: pressure was relieved at
            # admission, so the invoke path never hit the threshold
            assert server.health()["f"]["oom_retries"] == 0
            # BUSY sheds are health, never breaker food
            q = client.health()["q"]
            assert int(q.get("busy_replies", 0)) >= 1
            assert all(b["trips"] == 0
                       for b in q.get("breakers", {}).values())
            client["src"].end_of_stream()
            client.wait(timeout=30)
        finally:
            client.stop()
            server.stop()
