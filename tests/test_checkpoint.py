"""Periodic Orbax checkpointing + resume + profiler hooks (SURVEY §5.3/5.4)."""

import os

import numpy as np
import pytest

from nnstreamer_tpu.core import checkpoint as ckpt


class TestCheckpointStore:
    def test_save_latest_restore_prune(self, tmp_path):
        import jax.numpy as jnp

        d = str(tmp_path / "ck")
        state = {"params": {"w": jnp.arange(4.0)}, "opt_state": {"m": jnp.ones(4)}}
        assert ckpt.latest_step(d) is None
        for step in (1, 2, 3, 4):
            st = {
                "params": {"w": state["params"]["w"] + step},
                "opt_state": state["opt_state"],
            }
            ckpt.save_state(d, step, st)
        assert ckpt.latest_step(d) == 4
        restored = ckpt.restore_state(d, 4, state)
        np.testing.assert_allclose(
            np.asarray(restored["params"]["w"]), np.arange(4.0) + 4
        )
        ckpt.prune(d, keep=2)
        assert sorted(
            n for n in os.listdir(d) if n.startswith("step_")
        ) == ["step_3", "step_3.ok", "step_4", "step_4.ok"]
        assert ckpt.latest_step(d) == 4

    def test_torn_save_never_selected(self, tmp_path):
        """A crash between the Orbax write and the completion marker
        (the torn-save window) must leave the previous durable step as
        the resume point — never the torn one."""
        import jax.numpy as jnp

        d = str(tmp_path / "ck")
        ckpt.save_state(d, 1, {"w": jnp.zeros(2)}, meta={"cursor": {"step": 1}})
        ckpt.write_state(d, 2, {"w": jnp.ones(2)})  # no commit: torn
        assert os.path.isdir(os.path.join(d, "step_2"))
        assert ckpt.latest_step(d) == 1
        assert ckpt.load_meta(d, 1)["cursor"] == {"step": 1}
        ckpt.prune(d, keep=3)  # torn dirs are reclaimed
        assert not os.path.exists(os.path.join(d, "step_2"))
        assert ckpt.latest_step(d) == 1

    def test_orphan_marker_never_selected(self, tmp_path):
        """A marker without its step dir (half-pruned by a crash) is
        invisible to latest_step and reclaimed by prune."""
        import jax.numpy as jnp

        d = str(tmp_path / "ck")
        ckpt.save_state(d, 1, {"w": jnp.zeros(2)})
        ckpt.commit_state(d, 5, {})  # orphan: no step_5 dir
        assert ckpt.latest_step(d) == 1
        ckpt.prune(d, keep=3)
        assert not os.path.exists(os.path.join(d, "step_5.ok"))

    def test_prune_ignores_stray_files(self, tmp_path):
        import jax.numpy as jnp

        d = str(tmp_path / "ck")
        for step in (1, 2):
            ckpt.save_state(d, step, {"w": jnp.zeros(2)})
        open(os.path.join(d, "step_9"), "w").close()  # stray regular file
        ckpt.prune(d, keep=1)
        assert ckpt.latest_step(d) == 2  # real newest survives


class TestTrainerResume:
    def _props(self, tmp_path, resume):
        return {
            "model-config": (
                '{"arch": "mnist_cnn", "batch_size": 4, "learning_rate": 0.01}'
            ),
            "num-inputs": 1,
            "num-labels": 1,
            "num-training-samples": 8,
            "num-validation-samples": 0,
            "epochs": 2,
            "checkpoint-path": str(tmp_path / "ck"),
            "checkpoint-interval": 1,
            "checkpoint-keep": 0,
            "resume": resume,
        }

    def _feed(self, tr, rng, n):
        from nnstreamer_tpu.core.buffer import TensorFrame

        for _ in range(n):
            x = rng.random((28, 28, 1), np.float32)
            y = np.int32([rng.integers(0, 10)])
            tr.push_data(TensorFrame([x, y]))

    def test_resume_continues_epoch_count(self, tmp_path):
        from nnstreamer_tpu.trainer.jax_trainer import JaxTrainer

        rng = np.random.default_rng(0)
        tr = JaxTrainer()
        tr.create(self._props(tmp_path, False))
        tr.start()
        self._feed(tr, rng, 16)  # 2 epochs x 8
        tr.end_of_data()
        tr._thread.join(timeout=120)
        assert tr.error is None
        assert tr.status.epoch_count == 2
        assert ckpt.latest_step(str(tmp_path / "ck")) == 2

        # restart with resume: trains epochs 3..4 (honors prior progress)
        tr2 = JaxTrainer()
        props = self._props(tmp_path, True)
        props["epochs"] = 4
        tr2.create(props)
        tr2.start()
        self._feed(tr2, rng, 16)
        tr2.end_of_data()
        tr2._thread.join(timeout=120)
        assert tr2.error is None
        assert tr2.status.epoch_count == 4
        assert ckpt.latest_step(str(tmp_path / "ck")) == 4


class TestProfilerHooks:
    def test_refcounted_trace(self, tmp_path):
        from nnstreamer_tpu.core import profiler

        d1 = str(tmp_path / "t1")
        assert profiler.trace_start(d1)
        assert profiler.trace_start(d1)  # second ref joins
        profiler.trace_stop()
        profiler.trace_stop()  # session ends here
        assert profiler._refs == 0
        # a trace was actually written
        found = any(f.endswith(".xplane.pb") for _, _, fs in os.walk(d1) for f in fs)
        assert found

    def test_filter_trace_prop(self, tmp_path):
        from nnstreamer_tpu.pipeline import parse_pipeline

        d = str(tmp_path / "t2")
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=passthrough "
            f"trace=1 trace-dir={d} ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(np.zeros((4,), np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        pipe.stop()
        assert len(pipe["out"].frames) == 1
        found = any(f.endswith(".xplane.pb") for _, _, fs in os.walk(d) for f in fs)
        assert found

    def test_failed_start_does_not_leak_trace_ref(self, tmp_path):
        from nnstreamer_tpu.core import profiler
        from nnstreamer_tpu.elements.filter import TensorFilter
        from nnstreamer_tpu.pipeline.element import ElementError

        before = profiler._refs
        f = TensorFilter("f")
        f.set_property("framework", "passthrough")
        f.set_property("trace", 1)
        f.set_property("model", str(tmp_path / "missing.bin"))
        f.set_property("trace-dir", str(tmp_path / "t3"))
        with pytest.raises(ElementError):
            f.start()
        assert profiler._refs == before
