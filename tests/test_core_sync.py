"""Tests for time-sync policies (Collator).

Behavior modeled on the reference's mux/merge sync semantics
(``Documentation/synchronization-policies-at-mux-merge.md``,
``nnstreamer_plugin_api_impl.c:101-533``).
"""

import numpy as np

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.sync import BASEPAD, NOSYNC, REFRESH, SLOWEST, Collator, SyncPolicy


def frame(v, pts):
    return TensorFrame([np.array([v], np.int32)], pts=pts)


def val(f):
    return int(f.tensors[0][0])


class TestNoSync:
    def test_pairs_in_arrival_order(self):
        c = Collator(2, SyncPolicy(NOSYNC))
        assert c.collect() is None
        c.push(0, frame(1, 0.0))
        assert c.collect() is None  # pad 1 empty
        c.push(1, frame(10, 5.0))  # timestamps ignored
        out = c.collect()
        assert [val(f) for f in out] == [1, 10]

    def test_eos_pad_repeats_last(self):
        c = Collator(2, SyncPolicy(NOSYNC))
        c.push(0, frame(1, 0.0))
        c.push(1, frame(10, 0.0))
        c.collect()
        c.mark_eos(1)
        c.push(0, frame(2, 1.0))
        out = c.collect()
        assert [val(f) for f in out] == [2, 10]


class TestSlowest:
    def test_fast_pad_drops_to_base(self):
        c = Collator(2, SyncPolicy(SLOWEST))
        # pad 0 at 30fps-ish, pad 1 slower
        for i, t in enumerate([0.0, 0.033, 0.066]):
            c.push(0, frame(i, t))
        c.push(1, frame(100, 0.066))
        out = c.collect()
        # base = max heads = 0.066 after drops -> pad0 contributes frame at 0.066
        assert val(out[0]) == 2
        assert val(out[1]) == 100

    def test_not_ready_until_all_pads(self):
        c = Collator(2, SyncPolicy(SLOWEST))
        c.push(0, frame(0, 0.0))
        assert c.collect() is None

    def test_incremental_arrival_waits_for_fresh_frame(self):
        # regression: stale head must be dropped and the collator must WAIT,
        # not pair the stale frame with the newer base (arrival-order race)
        c = Collator(2, SyncPolicy(SLOWEST))
        c.push(0, frame(0, 0.0))
        c.push(1, frame(100, 0.2))
        assert c.collect() is None  # 0.0 dropped, pad0 must refill
        c.push(0, frame(1, 0.1))
        assert c.collect() is None
        c.push(0, frame(2, 0.2))
        out = c.collect()
        assert [val(f) for f in out] == [2, 100]

    def test_phase_offset_streams_emit_continuously(self):
        # two 30fps streams with a constant phase offset must keep emitting
        # (regression: strict drop-below-base livelocked here)
        c = Collator(2, SyncPolicy(SLOWEST))
        emitted = []
        for k in range(50):
            c.push(0, frame(k, k * 0.033))
            c.push(1, frame(100 + k, k * 0.033 + 0.015))
            while (out := c.collect()) is not None:
                emitted.append([val(f) for f in out])
        assert len(emitted) >= 45  # ~one set per frame period
        # each set pairs temporally adjacent frames
        for a, b in emitted:
            assert abs(a - (b - 100)) <= 1


class TestBasepad:
    def test_base_drives_output(self):
        c = Collator(2, SyncPolicy.from_string(BASEPAD, "0:1.0"))
        c.push(1, frame(10, 0.0))
        c.push(0, frame(1, 0.1))
        out = c.collect()
        assert [val(f) for f in out] == [1, 10]
        # next base frame reuses pad1's last when nothing newer in window
        c.push(0, frame(2, 0.2))
        out = c.collect()
        assert [val(f) for f in out] == [2, 10]

    def test_waits_for_other_pad_first_frame(self):
        c = Collator(2, SyncPolicy.from_string(BASEPAD, "0:1.0"))
        c.push(0, frame(1, 0.0))
        assert c.collect() is None  # pad 1 never seen yet


class TestRefresh:
    def test_any_new_frame_triggers(self):
        c = Collator(2, SyncPolicy(REFRESH))
        c.push(0, frame(1, 0.0))
        assert c.collect() is None  # pad1 never seen
        c.push(1, frame(10, 0.0))
        assert [val(f) for f in c.collect()] == [1, 10]
        # new frame only on pad 0 -> re-emit with pad1's last
        c.push(0, frame(2, 1.0))
        assert [val(f) for f in c.collect()] == [2, 10]
        assert c.collect() is None  # nothing new


class TestEOS:
    def test_nosync_needs_all_pads_drained(self):
        c = Collator(2, SyncPolicy(NOSYNC))
        c.mark_eos(0)
        assert not c.all_eos  # pad 1 still alive: EOS pad repeats its last
        c.mark_eos(1)
        assert c.all_eos

    def test_slowest_ends_with_slowest_pad(self):
        c = Collator(2, SyncPolicy(SLOWEST))
        c.mark_eos(0)
        assert c.all_eos  # slowest pad drained ends the stream

    def test_basepad_ends_with_base_pad(self):
        c = Collator(2, SyncPolicy.from_string(BASEPAD, "0:1.0"))
        c.mark_eos(1)
        assert not c.all_eos
        c.mark_eos(0)
        assert c.all_eos

    def test_basepad_zero_window_is_strict(self):
        assert SyncPolicy.from_string(BASEPAD, "0:0").window == 0.0  # explicit 0 = strict
        assert SyncPolicy.from_string(BASEPAD, "0").window is None  # omitted = unlimited
        c2 = Collator(2, SyncPolicy(BASEPAD, 0, 0.0))
        c2.push(0, frame(1, 0.0))
        c2.push(1, frame(10, 0.0))
        assert [val(f) for f in c2.collect()] == [1, 10]
        # frame far past the window must NOT be consumed for base pts 0.1
        c2.push(0, frame(2, 0.1))
        c2.push(1, frame(11, 99.0))
        out = c2.collect()
        assert [val(f) for f in out] == [2, 10]  # reuses last, not the future frame
