"""Bench evidence cache: a wedged-tunnel probe window must never turn a
round with banked chip evidence into a bare `value: null` driver artifact
(round-4 post-mortem: BENCH_r04.json was null while BENCH_ROWS.json held
the 1.82x headline captured hours earlier in the same round).

Covers bank_row/lookup_banked/emit_failure directly (no device needed).
"""

import importlib.util
import json
import os
import sys

import pytest

_BENCH = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")
_spec = importlib.util.spec_from_file_location("bench_module", _BENCH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


HEADLINE_META = {
    "model": "mobilenet", "batch": 128, "dtype": "bfloat16",
    "quantize": None, "dispatch_depth": 4, "ingest": "frame",
    "sink_split": True, "input": "device", "platform": "axon",
}
METRIC = "mobilenet_v2_image_labeling_fps_per_chip"


def _row(**over):
    row = {
        "metric": METRIC, "value": 1821.1, "unit": "fps",
        "vs_baseline": 1.821, **HEADLINE_META,
    }
    row.update(over)
    return row


@pytest.fixture
def cache_paths(tmp_path, monkeypatch):
    ev = str(tmp_path / "EVIDENCE.json")
    rows = str(tmp_path / "ROWS.json")
    monkeypatch.setattr(bench, "EVIDENCE_PATH", ev)
    monkeypatch.setattr(bench, "ROWS_PATH", rows)
    return ev, rows


class TestBankAndLookup:
    def test_roundtrip(self, cache_paths):
        ev, _ = cache_paths
        bench.bank_row(_row())
        got, since, source = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got["value"] == 1821.1
        assert source == "BENCH_EVIDENCE.json"
        assert since  # ISO timestamp recorded at bank time

    def test_null_and_cpu_and_stale_rows_not_banked(self, cache_paths):
        bench.bank_row(_row(value=None))
        bench.bank_row(_row(platform="cpu"))
        bench.bank_row(_row(stale=True))
        got, _, _ = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got is None

    def test_timed_out_rows_not_banked(self, cache_paths):
        # a host row that hit its per-row cap is partial evidence —
        # emitted and labeled, but never a stand-in for a completed run
        bench.bank_row(_row(timed_out=True, input="host"))
        got, _, _ = bench.lookup_banked(
            {**HEADLINE_META, "input": "host"}, METRIC)
        assert got is None

    def test_config_mismatch_never_matches(self, cache_paths):
        bench.bank_row(_row())
        for key, val in [
            ("batch", 256), ("quantize", "int8"), ("ingest", "block"),
            ("dispatch_depth", 1), ("input", "host"), ("dtype", "float32"),
        ]:
            got, _, _ = bench.lookup_banked(
                {**HEADLINE_META, key: val}, METRIC
            )
            assert got is None, f"{key}={val} wrongly matched banked row"

    def test_newest_wins_on_rebank(self, cache_paths):
        bench.bank_row(_row(value=1500.0))
        bench.bank_row(_row(value=1821.1))
        got, _, _ = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got["value"] == 1821.1

    def test_legacy_keys_migrate_newest_wins(self, cache_paths):
        # entries banked before a signature-axis addition sit under OLD
        # key strings; on the next bank/lookup they are rekeyed by their
        # recomputed sig and the NEWEST captured_at wins the collision
        ev, _ = cache_paths
        legacy = _row(value=1500.0)
        with open(ev, "w") as f:
            json.dump({
                "old|key|string": {
                    "captured_at": "2026-07-30T00:00:00Z", "row": legacy,
                },
            }, f)
        bench.bank_row(_row(value=1821.1))  # fresher, same config
        got, since, _ = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got["value"] == 1821.1
        cache = json.load(open(ev))
        assert list(cache) == [bench._sig(legacy)]  # one rekeyed entry

    def test_legacy_key_lookup_without_rebank(self, cache_paths):
        ev, _ = cache_paths
        with open(ev, "w") as f:
            json.dump({
                "old|key|string": {
                    "captured_at": "2026-07-30T00:00:00Z", "row": _row(),
                },
            }, f)
        got, _, _ = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got["value"] == 1821.1

    def test_seeds_from_sweep_rows_file(self, cache_paths):
        # rows captured before the cache existed (BENCH_ROWS.json) lack
        # ingest/sink_split keys: defaults must apply (frame / split)
        _, rows = cache_paths
        legacy = _row()
        del legacy["ingest"], legacy["sink_split"]
        with open(rows, "w") as f:
            json.dump([_row(value=None), legacy], f)
        got, since, source = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got["value"] == 1821.1
        assert source == "ROWS.json"
        assert since  # file mtime stamped

    def test_seed_rows_promoted_before_rows_file_overwritten(
        self, cache_paths
    ):
        # bench_all re-checkpoints the rows file from row 1: evidence for
        # OTHER configs read once during an outage must survive in the
        # cache even after the rows file is gutted
        ev, rows = cache_paths
        other = _row(
            metric="ssd_mobilenet_v2_bbox_fps_per_chip", model="ssd",
            value=900.0,
        )
        with open(rows, "w") as f:
            json.dump([_row(), other], f)
        got, _, _ = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got["value"] == 1821.1
        with open(rows, "w") as f:  # sweep overwrites the rows file
            json.dump([], f)
        got, _, src = bench.lookup_banked(
            {**HEADLINE_META, "model": "ssd"},
            "ssd_mobilenet_v2_bbox_fps_per_chip",
        )
        assert got["value"] == 900.0
        assert src == "BENCH_EVIDENCE.json"

    def test_seed_promotion_never_overwrites_newer_cache_entry(
        self, cache_paths
    ):
        ev, rows = cache_paths
        bench.bank_row(_row(value=2000.0))  # fresher than the seed
        with open(rows, "w") as f:
            json.dump([_row(value=1500.0)], f)
        # force the rows-file pass with a miss on another config first
        bench.lookup_banked({**HEADLINE_META, "batch": 999}, METRIC)
        got, _, _ = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got["value"] == 2000.0

    @pytest.mark.parametrize(
        "payload", ["{not json", "[]", '{"k": "notadict"}', "null"]
    )
    def test_corrupt_cache_files_fail_soft(self, cache_paths, payload):
        # invalid JSON AND valid-but-wrong-shape JSON (list/str/null):
        # neither side of the cache may crash on either
        ev, rows = cache_paths
        for p in (ev, rows):
            with open(p, "w") as f:
                f.write(payload)
        got, _, _ = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got is None
        bench.bank_row(_row())  # overwrites the corrupt cache
        got, _, _ = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got["value"] == 1821.1


class TestEmitFailure:
    def _capture(self, capsys, meta, err):
        bench.emit_failure(METRIC, "fps", meta, err)
        return json.loads(capsys.readouterr().out.strip())

    def test_stale_fallback_keeps_value_and_marks_it(
        self, cache_paths, capsys
    ):
        bench.bank_row(_row())
        out = self._capture(capsys, HEADLINE_META, "probe timed out")
        assert out["value"] == 1821.1
        assert out["stale"] is True
        assert out["live_error"] == "probe timed out"
        assert out["stale_source"] == "BENCH_EVIDENCE.json"
        assert out["stale_since"]

    def test_no_evidence_emits_null_row(self, cache_paths, capsys):
        out = self._capture(capsys, HEADLINE_META, "probe timed out")
        assert out["value"] is None
        assert out["error"] == "probe timed out"

    def test_platform_label_mismatch_still_finds_chip_evidence(
        self, cache_paths, capsys
    ):
        # probe-failure windows only know the env label (unset -> "default",
        # or "axon,cpu"): banked axon evidence must still match, and the
        # emitted row must KEEP the banked platform, not the env label
        bench.bank_row(_row(platform="axon"))
        for env_label in ("default", "axon,cpu"):
            out = self._capture(
                capsys, {**HEADLINE_META, "platform": env_label}, "wedged"
            )
            assert out["value"] == 1821.1, env_label
            assert out["platform"] == "axon", env_label

    def test_cpu_platform_never_gets_chip_evidence(
        self, cache_paths, capsys
    ):
        # a failed BENCH_PLATFORM=cpu run must not emit the banked axon
        # row relabeled platform=cpu (fabricated CPU performance)
        bench.bank_row(_row())
        out = self._capture(
            capsys, {**HEADLINE_META, "platform": "cpu"}, "deadline"
        )
        assert out["value"] is None

    def test_bench_no_stale_opt_out(self, cache_paths, capsys, monkeypatch):
        bench.bank_row(_row())
        monkeypatch.setenv("BENCH_NO_STALE", "1")
        out = self._capture(capsys, HEADLINE_META, "probe timed out")
        assert out["value"] is None

    def test_stale_row_never_rebanked_as_fresh(self, cache_paths, capsys):
        # an emitted stale row fed back through bank_row (as a future main
        # might) must not refresh the evidence timestamp
        bench.bank_row(_row())
        out = self._capture(capsys, HEADLINE_META, "err")
        ev = cache_paths[0]
        before = json.load(open(ev))
        bench.bank_row(out)
        assert json.load(open(ev)) == before


class TestMainIntegration:
    def test_bench_regression_on_healthy_backend_stays_null(
        self, cache_paths, monkeypatch, capsys
    ):
        """Probe passes but every run_child attempt fails: the backend is
        healthy, so this is a bench/code regression — masking it with
        yesterday's banked headline would be fabrication."""
        bench.bank_row(_row())
        monkeypatch.setattr(
            bench, "probe_backend", lambda *a, **k: ("", "axon")
        )
        monkeypatch.setattr(
            bench, "run_child", lambda *a, **k: (None, "child crashed")
        )
        for k in ("BENCH_MODEL", "BENCH_PLATFORM", "BENCH_NO_STALE"):
            monkeypatch.delenv(k, raising=False)
        bench.main()
        out = json.loads(capsys.readouterr().out.strip())
        assert out["value"] is None
        assert "backend healthy" in out["error"]

    def test_midrun_wedge_falls_back_to_banked_row(
        self, cache_paths, monkeypatch, capsys
    ):
        """Probe passes, run fails, re-probe fails (tunnel wedged
        MID-RUN, the r4 host-row scenario): stale fallback applies."""
        bench.bank_row(_row())
        probes = iter([("", "axon"), ("wedged", "")])
        monkeypatch.setattr(
            bench, "probe_backend", lambda *a, **k: next(probes)
        )
        monkeypatch.setattr(
            bench, "run_child",
            lambda *a, **k: (None, "run exceeded deadline"),
        )
        for k in ("BENCH_MODEL", "BENCH_PLATFORM", "BENCH_NO_STALE"):
            monkeypatch.delenv(k, raising=False)
        # the banked row predates the fuse/ingest_lane axes (= unfused,
        # serialized-staging seed dataplane); only a matching run may be
        # answered with it
        monkeypatch.setenv("BENCH_FUSE", "0")
        monkeypatch.setenv("BENCH_INGEST_LANE", "off")
        monkeypatch.setenv("BENCH_PROXY", "0")  # keep the test hermetic
        bench.main()
        out = json.loads(capsys.readouterr().out.strip())
        assert out["value"] == 1821.1
        assert out["stale"] is True
        assert "re-probe" in out["live_error"]

    def test_probe_failure_emits_stale_headline(
        self, cache_paths, monkeypatch, capsys
    ):
        """main() end-to-end: probe fails -> stale banked row, not null."""
        bench.bank_row(_row())
        monkeypatch.setattr(
            bench, "probe_backend", lambda *a, **k: ("down", "")
        )
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        for k in (
            "BENCH_MODEL", "BENCH_BATCH", "BENCH_DTYPE", "BENCH_QUANT",
            "BENCH_DEPTH", "BENCH_INGEST", "BENCH_SINK_SPLIT", "BENCH_HOST",
            "BENCH_PLATFORM", "BENCH_NO_STALE",
        ):
            monkeypatch.delenv(k, raising=False)
        # pre-axis banked row = unfused, serialized-staging seed
        # dataplane; match both axes
        monkeypatch.setenv("BENCH_FUSE", "0")
        monkeypatch.setenv("BENCH_INGEST_LANE", "off")
        monkeypatch.setenv("BENCH_PROXY", "0")
        bench.main()
        out = json.loads(capsys.readouterr().out.strip())
        assert out["value"] == 1821.1
        assert out["stale"] is True
        assert "down" in out["live_error"]

    def test_fuse_axis_separates_evidence(
        self, cache_paths, monkeypatch, capsys
    ):
        """A row banked from the unfused seed dataplane must NEVER stand
        in for a fused run (the fuse axis is part of the signature):
        serving pre-fusion fps under a fused config would mislabel the
        dataplane that produced the number."""
        bench.bank_row(_row())  # no fuse key -> then-implicit fuse=0
        monkeypatch.setattr(
            bench, "probe_backend", lambda *a, **k: ("down", "")
        )
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        for k in (
            "BENCH_MODEL", "BENCH_PLATFORM", "BENCH_NO_STALE", "BENCH_FUSE",
        ):
            monkeypatch.delenv(k, raising=False)  # default run: fuse=1
        bench.main()
        out = json.loads(capsys.readouterr().out.strip())
        assert out["value"] is None  # no mislabeled stale fallback
        assert out.get("stale") is not True

    def test_ingest_lane_axis_separates_evidence(
        self, cache_paths, monkeypatch, capsys
    ):
        """A row banked before the staging lane existed (then-implicit
        ingest_lane=off, serialized host->device staging) must never
        stand in for a lane-enabled run — and the failure row carries
        live, labeled `cpu_proxy` evidence for THIS code instead."""
        bench.bank_row(_row())  # no ingest_lane key -> implicit off
        monkeypatch.setattr(
            bench, "probe_backend", lambda *a, **k: ("down", "")
        )
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        for k in (
            "BENCH_MODEL", "BENCH_PLATFORM", "BENCH_NO_STALE",
            "BENCH_INGEST_LANE", "BENCH_PROXY",
        ):
            monkeypatch.delenv(k, raising=False)  # default run: lane auto
        monkeypatch.setenv("BENCH_FUSE", "0")  # isolate the lane axis
        bench.main()
        out = json.loads(capsys.readouterr().out.strip())
        assert out["value"] is None  # no mislabeled stale fallback
        assert out.get("stale") is not True
        proxy = out["cpu_proxy"]  # BENCH_PROXY default: attached
        assert proxy["proxy"] is True and proxy["platform"] == "cpu"
        assert proxy["dispatch_thread_blocking_syncs"] == 0
        assert proxy["ingest_overlap_speedup"] is not None
        # sharded-dataplane proxy evidence rides the same failure row
        # (shared measure_sharded_overhead harness): mesh plumbing ~free
        # on a single-device-equivalent mesh, dp:2 aggregate >= 1.5x on
        # the sim mesh twin
        assert proxy["sharded_ratio"] >= 0.85
        assert proxy["dp2_speedup"] >= 1.5

    def test_prefix_cache_axis_separates_evidence(self, cache_paths):
        """A row banked before the shared-prefix KV cache existed
        (then-implicit prefix_cache=0 via _SIG_DEFAULTS) must NEVER
        stand in for a warm-prefix run: cold-cache TTFT/throughput
        under a prefix_cache=1 config would mislabel the dataplane
        that produced the number — and vice versa."""
        assert bench._SIG_DEFAULTS["prefix_cache"] == 0
        assert "prefix_cache" in bench._SIG_KEYS
        cold = _row()  # no prefix_cache key -> then-implicit 0
        bench.bank_row(cold)
        warm_meta = {**HEADLINE_META, "prefix_cache": 1}
        got, _since, _src = bench.lookup_banked(warm_meta, METRIC)
        assert got is None  # cold evidence never serves a warm config
        # explicit 0 and the implicit default are the SAME signature
        assert bench._sig(cold) == bench._sig({**cold, "prefix_cache": 0})
        got, _since, _src = bench.lookup_banked(HEADLINE_META, METRIC)
        assert got["value"] == 1821.1

    def test_mesh_axis_separates_evidence(
        self, cache_paths, monkeypatch, capsys
    ):
        """A row banked from single-device serving (then-implicit
        mesh=0 via _SIG_DEFAULTS) must NEVER stand in for a sharded
        run: pre-mesh fps under a mesh=dp:2,tp:2 config would mislabel
        the dataplane that produced the number."""
        assert bench._SIG_DEFAULTS["mesh"] == 0  # pre-mesh implicit value
        bench.bank_row(_row())  # no mesh key -> then-implicit mesh=0
        monkeypatch.setattr(
            bench, "probe_backend", lambda *a, **k: ("down", "")
        )
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        for k in ("BENCH_MODEL", "BENCH_PLATFORM", "BENCH_NO_STALE"):
            monkeypatch.delenv(k, raising=False)
        # match every other axis of the banked row; flip ONLY the mesh
        monkeypatch.setenv("BENCH_FUSE", "0")
        monkeypatch.setenv("BENCH_INGEST_LANE", "off")
        monkeypatch.setenv("BENCH_PROXY", "0")
        monkeypatch.setenv("BENCH_MESH", "dp:2,tp:2")
        bench.main()
        out = json.loads(capsys.readouterr().out.strip())
        assert out["value"] is None  # no mislabeled stale fallback
        assert out.get("stale") is not True
        assert out["mesh"] == "dp:2,tp:2"  # canonical axis label
        # and the same banked row IS served when the mesh axis matches
        monkeypatch.setenv("BENCH_MESH", "")
        bench.main()
        out = json.loads(capsys.readouterr().out.strip())
        assert out["value"] == 1821.1 and out["stale"] is True
