"""Native C++ core: object mailbox (refcount-safe, blocking) + buffer pool."""

import queue
import sys
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.native import runtime

pytestmark = pytest.mark.skipif(
    not runtime.available(block=True), reason="native core toolchain unavailable"
)


class TestNativeMailbox:
    def test_fifo_roundtrip(self):
        mb = runtime.NativeMailbox(8)
        items = [(i, np.arange(i + 1)) for i in range(5)]
        for it in items:
            mb.put(it, timeout=1)
        assert mb.qsize() == 5
        out = [mb.get(timeout=1) for _ in range(5)]
        assert [o[0] for o in out] == [0, 1, 2, 3, 4]
        np.testing.assert_array_equal(out[3][1], np.arange(4))
        mb.close()

    def test_full_and_empty(self):
        mb = runtime.NativeMailbox(2)
        mb.put_nowait("a")
        mb.put_nowait("b")
        with pytest.raises(queue.Full):
            mb.put("c", timeout=0.05)
        assert mb.get_nowait() == "a"
        assert mb.get_nowait() == "b"
        with pytest.raises(queue.Empty):
            mb.get(timeout=0.05)
        mb.close()

    def test_refcounts_balanced(self):
        mb = runtime.NativeMailbox(4)
        obj = object()
        base = sys.getrefcount(obj)
        for _ in range(10):
            mb.put(obj, timeout=1)
            got = mb.get(timeout=1)
            assert got is obj
        del got
        assert sys.getrefcount(obj) == base
        # leftover items are released by close()
        mb.put(obj, timeout=1)
        assert sys.getrefcount(obj) == base + 1
        mb.close()
        assert sys.getrefcount(obj) == base

    def test_get_many_bulk_and_refcounts(self):
        mb = runtime.NativeMailbox(32)
        obj = object()
        base = sys.getrefcount(obj)
        for i in range(10):
            mb.put((i, obj), timeout=1)
        first = mb.get_many(4, timeout=1)
        assert [p[0] for p in first] == [0, 1, 2, 3]
        rest = mb.get_many(32, timeout=1)  # drains without waiting
        assert [p[0] for p in rest] == [4, 5, 6, 7, 8, 9]
        with pytest.raises(queue.Empty):
            mb.get_many(4, timeout=0.05)
        del first, rest
        assert sys.getrefcount(obj) == base  # one DecRef per popped item
        mb.close()

    def test_get_many_wakes_blocked_producer(self):
        # bulk pop frees several slots at once; every blocked producer
        # must wake (notify_all path)
        mb = runtime.NativeMailbox(2)
        mb.put_nowait(1)
        mb.put_nowait(2)
        done = []

        def producer(v):
            mb.put(v, timeout=5)
            done.append(v)

        threads = [threading.Thread(target=producer, args=(v,))
                   for v in (3, 4)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        assert mb.get_many(2, timeout=1) == [1, 2]
        for t in threads:
            t.join(timeout=5)
        assert sorted(done) == [3, 4]
        assert sorted(mb.get_many(2, timeout=1)) == [3, 4]
        mb.close()

    def test_blocking_handoff_across_threads(self):
        mb = runtime.NativeMailbox(1)
        got = []

        def consumer():
            for _ in range(20):
                got.append(mb.get(timeout=5))

        t = threading.Thread(target=consumer)
        t.start()
        for i in range(20):
            mb.put(i, timeout=5)
        t.join(timeout=10)
        assert got == list(range(20))
        mb.close()

    def test_wakeup_latency_beats_poll_loop(self):
        # the point of the native condvar: a blocked get() wakes on put()
        # immediately, not at the next 100ms poll tick
        mb = runtime.NativeMailbox(1)
        dt = []

        def consumer():
            t0 = time.perf_counter()
            mb.get(timeout=5)
            dt.append(time.perf_counter() - t0)

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.2)  # consumer is parked in the native wait
        mb.put("x", timeout=1)
        t.join(timeout=5)
        assert dt[0] >= 0.2 and dt[0] < 0.3  # woke ~immediately after put
        mb.close()


class TestBufferPool:
    def test_acquire_release_recycles(self):
        pool = runtime.BufferPool(1024, prealloc=2, alignment=64)
        ptr1, mv1 = pool.acquire()
        assert ptr1 % 64 == 0
        mv1[:4] = b"abcd"
        assert pool.outstanding == 1
        del mv1  # memoryview must be dropped before the block is reused
        pool.release(ptr1)
        assert pool.outstanding == 0
        ptr2, mv2 = pool.acquire()
        del mv2
        pool.release(ptr2)
        pool.destroy()

    def test_bad_alignment_rejected(self):
        with pytest.raises(ValueError):
            runtime.BufferPool(128, alignment=48)

    def test_double_release_rejected(self):
        pool = runtime.BufferPool(64, prealloc=1)
        ptr, mv = pool.acquire()
        del mv
        pool.release(ptr)
        with pytest.raises(ValueError):
            pool.release(ptr)
        pool.destroy()

    def test_use_after_close_raises_not_crashes(self):
        mb = runtime.NativeMailbox(2)
        mb.put("x")
        mb.close()
        with pytest.raises(queue.Full):
            mb.put("y")
        with pytest.raises(queue.Empty):
            mb.get(timeout=0.0)
        assert mb.qsize() == 0
        mb.close()  # idempotent

    def test_close_while_waiter_parked(self):
        mb = runtime.NativeMailbox(1)
        errs = []

        def consumer():
            try:
                mb.get(timeout=5)
            except queue.Empty:
                errs.append("empty")

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.1)  # consumer parked in the native wait
        mb.close()       # must wake it and not free memory under it
        t.join(timeout=5)
        assert errs == ["empty"]


class TestPipelineUsesNative:
    def test_pipeline_runs_on_native_mailboxes(self):
        from nnstreamer_tpu.pipeline import parse_pipeline

        # fuse=False: this test asserts the MAILBOX implementation, and
        # fused chains elide intermediate mailboxes entirely
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_transform mode=arithmetic "
            "option=mul:2 ! tensor_sink name=out",
            fuse=False,
        )
        pipe.start()
        mb = pipe["out"]._mailbox
        assert type(mb).__name__ == "NativeMailbox"
        for i in range(16):
            pipe["src"].push(np.float32([i]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        pipe.stop()
        frames = pipe["out"].frames
        assert len(frames) == 16
        assert float(frames[5].tensors[0][0]) == 10.0


class TestSampleReader:
    def test_reads_match_python_path(self, tmp_path):
        import numpy as np

        from nnstreamer_tpu.native.runtime import SampleReader, available

        if not available(block=True):
            pytest.skip("native core not buildable")
        rng = np.random.default_rng(0)
        data = rng.integers(0, 255, (10, 64), np.uint8)
        path = tmp_path / "samples.bin"
        path.write_bytes(data.tobytes())
        r = SampleReader(str(path), 64)
        assert r.total == 10
        for i in (0, 3, 9):
            np.testing.assert_array_equal(r.read(i), data[i])
        r.prefetch(5)  # advisory; must not fail
        with pytest.raises(IndexError):
            r.read(10)
        with pytest.raises(IndexError):
            r.read(-1)  # would wrap to 2^64-1 through ctypes (SIGSEGV bug)
        r.prefetch(-1)  # clamped, must not crash
        r.close()

    def test_open_missing_file(self):
        from nnstreamer_tpu.native.runtime import SampleReader, available

        if not available(block=True):
            pytest.skip("native core not buildable")
        with pytest.raises(OSError):
            SampleReader("/nonexistent/x.bin", 8)
