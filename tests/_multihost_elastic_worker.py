"""Worker for test_multihost_elastic.py: a 4-process gang on a 2-D DCN
hybrid mesh (dcn dp×sp across processes, ici tp within a host).

Phase A trains 2 steps, checkpoints, prints its local-shard fingerprint,
then the designated victim process dies WITHOUT cleanup (os._exit) while
the others walk into the next collective — the gang-scheduled failure
mode (multihost.py: "a lost process fails the job").

Phase B is the rejoined gang: fresh processes, same checkpoint dir —
restore, verify bit-identical shards, and continue training.
"""

import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from nnstreamer_tpu.parallel import multihost  # noqa: E402


def shard_fingerprint(tree) -> str:
    """sha1 over this process's addressable shards (device-ordered) of
    every leaf — bit-identity probe for checkpoint restore."""
    import jax

    h = hashlib.sha1()
    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, "addressable_shards"):
            h.update(np.asarray(leaf).tobytes())
            continue
        for shard in sorted(leaf.addressable_shards,
                            key=lambda s: s.device.id):
            h.update(np.ascontiguousarray(np.asarray(shard.data)).tobytes())
    return h.hexdigest()


def main() -> None:
    phase = os.environ["NNS_ELASTIC_PHASE"]
    ckpt = os.environ["NNS_ELASTIC_CKPT"]
    kill_pid = int(os.environ.get("NNS_ELASTIC_KILL_PID", "-1"))

    multihost.initialize(platform="cpu")

    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.core.checkpoint import restore_state, save_state
    from nnstreamer_tpu.models.transformer import (
        TransformerConfig,
        make_train_step,
    )

    pid = multihost.process_index()
    # 2-D DCN: dp AND sp cross processes (4 procs), tp rides "ICI"
    # (the 2 local devices) — the hybrid shape VERDICT item 9 asks for
    mesh = multihost.hybrid_mesh({"tp": -1}, {"dp": 2, "sp": 2})
    cfg = TransformerConfig(
        vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq=16, dtype=jnp.float32,
    )
    step, params, opt_state, data_sh = make_train_step(mesh, cfg)

    batch = 8  # dp=2 × sp=2 × tp local — divisible everywhere
    rng = np.random.default_rng(7)  # same stream on every process
    batches = [
        rng.integers(0, cfg.vocab, (batch, cfg.max_seq)).astype(np.int32)
        for _ in range(3)
    ]

    def put(arr):
        return jax.device_put(arr, data_sh)

    if phase == "A":
        losses = []
        for t in batches[:2]:
            params, opt_state, loss = step(params, opt_state, put(t))
            losses.append(float(loss))
        save_state(ckpt, 2, {"params": params, "opt_state": opt_state})
        print("RESULT " + json.dumps({
            "pid": pid,
            "phase": "A",
            "losses": losses,
            "fingerprint": shard_fingerprint(params),
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
        }), flush=True)
        multihost.barrier("phase_a_checkpointed")
        if pid == kill_pid:
            os._exit(1)  # hard death: no shutdown, no goodbye
        # survivors walk into the next collective against a dead peer;
        # the gang is now failed (hang or error — parent cleans up)
        step(params, opt_state, put(batches[2]))
        print("UNREACHABLE post-kill step completed", flush=True)
    else:
        # one throwaway step first: jit outputs carry fully-committed mesh
        # shardings (tx.init leaves are uncommitted, and a restore onto an
        # uncommitted scalar pins it to one device — incompatible with the
        # mesh-wide params in the next jitted call)
        t_params, t_opt, _ = step(params, opt_state, put(batches[0]))
        templates = {"params": t_params, "opt_state": t_opt}
        restored = restore_state(ckpt, 2, templates)
        # re-commit every leaf onto the template's mesh sharding (orbax
        # may restore replicated/single-device; the jitted step expects
        # the original placement)
        restored = jax.tree.map(
            lambda got, tmpl: (
                jax.device_put(got, tmpl.sharding)
                if hasattr(tmpl, "sharding") else got
            ),
            restored, templates,
        )
        params, opt_state = restored["params"], restored["opt_state"]
        fp = shard_fingerprint(params)
        params, opt_state, loss3 = step(params, opt_state, put(batches[2]))
        multihost.barrier("phase_b_resumed")
        print("RESULT " + json.dumps({
            "pid": pid,
            "phase": "B",
            "fingerprint": fp,
            "loss3": float(loss3),
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
        }), flush=True)


if __name__ == "__main__":
    main()
