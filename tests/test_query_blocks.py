"""Query data plane × block ingest.

Client side: a BatchFrame maps onto the wire micro-batch envelope (one RPC
per block).  Server side: ``tensor_query_serversrc block-ingress=true``
injects each wire micro-batch as ONE BatchFrame so the server pipeline
pays per-frame Python costs once per batch; the serversink splits answers
back per client RPC.

Reference analog: the nns-edge data plane delivers frames individually
(tensor_query_serversrc.c create :67) — block ingress is the TPU-native
delta that lets a remote stream saturate a chip.
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends.jax_xla import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture(autouse=True)
def _model():
    register_jax_model("qblk_affine", lambda p, xs: [xs[0] * 2.0], None)
    yield
    unregister_jax_model("qblk_affine")


def _server(sid, extra_src="", fw="jax-xla", model="qblk_affine",
            max_batch=8, custom=""):
    model_tok = f"model={model} " if model else ""
    custom_tok = f"custom={custom} " if custom else ""
    pipe = parse_pipeline(
        f"tensor_query_serversrc name=ssrc id={sid} port=0 {extra_src} ! "
        f"tensor_filter framework={fw} {model_tok}{custom_tok}"
        f"max-batch={max_batch} ! "
        f"tensor_query_serversink id={sid}"
    )
    pipe.start()
    return pipe, pipe["ssrc"].props["port"]


class TestClientBlocks:
    def test_pushed_blocks_map_to_wire_batches(self):
        """push_block upstream of a query client: one RPC per block, answers
        split back per frame in order."""
        server, port = _server(501)
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "wire-batch=8 ! tensor_sink name=out"
            )
            client.start()
            for b in range(3):
                client["src"].push_block(
                    np.arange(b * 8, b * 8 + 8, dtype=np.float32)[:, None],
                    pts=[0.1 * i for i in range(b * 8, b * 8 + 8)],
                )
            client["src"].end_of_stream()
            client.wait(timeout=30)
            client.stop()
            frames = client["out"].frames
            assert len(frames) == 24
            vals = [float(f.tensors[0][0]) for f in frames]
            assert vals == [2.0 * i for i in range(24)]
        finally:
            server.stop()

    def test_mixed_blocks_and_frames_through_client(self):
        server, port = _server(502)
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "wire-batch=4 ! tensor_sink name=out"
            )
            client.start()
            client["src"].push(np.float32([100.0]))
            client["src"].push_block(np.float32([[0.0], [1.0], [2.0]]))
            client["src"].push(np.float32([200.0]))
            client["src"].end_of_stream()
            client.wait(timeout=30)
            client.stop()
            vals = [float(f.tensors[0][0]) for f in client["out"].frames]
            assert vals == [200.0, 0.0, 2.0, 4.0, 400.0]
        finally:
            server.stop()


class TestServerBlockIngress:
    def test_block_ingress_batches_server_invokes(self):
        """block-ingress=true: the server filter sees whole wire batches
        (traced batch axes > 1), results identical and ordered."""
        sizes = set()

        def fn(p, xs):
            sizes.add(int(xs[0].shape[0]))
            return [xs[0] * 2.0]

        register_jax_model("qblk_sizes", fn, None)
        try:
            server, port = _server(
                503, extra_src="block-ingress=true", model="qblk_sizes"
            )
            try:
                client = parse_pipeline(
                    f"appsrc name=src ! tensor_query_client port={port} "
                    "wire-batch=8 ! tensor_sink name=out"
                )
                client.start()
                for b in range(2):
                    client["src"].push_block(
                        np.arange(b * 8, b * 8 + 8, dtype=np.float32)[:, None]
                    )
                client["src"].end_of_stream()
                client.wait(timeout=30)
                client.stop()
                vals = [float(f.tensors[0][0]) for f in client["out"].frames]
                assert vals == [2.0 * i for i in range(16)]
                # the server pipeline actually ran batched invokes
                assert max(sizes) > 1, f"server never saw a batch: {sizes}"
            finally:
                server.stop()
        finally:
            unregister_jax_model("qblk_sizes")

    def test_block_ingress_tcp_transport(self):
        """Same contract over the raw-TCP transport (shared process())."""
        server, port = _server(
            504, extra_src="connect-type=tcp block-ingress=true"
        )
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "connect-type=tcp wire-batch=8 ! tensor_sink name=out"
            )
            client.start()
            for i in range(16):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=30)
            client.stop()
            vals = [float(f.tensors[0][0]) for f in client["out"].frames]
            assert vals == [2.0 * i for i in range(16)]
        finally:
            server.stop()

    def test_block_ingress_mixed_dtype_falls_back(self):
        """Same shapes, different dtypes: np.stack would silently promote —
        the explicit uniformity check must inject per-frame instead."""
        server, port = _server(
            506, extra_src="block-ingress=true", fw="scaler", model="",
            custom="factor:2", max_batch=1,
        )
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "wire-batch=4 ! tensor_sink name=out"
            )
            client.start()
            client["src"].push(np.float32([1.0]))
            client["src"].push(np.int32([2]))
            client["src"].end_of_stream()
            client.wait(timeout=30)
            client.stop()
            frames = client["out"].frames
            assert len(frames) == 2
            assert frames[0].tensors[0].dtype == np.float32
            assert frames[1].tensors[0].dtype == np.int32
            np.testing.assert_allclose(frames[0].tensors[0], [2.0])
            np.testing.assert_array_equal(frames[1].tensors[0], [4])
        finally:
            server.stop()

    def test_block_ingress_nonuniform_falls_back(self):
        """A wire batch with mixed shapes cannot share a batch axis: the
        server injects per-frame (scaler fake is shape-polymorphic)."""
        server, port = _server(
            505, extra_src="block-ingress=true", fw="scaler", model="",
            max_batch=1,
        )
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "wire-batch=4 ! tensor_sink name=out"
            )
            client.start()
            client["src"].push(np.float32([1.0]))
            client["src"].push(np.float32([1.0, 2.0]))  # different shape
            client["src"].push(np.float32([3.0]))
            client["src"].end_of_stream()
            client.wait(timeout=30)
            client.stop()
            frames = client["out"].frames
            assert len(frames) == 3
            np.testing.assert_allclose(frames[0].tensors[0], [2.0])
            np.testing.assert_allclose(frames[1].tensors[0], [2.0, 4.0])
            np.testing.assert_allclose(frames[2].tensors[0], [6.0])
        finally:
            server.stop()
