"""Fail-static control plane (Documentation/resilience.md
"Control-plane resilience").

Truth tables and e2e pins for the machinery that lets the discovery/
control plane die without taking the dataplane with it:

* ``FencingToken`` / ``StaleEpochError`` — targets refuse commands from
  deposed controllers, exactly counted.
* ``LeaderLease`` — the fake-clock truth table: acquire only on provable
  vacancy, renew, expire -> steal with strict epoch monotonicity,
  split-lease resolution, self-fence before takeover.
* ``assess_plane`` + ``plan(plane=...)`` — the degradation ladder:
  DEGRADED freezes destructive actions, BLIND freezes everything, every
  frozen impulse is counted by reason.
* ``MqttClient`` broker-list failover, reconnect/reannounce counters,
  retained-publish coalescing during an outage.
* ``FleetObservatory`` broker-loss sensing (``plane_connected``,
  ingest age) and ``DigestPublisher`` exact failure accounting.
* e2e: a stale-epoch drain reject leaves the target server's streams
  and ledgers bit-untouched.
"""

import json
import threading
import time

import pytest

from nnstreamer_tpu.core.autoscale import (
    FencingToken,
    FleetPolicy,
    ControllerState,
    LeaderLease,
    LeaseChannel,
    PlaneStatus,
    StaleEpochError,
    assess_plane,
    plan,
)


# ---------------------------------------------------------------------------
# FencingToken: the target's side of lease fencing
# ---------------------------------------------------------------------------
class TestFencingToken:
    def test_admits_and_advances(self):
        f = FencingToken()
        assert f.epoch == 0 and f.rejects == 0
        f.check(0)          # unleased controllers carry epoch 0
        f.check(3)          # first leased command advances the fence
        assert f.epoch == 3
        f.check(3)          # same epoch: the lease guarantees one holder
        assert f.rejects == 0

    def test_stale_epoch_typed_reject(self):
        f = FencingToken()
        f.check(5)
        with pytest.raises(StaleEpochError) as ei:
            f.check(2)
        assert ei.value.offered == 2 and ei.value.current == 5
        assert f.rejects == 1
        assert f.epoch == 5            # a reject never moves the fence
        with pytest.raises(StaleEpochError):
            f.check(4)
        assert f.rejects == 2

    def test_none_is_operator_bypass(self):
        f = FencingToken()
        f.check(7)
        f.check(None)                  # a human on the box outranks it
        assert f.epoch == 7 and f.rejects == 0


# ---------------------------------------------------------------------------
# LeaderLease: fake-clock truth table
# ---------------------------------------------------------------------------
class TestLeaderLease:
    def test_cold_acquire_waits_full_ttl_vacancy_watch(self):
        ls = LeaderLease("ctl-a", ttl_s=6.0)
        assert not ls.attempt(100.0)       # watch starts: not provably vacant
        assert not ls.attempt(105.9)       # retained redelivery gets its TTL
        assert ls.attempt(106.0)
        assert ls.held and ls.epoch == 1 and ls.acquires == 1
        assert ls.steals == 0

    def test_renewal_cadence(self):
        ls = LeaderLease("ctl-a", ttl_s=6.0)
        ls.attempt(0.0)
        assert ls.attempt(6.0) and ls.renewals == 0  # acquire counted apart
        assert ls.attempt(7.0) and ls.renewals == 0  # renew not due (ttl/3)
        assert ls.attempt(8.1) and ls.renewals == 1
        assert ls.attempt(8.2) and ls.renewals == 1  # due-stamp paces it

    def test_fresh_foreign_lease_refuses_acquire(self):
        ls = LeaderLease("ctl-b", ttl_s=6.0)
        ls.observe({"owner": "ctl-a", "epoch": 4, "ttl_s": 6.0}, now=10.0)
        assert not ls.attempt(12.0)
        assert ls.refusals == 1 and not ls.held

    def test_expired_foreign_lease_is_stolen_epoch_monotonic(self):
        ls = LeaderLease("ctl-b", ttl_s=6.0)
        ls.observe({"owner": "ctl-a", "epoch": 4, "ttl_s": 6.0}, now=10.0)
        assert not ls.attempt(15.0)              # still inside its TTL
        assert ls.attempt(16.1)                  # provably expired
        assert ls.held and ls.steals == 1
        assert ls.epoch == 5                     # max-ever-seen + 1

    def test_deposed_by_higher_epoch(self):
        ls = LeaderLease("ctl-a", ttl_s=6.0)
        ls.attempt(0.0)
        ls.attempt(6.0)
        assert ls.held
        ls.observe({"owner": "ctl-b", "epoch": 9, "ttl_s": 6.0}, now=7.0)
        assert not ls.held and ls.losses == 1

    def test_split_lease_lower_owner_wins(self):
        # amnesiac broker: both sides believe they hold the same epoch.
        # Deterministic resolution — the LOWER owner id survives.
        hi = LeaderLease("ctl-b", ttl_s=6.0)
        hi.attempt(0.0)
        hi.attempt(6.0)
        hi.observe({"owner": "ctl-a", "epoch": hi.epoch, "ttl_s": 6.0},
                   now=7.0)
        assert not hi.held and hi.losses == 1
        lo = LeaderLease("ctl-a", ttl_s=6.0)
        lo.attempt(0.0)
        lo.attempt(6.0)
        lo.observe({"owner": "ctl-b", "epoch": lo.epoch, "ttl_s": 6.0},
                   now=7.0)
        assert lo.held and lo.losses == 0

    def test_self_fence_before_standby_takeover(self):
        # renewals unconfirmed (dead transport) for a full TTL => the
        # holder steps down ON ITS OWN — and since the standby must also
        # wait out the seen lease's TTL, the old leader is fenced before
        # the takeover epoch can land.
        sent = {"n": 0}

        def dead_publish(payload):
            sent["n"] += 1
            return False

        ls = LeaderLease("ctl-a", ttl_s=6.0, publish=lambda p: True)
        ls.attempt(0.0)
        ls.attempt(6.0)
        assert ls.held
        ls.publish = dead_publish
        assert ls.attempt(8.1)              # renewal attempt fails quietly
        assert ls.renewals == 0             # failed renewals never count
        assert not ls.attempt(12.2)         # ttl past last confirmation
        assert ls.self_fences == 1 and ls.losses == 1 and not ls.held

    def test_failed_publish_rolls_back_acquire(self):
        ls = LeaderLease("ctl-a", ttl_s=6.0, publish=lambda p: False)
        assert not ls.attempt(0.0)
        assert not ls.attempt(6.1)          # vacancy proven, publish refused
        assert not ls.held and ls.epoch == 0 and ls.acquires == 0

    def test_note_connected_reasserts_without_renewal(self):
        ls = LeaderLease("ctl-a", ttl_s=6.0, publish=lambda p: True)
        ls.attempt(0.0)
        ls.attempt(6.0)
        ls.note_connected(11.0)             # re-assert into amnesiac broker
        # the reconnect refreshed the confirmation clock: no self-fence
        assert ls.attempt(12.5) and ls.self_fences == 0

    def test_own_retained_echo_confirms(self):
        ls = LeaderLease("ctl-a", ttl_s=6.0, publish=lambda p: True)
        ls.attempt(0.0)
        ls.attempt(6.0)
        ls.observe(ls.payload(), now=11.0)  # broker echoes our own doc
        assert ls.attempt(11.5) and ls.held and ls.self_fences == 0


# ---------------------------------------------------------------------------
# assess_plane + plan(plane=...): the fail-static ladder
# ---------------------------------------------------------------------------
def _snap(fresh=0, stale=0, retired=0):
    rows = [
        {"topic": f"t{i}", "addr": f"h:{i}", "stale": False, "slots": 2,
         "occupied": 1}
        for i in range(fresh)
    ] + [
        {"topic": f"s{i}", "addr": f"h:9{i}", "stale": True}
        for i in range(stale)
    ]
    return {"servers": rows, "rollup": {"retired": retired}}


class TestAssessPlane:
    def test_healthy(self):
        st = ControllerState()
        p = assess_plane(_snap(fresh=3), FleetPolicy(), st)
        assert p.ok and p.reasons == ()
        assert st.known_fleet == 3

    def test_broker_disconnected_degrades(self):
        st = ControllerState()
        p = assess_plane(_snap(fresh=3), FleetPolicy(), st, connected=False)
        assert p.level == "degraded" and p.reasons == ("broker_disconnected",)

    def test_stale_fraction_degrades(self):
        st = ControllerState()
        p = assess_plane(_snap(fresh=1, stale=2), FleetPolicy(), st)
        assert p.level == "degraded" and "stale_fraction" in p.reasons

    def test_silent_coverage_loss_is_below_quorum(self):
        st = ControllerState()
        assert assess_plane(_snap(fresh=4), FleetPolicy(), st).ok
        # half the fleet vanished with NO tombstones: partition, not drain
        p = assess_plane(_snap(fresh=1), FleetPolicy(), st)
        assert p.level == "degraded" and "below_quorum" in p.reasons

    def test_tombstoned_departure_is_not_coverage_loss(self):
        st = ControllerState()
        assert assess_plane(_snap(fresh=4), FleetPolicy(), st).ok
        # two servers drained cleanly: retired counter explains them
        p = assess_plane(_snap(fresh=2, retired=2), FleetPolicy(), st)
        assert p.ok and st.known_fleet == 2

    def test_resurrection_rebaselines_retired(self):
        st = ControllerState()
        assert assess_plane(_snap(fresh=3), FleetPolicy(), st).ok
        # a row ages out (retired=1) then the server re-announces and the
        # rollup un-counts it (retired back to 0) — the baseline must
        # follow it DOWN, or the next real retirement is swallowed
        assess_plane(_snap(fresh=2, retired=1), FleetPolicy(), st)
        assess_plane(_snap(fresh=3, retired=0), FleetPolicy(), st)
        assert st.seen_retired == 0 and st.known_fleet == 3
        p = assess_plane(_snap(fresh=2, retired=1), FleetPolicy(), st)
        assert p.ok and st.known_fleet == 2

    def test_blind_when_no_fresh_rows(self):
        st = ControllerState()
        assess_plane(_snap(fresh=2), FleetPolicy(), st)
        p = assess_plane(_snap(stale=2), FleetPolicy(), st)
        assert p.level == "blind" and "no_fresh_rows" in p.reasons


class TestPlanFreeze:
    def test_degraded_freezes_ceiling_drain(self):
        pol = FleetPolicy(min_servers=1, max_servers=1,
                          cooldown_down_s=0.0)
        st = ControllerState()
        plane = PlaneStatus("degraded", ("broker_disconnected",))
        acts = plan(_snap(fresh=2), pol, st, now=1.0, plane=plane)
        assert acts == [] and st.frozen == 1
        assert st.frozen_by_reason == {"broker_disconnected": 1}

    def test_degraded_still_allows_floor_spawn(self):
        pol = FleetPolicy(min_servers=3, cooldown_up_s=0.0)
        st = ControllerState()
        plane = PlaneStatus("degraded", ("below_quorum",))
        acts = plan(_snap(fresh=2), pol, st, now=1.0, plane=plane)
        assert [a.kind for a in acts] == ["scale_up"]
        assert st.frozen == 0

    def test_blind_freezes_everything(self):
        pol = FleetPolicy(min_servers=1, cooldown_up_s=0.0)
        st = ControllerState()
        plane = PlaneStatus("blind", ("no_fresh_rows",))
        # a blind controller seeing "zero servers" must NOT spawn
        acts = plan(_snap(), pol, st, now=1.0, plane=plane)
        assert acts == [] and st.frozen == 1
        assert st.frozen_by_reason == {"no_fresh_rows": 1}

    def test_healed_plane_acts_first_trusted_tick(self):
        pol = FleetPolicy(min_servers=1, max_servers=1,
                          cooldown_down_s=0.0)
        st = ControllerState()
        plane = PlaneStatus("degraded", ("stale_fraction",))
        assert plan(_snap(fresh=2), pol, st, now=1.0, plane=plane) == []
        acts = plan(_snap(fresh=2), pol, st, now=2.0, plane=PlaneStatus())
        assert [a.kind for a in acts] == ["scale_down"]


# ---------------------------------------------------------------------------
# MqttClient: broker-list failover, reconnect + retained coalescing
# ---------------------------------------------------------------------------
def _blackhole_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port  # nothing listens: dials fail with ConnectionRefused


class TestBrokerFailover:
    def test_failover_dials_past_dead_broker(self):
        from nnstreamer_tpu.distributed.mqtt import MiniBroker, MqttClient

        broker = MiniBroker()
        dead = _blackhole_port()
        try:
            c = MqttClient("127.0.0.1", dead,
                           brokers=[("127.0.0.1", dead),
                                    ("127.0.0.1", broker.port)])
            try:
                assert c.connected.wait(5.0)
                got = threading.Event()
                c.subscribe("fo/t", lambda t, p: got.set(), qos=1)
                c.publish("fo/t", b"x", qos=1)
                assert got.wait(5.0)
            finally:
                c.close()
        finally:
            broker.close()

    def test_reconnect_counts_and_resubscribes(self):
        from nnstreamer_tpu.distributed.mqtt import MiniBroker, MqttClient

        broker = MiniBroker()
        port = broker.port
        c = MqttClient("127.0.0.1", port)
        try:
            assert c.connected.wait(5.0)
            seen = []
            c.subscribe("rc/t", lambda t, p: seen.append(p), qos=1)
            broker.close()                       # die...
            deadline = time.monotonic() + 5.0
            while c.connected.is_set() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not c.connected.is_set()
            broker = MiniBroker(port=port)       # ...and come back, amnesiac
            assert c.connected.wait(10.0)
            assert c.reconnects == 1
            assert broker.wait_subscriber("rc/t", 5.0)  # re-subscribed
            c2 = MqttClient("127.0.0.1", port)
            try:
                c2.publish("rc/t", b"after", qos=1)
                deadline = time.monotonic() + 5.0
                while not seen and time.monotonic() < deadline:
                    time.sleep(0.01)
                assert seen == [b"after"]
            finally:
                c2.close()
        finally:
            c.close()
            broker.close()

    def test_retained_coalescing_bounds_outage_backlog(self):
        from nnstreamer_tpu.distributed.mqtt import MiniBroker, MqttClient

        broker = MiniBroker()
        port = broker.port
        c = MqttClient("127.0.0.1", port)
        try:
            assert c.connected.wait(5.0)
            broker.close()
            deadline = time.monotonic() + 5.0
            while c.connected.is_set() and time.monotonic() < deadline:
                time.sleep(0.01)
            # an announce republished every interval during the outage:
            # only the NEWEST retained doc matters, and only it is kept
            for i in range(5):
                c.publish("co/t", b"v%d" % i, retain=True, qos=1)
            assert c.coalesced == 4
            assert c.unacked() == 1
        finally:
            c.close()
            broker.close()


class TestReannounce:
    def test_announce_survives_broker_amnesia(self):
        from nnstreamer_tpu.distributed.hybrid import Announcement
        from nnstreamer_tpu.distributed.mqtt import MiniBroker, MqttClient

        broker = MiniBroker()
        port = broker.port
        ann = None
        sub = None
        try:
            ann = Announcement("127.0.0.1", port, "nns/query/ra/s0",
                               {"host": "h", "port": 1, "seq": 1})
            assert ann.connected and ann.reannounces == 0
            broker.close()                       # retained store dies with it
            deadline = time.monotonic() + 5.0
            while ann.connected and time.monotonic() < deadline:
                time.sleep(0.01)
            ann.update({"seq": 2}, wait_ack=False)  # merged while dark
            broker = MiniBroker(port=port)
            deadline = time.monotonic() + 10.0
            while ((not ann.connected or ann.reannounces < 1)
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert ann.reconnects == 1 and ann.reannounces == 1
            # the re-announce carried the CURRENT merged info: a late
            # subscriber sees seq=2 from retained state alone
            got = []
            done = threading.Event()

            def on_msg(topic, payload):
                got.append(json.loads(payload.decode()))
                done.set()

            sub = MqttClient("127.0.0.1", port)
            sub.subscribe("nns/query/ra/#", on_msg, qos=1)
            assert done.wait(5.0)
            assert got[0]["seq"] == 2
        finally:
            if sub is not None:
                sub.close()
            if ann is not None:
                ann.clear()
            broker.close()


class TestLeaseChannel:
    def test_retained_lease_doc_reaches_standby(self):
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        broker = MiniBroker()
        ch_a = ch_b = None
        try:
            la = LeaderLease("ctl-a", ttl_s=2.0)
            ch_a = LeaseChannel("127.0.0.1", broker.port, "cp", la)
            t0 = time.monotonic()
            while not la.attempt(time.monotonic() - t0):
                time.sleep(0.02)
            assert la.held and la.epoch == 1
            lb = LeaderLease("ctl-b", ttl_s=2.0)
            ch_b = LeaseChannel("127.0.0.1", broker.port, "cp", lb)
            deadline = time.monotonic() + 5.0
            while lb._seen is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert lb._seen == {"owner": "ctl-a", "epoch": 1, "ttl_s": 2.0}
            assert not lb.attempt(time.monotonic())
            assert lb.refusals == 1
        finally:
            if ch_a is not None:
                ch_a.close()
            if ch_b is not None:
                ch_b.close()
            broker.close()


# ---------------------------------------------------------------------------
# Observatory broker-loss sensing + digest failure accounting
# ---------------------------------------------------------------------------
class TestPlaneSensing:
    def test_direct_feed_reads_connected(self):
        from nnstreamer_tpu.core.fleet import FleetObservatory

        obs = FleetObservatory(topic="pf", clock=lambda: 100.0)
        assert obs.plane_connected          # no link to lose
        assert obs.plane_ingest_age_s(now=103.0) == 3.0
        obs.ingest("nns/query/pf/s0",
                   {"host": "h", "port": 1, "digest": {"seq": 1}})
        assert obs.plane_ingest_age_s(now=100.5) == 0.5
        roll = obs.rollup()
        assert roll["plane_connected"] == 1
        assert roll["plane_ingest_age_s"] == 0.0

    def test_broker_death_clears_plane_connected(self):
        from nnstreamer_tpu.core.fleet import FleetObservatory
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        broker = MiniBroker()
        obs = FleetObservatory(topic="pf2")
        try:
            obs.start("127.0.0.1", broker.port)
            deadline = time.monotonic() + 5.0
            while not obs.plane_connected and time.monotonic() < deadline:
                time.sleep(0.01)
            assert obs.plane_connected
            broker.close()
            deadline = time.monotonic() + 5.0
            while obs.plane_connected and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not obs.plane_connected
            assert obs.rollup()["plane_connected"] == 0
        finally:
            obs.stop()
            broker.close()

    def test_digest_publisher_counts_outage_failures_exactly(self):
        from nnstreamer_tpu.core.fleet import DigestPublisher

        clk = {"t": 0.0}
        sink = []
        broken = {"on": False}

        def publish(d):
            if broken["on"]:
                raise ConnectionError("announce channel dark")
            sink.append(d)

        pub = DigestPublisher(lambda: {"gen_tokens": 0}, publish,
                              interval_s=1.0, clock=lambda: clk["t"])
        pub.poll(force=True)
        assert pub.published == 1 and pub.publish_failures == 0
        broken["on"] = True
        for _ in range(3):                 # outage: one failure per poll,
            clk["t"] += 1.0                # never more (no retry storm)
            pub.poll()
        assert pub.publish_failures == 3 and pub.published == 1
        broken["on"] = False
        clk["t"] += 1.0
        pub.poll()
        assert pub.published == 2
        # seq stays monotonic ACROSS the failures: a consumer can tell
        # the post-outage digest is newer than the last delivered one
        assert sink[-1]["seq"] > sink[0]["seq"]
        assert sink[-1]["seq"] == pub.seq


# ---------------------------------------------------------------------------
# e2e: a stale-epoch reject leaves the target bit-untouched
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_stale_epoch_drain_reject_leaves_server_untouched():
    """A deposed controller's drain lands on a serving server and is
    REFUSED: no drain state entered, no stream evicted, every ledger
    and token stream bit-identical to the oracle — then a current-epoch
    resize still works (the fence rejected the command, not the
    controller plane)."""
    import sys
    sys.path.insert(0, "tools") if "tools" not in sys.path else None
    from tools.chaos_fleet import FleetHarness

    h = FleetHarness(mode="generate", gen_slots=4, gen_max_new=32,
                     gen_step_ms=2.0, base_id=10300, topic="fencee2e",
                     digest_interval=0.25)
    try:
        h.start_server(0)
        pipe = h.servers[0]
        ssrc, gen = pipe["ssrc"], pipe["gen"]
        clients = [h.make_gen_client(f"F{i}") for i in range(2)]
        for c in clients:
            c.push_prompt()
        # a NEWER controller (epoch 3) has already actuated this target;
        # now the deposed epoch-1 leader's in-flight drain arrives
        ssrc._fence.check(3)
        with pytest.raises(StaleEpochError):
            ssrc.request_drain(epoch=1)
        assert not ssrc._drain_requested.is_set()
        assert ssrc.health_info()["stale_epoch_rejects"] == 1
        assert ssrc.health_info()["fence_epoch"] == 3
        # same refusal on the engine's fenced resize entry
        slots0 = int(h.server_gen_row(pipe).get("gen_slots", 0))
        gen._fence.check(3)
        with pytest.raises(StaleEpochError):
            gen.request_resize(slots0 + 2, epoch=2)
        assert int(h.server_gen_row(pipe).get("gen_slots", 0)) == slots0
        # the dataplane never noticed: streams complete bit-exactly
        for c in clients:
            c.settle(timeout=60.0)
        checks = [c.check_exact() for c in clients]
        assert all(r["mismatched"] == 0 for r in checks)
        assert sum(r["exact"] for r in checks) == len(clients)
        assert not pipe.draining
        # and the CURRENT epoch still actuates normally
        gen.request_resize(slots0 + 2, epoch=3)
        deadline = time.monotonic() + 10.0
        while (int(h.server_gen_row(pipe).get("gen_slots", 0)) != slots0 + 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert int(h.server_gen_row(pipe).get("gen_slots", 0)) == slots0 + 2
        for c in clients:
            c.finish()
    finally:
        h.stop_all()


# ---------------------------------------------------------------------------
# fleet_top: the control-plane line
# ---------------------------------------------------------------------------
class TestFleetTopControlPlane:
    def _snapshot(self, **auto):
        return {
            "rollup": {
                "servers": 1, "draining": 0, "degraded": 0, "stale": 0,
                "retired": 0, "stale_evicted": 0, "tokens_per_s": 0.0,
                "occupancy": 0.0, "occupied": 0, "slots": 2,
                "slot_headroom": 2, "mem_headroom_bytes": 0, "inflight": 0,
                "tokens": 0, "admitted": 0, "shed": 0,
                "plane_connected": auto.pop("plane_connected", 1),
                "plane_ingest_age_s": 0.4, "plane_reconnects": 2,
            },
            "servers": [{"addr": "h:1", "topic": "t0", "seq": 3,
                         "seen_s": 0.1}],
            "autoscale": auto or None,
        }

    def test_render_shows_broker_and_lease(self):
        from tools.fleet_top import render

        out = render(self._snapshot(
            plane_level="ok", plane_reasons=[], frozen=0,
            lease={"owner": "ctl-a", "epoch": 3, "held": True}), "t")
        assert "control plane: broker up" in out
        assert "reconnects 2" in out
        assert "lease ctl-a epoch 3 (leader)" in out
        assert "DEGRADED" not in out

    def test_render_shows_freeze_state(self):
        from tools.fleet_top import render

        out = render(self._snapshot(
            plane_connected=0, plane_level="degraded",
            plane_reasons=["broker_disconnected"], frozen=4,
            lease={"owner": "ctl-b", "epoch": 5, "held": False}), "t")
        assert "broker DOWN" in out
        assert "lease ctl-b epoch 5 (standby)" in out
        assert "[DEGRADED: broker_disconnected  frozen 4]" in out

    def test_render_without_controller_still_has_plane_line(self):
        from tools.fleet_top import render

        snap = self._snapshot()
        snap.pop("autoscale")
        out = render(snap, "t")
        assert "control plane: broker up" in out
