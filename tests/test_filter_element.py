"""tensor_filter element + backend ABI tests.

Modeled on the reference's parameterized filter-subplugin template
(``tests/nnstreamer_filter_extensions_common/unittest_tizen_template.cc.in``:
checkExistence, openClose_n, invoke, reloadModel, ...) using the fake
backends, plus filter-element behaviors (combinations, stats, sharing,
batching) from ``tests/unittest_filter_single`` and SSAT suites.
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends import find_backend, register_custom_easy, unregister_custom_easy
from nnstreamer_tpu.backends.base import parse_accelerator
from nnstreamer_tpu.core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from nnstreamer_tpu.core.buffer import CustomEvent
from nnstreamer_tpu.elements.basic import AppSrc, TensorSink
from nnstreamer_tpu.elements.filter import SingleShot
from nnstreamer_tpu.pipeline import Pipeline, make_element, parse_pipeline


def spec1(shape=(4,), dtype=np.float32):
    return StreamSpec((TensorSpec(shape, dtype),), FORMAT_STATIC)


class TestBackendABI:
    @pytest.mark.parametrize("name", ["passthrough", "scaler", "average", "custom-easy"])
    def test_check_existence(self, name):
        assert find_backend(name) is not None

    def test_unknown_backend_n(self):
        with pytest.raises(KeyError):
            find_backend("no_such_backend")

    def test_scaler_custom_props(self):
        be = find_backend("scaler")()
        be.open(None, {"custom": "factor:3"})
        out = be.invoke([np.array([1.0, 2.0], np.float32)])
        np.testing.assert_allclose(out[0], [3.0, 6.0])

    def test_average_set_input_info(self):
        be = find_backend("average")()
        be.open(None, {})
        out_spec = be.set_input_info(spec1((8, 8)))
        assert out_spec.tensors[0].shape == (1,)
        assert out_spec.tensors[0].dtype == np.dtype(np.float32)

    def test_batch_fallback(self):
        be = find_backend("average")()
        be.open(None, {})
        out = be.invoke_batch([np.ones((3, 4), np.float32)])
        assert out[0].shape == (3, 1)
        np.testing.assert_allclose(out[0], 1.0)

    def test_accelerator_parse(self):
        # reference tensor_filter_common.c:2719 dialect
        assert parse_accelerator("true:tpu,cpu") == (True, ["tpu", "cpu"])
        assert parse_accelerator("false") == (False, ["auto"])
        assert parse_accelerator("") == (True, ["auto"])


class TestCustomEasy:
    def test_register_invoke_unregister(self):
        register_custom_easy("sq", lambda xs: [np.asarray(x) ** 2 for x in xs])
        try:
            with SingleShot("custom-easy", "sq") as m:
                out = m.invoke([np.array([2.0, 3.0])])
                np.testing.assert_allclose(out[0], [4.0, 9.0])
        finally:
            assert unregister_custom_easy("sq")

    def test_unregistered_open_n(self):
        with pytest.raises(FileNotFoundError):
            SingleShot("custom-easy", "never_registered")


class TestFilterElement:
    def run_pipe(self, text, inputs):
        pipe = parse_pipeline(text)
        pipe.start()
        src, sink = pipe["src"], pipe["out"]
        for arr in inputs:
            src.push(arr)
        src.end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        return sink.frames

    def test_passthrough_pipeline(self):
        frames = self.run_pipe(
            "appsrc name=src ! tensor_filter framework=passthrough ! tensor_sink name=out",
            [np.arange(4, dtype=np.float32)],
        )
        np.testing.assert_array_equal(frames[0].tensors[0], [0, 1, 2, 3])

    def test_scaler_custom_prop(self):
        frames = self.run_pipe(
            "appsrc name=src ! tensor_filter framework=scaler custom=factor:5 ! tensor_sink name=out",
            [np.array([1, 2], np.int32)],
        )
        np.testing.assert_array_equal(frames[0].tensors[0], [5, 10])

    def test_input_output_combination(self):
        # input-combination picks tensor 1; output-combination emits i0,o0
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=average input-combination=1 "
            "output-combination=i0,o0 ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push([np.zeros(3, np.float32), np.full(4, 2.0, np.float32)])
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        f = pipe["out"].frames[0]
        assert len(f.tensors) == 2
        # 'i0' = the element's ORIGINAL input tensor 0 (pre input-combination)
        np.testing.assert_array_equal(f.tensors[0], np.zeros(3, np.float32))
        np.testing.assert_allclose(f.tensors[1], [2.0])  # o0 = average of picked input

    def test_appsrc_bounded_backpressure(self):
        pipe = parse_pipeline(
            "appsrc name=src max-buffers=4 ! identity sleep=0.005 ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(40):
            pipe["src"].push(np.float32([i]))  # blocks when queue full
        pipe["src"].end_of_stream()
        pipe.wait(timeout=20)
        pipe.stop()
        assert len(pipe["out"].frames) == 40

    def test_sink_eos_received(self):
        pipe = parse_pipeline("appsrc name=src ! tensor_sink name=out")
        pipe.start()
        pipe["src"].push(np.float32([1]))
        pipe["src"].end_of_stream()
        assert pipe["out"].eos_received.wait(timeout=10)

    def test_latency_throughput_props(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=passthrough latency=1 throughput=1 "
            "! tensor_sink name=out"
        )
        pipe.start()
        for i in range(5):
            pipe["src"].push(np.float32([i]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        f = pipe["f"]
        assert f.latency_us > 0
        assert f.throughput_fps > 0
        assert f.backend is not None and f.backend.stats.total_invoke_num == 5
        pipe.stop()

    def test_shared_backend_key(self):
        # two filters with the same key share one backend instance
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f1 framework=framecounter "
            "shared-tensor-filter-key=k1 ! tensor_filter name=f2 framework=framecounter "
            "shared-tensor-filter-key=k1 ! tensor_sink name=out"
        )
        pipe.start()
        assert pipe["f1"].backend is pipe["f2"].backend
        pipe["src"].push(np.float32([0]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        # both filters share the counter: f2 sees count 2
        np.testing.assert_array_equal(pipe["out"].frames[0].tensors[0], [2])

    def test_model_file_missing_n(self):
        pipe = Pipeline("t")
        f = make_element("tensor_filter", framework="custom-easy", model="zzz")
        pipe.chain(AppSrc("src"), f, TensorSink("out"))
        with pytest.raises(Exception):
            pipe.start()
        pipe.stop()

    def test_reload_event(self):
        calls = []
        register_custom_easy("m1", lambda xs: (calls.append(1), [x * 1 for x in xs])[1])
        register_custom_easy("m2", lambda xs: (calls.append(2), [x * 2 for x in xs])[1])
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_filter name=f framework=custom-easy model=m1 "
                "is-updatable=true ! tensor_sink name=out"
            )
            pipe.start()
            pipe["src"].push(np.float32([1]))
            pipe["src"].push_event(CustomEvent("reload-model", {"model": "m2"}))
            # the event rides the same source queue as frames, in order
            pipe["src"].end_of_stream()
            pipe.wait(timeout=15)
            pipe.stop()
        finally:
            unregister_custom_easy("m1")
            unregister_custom_easy("m2")


class TestBatching:
    def test_microbatch_preserves_order_and_pts(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=scaler custom=factor:2 "
            "max-batch=8 ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(20):
            pipe["src"].push(np.float32([i]), pts=i * 0.1)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        outs = pipe["out"].frames
        assert len(outs) == 20
        assert [float(f.tensors[0][0]) for f in outs] == [2.0 * i for i in range(20)]
        assert [f.pts for f in outs] == pytest.approx([i * 0.1 for i in range(20)])
        # batching actually engaged: fewer invokes than frames
        assert pipe["f"].backend is None  # stopped
