"""tensor_filter element + backend ABI tests.

Modeled on the reference's parameterized filter-subplugin template
(``tests/nnstreamer_filter_extensions_common/unittest_tizen_template.cc.in``:
checkExistence, openClose_n, invoke, reloadModel, ...) using the fake
backends, plus filter-element behaviors (combinations, stats, sharing,
batching) from ``tests/unittest_filter_single`` and SSAT suites.
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends import find_backend, register_custom_easy, unregister_custom_easy
from nnstreamer_tpu.backends.base import parse_accelerator
from nnstreamer_tpu.core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from nnstreamer_tpu.core.buffer import CustomEvent
from nnstreamer_tpu.elements.basic import AppSrc, TensorSink
from nnstreamer_tpu.elements.filter import SingleShot
from nnstreamer_tpu.pipeline import Pipeline, make_element, parse_pipeline


def spec1(shape=(4,), dtype=np.float32):
    return StreamSpec((TensorSpec(shape, dtype),), FORMAT_STATIC)


class TestBackendABI:
    @pytest.mark.parametrize("name", ["passthrough", "scaler", "average", "custom-easy"])
    def test_check_existence(self, name):
        assert find_backend(name) is not None

    def test_unknown_backend_n(self):
        with pytest.raises(KeyError):
            find_backend("no_such_backend")

    def test_scaler_custom_props(self):
        be = find_backend("scaler")()
        be.open(None, {"custom": "factor:3"})
        out = be.invoke([np.array([1.0, 2.0], np.float32)])
        np.testing.assert_allclose(out[0], [3.0, 6.0])

    def test_average_set_input_info(self):
        be = find_backend("average")()
        be.open(None, {})
        out_spec = be.set_input_info(spec1((8, 8)))
        assert out_spec.tensors[0].shape == (1,)
        assert out_spec.tensors[0].dtype == np.dtype(np.float32)

    def test_batch_fallback(self):
        be = find_backend("average")()
        be.open(None, {})
        out = be.invoke_batch([np.ones((3, 4), np.float32)])
        assert out[0].shape == (3, 1)
        np.testing.assert_allclose(out[0], 1.0)

    def test_accelerator_parse(self):
        # reference tensor_filter_common.c:2719 dialect
        assert parse_accelerator("true:tpu,cpu") == (True, ["tpu", "cpu"])
        assert parse_accelerator("false") == (False, ["auto"])
        assert parse_accelerator("") == (True, ["auto"])


class TestCustomEasy:
    def test_register_invoke_unregister(self):
        register_custom_easy("sq", lambda xs: [np.asarray(x) ** 2 for x in xs])
        try:
            with SingleShot("custom-easy", "sq") as m:
                out = m.invoke([np.array([2.0, 3.0])])
                np.testing.assert_allclose(out[0], [4.0, 9.0])
        finally:
            assert unregister_custom_easy("sq")

    def test_unregistered_open_n(self):
        with pytest.raises(FileNotFoundError):
            SingleShot("custom-easy", "never_registered")


class TestFilterElement:
    def run_pipe(self, text, inputs):
        pipe = parse_pipeline(text)
        pipe.start()
        src, sink = pipe["src"], pipe["out"]
        for arr in inputs:
            src.push(arr)
        src.end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        return sink.frames

    def test_passthrough_pipeline(self):
        frames = self.run_pipe(
            "appsrc name=src ! tensor_filter framework=passthrough ! tensor_sink name=out",
            [np.arange(4, dtype=np.float32)],
        )
        np.testing.assert_array_equal(frames[0].tensors[0], [0, 1, 2, 3])

    def test_scaler_custom_prop(self):
        frames = self.run_pipe(
            "appsrc name=src ! tensor_filter framework=scaler custom=factor:5 ! tensor_sink name=out",
            [np.array([1, 2], np.int32)],
        )
        np.testing.assert_array_equal(frames[0].tensors[0], [5, 10])

    def test_input_output_combination(self):
        # input-combination picks tensor 1; output-combination emits i0,o0
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=average input-combination=1 "
            "output-combination=i0,o0 ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push([np.zeros(3, np.float32), np.full(4, 2.0, np.float32)])
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        f = pipe["out"].frames[0]
        assert len(f.tensors) == 2
        # 'i0' = the element's ORIGINAL input tensor 0 (pre input-combination)
        np.testing.assert_array_equal(f.tensors[0], np.zeros(3, np.float32))
        np.testing.assert_allclose(f.tensors[1], [2.0])  # o0 = average of picked input

    def test_appsrc_bounded_backpressure(self):
        pipe = parse_pipeline(
            "appsrc name=src max-buffers=4 ! identity sleep=0.005 ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(40):
            pipe["src"].push(np.float32([i]))  # blocks when queue full
        pipe["src"].end_of_stream()
        pipe.wait(timeout=20)
        pipe.stop()
        assert len(pipe["out"].frames) == 40

    def test_sink_eos_received(self):
        pipe = parse_pipeline("appsrc name=src ! tensor_sink name=out")
        pipe.start()
        pipe["src"].push(np.float32([1]))
        pipe["src"].end_of_stream()
        assert pipe["out"].eos_received.wait(timeout=10)
        # stop, or the pipeline's registry collector stays registered
        # for the rest of the session (visible to any /metrics scrape)
        pipe.stop()

    def test_latency_throughput_props(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=passthrough latency=1 throughput=1 "
            "! tensor_sink name=out"
        )
        pipe.start()
        for i in range(5):
            pipe["src"].push(np.float32([i]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        f = pipe["f"]
        assert f.latency_us > 0
        assert f.throughput_fps > 0
        assert f.backend is not None and f.backend.stats.total_invoke_num == 5
        pipe.stop()

    def test_shared_backend_key(self):
        # two filters with the same key share one backend instance
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f1 framework=framecounter "
            "shared-tensor-filter-key=k1 ! tensor_filter name=f2 framework=framecounter "
            "shared-tensor-filter-key=k1 ! tensor_sink name=out"
        )
        pipe.start()
        assert pipe["f1"].backend is pipe["f2"].backend
        pipe["src"].push(np.float32([0]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        # both filters share the counter: f2 sees count 2
        np.testing.assert_array_equal(pipe["out"].frames[0].tensors[0], [2])

    def test_model_file_missing_n(self):
        pipe = Pipeline("t")
        f = make_element("tensor_filter", framework="custom-easy", model="zzz")
        pipe.chain(AppSrc("src"), f, TensorSink("out"))
        with pytest.raises(Exception):
            pipe.start()
        pipe.stop()

    def test_reload_event(self):
        calls = []
        register_custom_easy("m1", lambda xs: (calls.append(1), [x * 1 for x in xs])[1])
        register_custom_easy("m2", lambda xs: (calls.append(2), [x * 2 for x in xs])[1])
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_filter name=f framework=custom-easy model=m1 "
                "is-updatable=true ! tensor_sink name=out"
            )
            pipe.start()
            pipe["src"].push(np.float32([1]))
            pipe["src"].push_event(CustomEvent("reload-model", {"model": "m2"}))
            # the event rides the same source queue as frames, in order
            pipe["src"].end_of_stream()
            pipe.wait(timeout=15)
            pipe.stop()
        finally:
            unregister_custom_easy("m1")
            unregister_custom_easy("m2")


class TestBatching:
    def test_microbatch_preserves_order_and_pts(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=scaler custom=factor:2 "
            "max-batch=8 ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(20):
            pipe["src"].push(np.float32([i]), pts=i * 0.1)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        outs = pipe["out"].frames
        assert len(outs) == 20
        assert [float(f.tensors[0][0]) for f in outs] == [2.0 * i for i in range(20)]
        assert [f.pts for f in outs] == pytest.approx([i * 0.1 for i in range(20)])
        # batching actually engaged: fewer invokes than frames
        assert pipe["f"].backend is None  # stopped


class TestDispatchDepth:
    """Depth-N in-flight dispatch: the filter parks device outputs of up
    to dispatch-depth micro-batches and only blocks on the oldest, so
    batch k+1's stack/dispatch overlaps batch k's compute + transfer
    (VERDICT r3 #2; the reference's steady state is synchronous
    map->invoke->append, tensor_filter.c:642-930)."""

    @pytest.fixture(autouse=True)
    def _affine(self):
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model, unregister_jax_model)
        register_jax_model(
            "ddepth_affine", lambda p, xs: [xs[0] * 2.0 + 1.0], None)
        yield
        unregister_jax_model("ddepth_affine")

    def _run(self, n, extra=""):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=jax-xla "
            f"model=ddepth_affine max-batch=4 {extra} ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(n):
            pipe["src"].push(np.float32([i]), pts=i * 0.01)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        frames = pipe["out"].frames
        pipe.stop()
        return frames

    def test_order_and_completeness_at_default_depth(self):
        frames = self._run(50)
        assert len(frames) == 50
        assert [float(f.tensors[0][0]) for f in frames] == [
            2.0 * i + 1.0 for i in range(50)]
        # pts rides along unchanged through the parked window
        assert [f.pts for f in frames] == pytest.approx(
            [i * 0.01 for i in range(50)])

    def test_depth_1_is_synchronous_and_equivalent(self):
        frames = self._run(30, extra="dispatch-depth=1")
        assert [float(f.tensors[0][0]) for f in frames] == [
            2.0 * i + 1.0 for i in range(30)]

    def test_eos_drains_parked_window(self):
        """With a huge depth the window would hold everything until EOS;
        every frame must still come out, in order."""
        frames = self._run(20, extra="dispatch-depth=64")
        assert [float(f.tensors[0][0]) for f in frames] == [
            2.0 * i + 1.0 for i in range(20)]

    def test_window_bookkeeping_unit(self):
        """Direct element-level check of the completion-driven window (no
        pipeline): parking never blocks, emission is FIFO and strictly
        completion-gated (manual-completion fake device), EOS drains,
        Flush discards.  ingest-lane off: this pins the WINDOW alone."""
        from nnstreamer_tpu.elements.filter import TensorFilter

        el = TensorFilter("f")
        el.set_property("framework", "async-sim")
        el.set_property("custom", "manual:1")
        el.set_property("ingest-lane", "off")
        el.set_property("max-batch", 4)
        el.set_property("dispatch-depth", 3)
        el.start()
        try:
            from nnstreamer_tpu.core.buffer import TensorFrame

            be = el.backend

            def batch(i0):
                return [TensorFrame((np.float32([i]),)) for i in range(i0, i0 + 4)]

            out1 = el.handle_frame_batch(0, batch(0))
            assert out1 == [] and len(el._inflight) == 1
            out2 = el.handle_frame_batch(0, batch(4))
            assert out2 == [] and len(el._inflight) == 2
            assert el.pending_frames() == 8
            # nothing completed yet: batch 0 must NOT have been emitted
            # (the old design would block on it here); complete it and
            # the full-window park releases exactly it, in order
            be.release_one()
            out3 = el.handle_frame_batch(0, batch(8))
            assert [float(f.tensors[0][0]) for _, f in out3] == [1.0, 3.0, 5.0, 7.0]
            assert len(el._inflight) == 2
            be.release_all()
            drained = el.handle_eos(0)
            assert len(drained) == 8 and not len(el._inflight)
            assert [float(f.tensors[0][0]) for _, f in drained] == [
                2.0 * i + 1.0 for i in range(4, 12)]
            # flush discards parked frames
            el.handle_frame_batch(0, batch(12))
            assert len(el._inflight) == 1
            from nnstreamer_tpu.core.buffer import Flush
            el.handle_event(0, Flush())
            assert not len(el._inflight) and el.pending_frames() == 0
        finally:
            el.stop()

    def test_idle_drains_parked_window_without_eos(self):
        """Live-stream gap: parked batches must flow out on scheduler idle,
        not wait for the next frame or EOS."""
        import time as _t
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=jax-xla "
            "model=ddepth_affine max-batch=4 dispatch-depth=64 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        seen = []
        pipe["out"].connect_new_data(lambda f: seen.append(float(f.tensors[0][0])))
        for i in range(12):
            pipe["src"].push(np.float32([i]))
        # no EOS: within the idle poll period the window must drain
        deadline = _t.monotonic() + 10
        while len(seen) < 12 and _t.monotonic() < deadline:
            _t.sleep(0.05)
        try:
            assert seen == [2.0 * i + 1.0 for i in range(12)]
        finally:
            pipe["src"].end_of_stream()
            pipe.wait(timeout=10)
            pipe.stop()

    def test_event_does_not_overtake_parked_frames(self):
        """A custom event pushed after frames must reach downstream after
        them even while they are parked in the dispatch window."""
        from nnstreamer_tpu.core.buffer import CustomEvent, TensorFrame
        from nnstreamer_tpu.elements.filter import TensorFilter

        el = TensorFilter("f")
        el.set_property("framework", "jax-xla")
        el.set_property("model", "ddepth_affine")
        el.set_property("max-batch", 4)
        el.set_property("dispatch-depth", 8)
        el.start()
        try:
            frames = [TensorFrame((np.float32([i]),)) for i in range(4)]
            assert el.handle_frame_batch(0, frames) == []
            outs = el.handle_event(0, CustomEvent("app-marker", {}))
            # parked frames come out BEFORE the (forwarded) event
            kinds = [type(o).__name__ for _, o in outs]
            assert kinds[:4] == ["TensorFrame"] * 4
            assert not el._inflight
        finally:
            el.stop()

    def test_sync_degrade_latches_capability_once(self, caplog):
        """Host-resident outputs (no copy_to_host_async) degrade a
        depth>1 request to the synchronous path: latched ONCE per
        backend instance — one log line, no per-batch hasattr re-probe —
        and emission is immediate (nothing ever parks)."""
        import logging

        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.elements.filter import TensorFilter

        el = TensorFilter("f")
        el.set_property("framework", "scaler")
        el.set_property("custom", "factor:2")
        el.set_property("max-batch", 4)
        el.set_property("dispatch-depth", 4)
        el.start()
        try:
            assert el._win_async is None  # not probed until first batch
            with caplog.at_level(logging.INFO):
                for k in range(3):
                    outs = el.handle_frame_batch(0, [
                        TensorFrame((np.float32([i]),))
                        for i in range(4 * k, 4 * k + 4)
                    ])
                    # synchronous: every batch emits immediately
                    assert len(outs) == 4 and not len(el._inflight)
            assert el._win_async is False  # latched, not re-probed
            degrade_logs = [
                r for r in caplog.records
                if "degrades to the synchronous path" in r.message
            ]
            assert len(degrade_logs) == 1  # logged once, not per batch
        finally:
            el.stop()

    def test_private_batches_route_through_donated_entry(self):
        """Batches the filter stacked itself are private: they go
        through the backend's donated entry point (donated_calls
        counts); a pre-batched BatchFrame — upstream may retain it —
        must NOT (donation would destroy a shared buffer)."""
        from nnstreamer_tpu.core.buffer import BatchFrame, TensorFrame
        from nnstreamer_tpu.elements.filter import TensorFilter

        el = TensorFilter("f")
        el.set_property("framework", "scaler")
        el.set_property("custom", "factor:2")
        el.set_property("max-batch", 4)
        el.start()
        try:
            el.handle_frame_batch(0, [
                TensorFrame((np.float32([i]),)) for i in range(4)])
            assert el.backend.stats.donated_calls == 1
            block = BatchFrame(
                tensors=[np.arange(4, dtype=np.float32)[:, None]],
                frames_info=[(None, None, {}) for _ in range(4)],
            )
            el.handle_frame_batch(0, [block])
            assert el.backend.stats.donated_calls == 1  # unchanged
        finally:
            el.stop()


class TestIngestLane:
    """The double-buffered host->device staging lane (core/feed.py
    HostStagingLane) wired through the element."""

    def test_lane_defers_dispatch_by_one_batch_fifo(self):
        """ingest-lane=on: batch k is dispatched when k+1 is submitted
        (the double buffer), EOS flushes the last staged batch — FIFO
        values exact."""
        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.elements.filter import TensorFilter

        el = TensorFilter("f")
        el.set_property("framework", "async-sim")
        el.set_property("ingest-lane", "on")
        el.set_property("max-batch", 4)
        el.set_property("dispatch-depth", 1)
        el.start()
        try:
            assert el._lane is not None

            def batch(i0):
                return [
                    TensorFrame((np.float32([i]),))
                    for i in range(i0, i0 + 4)
                ]

            out1 = el.handle_frame_batch(0, batch(0))
            assert out1 == []  # staged, not yet dispatched
            assert el.pending_frames() == 4
            out2 = el.handle_frame_batch(0, batch(4))  # dispatches batch 0
            assert [float(f.tensors[0][0]) for _, f in out2] == [
                1.0, 3.0, 5.0, 7.0]
            drained = el.handle_eos(0)  # flushes the staged batch 1
            assert [float(f.tensors[0][0]) for _, f in drained] == [
                2.0 * i + 1.0 for i in range(4, 8)]
            assert el.pending_frames() == 0
        finally:
            el.stop()

    def test_lane_flush_discards_staged_batch(self):
        from nnstreamer_tpu.core.buffer import Flush, TensorFrame
        from nnstreamer_tpu.elements.filter import TensorFilter

        el = TensorFilter("f")
        el.set_property("framework", "async-sim")
        el.set_property("ingest-lane", "on")
        el.set_property("max-batch", 4)
        el.start()
        try:
            el.handle_frame_batch(0, [
                TensorFrame((np.float32([i]),)) for i in range(4)])
            assert el.pending_frames() == 4
            el.handle_event(0, Flush())
            assert el.pending_frames() == 0
            assert el.handle_eos(0) == []  # staged batch really gone
        finally:
            el.stop()

    def test_lane_refused_for_replay_policies(self):
        """The one-batch deferral would misattribute a failed batch's
        frames under skip/restart supervision: ingest-lane=on refuses at
        start(), auto silently keeps the lane off."""
        from nnstreamer_tpu.elements.filter import TensorFilter
        from nnstreamer_tpu.pipeline.element import ElementError

        el = TensorFilter("f")
        el.set_property("framework", "async-sim")
        el.set_property("ingest-lane", "on")
        el.set_property("max-batch", 4)
        el.set_property("error-policy", "skip")
        with pytest.raises(ElementError, match="ingest-lane=on"):
            el.start()
        el2 = TensorFilter("f2")
        el2.set_property("framework", "async-sim")
        el2.set_property("ingest-lane", "auto")
        el2.set_property("max-batch", 4)
        el2.set_property("error-policy", "skip")
        el2.start()
        try:
            assert el2._lane is None
        finally:
            el2.stop()

    def test_lane_on_requires_staging_capable_backend(self):
        from nnstreamer_tpu.elements.filter import TensorFilter
        from nnstreamer_tpu.pipeline.element import ElementError

        el = TensorFilter("f")
        el.set_property("framework", "scaler")
        el.set_property("custom", "factor:2")
        el.set_property("ingest-lane", "on")
        el.set_property("max-batch", 4)
        with pytest.raises(ElementError, match="staged"):
            el.start()

    def test_lane_staging_error_attributed_on_dispatch(self):
        """A staging failure (bad frame shapes) surfaces on the dispatch
        thread as an ordinary element error when the batch is
        collected — not silently swallowed on the lane thread."""
        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.elements.filter import TensorFilter

        el = TensorFilter("f")
        el.set_property("framework", "async-sim")
        el.set_property("ingest-lane", "on")
        el.set_property("max-batch", 4)
        el.start()
        try:
            # ragged shapes cannot stack into one staging buffer
            el.handle_frame_batch(0, [
                TensorFrame((np.zeros((2,), np.float32),)),
                TensorFrame((np.zeros((3,), np.float32),)),
            ])
            with pytest.raises(Exception):
                el.handle_eos(0)
        finally:
            el.stop()


class TestStackJitCacheLRU:
    """The device-stack jit cache is a bounded LRU: flexible-shape streams
    must not grow it without limit (each entry pins a compiled XLA
    program), and an evicted key simply retraces on next use."""

    def test_evicts_and_retraces(self, monkeypatch):
        import jax.numpy as jnp

        from nnstreamer_tpu.elements import filter as filter_mod

        monkeypatch.setattr(filter_mod, "_STACK_JIT_MAX", 4)
        monkeypatch.setattr(filter_mod, "_stack_jit_cache", type(
            filter_mod._stack_jit_cache
        )())
        shapes = [(1,), (2,), (3,), (4,), (5,), (6,)]
        for s in shapes:
            arrs = [jnp.zeros(s), jnp.ones(s)]
            out = np.asarray(filter_mod._stack_tensors(arrs))
            np.testing.assert_array_equal(
                out, np.stack([np.zeros(s), np.ones(s)])
            )
        cache = filter_mod._stack_jit_cache
        assert len(cache) == 4  # bounded: 6 shapes, cap 4
        # the two oldest shapes were evicted
        cached_shapes = {k[1] for k in cache}
        assert (1,) not in cached_shapes and (2,) not in cached_shapes
        # evict-and-retrace: the evicted shape works again (recompiles)
        arrs = [jnp.full((1,), 3.0), jnp.full((1,), 4.0)]
        out = np.asarray(filter_mod._stack_tensors(arrs))
        np.testing.assert_array_equal(out, np.array([[3.0], [4.0]]))
        assert (1,) in {k[1] for k in cache}
        assert len(cache) == 4

    def test_hit_refreshes_recency(self, monkeypatch):
        import jax.numpy as jnp

        from nnstreamer_tpu.elements import filter as filter_mod

        monkeypatch.setattr(filter_mod, "_STACK_JIT_MAX", 2)
        monkeypatch.setattr(filter_mod, "_stack_jit_cache", type(
            filter_mod._stack_jit_cache
        )())
        for s in [(1,), (2,)]:
            filter_mod._stack_tensors([jnp.zeros(s), jnp.zeros(s)])
        # touch (1,) so (2,) becomes the LRU victim
        filter_mod._stack_tensors([jnp.zeros((1,)), jnp.zeros((1,))])
        filter_mod._stack_tensors([jnp.zeros((3,)), jnp.zeros((3,))])
        cached_shapes = {k[1] for k in filter_mod._stack_jit_cache}
        assert cached_shapes == {(1,), (3,)}
