"""Model-file resolution in the jax-xla backend: .msgpack flax params and
Orbax checkpoint directories with ``custom=arch:<family>`` (the
reference's model= file contract, ≙ tensor_filter model=m.tflite).

Also pins hot reload between two weight files (≙ RELOAD_MODEL /
is-updatable, double-buffered reload in the reference's tflite
subplugin)."""

import time

import numpy as np

from nnstreamer_tpu.core.buffer import CustomEvent
from nnstreamer_tpu.elements.filter import SingleShot
from nnstreamer_tpu.models import build
from nnstreamer_tpu.pipeline import parse_pipeline

ARCH = "arch:mnist_cnn,dtype:float32"
PROPS = {"dtype": "float32"}


def _save_msgpack(path, seed):
    from flax import serialization

    fn, params, _, _ = build("mnist_cnn", {**PROPS, "seed": str(seed)})
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(params))
    return fn, params


def test_msgpack_file_load(tmp_path, rng):
    path = str(tmp_path / "w.msgpack")
    fn, params = _save_msgpack(path, seed=5)
    x = rng.normal(size=(2, 28, 28, 1)).astype(np.float32)
    want = np.asarray(fn(params, [x])[0])
    with SingleShot(framework="jax-xla", model=path, custom=ARCH) as s:
        got = np.asarray(s.invoke_batch([x])[0])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_orbax_dir_load(tmp_path, rng):
    """Orbax checkpoint-dir loading is FAITHFUL: the restored params are
    bit-identical to the saved ones, and invoking through the backend
    matches invoking the same backend on the original params exactly.

    Deterministic by construction (this was a suite-order flake): the
    async orbax save is awaited before restore, and the numeric
    comparison is jit-path vs jit-path — same process, same executable —
    instead of jit vs eager, so ambient jax state leaked by earlier
    tests cannot skew one side of the comparison."""
    import jax
    import orbax.checkpoint as ocp

    from nnstreamer_tpu.backends.jax_xla import (
        register_jax_model,
        unregister_jax_model,
    )

    fn, params, _, _ = build("mnist_cnn", {**PROPS, "seed": "8"})
    ckpt = str(tmp_path / "ckpt")
    # StandardCheckpointer is an AsyncCheckpointer: without the context
    # manager (wait_until_finished + close) the restore below races the
    # background commit
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(ckpt, jax.tree.map(np.asarray, params))
    x = rng.normal(size=(2, 28, 28, 1)).astype(np.float32)
    with SingleShot(framework="jax-xla", model=ckpt, custom=ARCH) as s:
        # round-trip fidelity: restored leaves == saved leaves, bit-exact
        restored = s.backend._params
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            restored, params,
        )
        got = np.asarray(s.invoke_batch([x])[0])
    register_jax_model("_orbax_ref", fn, params)
    try:
        with SingleShot(framework="jax-xla", model="_orbax_ref",
                        custom="dtype:float32") as ref:
            want = np.asarray(ref.invoke_batch([x])[0])
    finally:
        unregister_jax_model("_orbax_ref")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_hot_reload_swaps_weights(tmp_path, rng):
    """is-updatable reload mid-stream: outputs flip to the new weights'
    results without restarting the pipeline."""
    p1, p2 = str(tmp_path / "a.msgpack"), str(tmp_path / "b.msgpack")
    fn, params1 = _save_msgpack(p1, seed=1)
    _, params2 = _save_msgpack(p2, seed=2)
    x = rng.normal(size=(28, 28, 1)).astype(np.float32)
    want1 = np.asarray(fn(params1, [x[None]])[0])[0]
    want2 = np.asarray(fn(params2, [x[None]])[0])[0]

    pipe = parse_pipeline(
        f"appsrc name=src ! tensor_filter name=f framework=jax-xla "
        f"model={p1} custom={ARCH} is-updatable=true ! "
        "tensor_sink name=out",
        name="reload",
    )
    pipe.start()
    pipe["src"].push(x)
    # reload event travels the stream like the reference's RELOAD_MODEL;
    # it now STAGES the new weights on a second backend instance
    # (validate + JIT warmup off the hot path) and swaps at a frame
    # boundary — barrier on the swap landing before the second frame
    pipe["src"].push_event(CustomEvent("reload-model", {"model": p2}))

    def _staged():
        h = pipe.health()["f"]
        return h.get("swap_state") == "staged" or h["swaps"] >= 1

    deadline = time.time() + 60
    while not _staged() and time.time() < deadline:
        time.sleep(0.05)
    assert _staged(), pipe.health()["f"]
    # the staged swap lands at the next frame boundary — i.e. before
    # this frame's invoke, so it is served by the new weights
    pipe["src"].push(x)
    pipe["src"].end_of_stream()
    pipe.wait(timeout=60)
    outs = [np.asarray(f.tensors[0]) for f in pipe["out"].frames]
    pipe.stop()
    assert len(outs) == 2
    np.testing.assert_allclose(outs[0], want1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs[1], want2, rtol=1e-5, atol=1e-6)
    assert not np.allclose(outs[0], outs[1])
