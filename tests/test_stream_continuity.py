"""Durable generation streams: checkpointed resume and live migration
across server death (core/continuity.py + SlotEngine resume/goaway +
the query client's stream-continuity layer).

Oracles:

* REAL model — a stream killed at a chunk boundary and RESUMED on a
  fresh engine (prompt + prefix re-prefilled through the chunked-prefill
  path) must be BIT-IDENTICAL to an uninterrupted run, greedy AND
  seeded top-k (the per-step key folds at the absolute token index).
* SIM model — token 1 = ``sum(prompt) % vocab``, token j+1 =
  ``(31 t_j + 17) % vocab``: exact end-to-end accounting through kills,
  drains, and migrations without model cost.
* LEDGER — per-chunk ``tokens_done`` sequence numbers dedupe the
  post-resume overlap exactly: delivered tokens are exactly-once, the
  downstream chunk numbering contiguous, duplicates counted.
"""

import socket
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.continuity import (
    GOAWAY_META,
    RESUME_META,
    RESUME_REJECT_META,
    RESUME_REQ_META,
    StreamContinuity,
    prompt_digest,
    resume_signature,
)
from nnstreamer_tpu.core.liveness import ThreadBeat, thread_census
from nnstreamer_tpu.core.slots import SimSlotModel, SlotEngine
from nnstreamer_tpu.pipeline import parse_pipeline

PROPS = {
    "dtype": "float32", "vocab": 61, "d_model": 32, "heads": 2,
    "layers": 2, "d_ff": 64, "seq": 64, "seed": 11,
}
SAMPLING = {"temperature": "0.8", "top_k": "7", "gen_seed": "3"}


def sim_oracle(vocab, prompt, n):
    sim = SimSlotModel(1, vocab=vocab)
    t = int(prompt.sum()) % vocab
    out = [t]
    for _ in range(n - 1):
        t = sim.step_token(t)
        out.append(t)
    return np.asarray([out], np.int32)


def _drain_engine(engine, timeout=60.0):
    """Collect emitted frames until a final one (or timeout)."""
    out = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out.extend(f for _, f in engine.pop_ready())
        if out and out[-1].meta.get("final"):
            return out
        time.sleep(0.002)
    raise TimeoutError(f"engine produced no final chunk ({len(out)} frames)")


def _tokens(frames):
    parts = [np.asarray(f.tensors[0]) for f in frames if f.tensors]
    return (np.concatenate(parts, axis=1) if parts
            else np.zeros((1, 0), np.int32))


def _chunk(prompt, toks, idx, done, final=False, goaway=False,
           sig="S", chunk=4, extra=None):
    """Fabricate one resumable wire chunk the way the engine emits it."""
    f = TensorFrame([np.asarray(toks, np.int32)] if toks is not None
                    else [])
    f.meta.update(stream_seq=7, chunk_index=idx, tokens_done=done,
                  final=final)
    f.meta[RESUME_META] = {
        "v": 1, "sig": sig, "digest": prompt_digest(prompt),
        "chunk": chunk,
    }
    if goaway:
        f.meta[GOAWAY_META] = True
        f.meta["evicted"] = "goaway"
    if extra:
        f.meta.update(extra)
    return f


# ---------------------------------------------------------------------------
# The client-side ledger: dedupe exactness, renumbering, resume frames
# ---------------------------------------------------------------------------
class TestContinuityLedger:
    def test_non_resumable_chunks_pass_through_untouched(self):
        req = TensorFrame([np.int32([[1, 2]])])
        cont = StreamContinuity(req)
        ans = TensorFrame([np.float32([3.0])], meta={"final": True})
        v = cont.accept(ans)
        assert v.emit is ans and v.finished and not cont.capable
        assert v.dup == 0 and not v.handoff

    def test_dedupe_exactness_across_a_handoff(self):
        """The issue's exactly-once contract, pinned deterministically:
        chunks 0-1 delivered, a handoff flushes 2 partial tokens, the
        resume snaps DOWN to the chunk boundary, and the resumed
        server's overlapping chunk is trimmed to exactly the new
        tokens — contiguous downstream indices, exact dup count."""
        prompt = np.int32([[5, 6, 7]])
        oracle = np.arange(100, 114, dtype=np.int32)[None]  # 14 tokens
        req = TensorFrame([prompt])
        cont = StreamContinuity(req)
        emitted = []

        def feed(*a, **kw):
            v = cont.accept(_chunk(prompt, *a, **kw))
            if v.emit is not None:
                emitted.append(v.emit)
            return v

        feed(oracle[:, 0:4], 0, 4)
        feed(oracle[:, 4:8], 1, 8)
        assert cont.capable and cont.delivered == 8
        # handoff: 2 partial tokens past the boundary ride the final
        v = feed(oracle[:, 8:10], 2, 10, final=True, goaway=True)
        assert v.handoff and not v.finished and cont.take_handoff()
        assert cont.delivered == 10 and cont.resume_point() == 8
        rf = cont.build_resume_frame()
        rs = rf.meta[RESUME_REQ_META]
        assert rs["tokens_done"] == 8 and rs["chunk"] == 4
        assert rs["digest"] == prompt_digest(prompt)
        np.testing.assert_array_equal(rf.tensors[0], prompt)
        np.testing.assert_array_equal(rf.tensors[1], oracle[:, :8])
        # resumed server re-decodes from token 9: its first chunk
        # overlaps the 2 delivered partials -> trimmed exactly
        v = feed(oracle[:, 8:12], 2, 12)
        assert v.dup == 2 and cont.duplicates_dropped == 2
        np.testing.assert_array_equal(
            np.asarray(v.emit.tensors[0]), oracle[:, 10:12])
        assert v.emit.meta["tokens_done"] == 12
        v = feed(oracle[:, 12:14], 3, 14, final=True)
        assert v.finished
        # downstream view: contiguous indices, exactly-once tokens
        assert [f.meta["chunk_index"] for f in emitted] == [0, 1, 2, 3, 4]
        np.testing.assert_array_equal(_tokens(emitted), oracle)
        assert all(f.meta.get("stream_seq") == 7 for f in emitted)
        assert GOAWAY_META not in emitted[2].meta

    def test_fully_duplicate_chunk_drops_silently(self):
        prompt = np.int32([[1]])
        cont = StreamContinuity(TensorFrame([prompt]))
        oracle = np.arange(8, dtype=np.int32)[None]
        cont.accept(_chunk(prompt, oracle[:, :4], 0, 4))
        cont.accept(_chunk(prompt, oracle[:, 4:8], 1, 8))
        v = cont.accept(_chunk(prompt, oracle[:, 4:8], 1, 8))
        assert v.emit is None and v.dup == 4
        assert cont.delivered == 8 and cont.duplicates_dropped == 4

    def test_reject_chunk_classified(self):
        prompt = np.int32([[1]])
        cont = StreamContinuity(TensorFrame([prompt]))
        f = TensorFrame([])
        f.meta[RESUME_REJECT_META] = "signature mismatch"
        v = cont.accept(f)
        assert v.reject == "signature mismatch" and v.emit is None

    def test_incoherent_ledger_refuses_to_resume(self):
        """A gapped token ledger can no longer guarantee exactly-once:
        build_resume_frame must refuse loudly, never resume wrong."""
        prompt = np.int32([[1]])
        cont = StreamContinuity(TensorFrame([prompt]))
        oracle = np.arange(12, dtype=np.int32)[None]
        cont.accept(_chunk(prompt, oracle[:, :4], 0, 4))
        # chunk 2 arrives with a tokens_done GAP (chunk 1 lost)
        cont.accept(_chunk(prompt, oracle[:, 8:12], 2, 12))
        with pytest.raises(RuntimeError, match="incoherent"):
            cont.build_resume_frame()
        assert not cont.capable


# ---------------------------------------------------------------------------
# Engine-level resume: bit-parity matrix (kill at every chunk boundary)
# ---------------------------------------------------------------------------
class TestEngineResumeParity:
    def _oracle_sim(self, prompt, max_new, chunk):
        m = SimSlotModel(2, step_base_ms=0.05)
        e = SlotEngine(m, None, max_seq=1 << 20, chunk=chunk,
                       resume_sig="SIG")
        e.start()
        try:
            e.submit(TensorFrame([prompt]), prompt, max_new, chunk)
            return _tokens(_drain_engine(e))
        finally:
            e.stop()

    def test_sim_resume_bit_parity_every_point(self):
        """Resume from EVERY possible delivered count 1..max_new-1 (the
        client snaps to boundaries, but the engine contract is general):
        suffix bit-identical, meta counters continue from R."""
        prompt = np.arange(4, dtype=np.int32)[None]
        max_new, chunk = 16, 4
        oracle = self._oracle_sim(prompt, max_new, chunk)
        assert oracle.shape[1] == max_new
        for r in range(1, max_new):
            m = SimSlotModel(2, step_base_ms=0.05)
            e = SlotEngine(m, None, max_seq=1 << 20, chunk=chunk,
                           resume_sig="SIG")
            e.start()
            try:
                e.submit(
                    TensorFrame([prompt]), prompt, max_new, chunk,
                    resume={"tokens_done": r, "prefix": oracle[:, :r]})
                frames = _drain_engine(e)
            finally:
                e.stop()
            got = _tokens(frames)
            np.testing.assert_array_equal(got, oracle[:, r:],
                                          err_msg=f"resume at {r}")
            assert frames[-1].meta["tokens_done"] == max_new
            assert frames[0].meta[RESUME_META]["sig"] == "SIG"
            assert e.resumes == 1

    @pytest.mark.slow  # tier-1 budget: ~35s+25s O(boundaries) zoo sweep;
    # tier-1 keeps the sim every-point sweep plus the real-model single-kill
    # parity pins (test_kill_mid_stream_resumes_bit_exact, prefix cold-resume)
    @pytest.mark.parametrize("extra", [None, SAMPLING],
                             ids=["greedy", "seeded-topk"])
    def test_zoo_resume_bit_parity_every_boundary(self, rng, extra):
        """REAL transformer: kill at every chunk boundary x {greedy,
        seeded top-k}; the resumed engine re-prefills prompt + prefix
        through the chunked-prefill path and the remaining tokens are
        BIT-IDENTICAL (per-step key folded at the absolute index)."""
        from nnstreamer_tpu.models.transformer import build_slot_stream

        props = {k: str(v) for k, v in PROPS.items()}
        if extra:
            props.update(extra)
        prompt = rng.integers(0, 61, (1, 6)).astype(np.int32)
        max_new, chunk = 12, 4

        def engine():
            model, params, max_seq = build_slot_stream(props, 2)
            return SlotEngine(model, params, max_seq=max_seq,
                              chunk=chunk, resume_sig="Z")

        e = engine()
        e.start()
        try:
            e.submit(TensorFrame([prompt]), prompt, max_new, chunk)
            oracle = _tokens(_drain_engine(e))
        finally:
            e.stop()
        assert oracle.shape[1] == max_new
        # every chunk boundary + one non-boundary point (engine general)
        for r in [chunk, 2 * chunk, 6]:
            e = engine()
            e.start()
            try:
                e.submit(
                    TensorFrame([prompt]), prompt, max_new, chunk,
                    resume={"tokens_done": r, "prefix": oracle[:, :r]})
                got = _tokens(_drain_engine(e))
            finally:
                e.stop()
            np.testing.assert_array_equal(got, oracle[:, r:],
                                          err_msg=f"resume at {r}")

    def test_goaway_handoff_resumable_chunk(self):
        """A drain flushes live streams as resumable GOAWAY final
        chunks: partial tokens + resume state, no deadline_expired
        marker, slot freed, counters exact."""
        prompt = np.arange(4, dtype=np.int32)[None]
        m = SimSlotModel(2, step_base_ms=3.0)
        e = SlotEngine(m, None, max_seq=1 << 20, chunk=4,
                       resume_sig="SIG")
        e.start()
        try:
            e.submit(TensorFrame([prompt]), prompt, 64, 4)
            deadline = time.monotonic() + 10
            while e.tokens_total < 8 and time.monotonic() < deadline:
                time.sleep(0.005)
            e.begin_goaway()
            frames = _drain_engine(e)
            last = frames[-1]
            assert last.meta.get(GOAWAY_META) is True
            assert last.meta["final"] is True
            assert "deadline_expired" not in last.meta
            assert last.meta[RESUME_META]["sig"] == "SIG"
            got = _tokens(frames)
            oracle = sim_oracle(m.vocab, prompt, 64)
            np.testing.assert_array_equal(
                got, oracle[:, :got.shape[1]])
            assert 0 < got.shape[1] < 64
            assert e.goaway_evicted == 1
            snap = e.snapshot()
            assert snap["gen_occupied"] == 0
            assert snap["gen_goaway_evicted"] == 1
            # a stream submitted DURING the drain hands off too
            e.submit(TensorFrame([prompt]), prompt, 64, 4)
            frames2 = _drain_engine(e)
            assert frames2[-1].meta.get(GOAWAY_META) is True
            assert e.goaway_evicted == 2
        finally:
            e.stop()

    def test_legacy_engine_without_sig_ignores_goaway(self):
        """No resume signature armed -> no handoff (a chunk the client
        cannot resume would silently truncate the stream): streams
        finish in place."""
        prompt = np.arange(3, dtype=np.int32)[None]
        m = SimSlotModel(1, step_base_ms=0.2)
        e = SlotEngine(m, None, max_seq=1 << 20, chunk=4)
        e.start()
        try:
            e.submit(TensorFrame([prompt]), prompt, 12, 4)
            e.begin_goaway()  # warns + no-op
            frames = _drain_engine(e)
            assert _tokens(frames).shape[1] == 12
            assert e.goaway_evicted == 0
            assert RESUME_META not in frames[0].meta
        finally:
            e.stop()


# ---------------------------------------------------------------------------
# Client classification: crash vs drain-handoff (the satellite pin)
# ---------------------------------------------------------------------------
def _client(props=None):
    from nnstreamer_tpu.elements.query import TensorQueryClient

    q = TensorQueryClient("q")
    q.set_property("stream", True)
    q.set_property("timeout", 30.0)
    q.set_property("retry-backoff", 0.0)
    for k, v in (props or {}).items():
        q.set_property(k, v)
    q._stopped = False
    return q


PROMPT = np.arange(5, dtype=np.int32)[None]
ORACLE = np.arange(200, 216, dtype=np.int32)[None]  # 16 "tokens"


class _ResumeServer:
    """Fake conn serving the ORACLE suffix from a RESUME request."""

    def __init__(self, addr="good:2", sig="S", reject=None):
        self.addr = addr
        self.sig = sig
        self.reject = reject
        self.resume_reqs = []

    def invoke_stream(self, frame, timeout):
        rs = frame.meta.get(RESUME_REQ_META)
        assert rs is not None, "expected a RESUME request"
        self.resume_reqs.append(rs)
        if self.reject is not None:
            f = TensorFrame([])
            f.meta.update(stream_seq=9, chunk_index=0, tokens_done=0,
                          final=True)
            f.meta[RESUME_REJECT_META] = self.reject
            yield f
            return
        assert rs["sig"] == self.sig
        r = int(rs["tokens_done"])
        np.testing.assert_array_equal(
            np.asarray(frame.tensors[1]), ORACLE[:, :r])
        for i in range(r, 16, 4):
            yield _chunk(PROMPT, ORACLE[:, i:i + 4], i // 4, i + 4,
                         final=(i + 4 >= 16), sig=self.sig)


class TestGoawayClassification:
    def test_crash_vs_handoff_breaker_and_cooldown(self):
        """THE satellite pin: a drain-initiated mid-stream break must
        not burn the 10s crash cooldown or count as a breaker failure
        the way a crash does — and both resume exactly-once."""
        import time as _t

        for kind in ("crash", "handoff"):
            class Breaks:
                addr = "bad:1"

                def invoke_stream(self, frame, timeout):
                    yield _chunk(PROMPT, ORACLE[:, 0:4], 0, 4)
                    yield _chunk(PROMPT, ORACLE[:, 4:8], 1, 8)
                    if kind == "crash":
                        raise ConnectionResetError("server died")
                    # drain handoff: 2 partial tokens + resume state
                    yield _chunk(PROMPT, ORACLE[:, 8:10], 2, 10,
                                 final=True, goaway=True)

            from nnstreamer_tpu.elements.query import _PoolState

            good = _ResumeServer()
            q = _client({"breaker-threshold": 1})
            ps = _PoolState((Breaks(), good),
                            (("bad", 1), ("good", 2)), 0)
            q._pstate = ps
            t0 = _t.monotonic()
            out = [f for _, f in q._stream_invoke(TensorFrame([PROMPT]))]
            np.testing.assert_array_equal(_tokens(out), ORACLE)
            assert [f.meta["chunk_index"] for f in out] == list(
                range(len(out)))
            h = q.health_info()
            bad = h["breakers"].get("bad:1", {})
            cool = ps.down_until.get(0, 0) - t0
            if kind == "crash":
                # crash: breaker failure (threshold 1 -> trip) + the
                # 10s cooldown; counted as a RESUME
                assert bad.get("trips") == 1
                assert 8.0 < cool <= 10.5
                assert h["stream_resumes"] == 1
                assert h["stream_migrations"] == 0
                assert h["duplicate_tokens_dropped"] == 0
            else:
                # handoff: breaker-immune (no failure, no trip), only
                # the short draining deprioritization; counted as a
                # MIGRATION; the 2 re-decoded partials deduped exactly
                assert bad.get("trips", 0) == 0
                assert 0 < cool <= 5.5
                assert h["stream_migrations"] == 1
                assert h["stream_resumes"] == 0
                assert h["duplicate_tokens_dropped"] == 2
            assert h["resume_failures"] == 0
            # resume snapped DOWN to the chunk boundary either way
            assert good.resume_reqs == [{
                "v": 1, "sig": "S", "digest": prompt_digest(PROMPT),
                "chunk": 4, "tokens_done": 8,
            }]

    def test_resume_disabled_keeps_legacy_no_replay(self):
        class Breaks:
            addr = "bad:1"

            def invoke_stream(self, frame, timeout):
                yield _chunk(PROMPT, ORACLE[:, 0:4], 0, 4)
                raise ConnectionResetError("server died")

        from nnstreamer_tpu.elements.query import _PoolState

        q = _client({"stream-resume": False})
        q._pstate = _PoolState((Breaks(), _ResumeServer()),
                               (("bad", 1), ("good", 2)), 0)
        with pytest.raises(ConnectionResetError):
            list(q._stream_invoke(TensorFrame([PROMPT])))
        h = q.health_info()
        assert h["stream_resumes"] == 0 and h["resume_failures"] == 0

    def test_reject_retry_counts_one_resume_not_two(self):
        """The fleet cross-check 'client resumes + migrations == engine
        gen_resumes' requires a retry after a REJECT to continue the
        SAME logical resume — one break, one reject, one success must
        count exactly ONE resume and ONE failure."""
        from nnstreamer_tpu.elements.query import _PoolState

        class Breaks:
            addr = "bad:1"

            def invoke_stream(self, frame, timeout):
                yield _chunk(PROMPT, ORACLE[:, 0:4], 0, 4)
                raise ConnectionResetError("server died")

        rejecter = _ResumeServer(addr="rej:2", reject="sig mismatch")
        good = _ResumeServer(addr="good:3")
        q = _client()
        q._pstate = _PoolState(
            (Breaks(), rejecter, good),
            (("bad", 1), ("rej", 2), ("good", 3)), 0)
        out = [f for _, f in q._stream_invoke(TensorFrame([PROMPT]))]
        np.testing.assert_array_equal(_tokens(out), ORACLE)
        h = q.health_info()
        assert h["stream_resumes"] == 1  # NOT one per reject retry
        assert h["resume_failures"] == 1
        assert len(rejecter.resume_reqs) == 1
        assert len(good.resume_reqs) == 1

    def test_failed_resume_attempt_not_recounted(self):
        """A resume attempt that dies before reaching a server does NOT
        bump stream_resumes again (it continues the same logical
        recovery, already counted as a failure) — the client-vs-engine
        cross-check stays exact."""
        from nnstreamer_tpu.elements.query import _PoolState

        class Breaks:
            addr = "bad:1"

            def invoke_stream(self, frame, timeout):
                yield _chunk(PROMPT, ORACLE[:, 0:4], 0, 4)
                raise ConnectionResetError("server died")

        class Refuses:
            addr = "dead:2"

            def invoke_stream(self, frame, timeout):
                raise ConnectionRefusedError("refused")

        good = _ResumeServer(addr="good:3")
        q = _client()
        q._pstate = _PoolState(
            (Breaks(), Refuses(), good),
            (("bad", 1), ("dead", 2), ("good", 3)), 0)
        out = [f for _, f in q._stream_invoke(TensorFrame([PROMPT]))]
        np.testing.assert_array_equal(_tokens(out), ORACLE)
        h = q.health_info()
        assert h["stream_resumes"] == 1  # one logical recovery
        assert h["resume_failures"] == 1  # the unreachable attempt
        assert len(good.resume_reqs) == 1

    def test_unslotted_generator_refuses_resume(self):
        """A RESUME request landing on a pre-slot (slots=0) generator is
        refused with the typed reject — the unvalidated path must never
        silently replay under a possibly-different config."""
        from nnstreamer_tpu.elements.generator import TensorGenerator

        g = TensorGenerator("g")
        g._prefill = object()  # "started", pre-slot path
        f = TensorFrame([PROMPT])
        f.meta[RESUME_REQ_META] = {
            "v": 1, "sig": "x", "digest": "y", "chunk": 4,
            "tokens_done": 4,
        }
        out = g.handle_frame(0, f)
        assert len(out) == 1
        rej = out[0][1]
        assert "slotted" in rej.meta[RESUME_REJECT_META]
        assert rej.meta["final"] is True and not rej.tensors
        assert g.health_info()["gen_resume_rejects"] == 1

    def test_handoff_with_resume_disabled_surfaces_goaway(self):
        """stream-resume=false: a mid-stream handoff must SURFACE (the
        legacy contract), never be silently replayed by the
        pre-first-answer GOAWAY failover."""
        from nnstreamer_tpu.core.lifecycle import ServerGoawayError
        from nnstreamer_tpu.elements.query import _PoolState

        class HandsOff:
            addr = "bad:1"

            def invoke_stream(self, frame, timeout):
                yield _chunk(PROMPT, ORACLE[:, 0:4], 0, 4)
                yield _chunk(PROMPT, ORACLE[:, 4:8], 1, 8,
                             final=True, goaway=True)

        q = _client({"stream-resume": False})
        q._pstate = _PoolState((HandsOff(), _ResumeServer()),
                               (("bad", 1), ("good", 2)), 0)
        out = []
        with pytest.raises(ServerGoawayError, match="handed the stream"):
            for item in q._stream_invoke(TensorFrame([PROMPT])):
                out.append(item)
        # chunk 0 plus the handoff's tokens (delivered, never final):
        # the error then tells the consumer the stream is dead
        assert len(out) == 2
        assert not out[-1][1].meta["final"]
        h = q.health_info()
        assert h["stream_migrations"] == 0

    def test_resume_reject_budget_and_surfacing(self):
        """Every healthy server refuses the resume (config mismatch):
        the budget bounds the attempts, failures are counted, and the
        refusal surfaces as the typed application error."""
        from nnstreamer_tpu.core.resilience import RemoteApplicationError
        from nnstreamer_tpu.elements.query import _PoolState

        class Breaks:
            addr = "bad:1"

            def invoke_stream(self, frame, timeout):
                yield _chunk(PROMPT, ORACLE[:, 0:4], 0, 4)
                raise ConnectionResetError("server died")

        rejecter = _ResumeServer(reject="signature mismatch")
        q = _client({"resume-retries": 2})
        q._pstate = _PoolState((Breaks(), rejecter),
                               (("bad", 1), ("good", 2)), 0)
        with pytest.raises(RemoteApplicationError, match="resume refused"):
            list(q._stream_invoke(TensorFrame([PROMPT])))
        h = q.health_info()
        # interrupt 1 = the crash (a resume), then rejects until the
        # budget (2) runs out
        assert h["stream_resumes"] >= 1
        assert h["resume_failures"] >= 2
        assert len(rejecter.resume_reqs) >= 1


# ---------------------------------------------------------------------------
# Pooled-socket hygiene after a mid-stream break (satellite)
# ---------------------------------------------------------------------------
class TestSocketHygiene:
    def test_mid_stream_death_evicts_socket_never_repools(self):
        """A socket whose stream died mid-chunk is desynced: it must be
        EVICTED, never handed to the next unary request."""
        from nnstreamer_tpu.distributed.tcp_query import (
            TcpQueryConnection,
            _T_QUERY,
            _T_STREAM,
            encode_msg,
            parse_msg,
        )
        from nnstreamer_tpu.distributed.wire import decode_frame, encode_frame

        ls = socket.socket()
        ls.bind(("127.0.0.1", 0))
        ls.listen(4)
        port = ls.getsockname()[1]
        served = {"n": 0}

        def serve():
            while True:
                try:
                    c, _ = ls.accept()
                except OSError:
                    return
                served["n"] += 1
                try:
                    data = c.recv(1 << 20)
                    mtype, body, _ = parse_msg(data, version=1)
                    if mtype == _T_STREAM:
                        ans = decode_frame(bytes(body)).with_tensors(
                            [np.int32([[1, 2]])])
                        ans.meta.update(final=False, chunk_index=0,
                                        tokens_done=2)
                        c.sendall(encode_msg(
                            _T_STREAM, encode_frame(ans), version=1))
                        c.close()  # die mid-stream
                    elif mtype == _T_QUERY:
                        ans = decode_frame(bytes(body))
                        c.sendall(encode_msg(
                            _T_QUERY, encode_frame(ans), version=1))
                        c.close()
                except OSError:
                    pass

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        conn = TcpQueryConnection("127.0.0.1", port, timeout=5.0,
                                  wire_version=1)
        try:
            frame = TensorFrame([np.int32([[9]])])
            got = []
            with pytest.raises((ConnectionError, OSError)):
                for ans in conn.invoke_stream(frame, 5.0):
                    got.append(ans)
            assert len(got) == 1  # the one chunk before the death
            # the desynced socket was evicted, not repooled
            assert conn._free == [] and conn._live == 0
            assert not conn._held
            # the next unary request gets a FRESH dial and succeeds
            ans = conn.invoke(frame, 5.0)
            np.testing.assert_array_equal(
                np.asarray(ans.tensors[0]), [[9]])
            assert served["n"] >= 2  # provably a second connection
        finally:
            conn.close()
            ls.close()


# ---------------------------------------------------------------------------
# Background-thread liveness (satellite)
# ---------------------------------------------------------------------------
class TestThreadLiveness:
    def test_threadbeat_edge_triggered_stall(self):
        clock = [0.0]
        hb = ThreadBeat("pump", stall_after_s=1.0,
                        clock=lambda: clock[0])
        hb.beat()
        assert not hb.check_stall(busy=True)
        clock[0] = 2.5
        assert hb.check_stall(busy=False) is False  # idle: never stalled
        assert hb.check_stall(busy=True) is True    # wedged: fires ONCE
        assert hb.check_stall(busy=True) is False   # edge-triggered
        assert hb.stalls == 1
        hb.beat()
        assert not hb.check_stall(busy=True)        # beat re-arms
        clock[0] = 4.0
        assert hb.check_stall(busy=True) is True
        assert hb.stalls == 2
        snap = hb.snapshot()
        assert snap["beats"] == 2 and snap["stalls"] == 2
        assert snap["alive"] is False  # never bound to a thread

    def test_wedged_pump_fires_incident(self):
        """A pump stuck inside a device call never returns, so the
        sticky pop_ready error can never surface — the element's idle
        poll must detect the stale heartbeat and fire ONE incident."""
        from nnstreamer_tpu.elements.generator import TensorGenerator

        class WedgeModel(SimSlotModel):
            def __init__(self):
                super().__init__(1, step_base_ms=0.01)
                self.release = threading.Event()

            def decode_fn(self, k):
                inner = super().decode_fn(k)

                def fn(*a):
                    self.release.wait(20.0)
                    return inner(*a)

                return fn

        class FakePipe:
            def __init__(self):
                self.incidents = []

            def incident(self, kind, source, detail=None):
                self.incidents.append((kind, source, detail))

        model = WedgeModel()
        eng = SlotEngine(model, None, max_seq=1 << 20, chunk=4)
        g = TensorGenerator("g")
        g._engine = eng
        pipe = FakePipe()
        g._pipeline = pipe
        eng.start()
        try:
            prompt = np.arange(3, dtype=np.int32)[None]
            eng.submit(TensorFrame([prompt]), prompt, 8, 4)
            deadline = time.monotonic() + 10
            while (model.prefill_compiles == 0
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            time.sleep(0.1)  # let the pump enter the wedged decode
            eng.heartbeat.stall_after_s = 0.05
            time.sleep(0.15)
            g.handle_idle()
            assert pipe.incidents and pipe.incidents[0][0] == "thread_stall"
            assert "slots" in pipe.incidents[0][2]
            g.handle_idle()
            assert len(pipe.incidents) == 1  # edge-triggered
            census = g.health_info()["threads"]
            row = census[eng.heartbeat.name]
            assert row["alive"] is True and row["stalls"] == 1
        finally:
            model.release.set()
            eng.stop()

    def test_named_thread_census_in_health(self):
        """Generator pump + filter window-reaper/staging-lane rows show
        up in Pipeline.health() under ``threads``."""
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_generator name=gen slots=1 "
            "custom=sim:1 max-new=4 chunk=2 ! tensor_sink name=out")
        pipe.start()
        try:
            prompt = np.arange(3, dtype=np.int32)[None]
            pipe["src"].push(TensorFrame([prompt]))
            deadline = time.monotonic() + 10
            while (len(pipe["out"].frames) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            h = pipe.health()["gen"]
            row = h["threads"]["gen-slots"]
            assert row["alive"] is True and row["beats"] > 0
            assert h["gen_resume_rejects"] == 0
        finally:
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            pipe.stop()
        fpipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=scaler "
            "custom=factor:2 name=f ! tensor_sink name=out")
        fpipe.start()
        try:
            threads = fpipe.health()["f"]["threads"]
            assert any(k.endswith("-reaper") for k in threads)
        finally:
            fpipe.stop()

    def test_census_helper_skips_none(self):
        hb = ThreadBeat("x")
        assert set(thread_census(None, hb)) == {"x"}

    def test_lane_beats_on_dequeue_after_idle(self):
        """The worker beats when it CLAIMS a job, not only at the loop
        top: after a long idle wait, a healthy first job must not show
        the stale-beat-while-busy wedge signature."""
        from nnstreamer_tpu.core.feed import HostStagingLane

        lane = HostStagingLane(lambda bufs: [b.copy() for b in bufs],
                               name="t")
        try:
            lane.submit([[np.zeros((2,), np.float32)]]).result()
            lane.heartbeat._last -= 100.0  # simulate a long idle
            lane.submit([[np.zeros((2,), np.float32)]]).result()
            deadline = time.monotonic() + 5
            while (lane.heartbeat.age_s() > 50
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert lane.heartbeat.age_s() < 50
        finally:
            lane.close()


# ---------------------------------------------------------------------------
# E2E over raw TCP: kill + drain with real servers (fused and unfused)
# ---------------------------------------------------------------------------
def _gen_server(sid, port=0, vocab=997, step_ms=3.0, max_new=48,
                name="server"):
    pipe = parse_pipeline(
        f"tensor_query_serversrc name=ssrc id={sid} port={port} "
        "connect-type=tcp ! "
        f"tensor_generator name=gen slots=4 "
        f"custom=sim:1,sim_step_ms:{step_ms},vocab:{vocab} "
        f"max-new={max_new} chunk=4 ! "
        f"tensor_query_serversink id={sid}", name=name)
    pipe.start()
    return pipe


class TestDurableStreamE2E:
    @pytest.mark.parametrize("fuse", [True, False],
                             ids=["fused", "unfused"])
    def test_kill_mid_stream_resumes_bit_exact(self, fuse):
        """Hard server kill mid-decode: the stream resumes on the
        second server, delivered tokens bit-identical to the sim
        oracle, exactly-once, with exact counters — fused AND unfused
        client dataplane."""
        s1 = _gen_server(9901, name="cont-s1")
        s2 = _gen_server(9902, name="cont-s2")
        p1 = s1["ssrc"].props["port"]
        p2 = s2["ssrc"].props["port"]
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q "
            f"connect-type=tcp hosts=localhost:{p1},localhost:{p2} "
            "stream=true timeout=60 retry-backoff=0.01 ! "
            "tensor_sink name=out", fuse=fuse, name=f"cli-fuse{fuse}")
        client.start()
        try:
            prompt = np.arange(6, dtype=np.int32)[None]
            client["src"].push(TensorFrame([prompt]))
            deadline = time.monotonic() + 30
            while (not client["out"].frames
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert client["out"].frames, "no chunk before the kill"
            s1.stop()  # hard kill mid-decode
            client["src"].end_of_stream()
            client.wait(timeout=60)
            frames = list(client["out"].frames)
            oracle = sim_oracle(997, prompt, 48)
            np.testing.assert_array_equal(_tokens(frames), oracle)
            assert [f.meta["chunk_index"] for f in frames] == list(
                range(len(frames)))
            h = client.health()["q"]
            assert h["stream_resumes"] == 1
            assert h["stream_migrations"] == 0
            assert h["resume_failures"] == 0
            srv_h = s2.health()["gen"]
            assert srv_h["gen_resumes"] == 1
        finally:
            client.stop()
            s1.stop()
            s2.stop()

    def test_rolling_drain_migrates_stream(self):
        """request_drain() on the serving host mid-decode: the stream
        is handed off as a resumable GOAWAY chunk and MIGRATES —
        bit-exact tokens, a migration (never a failure), zero breaker
        trips, and the drain completes with zero dropped frames."""
        s1 = _gen_server(9903, name="mig-s1")
        s2 = _gen_server(9904, name="mig-s2")
        p1 = s1["ssrc"].props["port"]
        p2 = s2["ssrc"].props["port"]
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q "
            f"connect-type=tcp hosts=localhost:{p1},localhost:{p2} "
            "stream=true timeout=60 retry-backoff=0.01 ! "
            "tensor_sink name=out", name="cli-mig")
        client.start()
        try:
            prompt = np.arange(5, dtype=np.int32)[None]
            client["src"].push(TensorFrame([prompt]))
            deadline = time.monotonic() + 30
            while (not client["out"].frames
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            res = s1.drain(timeout=15)
            assert res["dropped"] == 0
            client["src"].end_of_stream()
            client.wait(timeout=60)
            frames = list(client["out"].frames)
            oracle = sim_oracle(997, prompt, 48)
            np.testing.assert_array_equal(_tokens(frames), oracle)
            h = client.health()["q"]
            assert h["stream_migrations"] == 1
            assert h["stream_resumes"] == 0
            assert h["resume_failures"] == 0
            assert all(b["trips"] == 0
                       for b in h["breakers"].values())
            # the handoff's partial tokens were deduped exactly: total
            # received == delivered + duplicates (oracle pins delivered)
            assert h["duplicate_tokens_dropped"] >= 0
            srv_h = s2.health()["gen"]
            assert srv_h["gen_resumes"] == 1
        finally:
            client.stop()
            s1.stop()
            s2.stop()

    def test_resume_reject_on_mismatched_fleet(self):
        """The second server runs a DIFFERENT model config: it refuses
        the resume with a typed chunk (its other slots keep serving),
        and the client surfaces the failure after its budget."""
        s1 = _gen_server(9905, vocab=997, name="rej-s1")
        s2 = _gen_server(9906, vocab=499, name="rej-s2")  # mismatched
        p1 = s1["ssrc"].props["port"]
        p2 = s2["ssrc"].props["port"]
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q "
            f"connect-type=tcp hosts=localhost:{p1},localhost:{p2} "
            "stream=true timeout=20 retry-backoff=0.01 "
            "resume-retries=1 ! tensor_sink name=out", name="cli-rej")
        client.start()
        try:
            prompt = np.arange(4, dtype=np.int32)[None]
            client["src"].push(TensorFrame([prompt]))
            deadline = time.monotonic() + 30
            while (not client["out"].frames
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            s1.stop()  # kill: resume can only try the mismatched host
            client["src"].end_of_stream()
            with pytest.raises(Exception):
                client.wait(timeout=60)
            h = client.health()["q"]
            assert h["resume_failures"] >= 1
            assert s2.health()["gen"]["gen_resume_rejects"] >= 1
        finally:
            client.stop()
            s1.stop()
            s2.stop()


# ---------------------------------------------------------------------------
# The acceptance chaos e2e (tier-1, chaos-marked): 8 concurrent streams
# survive a hard kill AND a rolling restart mid-decode
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_generate_resume_chaos_smoke():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from tools.chaos_fleet import run_generate_resume_script

    v = run_generate_resume_script(servers=3, streams=8, seed=7)
    assert v["ok"], v
    # the acceptance contract, spelled out
    assert v["exact"] == 8 and v["mismatched"] == 0
    assert v["resumes"]["stream_resumes"] == 8
    assert v["resumes"]["stream_migrations"] == v["rolled_goaway_evicted"]
    assert v["rolled_goaway_evicted"] >= 1
    assert v["gen"]["gen_resumes"] == (
        v["resumes"]["stream_resumes"]
        + v["resumes"]["stream_migrations"])
    assert v["resumes"]["resume_failures"] == 0
    assert v["foreign_breaker_trips"] == 0
    assert v["rolling_restart"]["drain_dropped"] == 0
