"""Worker process for test_multihost.py: one simulated "host".

Initializes the multi-process runtime from NNS_TPU_* env vars, builds a
hybrid DCN×ICI mesh, runs a dp-across-hosts / tp-within-host sharded
train-ish step, and exercises the cross-process utilities.  Prints
RESULT <json> on success; any mismatch raises (nonzero exit)."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from nnstreamer_tpu.parallel import multihost  # noqa: E402


def main() -> None:
    # platform="cpu" must beat the container's sitecustomize (which pins
    # jax to the TPU tunnel); local device count comes from env
    multihost.initialize(platform="cpu")

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    nproc = multihost.process_count()
    pid = multihost.process_index()
    nlocal = jax.local_device_count()

    mesh = multihost.hybrid_mesh({"tp": 2, "sp": -1}, {"dp": nproc})
    assert mesh.shape["dp"] == nproc
    assert mesh.shape["tp"] == 2
    assert mesh.shape["sp"] == nlocal // 2

    # every process contributes its own slice of the global batch
    # (dp-sharded over hosts); weights are tp-sharded within a host
    d = 8
    local_batch = np.full((4, d), float(pid + 1), np.float32)
    x = multihost.global_array(mesh, P("dp", None), local_batch)
    w = jax.device_put(
        np.eye(d, dtype=np.float32),
        NamedSharding(mesh, P(None, "tp")),
    )

    @jax.jit
    def step(w, x):
        y = x @ w  # tp-sharded matmul: all-gather rides ICI
        return jnp.mean(y**2)  # mean over the global batch: psum over DCN

    loss = float(step(w, x))
    # oracle: mean over all processes' slices of value (pid+1)^2
    want = float(np.mean([(p + 1) ** 2 for p in range(nproc)]))
    assert abs(loss - want) < 1e-5, (loss, want)

    multihost.barrier("phase1")

    # broadcast: non-primary must observe primary's value
    blob = multihost.broadcast_from_primary(
        np.asarray([42.0 if pid == 0 else -1.0], np.float32)
    )
    assert float(np.asarray(blob)[0]) == 42.0

    assert multihost.all_processes_agree(np.asarray([d], np.int32))

    # gather: every host sees the full dp-sharded array
    full = multihost.gather_to_host(x)
    assert full.shape == (4 * nproc, d)
    for p in range(nproc):
        assert np.all(full[4 * p : 4 * (p + 1)] == p + 1)

    print(
        "RESULT "
        + json.dumps({
            "pid": pid,
            "nproc": nproc,
            "global_devices": jax.device_count(),
            "loss": loss,
            "primary": multihost.is_primary(),
        }),
        flush=True,
    )


if __name__ == "__main__":
    main()
