"""Fused-vs-unfused dataplane parity.

The streaming-thread fusion pass (pipeline/pipeline.py _compute_segments)
elides mailboxes and threads, but every PR-1/PR-2 contract must survive
unchanged: identical outputs, identical bus traffic, and EXACT health()
accounting (restarts, dead-letters, deadline_drops, qos-dropped) for the
policy truth tables run under chain fusion.  Each test here runs the same
pipeline twice — fuse=True and fuse=False — and byte-compares what the
application can observe.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core.liveness import DEADLINE_META
from nnstreamer_tpu.core.resilience import FAULTS
from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.elements.basic import AppSrc, TensorSink
from nnstreamer_tpu.pipeline import Pipeline, TransformElement, parse_pipeline
from nnstreamer_tpu.pipeline.element import SinkElement, element, make_element


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


class Pass(TransformElement):
    """Counting identity used as the supervision target."""

    FACTORY_NAME = "pass"

    def __init__(self, name=None):
        super().__init__(name)
        self.starts = 0

    def start(self):
        self.starts += 1

    def transform(self, frame):
        return frame


def _health_sig(pipe, name):
    h = pipe.health()[name]
    return {
        k: h[k]
        for k in ("state", "restarts", "dead_letters", "deadline_drops")
    }


def _bus_sig(messages):
    """Comparable bus fingerprint: (kind, source, policy-ish payload)."""
    out = []
    for m in messages:
        data = m.data if isinstance(m.data, dict) else {}
        out.append((
            m.kind, m.source,
            data.get("policy"), data.get("dropped"), data.get("restart"),
            data.get("liveness"),
        ))
    return out


def _run_policy(fuse, policy, n=9, site_kw=None, el_props=None,
                expect_error=None):
    pipe = Pipeline("par", fuse=fuse)
    src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
    mid.set_property("error-policy", policy)
    for k, v in (el_props or {}).items():
        mid.set_property(k, v)
    pipe.chain(src, mid, sink)
    messages = []
    pipe.add_bus_watcher(
        lambda m: messages.append(m) if m.kind in ("warning", "eos") else None
    )
    if site_kw:
        FAULTS.arm("element.mid.handle_frame", **site_kw)
    pipe.start()
    for i in range(n):
        src.push(np.float32([i]))
    src.end_of_stream()
    if expect_error is None:
        pipe.wait(timeout=30)
    else:
        with pytest.raises(expect_error):
            pipe.wait(timeout=30)
    vals = [float(f.tensors[0][0]) for f in sink.frames]
    sig = (_health_sig(pipe, "mid"), _bus_sig(messages), vals)
    pipe.stop()
    FAULTS.reset()
    return sig


class TestPolicyTruthTableParity:
    """The PR-1 error-policy truth table, fused vs unfused: outputs, bus
    warnings, and health counters must be identical."""

    def test_skip_accounting_identical(self):
        fused = _run_policy(
            True, "skip", site_kw=dict(every=3, exc=ConnectionResetError))
        unfused = _run_policy(
            False, "skip", site_kw=dict(every=3, exc=ConnectionResetError))
        assert fused == unfused
        assert fused[0]["dead_letters"] == 3 and len(fused[2]) == 6

    def test_restart_accounting_identical(self):
        kw = dict(every=4, times=2, exc=ConnectionResetError)
        props = {"restart-backoff": 0.0}
        fused = _run_policy(True, "restart", site_kw=kw, el_props=props)
        unfused = _run_policy(False, "restart", site_kw=kw, el_props=props)
        assert fused == unfused
        # zero frame loss, in order, and the restarts were really taken
        assert fused[2] == [float(i) for i in range(9)]
        assert fused[0]["restarts"] == 2

    def test_fail_stop_identical(self):
        kw = dict(after=2, exc=ConnectionResetError)  # poison = frame 2
        fused = _run_policy(
            True, "fail-stop", site_kw=kw, expect_error=ConnectionResetError)
        unfused = _run_policy(
            False, "fail-stop", site_kw=kw,
            expect_error=ConnectionResetError)
        assert fused[0] == unfused[0]  # health: state=failed, no drops
        assert fused[0]["state"] == "failed"
        # the fused dataplane is fully deterministic: frames before the
        # poison are delivered end-to-end before the teardown.  The
        # unfused plane can only promise a prefix — teardown may catch
        # already-processed frames still sitting in the sink's mailbox
        # (that in-flight loss window is exactly what fusion removes).
        assert fused[2] == [0.0, 1.0]
        assert fused[2][: len(unfused[2])] == unfused[2]

    def test_fatal_error_dead_letters_not_restarts(self):
        # fatal classification (bad input) must dead-letter under restart
        # policy in BOTH dataplanes, preserving the restart budget
        kw = dict(every=3, times=1, exc=ValueError)
        props = {"restart-backoff": 0.0}
        fused = _run_policy(True, "restart", site_kw=kw, el_props=props)
        unfused = _run_policy(False, "restart", site_kw=kw, el_props=props)
        assert fused == unfused
        assert fused[0]["restarts"] == 0 and fused[0]["dead_letters"] == 1


class TestDeadlineParity:
    """PR-2 deadline QoS: exact deadline_drops accounting under fusion."""

    def _run(self, fuse):
        pipe = Pipeline("dl", fuse=fuse)
        src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
        pipe.chain(src, mid, sink)
        pipe.start()
        # deterministic expiry: stamp absolute deadlines directly — 3 of 6
        # frames are already expired when pushed, so `mid` must drop
        # exactly those regardless of scheduling
        for i in range(6):
            f = TensorFrame([np.float32([i])])
            if i % 2:
                f.meta[DEADLINE_META] = time.monotonic() - 1.0
            else:
                f.meta[DEADLINE_META] = time.monotonic() + 60.0
            src.push(f)
        src.end_of_stream()
        pipe.wait(timeout=20)
        vals = [float(f.tensors[0][0]) for f in sink.frames]
        sig = (_health_sig(pipe, "mid"), vals)
        pipe.stop()
        return sig

    def test_deadline_drops_identical(self):
        fused, unfused = self._run(True), self._run(False)
        assert fused == unfused
        assert fused[0]["deadline_drops"] == 3
        assert fused[1] == [0.0, 2.0, 4.0]

    def test_late_policy_deliver_identical(self):
        def run(fuse):
            pipe = Pipeline("dl2", fuse=fuse)
            src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
            # every element on the path must opt in: each one runs its
            # own expiry check (the sink included)
            mid.set_property("late-policy", "deliver")
            sink.set_property("late-policy", "deliver")
            pipe.chain(src, mid, sink)
            pipe.start()
            f = TensorFrame([np.float32([7.0])])
            f.meta[DEADLINE_META] = time.monotonic() - 1.0
            src.push(f)
            src.end_of_stream()
            pipe.wait(timeout=20)
            sig = (_health_sig(pipe, "mid"), len(sink.frames))
            pipe.stop()
            return sig

        fused, unfused = run(True), run(False)
        assert fused == unfused
        assert fused[0]["deadline_drops"] == 0 and fused[1] == 1


class TestQosFeedbackParity:
    """Deadline misses throttle upstream tensor_rate (qos-dropped) the
    same way in both dataplanes.  Pushes are serialized (one frame fully
    drains before the next enters) so the feedback ordering — racy in a
    free-running pipeline — is deterministic in BOTH modes."""

    def _run(self, fuse):
        pipe = Pipeline("qos", fuse=fuse)
        src = AppSrc("src")
        rate = make_element("tensor_rate", name="rate")
        # rate must pass expired frames THROUGH (late-policy=deliver) so
        # the deadline drop happens downstream at `mid` — that drop's
        # feedback is what throttles rate (shedding earlier, where it's
        # cheapest, is the whole point of the QoS loop)
        rate.set_property("late-policy", "deliver")
        mid, sink = Pass("mid"), TensorSink("out")
        pipe.chain(src, rate, mid, sink)
        pipe.start()
        delivered = {"n": 0}
        sink.connect_new_data(
            lambda f: delivered.__setitem__("n", delivered["n"] + 1))

        def push_and_drain(frame, expect_delivery):
            before = delivered["n"]
            drops_before = pipe.health()["mid"]["deadline_drops"]
            rate_in = rate.in_frames
            src.push(frame)
            deadline = time.time() + 10
            while time.time() < deadline:
                if expect_delivery and delivered["n"] > before:
                    return
                if not expect_delivery and (
                        pipe.health()["mid"]["deadline_drops"] > drops_before
                        or rate.in_frames > rate_in and rate.qos_dropped):
                    # dropped at mid (deadline) or shed at rate (QoS)
                    return
                time.sleep(0.005)
            raise AssertionError("frame neither delivered nor dropped")

        # frame 0: healthy, pts=0.0
        f0 = TensorFrame([np.float32([0])], pts=0.0)
        f0.meta[DEADLINE_META] = time.monotonic() + 60.0
        push_and_drain(f0, True)
        # frame 1: pts=1.0, expired 0.5s ago -> mid drops it, feedback
        # tells rate to shed up to pts 1.0 + lateness
        f1 = TensorFrame([np.float32([1])], pts=1.0)
        f1.meta[DEADLINE_META] = time.monotonic() - 0.5
        push_and_drain(f1, False)
        # frame 2: pts=1.2, inside the shed window -> rate qos-drops it
        f2 = TensorFrame([np.float32([2])], pts=1.2)
        f2.meta[DEADLINE_META] = time.monotonic() + 60.0
        push_and_drain(f2, False)
        # frame 3: pts far beyond the window -> flows
        f3 = TensorFrame([np.float32([3])], pts=99.0)
        f3.meta[DEADLINE_META] = time.monotonic() + 60.0
        push_and_drain(f3, True)
        src.end_of_stream()
        pipe.wait(timeout=20)
        sig = (
            _health_sig(pipe, "mid"),
            rate.qos_dropped,
            [float(f.tensors[0][0]) for f in sink.frames],
        )
        pipe.stop()
        return sig

    def test_qos_dropped_identical(self):
        fused, unfused = self._run(True), self._run(False)
        assert fused == unfused
        assert fused[0]["deadline_drops"] == 1
        assert fused[1] == 1  # exactly one frame shed at the throttle
        assert fused[2] == [0.0, 3.0]


class TestWatchdogParity:
    """PR-2 stall watchdog under fusion: a hang inside a fused element is
    detected, cooperatively interrupted, and restarted with zero loss —
    same counters as the unfused run."""

    def _run(self, fuse):
        FAULTS.arm("element.mid.handle_frame", every=3, times=1, hang=True)
        pipe = Pipeline("wd", fuse=fuse)
        src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
        mid.set_property("frame-deadline", 0.12)
        mid.set_property("stall-policy", "restart")
        mid.set_property("restart-backoff", 0.01)
        pipe.chain(src, mid, sink)
        pipe.start()
        n = 8
        for i in range(n):
            src.push(np.float32([i]))
        src.end_of_stream()
        pipe.wait(timeout=30)
        h = pipe.health()["mid"]
        sig = (
            {k: h[k] for k in ("state", "restarts", "overruns")},
            [float(f.tensors[0][0]) for f in sink.frames],
        )
        pipe.stop()
        FAULTS.reset()
        return sig

    def test_hang_restart_zero_loss_identical(self):
        fused, unfused = self._run(True), self._run(False)
        assert fused == unfused
        assert fused[0] == {"state": "finished", "restarts": 1, "overruns": 1}
        assert fused[1] == [float(i) for i in range(8)]


class TestSegmentation:
    """The fusion pass itself: boundary rules produce the expected thread
    partition."""

    @staticmethod
    def _segs(pipe):
        pipe.start()
        try:
            return [
                [e.name for e in seg.chain] for seg in pipe._segments
            ]
        finally:
            pipe.stop()

    def test_linear_chain_one_thread(self):
        pipe = parse_pipeline(
            "videotestsrc name=a num-buffers=1 ! identity name=b ! "
            "identity name=c ! tensor_sink name=d")
        assert self._segs(pipe) == [["a", "b", "c", "d"]]

    def test_queue_is_a_boundary(self):
        pipe = parse_pipeline(
            "videotestsrc name=a num-buffers=1 ! identity name=b ! "
            "queue name=q ! tensor_sink name=d")
        assert self._segs(pipe) == [["a", "b"], ["q", "d"]]

    def test_tee_branches_keep_threads(self):
        pipe = parse_pipeline(
            "videotestsrc name=a num-buffers=1 ! tee name=t "
            "t. ! tensor_sink name=x  t. ! tensor_sink name=y")
        segs = self._segs(pipe)
        assert ["a", "t"] in segs and ["x"] in segs and ["y"] in segs

    def test_micro_batcher_keeps_boundaries(self):
        # a preferred_batch>1 element must keep its mailbox (to drain
        # batches) and its downstream boundary (to overlap invoke/decode)
        from nnstreamer_tpu.backends.jax_xla import register_jax_model

        def fn(params, xs):
            return [xs[0]]

        register_jax_model("parity_id", fn, {})
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=jax-xla "
            "model=parity_id max-batch=4 ! tensor_sink name=out")
        segs = self._segs(pipe)
        assert ["f"] in segs  # the batcher is alone on its thread

    def test_fuse_false_gives_seed_partition(self):
        pipe = parse_pipeline(
            "videotestsrc name=a num-buffers=1 ! identity name=b ! "
            "tensor_sink name=c", fuse=False)
        assert sorted(self._segs(pipe)) == [["a"], ["b"], ["c"]]


# ---------------------------------------------------------------------------
# Async device feed (completion-driven dispatch window, core/feed.py):
# every supervision contract over the DEEPER window, fused vs unfused.
# ---------------------------------------------------------------------------
@element("fp_gate_sink")
class FpGateSink(SinkElement):
    """Renders only as many frames as the test releases (deterministic
    in-flight population for exact drain/stop accounting); an interrupted
    wait raises so the frame counts as NOT delivered."""

    def __init__(self, name=None):
        super().__init__(name)
        self.sema = threading.Semaphore(0)
        self.got: list = []

    def render(self, frame):
        while not self.sema.acquire(timeout=0.02):
            if self.interrupted:
                raise RuntimeError("gate interrupted before delivery")
        self.got.append(float(np.asarray(frame.tensors[0]).ravel()[0]))


def _window_pipe(fuse, depth, custom="compute_ms:3,transfer_ms:1",
                 sink="tensor_sink name=out", name="awin"):
    return parse_pipeline(
        "appsrc name=src max-buffers=256 ! "
        "tensor_filter name=f framework=async-sim "
        f"custom={custom} max-batch=4 dispatch-depth={depth} "
        f"ingest-lane=off ! {sink}",
        fuse=fuse, name=name,
    )


def _sink_bytes(pipe):
    """Byte-exact emission fingerprint, in delivery order."""
    return [
        np.ascontiguousarray(np.asarray(f.tensors[0])).tobytes()
        for f in pipe["out"].frames
    ]


class TestAsyncWindowParity:
    """The completion-driven dispatch window (PR-6): FIFO emission order
    byte-identical fused vs unfused at depths {1, 4, 8}, with the
    dispatch thread never blocking inside a device_get-style sync for
    depth > 1 (the reaper thread owns every pre-completion wait)."""

    def _run_fifo(self, fuse, depth, n=24):
        pipe = _window_pipe(fuse, depth)
        pipe.start()
        for i in range(n):
            pipe["src"].push(np.float32([i]))
        pipe["src"].end_of_stream()
        be = pipe["f"].backend
        pipe.wait(timeout=30)
        sig = (_sink_bytes(pipe), _health_sig(pipe, "f"))
        foreign = [
            t for t in be.blocking_syncs if not t.endswith("-reaper")
        ]
        pipe.stop()
        return sig, foreign

    @pytest.mark.parametrize("depth", [1, 4, 8])
    def test_fifo_emission_byte_identical(self, depth):
        fused, f_foreign = self._run_fifo(True, depth)
        unfused, u_foreign = self._run_fifo(False, depth)
        assert fused == unfused
        want = [
            np.float32([2.0 * i + 1.0]).tobytes() for i in range(24)
        ]
        assert fused[0] == want  # strict FIFO, byte-exact values
        if depth > 1:
            # the async window's structural claim: every pre-completion
            # device sync happened on the window's reaper thread
            assert f_foreign == [] and u_foreign == []

    def test_deadline_drops_identical_over_window(self):
        """PR-2 deadline QoS over the parked window: already-expired
        frames are dropped pre-dispatch with exact accounting, live
        frames flow FIFO — identical fused and unfused."""
        def run(fuse):
            pipe = _window_pipe(fuse, 8)
            pipe.start()
            for i in range(6):
                f = TensorFrame([np.float32([i])])
                f.meta[DEADLINE_META] = (
                    time.monotonic() + (60.0 if i % 2 == 0 else -1.0))
                pipe["src"].push(f)
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            sig = (_sink_bytes(pipe), _health_sig(pipe, "f"))
            pipe.stop()
            return sig

        fused, unfused = run(True), run(False)
        assert fused == unfused
        assert fused[1]["deadline_drops"] == 3
        assert fused[0] == [
            np.float32([2.0 * i + 1.0]).tobytes() for i in (0, 2, 4)
        ]

    @pytest.mark.parametrize("fuse", [True, False])
    def test_drain_flushes_deep_window_zero_loss(self, fuse):
        """Pipeline.drain() over a depth-8 window with slow compute:
        every parked batch lands at the sink in order, zero dropped."""
        pipe = _window_pipe(fuse, 8, custom="compute_ms:15,transfer_ms:2")
        pipe.start()
        for i in range(16):
            pipe["src"].push(np.float32([i]))
        r = pipe.drain(timeout=20)
        assert r["dropped"] == 0
        assert pipe.delivered_frames() == 16
        assert _sink_bytes(pipe) == [
            np.float32([2.0 * i + 1.0]).tobytes() for i in range(16)
        ]
        pipe.stop()

    @pytest.mark.parametrize("fuse", [True, False])
    def test_drain_deadline_exact_dropped_over_window(self, fuse):
        """An expired drain accounts every undelivered frame exactly —
        whether it sat in a mailbox, the parked window, or mid-call —
        over the async feed: 12 pushed = 4 delivered + 8 dropped."""
        pipe = _window_pipe(
            fuse, 8, custom="compute_ms:2,transfer_ms:1",
            sink="fp_gate_sink name=out")
        pipe.start()
        pipe["out"].sema.release(4)
        for i in range(12):
            pipe["src"].push(np.float32([i]))
        deadline = time.monotonic() + 10
        while len(pipe["out"].got) < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(pipe["out"].got) == 4
        r = pipe.drain(timeout=0.6)
        assert r["dropped"] == 8  # exact: 12 pushed - 4 delivered
        assert pipe["out"].got == [2.0 * i + 1.0 for i in range(4)]
        pipe.stop()

    def test_hot_swap_at_window_boundary_identical(self):
        """PR-5 hot swap over the deeper window: the swap applies at a
        frame boundary strictly after the in-flight window drains — every
        pre-swap frame is served by the old model, every post-swap frame
        by the new one, byte-identical fused vs unfused."""
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model, unregister_jax_model)

        register_jax_model("fp_m1", lambda p, xs: [xs[0] * 2.0], None)
        register_jax_model("fp_m2", lambda p, xs: [xs[0] * 3.0], None)

        def run(fuse):
            pipe = parse_pipeline(
                "appsrc name=src max-buffers=256 ! "
                "tensor_filter name=f framework=jax-xla model=fp_m1 "
                "is-updatable=true max-batch=4 dispatch-depth=8 "
                "ingest-lane=off ! tensor_sink name=out",
                fuse=fuse, name="swapwin",
            )
            pipe.start()
            for i in range(8):
                pipe["src"].push(np.float32([i]))
            deadline = time.monotonic() + 15
            while (len(pipe["out"].frames) < 8
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert len(pipe["out"].frames) == 8  # old model served all
            ticket = pipe.reload_model("f", "fp_m2")
            assert ticket.wait_applied(timeout=15)
            for i in range(8, 16):
                pipe["src"].push(np.float32([i]))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            h = pipe["f"].health_info()
            sig = (
                _sink_bytes(pipe),
                {k: h[k] for k in ("swaps", "rollbacks", "model_version")},
            )
            pipe.stop()
            return sig

        try:
            fused, unfused = run(True), run(False)
        finally:
            unregister_jax_model("fp_m1")
            unregister_jax_model("fp_m2")
        assert fused == unfused
        want = [
            np.float32([2.0 * i]).tobytes() for i in range(8)
        ] + [
            np.float32([3.0 * i]).tobytes() for i in range(8, 16)
        ]
        assert fused[0] == want
        assert fused[1] == {"swaps": 1, "rollbacks": 0, "model_version": 1}
