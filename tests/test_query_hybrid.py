"""Hybrid discovery for tensor_query (≙ reference connect-type=HYBRID:
MQTT control plane announces endpoints, data flows directly).

Servers publish retained announces under nns/query/<topic>/<instance>;
clients resolve the server set from the broker instead of static
host:port — pod membership changes on the broker, not in pipeline text.
"""

import time

import numpy as np
import pytest

from nnstreamer_tpu.backends.custom_easy import (
    register_custom_easy,
    unregister_custom_easy,
)
from nnstreamer_tpu.distributed.mqtt import MiniBroker
from nnstreamer_tpu.pipeline import parse_pipeline
from nnstreamer_tpu.pipeline.element import ElementError, make_element


@pytest.fixture
def broker():
    b = MiniBroker()
    yield b
    b.close()


def _server(broker, i, topic="pods"):
    sp = parse_pipeline(
        f"tensor_query_serversrc name=src id={10 + i} port=0 "
        f"connect-type=tcp topic={topic} dest-host=127.0.0.1 "
        f"dest-port={broker.port} ! "
        "tensor_filter framework=custom-easy model=qh_double ! "
        f"tensor_query_serversink id={10 + i}",
        name=f"qh-server-{i}",
    )
    sp.start()
    return sp


class TestHybridDiscovery:
    def test_client_discovers_and_round_robins_two_servers(self, broker):
        register_custom_easy(
            "qh_double", lambda xs: [np.asarray(xs[0]) * 2.0]
        )
        servers = []
        try:
            servers = [_server(broker, i) for i in range(2)]
            client = parse_pipeline(
                "appsrc name=a ! "
                f"tensor_query_client name=q topic=pods dest-host=127.0.0.1 "
                f"dest-port={broker.port} discovery-timeout=10 "
                "connect-type=tcp timeout=30 ! "
                "tensor_sink name=out",
                name="qh-client",
            )
            client.start()
            # both endpoints resolved from the broker
            assert len(client["q"]._conns) == 2
            for i in range(8):
                client["a"].push(np.full((4,), float(i), np.float32))
            client["a"].end_of_stream()
            client.wait(timeout=60)
            got = [
                np.asarray(f.tensors[0]) for f in client["out"].frames
            ]
            client.stop()
            assert len(got) == 8
            for i, arr in enumerate(got):
                assert np.allclose(arr, 2.0 * i), (i, arr)
        finally:
            for sp in servers:
                sp.stop()
            unregister_custom_easy("qh_double")

    def test_stopped_server_clears_retained_announce(self, broker):
        register_custom_easy(
            "qh_double", lambda xs: [np.asarray(xs[0]) * 2.0]
        )
        try:
            sp = _server(broker, 7, topic="ephemeral")
            sp.stop()
            # tombstoned: discovery must now time out, not dial the dead port
            el = make_element(
                "tensor_query_client",
                **{"topic": "ephemeral", "dest-host": "127.0.0.1",
                   "dest-port": broker.port, "discovery-timeout": 1.0,
                   "connect-type": "tcp"},
            )
            with pytest.raises(ElementError, match="server announced"):
                el.start()
        finally:
            unregister_custom_easy("qh_double")

    def test_discovery_timeout_without_broker_announces(self, broker):
        el = make_element(
            "tensor_query_client",
            **{"topic": "nobody-home", "dest-host": "127.0.0.1",
               "dest-port": broker.port, "discovery-timeout": 0.5},
        )
        t0 = time.monotonic()
        with pytest.raises(ElementError, match="server announced"):
            el.start()
        assert time.monotonic() - t0 < 5.0

    def test_stale_announce_from_crashed_server_skipped(self, broker):
        """A crashed server never tombstones its retained announce; the
        client's liveness probe must drop it and use the live server."""
        import json

        from nnstreamer_tpu.distributed.mqtt import MqttClient

        register_custom_easy(
            "qh_double", lambda xs: [np.asarray(xs[0]) * 2.0]
        )
        servers = []
        try:
            # fake crash leftover: retained announce for a port nobody owns
            c = MqttClient("127.0.0.1", broker.port)
            c.publish(
                "nns/query/mixed/crashed-1",
                json.dumps({"host": "127.0.0.1", "port": 1,
                            "connect_type": "tcp"}).encode(),
                retain=True, qos=1,
            )
            assert c.drain(5.0) == 0
            c.close()
            servers = [_server(broker, 5, topic="mixed")]
            el = make_element(
                "tensor_query_client",
                **{"topic": "mixed", "dest-host": "127.0.0.1",
                   "dest-port": broker.port, "discovery-timeout": 5.0,
                   "connect-type": "tcp"},
            )
            el.start()
            try:
                assert len(el._conns) == 1  # only the live server
            finally:
                el.stop()
        finally:
            for sp in servers:
                sp.stop()
            unregister_custom_easy("qh_double")

    def test_elastic_rediscovery_after_pod_replacement(self, broker):
        """The pod's only server dies and a REPLACEMENT (different port)
        announces on the same topic: a topic-mode client must refresh
        from the broker mid-stream and deliver on the new server —
        elastic recovery as a broker-membership change.

        retries=1 opts into at-least-once: a request that died with the
        old server cannot be PROVEN un-ingested (socket closed mid-
        receive), so re-execution on the new pod requires the same opt-in
        as ordinary failover.  Without it the topology still refreshes,
        but the in-flight request surfaces its error."""
        register_custom_easy(
            "qh_double", lambda xs: [np.asarray(xs[0]) * 2.0]
        )
        old = new = None
        try:
            old = _server(broker, 30, topic="elastic")
            client = parse_pipeline(
                "appsrc name=a ! "
                f"tensor_query_client name=q topic=elastic retries=1 "
                f"dest-host=127.0.0.1 dest-port={broker.port} "
                "discovery-timeout=10 connect-type=tcp timeout=5 ! "
                "tensor_sink name=out",
                name="qh-elastic",
            )
            client.start()
            client["a"].push(np.full((4,), 1.0, np.float32))
            deadline = time.monotonic() + 30
            while (
                len(client["out"].frames) < 1
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert len(client["out"].frames) == 1
            # pod replacement: old dies (tombstoned), new announces
            old.stop()
            old = None
            new = _server(broker, 31, topic="elastic")
            client["a"].push(np.full((4,), 3.0, np.float32))
            client["a"].end_of_stream()
            client.wait(timeout=60)
            got = [np.asarray(f.tensors[0]) for f in client["out"].frames]
            client.stop()
            assert len(got) == 2, got
            assert np.allclose(got[1], 6.0)
        finally:
            for sp in (old, new):
                if sp is not None:
                    sp.stop()
            unregister_custom_easy("qh_double")

    def test_connect_type_mismatch_announces_skipped(self, broker):
        register_custom_easy(
            "qh_double", lambda xs: [np.asarray(xs[0]) * 2.0]
        )
        servers = []
        try:
            servers = [_server(broker, 3, topic="tcponly")]  # announces tcp
            el = make_element(
                "tensor_query_client",
                **{"topic": "tcponly", "dest-host": "127.0.0.1",
                   "dest-port": broker.port, "discovery-timeout": 1.0,
                   "connect-type": "grpc"},
            )
            with pytest.raises(ElementError, match="server announced"):
                el.start()
        finally:
            for sp in servers:
                sp.stop()
            unregister_custom_easy("qh_double")
