"""tensor_generator: streaming KV-cache decoding through a pipeline.

Oracle: the streamed chunk concatenation must be BIT-EQUAL to the
one-shot ``generate:<N>`` path (same params seed, same sampling seed,
same per-step key folding) — streaming is a transport change, never a
sampling change.
"""

import numpy as np
import pytest

from nnstreamer_tpu.models import build
from nnstreamer_tpu.pipeline import parse_pipeline

PROPS = {
    "dtype": "float32", "vocab": 61, "d_model": 32, "heads": 2,
    "layers": 2, "d_ff": 64, "seq": 64, "seed": 11,
}
CUSTOM = ",".join(f"{k}:{v}" for k, v in PROPS.items())


def _oneshot(prompt, n):
    fn, params, _, _ = build(
        "transformer", {**PROPS, "generate": str(n)}
    )
    out = np.asarray(fn(params, [prompt])[0])
    return out[:, prompt.shape[1]:]


def _run_stream(prompt, n, chunk, extra_custom=""):
    custom = CUSTOM + ("," + extra_custom if extra_custom else "")
    pipe = parse_pipeline(
        f"appsrc name=src ! tensor_generator custom={custom} "
        f"max-new={n} chunk={chunk} ! tensor_sink name=out"
    )
    pipe.start()
    pipe["src"].push(prompt)
    pipe["src"].end_of_stream()
    pipe.wait(timeout=120)
    frames = pipe["out"].frames
    pipe.stop()
    return frames


class TestStreamingGeneration:
    def test_chunks_equal_oneshot_tokens(self, rng):
        prompt = rng.integers(0, PROPS["vocab"], (1, 7)).astype(np.int32)
        n, chunk = 13, 4
        frames = _run_stream(prompt, n, chunk)
        # ceil((n - 1 prefill-token rounds into chunks)): emission sizes
        # are chunk-aligned with one tail
        toks = np.concatenate([np.asarray(f.tensors[0]) for f in frames],
                              axis=1)
        want = _oneshot(prompt, n)
        np.testing.assert_array_equal(toks, want)
        # chunk metadata is coherent and ordered
        assert [f.meta["chunk_index"] for f in frames] == list(
            range(len(frames))
        )
        assert frames[-1].meta["final"] is True
        assert all(f.meta["final"] is False for f in frames[:-1])
        assert frames[-1].meta["tokens_done"] == n
        assert all(f.meta["stream_seq"] is not None for f in frames)
        assert len(frames) == -(-n // chunk)

    @pytest.mark.slow  # tier-1 budget: ~12s extra (3,T) compile of the same
    # stream-vs-oneshot parity; chunks_equal_oneshot_tokens stays tier-1
    def test_batched_prompts(self, rng):
        prompt = rng.integers(0, PROPS["vocab"], (3, 5)).astype(np.int32)
        n, chunk = 8, 3
        frames = _run_stream(prompt, n, chunk)
        toks = np.concatenate([np.asarray(f.tensors[0]) for f in frames],
                              axis=1)
        assert toks.shape == (3, n)
        np.testing.assert_array_equal(toks, _oneshot(prompt, n))

    @pytest.mark.slow  # tier-1 budget: ~18s; seeded stream-vs-oneshot parity
    # stays tier-1 on the slotted engine (test_sampling_parity_slotted) and
    # the seeded prefix warm-hit pin, both of which run this sampler
    def test_sampling_stream_matches_oneshot(self, rng):
        """temperature/top-k sampling: per-step key folding must line up
        across the chunk boundaries (gen_seed dialect)."""
        prompt = rng.integers(0, PROPS["vocab"], (1, 4)).astype(np.int32)
        n = 9
        fn, params, _, _ = build(
            "transformer",
            {**PROPS, "generate": str(n), "temperature": "0.8",
             "top_k": "7", "gen_seed": "3"},
        )
        want = np.asarray(fn(params, [prompt])[0])[:, prompt.shape[1]:]
        frames = _run_stream(
            prompt, n, 4, "temperature:0.8,top_k:7,gen_seed:3"
        )
        toks = np.concatenate([np.asarray(f.tensors[0]) for f in frames],
                              axis=1)
        np.testing.assert_array_equal(toks, want)

    def test_detokenizer_streams_text(self, rng):
        """Full streaming-serving pipeline: generator -> detokenizer ->
        sink; each chunk arrives as text."""
        prompt = rng.integers(0, 61, (1, 4)).astype(np.int32)
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_generator custom={CUSTOM} "
            "max-new=6 chunk=2 ! tensor_decoder mode=detokenizer ! "
            "tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(prompt)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=120)
        frames = pipe["out"].frames
        pipe.stop()
        assert len(frames) == 3
        assert all(isinstance(f.meta.get("text"), str) for f in frames)
        assert frames[-1].meta["final"] is True
        text = "".join(f.meta["text"] for f in frames)
        want = _oneshot(prompt, 6).ravel()
        want_text = bytes(
            int(t) if 0 <= t < 256 else ord("?") for t in want
        ).decode("utf-8", errors="replace")
        assert text == want_text

    def test_max_new_zero_emits_nothing(self, rng):
        prompt = rng.integers(0, PROPS["vocab"], (1, 4)).astype(np.int32)
        frames = _run_stream(prompt, 0, 4)
        assert frames == []

    @pytest.mark.slow  # tier-1 budget: ~16s; block-splitting order/parity
    # stays tier-1 via the slotted block-split test, which exercises the
    # same prompt-block fan-out on the serving engine
    def test_block_of_prompts_streams_in_order(self, rng):
        """A BatchFrame of prompts: each logical prompt streams its own
        chunk sequence, in prompt order (lazy chain, BATCH_AWARE)."""
        prompts = rng.integers(0, PROPS["vocab"], (2, 5)).astype(np.int32)
        n, chunk = 6, 4
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_generator custom={CUSTOM} "
            f"max-new={n} chunk={chunk} ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push_block(prompts)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=120)
        frames = pipe["out"].frames
        pipe.stop()
        # 2 prompts x ceil(6/4) = 2 chunks each, grouped by stream_seq
        assert len(frames) == 4
        seqs = [f.meta["stream_seq"] for f in frames]
        assert seqs[0] == seqs[1] and seqs[2] == seqs[3]
        assert seqs[0] != seqs[2]
        for j in range(2):
            toks = np.concatenate(
                [np.asarray(f.tensors[0]) for f in frames[2 * j:2 * j + 2]],
                axis=1,
            )
            np.testing.assert_array_equal(
                toks, _oneshot(prompts[j:j + 1], n)
            )

    def test_overrun_fails_loud(self, rng):
        """prompt + max-new beyond the model's seq must error, not stream
        corrupt tokens (cache ring wrap / pos_embed overflow)."""
        prompt = rng.integers(0, PROPS["vocab"], (1, 60)).astype(np.int32)
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_generator custom={CUSTOM} "
            "max-new=32 chunk=8 ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(prompt)
        pipe["src"].end_of_stream()
        with pytest.raises(Exception, match="exceeds the model's seq"):
            pipe.wait(timeout=60)
        pipe.stop()



class TestRemoteStreaming:
    """Streaming generation across the query data plane: a generator
    server pipeline streams chunk frames back over ONE server-streaming
    RPC; the client emits them as they arrive."""

    def test_remote_stream_roundtrip(self, rng):
        n, chunk = 10, 4
        server = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id=701 port=0 ! "
            f"tensor_generator custom={CUSTOM} max-new={n} chunk={chunk} ! "
            f"tensor_query_serversink id=701"
        )
        server.start()
        port = server["ssrc"].props["port"]
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "stream=true timeout=120 ! tensor_sink name=out"
            )
            client.start()
            prompt = rng.integers(0, PROPS["vocab"], (1, 6)).astype(np.int32)
            client["src"].push(prompt)
            client["src"].end_of_stream()
            client.wait(timeout=180)
            frames = client["out"].frames
            client.stop()
            assert len(frames) == -(-n // chunk)
            assert [f.meta["chunk_index"] for f in frames] == list(
                range(len(frames))
            )
            assert frames[-1].meta["final"] is True
            toks = np.concatenate(
                [np.asarray(f.tensors[0]) for f in frames], axis=1
            )
            np.testing.assert_array_equal(toks, _oneshot(prompt, n))
        finally:
            server.stop()

    def test_stream_with_plain_filter_server(self, rng):
        """A non-streaming server graph under stream=true: exactly one
        answer per request (absent final meta closes the stream)."""
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model, unregister_jax_model)

        register_jax_model("qstream_aff", lambda p, xs: [xs[0] * 2.0], None)
        try:
            server = parse_pipeline(
                "tensor_query_serversrc name=ssrc id=702 port=0 ! "
                "tensor_filter framework=jax-xla model=qstream_aff ! "
                "tensor_query_serversink id=702"
            )
            server.start()
            port = server["ssrc"].props["port"]
            try:
                client = parse_pipeline(
                    f"appsrc name=src ! tensor_query_client port={port} "
                    "stream=true ! tensor_sink name=out"
                )
                client.start()
                for i in range(4):
                    client["src"].push(np.float32([i]))
                client["src"].end_of_stream()
                client.wait(timeout=60)
                frames = client["out"].frames
                client.stop()
                vals = [float(f.tensors[0][0]) for f in frames]
                assert vals == [0.0, 2.0, 4.0, 6.0]
            finally:
                server.stop()
        finally:
            unregister_jax_model("qstream_aff")

    def test_stream_rejects_bad_config(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_query_client port=1 stream=true "
            "wire-batch=4 ! tensor_sink name=out"
        )
        with pytest.raises(Exception, match="wire-batch"):
            pipe.start()
        pipe.stop()
