"""Zero-downtime operations (core/lifecycle.py): validated hot model
swap with rollback, graceful pipeline drain, and the Pipeline.stop()
in-flight contract.

Acceptance contracts pinned here:

* a failed or faulted hot swap (load-fail, warmup-fail, validate-fail,
  post-swap error burst) never drops a frame and never consumes the
  supervisor's restart budget — ``swap_failures``/``rollbacks`` account
  exactly;
* ``Pipeline.drain(timeout)`` flushes all in-flight frames with
  identical accounting fused and unfused;
* immediate ``stop()`` drops exactly the frames that had not reached the
  sink; ``drain()`` flushes them.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.backends.base import FilterBackend, register_backend
from nnstreamer_tpu.core.lifecycle import ServerGoawayError
from nnstreamer_tpu.backends.jax_xla import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.core.buffer import CustomEvent
from nnstreamer_tpu.core.resilience import FAULTS
from nnstreamer_tpu.core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from nnstreamer_tpu.pipeline import parse_pipeline
from nnstreamer_tpu.pipeline.element import (
    ElementError,
    SinkElement,
    element,
)


@pytest.fixture(scope="module", autouse=True)
def _leaks(module_leak_check):
    """Drain and swap must not strand workers or sockets (tier-1 gate)."""
    yield


@pytest.fixture(autouse=True)
def _faults_reset():
    yield
    FAULTS.reset()


# ---------------------------------------------------------------------------
# Deterministic updatable backend: model string "f:<factor>" scales the
# input; variants exercise slow opens and reload failures.
# ---------------------------------------------------------------------------
class RecBackend(FilterBackend):
    NAME = "lc-rec"
    INSTANCES: list = []

    def __init__(self):
        super().__init__()
        self.closed = False
        self.factor = 2.0
        RecBackend.INSTANCES.append(self)

    def framework_info(self):
        info = super().framework_info()
        info.run_without_model = True
        info.verify_model_path = False
        return info

    def open(self, model, props):
        super().open(model, props)
        model = model or ""
        if model.startswith("slow"):
            time.sleep(0.4)
        if model.startswith("explode-open"):
            raise RuntimeError("bad model artifact")
        if ":" in model:
            self.factor = float(model.split(":", 1)[1])

    def reload(self, model):
        if "explode" in (model or ""):
            raise RuntimeError("reload blew up")
        if ":" in (model or ""):
            self.factor = float(model.split(":", 1)[1])
        self.model_path = model

    def set_input_info(self, in_spec):
        return in_spec

    def invoke(self, inputs):
        return [np.asarray(a, np.float32) * self.factor for a in inputs]

    def close(self):
        self.closed = True


register_backend(RecBackend)


# ---------------------------------------------------------------------------
# Gate sink: renders only as many frames as the test releases; gives the
# stop()/drain() contract a deterministic in-flight population.  An
# interrupted wait raises (the frame was NOT delivered) so the drained /
# dropped accounting stays exact.
# ---------------------------------------------------------------------------
@element("lc_gate_sink")
class GateSink(SinkElement):
    def __init__(self, name=None):
        super().__init__(name)
        self.sema = threading.Semaphore(0)
        self.got: list = []

    def render(self, frame):
        while not self.sema.acquire(timeout=0.02):
            if self.interrupted:
                raise RuntimeError("gate interrupted before delivery")
        self.got.append(float(np.asarray(frame.tensors[0]).ravel()[0]))


def _swap_pipe(model="f:2", extra=""):
    pipe = parse_pipeline(
        f"appsrc name=src ! tensor_filter name=f framework=lc-rec "
        f"model={model} is-updatable=true {extra}! tensor_sink name=out"
    )
    pipe.start()
    return pipe


def _outs(pipe):
    return [float(f.tensors[0][0]) for f in pipe["out"].frames]


def _wait_outs(pipe, n, timeout=10.0):
    """Barrier: the sink has received >= n frames.  Needed before a
    reload request when the test wants those frames served by the OLD
    model — the swap contract is 'next frame boundary after staging',
    which says nothing about frames still queued upstream."""
    deadline = time.monotonic() + timeout
    while len(pipe["out"].frames) < n and time.monotonic() < deadline:
        time.sleep(0.005)
    assert len(pipe["out"].frames) >= n, (
        f"sink saw {len(pipe['out'].frames)}/{n} frames in {timeout}s")


class TestHotSwap:
    def test_staged_swap_switches_at_frame_boundary(self):
        pipe = _swap_pipe()
        try:
            for i in range(3):
                pipe["src"].push(np.float32([i]))
            _wait_outs(pipe, 3)  # old model must have served these
            ticket = pipe.reload_model("f", "f:3")
            assert ticket.wait_staged(5) and ticket.ok, ticket.error
            for i in range(3, 6):
                pipe["src"].push(np.float32([i]))
            assert ticket.wait_applied(5)
            pipe["src"].end_of_stream()
            pipe.wait(10)
            h = pipe.health()["f"]
            assert h["swaps"] == 1 and h["model_version"] == 1
            assert h["swap_failures"] == 0 and h["rollbacks"] == 0
            assert h["restarts"] == 0  # swaps never touch restart budget
            outs = _outs(pipe)
            assert outs[:3] == [0.0, 2.0, 4.0]  # old model (x2)
            assert outs[3:] == [9.0, 12.0, 15.0]  # new model (x3)
        finally:
            pipe.stop()

    def test_jax_xla_staged_swap_with_jit_warmup(self):
        """The flagship backend: staging opens+warms the new model's XLA
        program off the hot path, then the swap lands at a boundary."""
        register_jax_model("lc_m1", lambda p, xs: [xs[0] * 2.0], None)
        register_jax_model("lc_m2", lambda p, xs: [xs[0] * 3.0], None)
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_filter name=f framework=jax-xla "
                "model=lc_m1 is-updatable=true ! tensor_sink name=out"
            )
            pipe.start()
            try:
                pipe["src"].push(np.float32([1.0]))
                _wait_outs(pipe, 1)  # old model must have served it
                t = pipe.reload_model("f", "lc_m2")
                assert t.wait_staged(30) and t.ok, t.error
                pipe["src"].push(np.float32([1.0]))
                pipe["src"].end_of_stream()
                pipe.wait(30)
                assert _outs(pipe) == [2.0, 3.0]
                assert pipe.health()["f"]["swaps"] == 1
            finally:
                pipe.stop()
        finally:
            unregister_jax_model("lc_m1")
            unregister_jax_model("lc_m2")

    @pytest.mark.parametrize("site", ["filter.reload.load",
                                      "filter.reload.warmup"])
    def test_staging_fault_keeps_old_model_serving(self, site):
        """load-fail / warmup-fail: the swap is refused during staging —
        zero frames dropped, zero restart budget burned, exact
        swap_failures accounting."""
        pipe = _swap_pipe()
        try:
            FAULTS.arm(site, exc=RuntimeError("injected staging fault"))
            for i in range(2):
                pipe["src"].push(np.float32([i]))
            ticket = pipe.reload_model("f", "f:5")
            assert ticket.wait_staged(5)
            assert not ticket.ok and ticket.state == "failed"
            for i in range(2, 4):
                pipe["src"].push(np.float32([i]))
            pipe["src"].end_of_stream()
            pipe.wait(10)
            h = pipe.health()["f"]
            assert h["swap_failures"] == 1 and h["swaps"] == 0
            assert h["restarts"] == 0 and h["state"] == "finished"
            assert _outs(pipe) == [0.0, 2.0, 4.0, 6.0]  # all old model
        finally:
            pipe.stop()

    def test_open_failure_keeps_old_model_serving(self):
        """A genuinely broken model artifact (open() raises) is a
        staging failure, not an element death."""
        pipe = _swap_pipe()
        try:
            ticket = pipe.reload_model("f", "explode-open:9")
            assert ticket.wait_staged(5) and not ticket.ok
            pipe["src"].push(np.float32([1]))
            pipe["src"].end_of_stream()
            pipe.wait(10)
            h = pipe.health()["f"]
            assert h["swap_failures"] == 1 and h["restarts"] == 0
            assert _outs(pipe) == [2.0]
        finally:
            pipe.stop()

    def test_schema_incompatible_model_refused_at_validation(self):
        """StreamSpec compatibility check against the negotiated specs:
        a staged model that cannot accept the live stream never swaps."""
        bad_in = StreamSpec(
            (TensorSpec((3, 7), np.float32),), FORMAT_STATIC, None)
        register_jax_model(
            "lc_bad", lambda p, xs: [xs[0]], None, in_spec=bad_in)
        register_jax_model("lc_ok", lambda p, xs: [xs[0] * 2.0], None)
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_filter name=f framework=jax-xla "
                "model=lc_ok is-updatable=true ! tensor_sink name=out"
            )
            pipe["src"].set_spec(StreamSpec(
                (TensorSpec((1,), np.float32),), FORMAT_STATIC, None))
            pipe.start()
            try:
                pipe["src"].push(np.float32([1.0]))
                t = pipe.reload_model("f", "lc_bad")
                assert t.wait_staged(30)
                assert not t.ok and "does not accept" in str(t.error)
                pipe["src"].push(np.float32([2.0]))
                pipe["src"].end_of_stream()
                pipe.wait(30)
                assert _outs(pipe) == [2.0, 4.0]
                h = pipe.health()["f"]
                assert h["swap_failures"] == 1 and h["restarts"] == 0
            finally:
                pipe.stop()
        finally:
            unregister_jax_model("lc_bad")
            unregister_jax_model("lc_ok")

    def test_post_swap_error_burst_rolls_back(self):
        """Errors inside the observation window are served by the
        RETAINED old model (zero loss); a burst rolls the swap back —
        rollbacks counted, restart budget untouched."""
        pipe = _swap_pipe(
            extra="observation-window=60 rollback-error-burst=2 ")
        try:
            pipe["src"].push(np.float32([0]))
            ticket = pipe.reload_model("f", "f:3")
            assert ticket.wait_staged(5) and ticket.ok
            FAULTS.arm("filter.reload.post",
                       exc=RuntimeError("new model is broken"))
            for i in range(1, 5):
                pipe["src"].push(np.float32([i]))
            pipe["src"].end_of_stream()
            pipe.wait(10)
            h = pipe.health()["f"]
            assert h["swaps"] == 1 and h["rollbacks"] == 1
            assert h["model_version"] == 0  # back to the original
            assert h["restarts"] == 0
            assert ticket.state == "rolled-back"
            # ZERO frames lost: the faulted post-swap frames were served
            # by the retained old model (x2), as was everything after
            # the rollback
            assert _outs(pipe) == [0.0, 2.0, 4.0, 6.0, 8.0]
        finally:
            pipe.stop()

    def test_observation_window_commit_closes_old_backend_after_drain(self):
        """The retiring backend closes only at a drained frame boundary
        after the observation window elapses — never under in-flight
        frames."""
        RecBackend.INSTANCES.clear()
        pipe = _swap_pipe(extra="observation-window=0.01 ")
        try:
            pipe["src"].push(np.float32([0]))
            time.sleep(0.2)
            old = RecBackend.INSTANCES[0]
            ticket = pipe.reload_model("f", "f:3")
            assert ticket.wait_staged(5) and ticket.ok
            pipe["src"].push(np.float32([1]))  # applies the swap
            time.sleep(0.1)  # > observation-window
            pipe["src"].push(np.float32([2]))  # commits
            pipe["src"].push(np.float32([3]))  # reaps the graveyard
            deadline = time.monotonic() + 5
            while not old.closed and time.monotonic() < deadline:
                time.sleep(0.02)
            assert old.closed
            assert ticket.state == "committed"
            pipe["src"].end_of_stream()
            pipe.wait(10)
            assert _outs(pipe) == [0.0, 3.0, 6.0, 9.0]
        finally:
            pipe.stop()

    def test_legacy_inline_reload_failure_keeps_serving(self):
        """Satellite bugfix: with the staging path bypassed
        (staged-reload=false), a failing backend.reload() in the
        RELOAD_MODEL event path must log + count + keep serving — it
        must NOT escape into supervision and kill/restart the element."""
        pipe = _swap_pipe(extra="staged-reload=false ")
        try:
            pipe["src"].push(np.float32([1]))
            pipe["src"].push_event(
                CustomEvent("reload-model", {"model": "explode:7"}))
            pipe["src"].push(np.float32([2]))
            pipe["src"].end_of_stream()
            pipe.wait(10)
            h = pipe.health()["f"]
            assert h["state"] == "finished"
            assert h["swap_failures"] == 1
            assert h["restarts"] == 0 and h["dead_letters"] == 0
            assert _outs(pipe) == [2.0, 4.0]  # old model kept serving
        finally:
            pipe.stop()

    def test_reload_event_routes_through_staged_swap(self):
        """The RELOAD_MODEL event (≙ reference is-updatable contract)
        uses the staged path by default."""
        pipe = _swap_pipe()
        try:
            pipe["src"].push(np.float32([1]))
            pipe["src"].push_event(
                CustomEvent("reload-model", {"model": "f:10"}))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                h = pipe.health()["f"]
                if h.get("swap_state") == "staged" or h["swaps"] > 0:
                    break
                time.sleep(0.02)
            pipe["src"].push(np.float32([2]))
            pipe["src"].end_of_stream()
            pipe.wait(10)
            assert pipe.health()["f"]["swaps"] == 1
            assert _outs(pipe) == [2.0, 20.0]
        finally:
            pipe.stop()

    def test_legacy_inline_reload_success(self):
        pipe = _swap_pipe(extra="staged-reload=false ")
        try:
            pipe["src"].push(np.float32([1]))
            time.sleep(0.2)
            t = pipe.reload_model("f", "f:4")
            assert t.ok and t.state == "committed"
            pipe["src"].push(np.float32([2]))
            pipe["src"].end_of_stream()
            pipe.wait(10)
            assert _outs(pipe) == [2.0, 8.0]
            assert pipe.health()["f"]["swaps"] == 1
        finally:
            pipe.stop()

    def test_reload_requires_is_updatable(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=lc-rec "
            "model=f:2 ! tensor_sink name=out"
        )
        pipe.start()
        try:
            with pytest.raises(ElementError, match="is-updatable"):
                pipe.reload_model("f", "f:3")
            # the event path only warns (reference parity)
            pipe["src"].push_event(
                CustomEvent("reload-model", {"model": "f:3"}))
            pipe["src"].push(np.float32([1]))
            pipe["src"].end_of_stream()
            pipe.wait(10)
            assert _outs(pipe) == [2.0]
        finally:
            pipe.stop()

    def test_concurrent_swap_refused_without_counting_failure(self):
        pipe = _swap_pipe()
        try:
            t1 = pipe.reload_model("f", "slow:3")
            t2 = pipe.reload_model("f", "f:4")
            assert t2.state == "refused"
            assert t1.wait_staged(5) and t1.ok
            h = pipe.health()["f"]
            assert h["swap_failures"] == 0  # a refusal tried nothing
        finally:
            pipe.stop()


class TestDrainAndStop:
    """Pipeline.drain() vs immediate stop(): the in-flight contract,
    pinned identically fused and unfused (satellite + acceptance)."""

    @pytest.mark.parametrize("fuse", [True, False])
    def test_drain_flushes_everything(self, fuse):
        pipe = parse_pipeline(
            "appsrc name=src ! identity sleep=0.01 ! lc_gate_sink name=out",
            fuse=fuse,
        )
        pipe.start()
        pipe["out"].sema.release(100)
        for i in range(12):
            pipe["src"].push(np.float32([i]))
        r = pipe.drain(timeout=10)
        # pre-drain deliveries land in the baseline (not "drained"); the
        # contract is zero dropped and all 12 at the sink in order
        assert r["dropped"] == 0 and r["drained"] <= 12
        assert pipe.delivered_frames() == 12
        assert pipe["out"].got == [float(i) for i in range(12)]
        pipe.stop()

    @pytest.mark.parametrize("fuse", [True, False])
    def test_immediate_stop_drops_undelivered(self, fuse):
        """Immediate stop() abandons exactly the frames that had not
        reached the sink: the 2 released frames were delivered, frames
        2..4 never appear."""
        pipe = parse_pipeline(
            "appsrc name=src ! lc_gate_sink name=out", fuse=fuse)
        pipe.start()
        pipe["out"].sema.release(2)
        for i in range(5):
            pipe["src"].push(np.float32([i]))
        deadline = time.monotonic() + 5
        while len(pipe["out"].got) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        pipe.stop()
        assert pipe["out"].got == [0.0, 1.0]

    @pytest.mark.parametrize("fuse", [True, False])
    def test_drain_deadline_exact_dropped_accounting(self, fuse):
        """A drain that cannot finish tears down at the deadline and
        accounts every undelivered frame — identical fused and
        unfused."""
        pipe = parse_pipeline(
            "appsrc name=src ! lc_gate_sink name=out", fuse=fuse)
        pipe.start()
        pipe["out"].sema.release(2)
        for i in range(5):
            pipe["src"].push(np.float32([i]))
        deadline = time.monotonic() + 5
        while len(pipe["out"].got) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        r = pipe.drain(timeout=0.4)
        assert r["drained"] == 0  # the 2 delivered landed pre-drain
        assert r["dropped"] == 3
        assert pipe["out"].got == [0.0, 1.0]
        pipe.stop()

    @pytest.mark.parametrize("fuse", [True, False])
    def test_stop_drain_true_loses_nothing(self, fuse):
        pipe = parse_pipeline(
            "appsrc name=src ! identity sleep=0.01 ! lc_gate_sink name=out",
            fuse=fuse,
        )
        pipe.start()
        pipe["out"].sema.release(100)
        for i in range(8):
            pipe["src"].push(np.float32([i]))
        pipe.stop(drain=True, drain_timeout=10)
        assert pipe["out"].got == [float(i) for i in range(8)]

    def test_drain_with_microbatching_filter_flushes_inflight_window(self):
        """The filter's parked dispatch window (pending_frames) flushes
        on drain — frames in flight inside an element are not lost."""
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=lc-rec "
            "model=f:2 max-batch=4 ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(10):
            pipe["src"].push(np.float32([i]))
        # frames the scheduler delivers between push() and the drain call
        # count into the baseline, not "drained" — the contract is zero
        # dropped and every frame at the sink
        r = pipe.drain(timeout=10)
        assert r["dropped"] == 0 and r["drained"] <= 10
        assert pipe.delivered_frames() == 10
        assert sorted(_outs(pipe)) == [float(2 * i) for i in range(10)]
        pipe.stop()

    def test_drain_on_finished_pipeline_is_empty(self):
        pipe = parse_pipeline("appsrc name=src ! tensor_sink name=out")
        pipe.start()
        pipe["src"].push(np.float32([1]))
        pipe["src"].end_of_stream()
        pipe.wait(10)
        r = pipe.drain(timeout=1)
        assert r["drained"] == 0 and r["dropped"] == 0
        pipe.stop()

    def test_drain_not_started(self):
        pipe = parse_pipeline("appsrc name=src ! tensor_sink name=out")
        assert pipe.drain(1) == {"drained": 0, "dropped": 0, "elapsed": 0.0}


# ---------------------------------------------------------------------------
# Rolling query-server restart (acceptance e2e)
# ---------------------------------------------------------------------------
class TestRollingRestart:
    """serving -> draining -> stopped: a draining server refuses NEW
    requests with GOAWAY (immediate resend-safe failover, never a
    breaker event), finishes in-flight work, closes its listeners, and
    comes back on the same port — zero requests lost or duplicated."""

    def _server(self, sid, port=0, sleep=0.03):
        pipe = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port={port} "
            "connect-type=tcp ! "
            f"identity sleep={sleep} ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            f"tensor_query_serversink id={sid}")
        pipe.start()
        return pipe, pipe["ssrc"].props["port"]

    def test_rolling_restart_zero_loss_zero_dupes(self):
        """Two servers under continuous client load; drain + restart one:
        every request answered exactly once (exact delivered/failover
        accounting) and the drained server's breaker never trips."""
        sa, pa = self._server(971)
        sb, pb = self._server(972)
        restarted = None
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"hosts=localhost:{pa},localhost:{pb} retries=3 "
            "retry-backoff=0.01 breaker-threshold=3 timeout=2 "
            "max-in-flight=4 ! tensor_sink name=out")
        client.start()
        try:
            n = 30
            for i in range(12):
                client["src"].push(np.float32([i]))
            # drain server A mid-load: its in-flight requests finish,
            # NEW ones are GOAWAY-refused and fail over to B immediately
            res = sa.drain(timeout=15)
            assert res["dropped"] == 0
            hs = sa.health()["ssrc"]
            assert hs["lifecycle"] == "stopped"
            assert hs["draining"] and hs["goaway_sent"] >= 1
            sa.stop()
            for i in range(12, 21):
                client["src"].push(np.float32([i]))
            # rolling restart: server A returns on the SAME port
            restarted, _ = self._server(971, port=pa)
            for i in range(21, n):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=60)
            hq = client.health()["q"]
            vals = sorted(
                float(f.tensors[0][0]) for f in client["out"].frames)
            # zero lost, zero duplicated: every request answered exactly
            # once with the correct value
            assert vals == [i * 2.0 for i in range(n)]
            assert hq["delivered"] == n and hq["degraded_frames"] == 0
            # the roll was exercised: GOAWAY refusals happened and were
            # failed over
            assert hq["goaway_replies"] >= 1
            # GOAWAY is health, not failure: no breaker ever tripped
            # (the continuous-load client deprioritizes the rolled host
            # for a cooldown, so prove "serving again" with a probe
            # client pinned to the restarted server below)
            for snap in hq["breakers"].values():
                assert snap["state"] == "closed" and snap["trips"] == 0
            probe = parse_pipeline(
                "appsrc name=src ! tensor_query_client name=q "
                f"connect-type=tcp host=localhost port={pa} retries=2 "
                "timeout=5 ! tensor_sink name=out")
            probe.start()
            try:
                probe["src"].push(np.float32([50]))
                probe["src"].end_of_stream()
                probe.wait(timeout=30)
                assert [float(f.tensors[0][0])
                        for f in probe["out"].frames] == [100.0]
            finally:
                probe.stop()
            assert restarted.health()["ssrc"]["admitted"] >= 1
        finally:
            client.stop()
            sb.stop()
            if restarted is not None:
                restarted.stop()

    def test_drain_deadline_closes_listeners_without_cutting_replies(self):
        """drain-deadline expiry closes the listeners even while a
        request is still in flight — and that request's reply STILL
        completes (connection readers outlive the listener)."""
        sa, port = self._server(973, sleep=0.4)
        sa["ssrc"].props["drain-deadline"] = 0.1
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"host=localhost port={port} retries=0 timeout=10 ! "
            "tensor_sink name=out")
        client.start()
        try:
            client["src"].push(np.float32([21]))
            deadline = time.monotonic() + 5
            while (sa["ssrc"]._core.admission.inflight == 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)  # request admitted (inside the pipeline)
            sa["ssrc"].request_drain()
            client["src"].end_of_stream()
            client.wait(timeout=30)
            # the in-flight reply was delivered, not cut
            assert [float(f.tensors[0][0])
                    for f in client["out"].frames] == [42.0]
            h = sa.health()["ssrc"]
            assert h["lifecycle"] == "stopped"
        finally:
            client.stop()
            sa.stop()

    def test_grpc_unavailable_goaway_detail_maps_to_goaway_error(self):
        """gRPC parity for the raw-TCP 'G' reply: UNAVAILABLE carrying
        the goaway detail maps to ServerGoawayError; a bare UNAVAILABLE
        stays a transport fault (it keeps counting against the remote)."""
        grpc = pytest.importorskip("grpc")
        from nnstreamer_tpu.distributed.service import QueryConnection

        class FakeRpcError(Exception):
            def __init__(self, code, details):
                self._code, self._details = code, details

            def code(self):
                return self._code

            def details(self):
                return self._details

        with pytest.raises(ServerGoawayError):
            QueryConnection._map_busy(FakeRpcError(
                grpc.StatusCode.UNAVAILABLE, "goaway: server draining"))
        # bare UNAVAILABLE: not a goaway — falls through (returns None)
        assert QueryConnection._map_busy(FakeRpcError(
            grpc.StatusCode.UNAVAILABLE, "connection refused")) is None

    def test_goaway_is_resend_safe_classification(self):
        """ServerGoawayError subclasses RemoteApplicationError: the
        server answered, so breakers/cooldowns must treat it as health."""
        from nnstreamer_tpu.core.resilience import (
            is_remote_application_error,
        )

        assert is_remote_application_error(ServerGoawayError())
