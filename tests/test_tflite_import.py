"""TFLite importer: parse .tflite flatbuffers and lower to XLA.

≙ reference ``tests/nnstreamer_filter_tensorflow2_lite/runTest.sh`` (run
real converted models through the tflite subplugin) — but here the models
are lowered to JAX and the "interpreter" is XLA.  Validation strategy (no
TFLite runtime exists in this image to produce goldens):

* hand-built .tflite buffers via the official ``flatbuffers`` Builder —
  an independent encoder — with analytically-known outputs;
* the reference repo's own model files (add / simple_32 / 5-D broadcast /
  mobilenet_v2 quant / deeplabv3), checked for exact arithmetic where
  derivable and for full-graph shape agreement with the shapes the TFLite
  converter declared in the file (every op's output shape re-derived by
  our padding/stride/layout semantics must match the file's);
* op-level cross-checks against torch (an independent conv implementation).
"""

import os

import numpy as np
import pytest

import flatbuffers

from nnstreamer_tpu.importers.tflite_reader import (
    TFLiteParseError, read_tflite)
from nnstreamer_tpu.importers.tflite_lower import (
    TFLiteLowerError, _Lowering, _same_pads, lower_tflite)

MODELS = "/root/reference/tests/test_models/models"
MOBILENET_QUANT = os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite")
needs_ref_models = pytest.mark.skipif(
    not os.path.isdir(MODELS), reason="reference test models not present")


# -- hand-built .tflite buffers (independent encoder) ------------------------

_F32, _U8, _I32 = 0, 3, 2  # TensorType codes
_ADD, _MUL, _CONV = 0, 18, 3  # BuiltinOperator codes


def _ivec(b, vals):
    b.StartVector(4, len(vals), 4)
    for v in reversed(vals):
        b.PrependInt32(int(v))
    return b.EndVector()


def _offvec(b, offs):
    b.StartVector(4, len(offs), 4)
    for o in reversed(offs):
        b.PrependUOffsetTRelative(o)
    return b.EndVector()


def _buffer(b, data: bytes):
    dv = b.CreateByteVector(data) if data else None
    b.StartObject(1)
    if dv is not None:
        b.PrependUOffsetTRelativeSlot(0, dv, 0)
    return b.EndObject()


def _tensor(b, shape, dtype_code, buffer_idx, name):
    sv = _ivec(b, shape)
    nv = b.CreateString(name)
    b.StartObject(8)
    b.PrependUOffsetTRelativeSlot(0, sv, 0)
    b.PrependInt8Slot(1, dtype_code, 0)
    b.PrependUint32Slot(2, buffer_idx, 0)
    b.PrependUOffsetTRelativeSlot(3, nv, 0)
    return b.EndObject()


def _opcode(b, code):
    b.StartObject(4)
    b.PrependInt8Slot(0, code, 0)
    b.PrependInt32Slot(3, code, 0)
    return b.EndObject()


def _operator(b, opcode_index, inputs, outputs, options_off=None,
              options_type=0):
    iv = _ivec(b, inputs)
    ov = _ivec(b, outputs)
    b.StartObject(9)
    b.PrependUint32Slot(0, opcode_index, 0)
    b.PrependUOffsetTRelativeSlot(1, iv, 0)
    b.PrependUOffsetTRelativeSlot(2, ov, 0)
    if options_off is not None:
        b.PrependInt8Slot(3, options_type, 0)
        b.PrependUOffsetTRelativeSlot(4, options_off, 0)
    return b.EndObject()


def _subgraph(b, tensors, inputs, outputs, operators):
    tv = _offvec(b, tensors)
    iv = _ivec(b, inputs)
    ov = _ivec(b, outputs)
    opv = _offvec(b, operators)
    b.StartObject(5)
    b.PrependUOffsetTRelativeSlot(0, tv, 0)
    b.PrependUOffsetTRelativeSlot(1, iv, 0)
    b.PrependUOffsetTRelativeSlot(2, ov, 0)
    b.PrependUOffsetTRelativeSlot(3, opv, 0)
    return b.EndObject()


def _model(b, opcodes, subgraphs, buffers):
    ocv = _offvec(b, opcodes)
    sgv = _offvec(b, subgraphs)
    bv = _offvec(b, buffers)
    b.StartObject(8)
    b.PrependUint32Slot(0, 3, 0)
    b.PrependUOffsetTRelativeSlot(1, ocv, 0)
    b.PrependUOffsetTRelativeSlot(2, sgv, 0)
    b.PrependUOffsetTRelativeSlot(4, bv, 0)
    return b.EndObject()


def build_affine_tflite() -> bytes:
    """y = 2x + 1 on a (1, 4) float input, as MUL(const) then ADD(const)."""
    b = flatbuffers.Builder(1024)
    buffers = [
        _buffer(b, b""),
        _buffer(b, np.full(4, 2.0, np.float32).tobytes()),
        _buffer(b, np.full(4, 1.0, np.float32).tobytes()),
    ]
    tensors = [
        _tensor(b, (1, 4), _F32, 0, "x"),
        _tensor(b, (1, 4), _F32, 1, "w_mul"),
        _tensor(b, (1, 4), _F32, 2, "b_add"),
        _tensor(b, (1, 4), _F32, 0, "mul_out"),
        _tensor(b, (1, 4), _F32, 0, "y"),
    ]
    opcodes = [_opcode(b, _MUL), _opcode(b, _ADD)]
    ops = [
        _operator(b, 0, [0, 1], [3]),
        _operator(b, 1, [3, 2], [4]),
    ]
    sg = _subgraph(b, tensors, [0], [4], ops)
    m = _model(b, opcodes, [sg], buffers)
    b.Finish(m, file_identifier=b"TFL3")
    return bytes(b.Output())


def _conv2d_options(b, padding, stride_h, stride_w, activation=0):
    b.StartObject(6)
    b.PrependInt8Slot(0, padding, 0)
    b.PrependInt32Slot(1, stride_w, 0)
    b.PrependInt32Slot(2, stride_h, 0)
    b.PrependInt8Slot(3, activation, 0)
    return b.EndObject()


def build_conv_tflite(x_shape, w, bias, padding, stride) -> bytes:
    """One CONV_2D: weights [O,Kh,Kw,I], explicit options table."""
    n, h, wd, ci = x_shape
    co, kh, kw, _ = w.shape
    if padding == "SAME":
        oh = -(-h // stride)
        ow = -(-wd // stride)
        pad_code = 0
    else:
        oh = (h - kh) // stride + 1
        ow = (wd - kw) // stride + 1
        pad_code = 1
    b = flatbuffers.Builder(4096)
    buffers = [
        _buffer(b, b""),
        _buffer(b, np.ascontiguousarray(w, np.float32).tobytes()),
        _buffer(b, np.ascontiguousarray(bias, np.float32).tobytes()),
    ]
    tensors = [
        _tensor(b, x_shape, _F32, 0, "x"),
        _tensor(b, w.shape, _F32, 1, "w"),
        _tensor(b, (co,), _F32, 2, "b"),
        _tensor(b, (n, oh, ow, co), _F32, 0, "y"),
    ]
    opcodes = [_opcode(b, _CONV)]
    opts = _conv2d_options(b, pad_code, stride, stride)
    ops = [_operator(b, 0, [0, 1, 2], [3], opts, options_type=1)]
    sg = _subgraph(b, tensors, [0], [3], ops)
    m = _model(b, opcodes, [sg], buffers)
    b.Finish(m, file_identifier=b"TFL3")
    return bytes(b.Output())


# -- parser ------------------------------------------------------------------

class TestReader:
    def test_rejects_garbage(self):
        with pytest.raises(TFLiteParseError):
            read_tflite(b"\x00" * 64)
        with pytest.raises(TFLiteParseError):
            read_tflite(b"nope")

    def test_handbuilt_roundtrip(self):
        m = read_tflite(build_affine_tflite())
        assert m.version == 3
        assert [m.tensors[i].name for i in m.inputs] == ["x"]
        assert [m.tensors[i].name for i in m.outputs] == ["y"]
        assert [op.opcode for op in m.ops] == ["MUL", "ADD"]
        w = m.tensors[1]
        assert w.is_const and w.dtype == "float32"
        np.testing.assert_array_equal(w.data, np.full((1, 4), 2.0))

    @needs_ref_models
    def test_reference_add(self):
        m = read_tflite(os.path.join(MODELS, "add.tflite"))
        assert m.op_histogram() == {"ADD": 1}
        assert m.tensors[m.inputs[0]].shape == (1,)

    @needs_ref_models
    def test_reference_mobilenet_quant(self):
        m = read_tflite(MOBILENET_QUANT)
        t_in = m.tensors[m.inputs[0]]
        assert t_in.shape == (1, 224, 224, 3) and t_in.dtype == "uint8"
        assert t_in.quant is not None and t_in.quant.scale[0] > 0
        h = m.op_histogram()
        assert h["CONV_2D"] == 36 and h["DEPTHWISE_CONV_2D"] == 17
        # every constant weight tensor carries usable quant params
        # (this vintage of the model is per-tensor throughout)
        for t in m.tensors:
            if t.is_const and t.dtype == "uint8":
                assert t.quant is not None and t.quant.scale[0] > 0


# -- lowering: exact arithmetic ---------------------------------------------

class TestLowerExact:
    def test_affine(self):
        fn = lower_tflite(read_tflite(build_affine_tflite()))
        x = np.array([[0.0, 1.0, -2.0, 3.5]], np.float32)
        (y,) = fn(x)
        np.testing.assert_allclose(np.asarray(y), x * 2 + 1)

    @needs_ref_models
    def test_add_model(self):
        m = read_tflite(os.path.join(MODELS, "add.tflite"))
        const = next(m.tensors[i].data for op in m.ops for i in op.inputs
                     if m.tensors[i].is_const)
        fn = lower_tflite(m)
        x = np.array([3.5], np.float32)
        (y,) = fn(x)
        np.testing.assert_allclose(np.asarray(y), x + const)

    @needs_ref_models
    def test_5d_broadcast_add(self):
        m = read_tflite(os.path.join(
            MODELS, "sample_4x4x4x4x4_two_input_one_output.tflite"))
        fn = lower_tflite(m)
        rng = np.random.default_rng(0)
        a = rng.random((1, 4, 4, 4, 4, 4), np.float32)
        b = rng.random((1, 4, 4, 4, 4, 4), np.float32)
        (y,) = fn(a, b)
        np.testing.assert_allclose(np.asarray(y), a + b, rtol=1e-6)

    def test_unsupported_op_fails_at_load(self):
        m = read_tflite(build_affine_tflite())
        m.ops[0].opcode = "BUILTIN_9999"
        with pytest.raises(TFLiteLowerError, match="BUILTIN_9999"):
            _Lowering(m)


# -- lowering: conv semantics vs torch (independent implementation) ----------

class TestConvVsTorch:
    @pytest.mark.parametrize("padding,stride,hw,k", [
        ("VALID", 1, 8, 3),
        ("VALID", 2, 9, 3),
        ("SAME", 1, 8, 3),
        ("SAME", 1, 7, 5),
        ("SAME", 2, 8, 3),   # even-size SAME: pad splits low/high unevenly
        ("SAME", 2, 7, 3),
    ])
    def test_conv2d(self, padding, stride, hw, k):
        import torch
        import torch.nn.functional as F

        rng = np.random.default_rng(1)
        x = rng.standard_normal((1, hw, hw, 3), np.float32)
        w = rng.standard_normal((4, k, k, 3), np.float32)
        bias = rng.standard_normal(4).astype(np.float32)

        fn = lower_tflite(read_tflite(
            build_conv_tflite(x.shape, w, bias, padding, stride)))
        (got,) = fn(x)
        got = np.asarray(got)

        xt = torch.from_numpy(x.transpose(0, 3, 1, 2))
        wt = torch.from_numpy(w.transpose(0, 3, 1, 2))
        if padding == "SAME":
            pt, pb = _same_pads(hw, stride, k)
            pl, pr = _same_pads(hw, stride, k)
            xt = F.pad(xt, (pl, pr, pt, pb))
        ref = F.conv2d(xt, wt, torch.from_numpy(bias), stride=stride)
        ref = ref.numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


# -- lowering: full-graph shape agreement on real CNNs -----------------------

class TestDeclaredShapes:
    """Run eagerly with validate_shapes: every op output's computed shape
    must equal the shape the TFLite converter wrote into the file."""

    @needs_ref_models
    @pytest.mark.parametrize("fname,make_input", [
        ("mobilenet_v2_1.0_224_quant.tflite",
         lambda: np.random.default_rng(2).integers(
             0, 256, (1, 224, 224, 3), np.uint8)),
        ("deeplabv3_257_mv_gpu.tflite",
         lambda: np.random.default_rng(3).random(
             (1, 257, 257, 3), np.float32) * 2 - 1),
    ])
    def test_shapes_match_file(self, fname, make_input):
        m = read_tflite(os.path.join(MODELS, fname))
        lowering = _Lowering(m)
        lowering.validate_shapes = True
        outs = lowering(make_input())
        for got, idx in zip(outs, m.outputs):
            assert tuple(got.shape) == m.tensors[idx].shape


# -- quantized execution -----------------------------------------------------

class TestQuantExec:
    @needs_ref_models
    def test_mobilenet_quant_contract(self):
        m = read_tflite(MOBILENET_QUANT)
        fn = lower_tflite(m)
        img = np.random.default_rng(4).integers(
            0, 256, (1, 224, 224, 3), np.uint8)
        (y,) = fn(img)
        y = np.asarray(y)
        assert y.shape == (1, 1001) and y.dtype == np.uint8
        # deterministic
        (y2,) = fn(img)
        np.testing.assert_array_equal(y, np.asarray(y2))

    @needs_ref_models
    def test_fake_quant_off_agrees_on_top1(self):
        m = read_tflite(MOBILENET_QUANT)
        img = np.random.default_rng(5).integers(
            0, 256, (1, 224, 224, 3), np.uint8)
        (yq,) = lower_tflite(m, fake_quant=True)(img)
        (yf,) = lower_tflite(read_tflite(MOBILENET_QUANT),
                             fake_quant=False)(img)
        # requantization noise is bounded: the two executions' logit
        # vectors must correlate strongly (argmax on random-noise input
        # is not stable — the logits are nearly flat)
        a = np.asarray(yq).astype(np.float32).ravel()
        b = np.asarray(yf).astype(np.float32).ravel()
        a -= a.mean(); b -= b.mean()
        cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-9))
        assert cos > 0.9, f"fake-quant on/off outputs diverged (cos={cos:.3f})"

    def test_fake_quant_roundtrip_identity_on_grid(self):
        from nnstreamer_tpu.importers.tflite_lower import _fake_quant
        from nnstreamer_tpu.importers.tflite_reader import QuantParams
        q = QuantParams(np.array([0.5], np.float32), np.array([10]))
        xs = (np.arange(0, 256) - 10) * 0.5  # exactly on the uint8 grid
        out = np.asarray(_fake_quant(xs.astype(np.float32), q, "uint8"))
        np.testing.assert_allclose(out, xs, atol=1e-6)


class TestInt8Compute:
    """int8:true — quantized conv/dense as true integer arithmetic
    (int8×int8→int32 MXU path with zero-point expansion)."""

    @staticmethod
    def _conv_model():
        from nnstreamer_tpu.importers.tflite_reader import (
            QuantParams, TFLOp, TFLTensor, TFLiteModel)

        rng = np.random.default_rng(0)
        H = W = 5
        CI, CO, K = 3, 4, 3
        s_in, zp_in = 0.02, 120
        s_w, zp_w = 0.05, 131
        s_out, zp_out = 0.1, 7
        q_x = rng.integers(0, 256, (1, H, W, CI)).astype(np.uint8)
        q_w = rng.integers(0, 256, (CO, K, K, CI)).astype(np.uint8)
        q_b = rng.integers(-500, 500, CO).astype(np.int32)

        def qp(s, z):
            return QuantParams(np.array([s], np.float32),
                               np.array([z], np.int64))

        tensors = [
            TFLTensor(0, "x", (1, H, W, CI), "uint8", 0, qp(s_in, zp_in)),
            TFLTensor(1, "w", (CO, K, K, CI), "uint8", 1,
                      qp(s_w, zp_w), q_w),
            TFLTensor(2, "b", (CO,), "int32", 2, qp(s_in * s_w, 0), q_b),
            TFLTensor(3, "y", (1, H, W, CO), "uint8", 0,
                      qp(s_out, zp_out)),
        ]
        ops = [TFLOp("CONV_2D", [0, 1, 2], [3], {
            "padding": "SAME", "stride_w": 1, "stride_h": 1,
            "activation": None, "dilation_w": 1, "dilation_h": 1})]
        model = TFLiteModel(3, "", tensors, [0], [3], ops)
        return model, q_x, q_w, q_b, (s_in, zp_in, s_w, zp_w, s_out, zp_out)

    def test_conv_bit_exact_vs_integer_reference(self):
        """SAME-padded quantized conv matches an exact float64 reference
        to ZERO quanta (incl. the padded border, where implicit conv
        padding would inject a wrong shifted zero)."""
        import itertools

        model, q_x, q_w, q_b, (s_in, zp_in, s_w, zp_w, s_out, zp_out) = (
            self._conv_model())
        H = W = 5
        K = 3
        CO = q_w.shape[0]
        x_real = (q_x.astype(np.float64) - zp_in) * s_in
        w_real = (q_w.astype(np.float64) - zp_w) * s_w
        pad = K // 2
        xp = np.pad(x_real, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        ref = np.zeros((1, H, W, CO))
        for i, j, o in itertools.product(range(H), range(W), range(CO)):
            ref[0, i, j, o] = (xp[0, i:i + K, j:j + K, :] * w_real[o]).sum()
        ref += q_b * (s_in * s_w)
        q_ref = np.clip(np.round(ref / s_out + zp_out), 0, 255)

        (y,) = _Lowering(model, int8_compute=True)(q_x)
        np.testing.assert_array_equal(
            np.asarray(y).astype(np.int64), q_ref.astype(np.int64))

    @needs_ref_models
    def test_mobilenet_int8_agrees_with_fake_quant(self):
        img = np.random.default_rng(9).integers(
            0, 256, (1, 224, 224, 3), np.uint8)
        (y_f,) = lower_tflite(read_tflite(MOBILENET_QUANT))(img)
        (y_i,) = lower_tflite(read_tflite(MOBILENET_QUANT),
                              int8_compute=True)(img)
        y_f = np.asarray(y_f).astype(np.int64)
        y_i = np.asarray(y_i).astype(np.int64)
        assert np.abs(y_f - y_i).max() <= 3  # rounding-path differences
        assert y_f.argmax() == y_i.argmax()

    @needs_ref_models
    def test_backend_int8_prop(self):
        from nnstreamer_tpu.backends.tflite_import import TFLiteBackend

        be = TFLiteBackend()
        be.open(MOBILENET_QUANT, {"custom": "int8:true"})
        try:
            img = np.random.default_rng(10).integers(
                0, 256, (1, 224, 224, 3), np.uint8)
            (out,) = be.invoke([img])
            assert np.asarray(out).shape == (1, 1001)
        finally:
            be.close()

    def test_per_channel_symmetric_int8_conv_bit_exact(self):
        """The TFLite int8 spec's standard layout: per-channel symmetric
        int8 weights (zp 0), per-tensor int8 activations."""
        import itertools

        from nnstreamer_tpu.importers.tflite_reader import (
            QuantParams, TFLOp, TFLTensor, TFLiteModel)

        rng = np.random.default_rng(1)
        H = W = 4
        CI, CO, K = 2, 3, 3
        s_in, zp_in = 0.04, -5
        s_w_vec = np.array([0.02, 0.05, 0.013], np.float32)
        s_out, zp_out = 0.08, 3
        q_x = rng.integers(-128, 128, (1, H, W, CI)).astype(np.int8)
        q_w = rng.integers(-127, 128, (CO, K, K, CI)).astype(np.int8)
        q_b = rng.integers(-200, 200, CO).astype(np.int32)

        tensors = [
            TFLTensor(0, "x", (1, H, W, CI), "int8", 0, QuantParams(
                np.array([s_in], np.float32), np.array([zp_in]))),
            TFLTensor(1, "w", (CO, K, K, CI), "int8", 1, QuantParams(
                s_w_vec, np.zeros(CO, np.int64), 0), q_w),
            TFLTensor(2, "b", (CO,), "int32", 2, QuantParams(
                s_in * s_w_vec, np.zeros(CO, np.int64), 0), q_b),
            TFLTensor(3, "y", (1, H, W, CO), "int8", 0, QuantParams(
                np.array([s_out], np.float32), np.array([zp_out]))),
        ]
        ops = [TFLOp("CONV_2D", [0, 1, 2], [3], {
            "padding": "SAME", "stride_w": 1, "stride_h": 1,
            "activation": None, "dilation_w": 1, "dilation_h": 1})]
        model = TFLiteModel(3, "", tensors, [0], [3], ops)

        x_real = (q_x.astype(np.float64) - zp_in) * s_in
        w_real = q_w.astype(np.float64) * s_w_vec[:, None, None, None]
        pad = K // 2
        xp = np.pad(x_real, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        ref = np.zeros((1, H, W, CO))
        for i, j, o in itertools.product(range(H), range(W), range(CO)):
            ref[0, i, j, o] = (xp[0, i:i + K, j:j + K, :] * w_real[o]).sum()
        ref += q_b * (s_in * s_w_vec)
        q_ref = np.clip(np.round(ref / s_out + zp_out), -128, 127)

        (y,) = _Lowering(model, int8_compute=True)(q_x)
        np.testing.assert_array_equal(
            np.asarray(y).astype(np.int64), q_ref.astype(np.int64))

    def test_per_channel_symmetric_int8_depthwise_bit_exact(self):
        """Depthwise per-channel (quantized_dimension=3, the multiplier-
        ordered last axis — the TFLite int8 spec's primary user)."""
        import itertools

        from nnstreamer_tpu.importers.tflite_reader import (
            QuantParams, TFLOp, TFLTensor, TFLiteModel)

        rng = np.random.default_rng(2)
        H = W = 4
        C, K = 3, 3
        s_in, zp_in = 0.03, 4
        s_w_vec = np.array([0.015, 0.04, 0.02], np.float32)
        s_out, zp_out = 0.06, -2
        q_x = rng.integers(-128, 128, (1, H, W, C)).astype(np.int8)
        q_w = rng.integers(-127, 128, (1, K, K, C)).astype(np.int8)
        q_b = rng.integers(-200, 200, C).astype(np.int32)

        tensors = [
            TFLTensor(0, "x", (1, H, W, C), "int8", 0, QuantParams(
                np.array([s_in], np.float32), np.array([zp_in]))),
            TFLTensor(1, "w", (1, K, K, C), "int8", 1, QuantParams(
                s_w_vec, np.zeros(C, np.int64), 3), q_w),
            TFLTensor(2, "b", (C,), "int32", 2, QuantParams(
                s_in * s_w_vec, np.zeros(C, np.int64), 0), q_b),
            TFLTensor(3, "y", (1, H, W, C), "int8", 0, QuantParams(
                np.array([s_out], np.float32), np.array([zp_out]))),
        ]
        ops = [TFLOp("DEPTHWISE_CONV_2D", [0, 1, 2], [3], {
            "padding": "SAME", "stride_w": 1, "stride_h": 1,
            "depth_multiplier": 1, "activation": None,
            "dilation_w": 1, "dilation_h": 1})]
        model = TFLiteModel(3, "", tensors, [0], [3], ops)

        x_real = (q_x.astype(np.float64) - zp_in) * s_in
        w_real = q_w.astype(np.float64) * s_w_vec
        pad = K // 2
        xp = np.pad(x_real, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        ref = np.zeros((1, H, W, C))
        for i, j, c in itertools.product(range(H), range(W), range(C)):
            ref[0, i, j, c] = (
                xp[0, i:i + K, j:j + K, c] * w_real[0, :, :, c]).sum()
        ref += q_b * (s_in * s_w_vec)
        q_ref = np.clip(np.round(ref / s_out + zp_out), -128, 127)

        (y,) = _Lowering(model, int8_compute=True)(q_x)
        np.testing.assert_array_equal(
            np.asarray(y).astype(np.int64), q_ref.astype(np.int64))

    def test_per_channel_symmetric_int8_dense_bit_exact(self):
        from nnstreamer_tpu.importers.tflite_reader import (
            QuantParams, TFLOp, TFLTensor, TFLiteModel)

        rng = np.random.default_rng(3)
        I, O = 6, 4
        s_in, zp_in = 0.05, 11
        s_w_vec = (rng.random(O).astype(np.float32) + 0.5) * 0.02
        s_out, zp_out = 0.09, 1
        q_x = rng.integers(-128, 128, (1, I)).astype(np.int8)
        q_w = rng.integers(-127, 128, (O, I)).astype(np.int8)
        q_b = rng.integers(-100, 100, O).astype(np.int32)

        tensors = [
            TFLTensor(0, "x", (1, I), "int8", 0, QuantParams(
                np.array([s_in], np.float32), np.array([zp_in]))),
            TFLTensor(1, "w", (O, I), "int8", 1, QuantParams(
                s_w_vec, np.zeros(O, np.int64), 0), q_w),
            TFLTensor(2, "b", (O,), "int32", 2, QuantParams(
                s_in * s_w_vec, np.zeros(O, np.int64), 0), q_b),
            TFLTensor(3, "y", (1, O), "int8", 0, QuantParams(
                np.array([s_out], np.float32), np.array([zp_out]))),
        ]
        ops = [TFLOp("FULLY_CONNECTED", [0, 1, 2], [3], {
            "activation": None, "weights_format": 0,
            "keep_num_dims": False})]
        model = TFLiteModel(3, "", tensors, [0], [3], ops)

        x_real = (q_x.astype(np.float64) - zp_in) * s_in
        w_real = q_w.astype(np.float64) * s_w_vec[:, None]
        ref = x_real @ w_real.T + q_b * (s_in * s_w_vec)
        q_ref = np.clip(np.round(ref / s_out + zp_out), -128, 127)

        (y,) = _Lowering(model, int8_compute=True)(q_x)
        np.testing.assert_array_equal(
            np.asarray(y).astype(np.int64), q_ref.astype(np.int64))

    def test_per_channel_wrong_axis_falls_back_to_fake_quant(self):
        """quantized_dimension on a non-output axis must NOT take the
        int8 path (its epilogue assumes output-channel scales)."""
        from nnstreamer_tpu.importers.tflite_reader import (
            QuantParams, TFLOp, TFLTensor, TFLiteModel)

        q_w = np.ones((2, 3, 3, 2), np.int8)
        tensors = [
            TFLTensor(0, "x", (1, 4, 4, 2), "int8", 0, QuantParams(
                np.array([0.1], np.float32), np.array([0]))),
            TFLTensor(1, "w", (2, 3, 3, 2), "int8", 1, QuantParams(
                np.array([0.1, 0.2], np.float32),
                np.zeros(2, np.int64), 3), q_w),  # axis 3 = input chans
            TFLTensor(2, "y", (1, 4, 4, 2), "int8", 0, QuantParams(
                np.array([0.2], np.float32), np.array([0]))),
        ]
        ops = [TFLOp("CONV_2D", [0, 1], [2], {
            "padding": "SAME", "stride_w": 1, "stride_h": 1,
            "activation": None, "dilation_w": 1, "dilation_h": 1})]
        model = TFLiteModel(3, "", tensors, [0], [2], ops)
        L = _Lowering(model, int8_compute=True)
        from nnstreamer_tpu.importers.tflite_lower import _int8_quant_triple
        _, _, ok = _int8_quant_triple(L, model.ops[0])
        assert not ok  # falls back; fake-quant handles any quant dim


class TestParserRobustness:
    """Untrusted .tflite bytes must raise parse errors — never crash or
    hang (model files cross trust boundaries)."""

    def test_fuzz_tflite_reader(self):
        rng = np.random.default_rng(0)
        blob = build_affine_tflite()
        for _ in range(300):
            buf = bytearray(blob)
            for _ in range(rng.integers(1, 10)):
                buf[rng.integers(8, len(buf))] = rng.integers(0, 256)
            try:
                m = read_tflite(bytes(buf))
                try:
                    _Lowering(m)
                except Exception:
                    pass  # lowering may reject; must not hang/segfault
            except TFLiteParseError:
                pass  # the ONLY exception type allowed to escape

    def test_fuzz_random_bytes_with_magic(self):
        rng = np.random.default_rng(1)
        for n in (16, 64, 1024):
            buf = bytearray(rng.integers(0, 256, n, dtype=np.uint8))
            buf[4:8] = b"TFL3"  # valid identifier, garbage body
            try:
                read_tflite(bytes(buf))
            except Exception as e:
                assert isinstance(e, TFLiteParseError), repr(e)
