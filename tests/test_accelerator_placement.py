"""accelerator prop -> real device placement.

≙ reference ``accelerator=true:hw1,hw2`` ordered-wish parsing
(``tensor_filter_common.c:2719-2878``), which there only selects a
vendor delegate.  Here the wish list resolves to a concrete
``jax.Device`` (with a ``.N`` ordinal extension), so two filters in one
process can pin to two different chips — the bridge between the
single-chip element API and multi-device serving (VERDICT r3 weak #6).

Runs on the conftest's 8-virtual-CPU-device platform.
"""

import jax
import numpy as np
import pytest

from nnstreamer_tpu.backends.jax_xla import (
    pick_device, register_jax_model, unregister_jax_model)
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture(autouse=True)
def _model():
    register_jax_model("accl_affine", lambda p, xs: [xs[0] + 1.0], None)
    yield
    unregister_jax_model("accl_affine")


class TestPickDevice:
    def test_ordinal_suffix(self):
        devs = jax.devices("cpu")
        assert pick_device(["cpu.3"]) is devs[3]
        assert pick_device(["cpu.0"]) is devs[0]
        assert pick_device(["cpu"]) is devs[0]

    def test_ordered_fallthrough(self):
        # no TPU on the test platform: tpu wish falls through to cpu.2
        devs = jax.devices("cpu")
        assert pick_device(["tpu", "cpu.2"]) is devs[2]

    def test_out_of_range_ordinal_falls_through(self):
        devs = jax.devices("cpu")
        assert pick_device(["cpu.99", "cpu.1"]) is devs[1]

    def test_unknown_wish_skipped(self):
        devs = jax.devices("cpu")
        assert pick_device(["vendorsdk", "cpu.1"]) is devs[1]

    def test_exhausted_list_falls_back_to_default(self):
        assert pick_device(["tpu.5", "gpu"]) is jax.devices()[0]

    def test_auto(self):
        assert pick_device(["auto"]) is jax.devices()[0]


class TestPipelinePinning:
    def test_two_filters_two_devices(self):
        """Two chained filters with distinct ordinals run on distinct
        devices; each filter's outputs are committed to ITS device."""
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter name=f1 framework=jax-xla model=accl_affine "
            "accelerator=true:cpu.1 ! "
            "tensor_filter name=f2 framework=jax-xla model=accl_affine "
            "accelerator=true:cpu.3 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        try:
            d1 = pipe["f1"].backend._device
            d2 = pipe["f2"].backend._device
            assert d1 is jax.devices("cpu")[1]
            assert d2 is jax.devices("cpu")[3]
            assert d1 is not d2
            # and the compute really lands there: invoke through the
            # backends directly and inspect output residency
            (o1,) = pipe["f1"].backend.invoke([np.float32([1.0])])
            (o2,) = pipe["f2"].backend.invoke([np.float32([1.0])])
            assert list(o1.devices()) == [d1]
            assert list(o2.devices()) == [d2]
        finally:
            pipe["src"].end_of_stream()
            pipe.stop()

    def test_accelerator_false_forces_cpu(self):
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter name=f framework=jax-xla model=accl_affine "
            "accelerator=false ! tensor_sink name=out"
        )
        pipe.start()
        try:
            assert pipe["f"].backend._device.platform == "cpu"
        finally:
            pipe["src"].end_of_stream()
            pipe.stop()

    def test_end_to_end_values_cross_device(self):
        """Frames hop f1(dev1) -> f2(dev3) -> host sink; values intact."""
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter framework=jax-xla model=accl_affine "
            "accelerator=true:cpu.1 ! "
            "tensor_filter framework=jax-xla model=accl_affine "
            "accelerator=true:cpu.3 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        for i in range(4):
            pipe["src"].push(np.float32([i]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=20)
        vals = [float(f.tensors[0][0]) for f in pipe["out"].frames]
        pipe.stop()
        assert vals == [i + 2.0 for i in range(4)]

    def test_unsatisfiable_ordinal_stays_in_family(self):
        """cpu.99 with no later wish must stay on CPU (family fallback),
        never invert an explicit cpu-only request onto the default
        device (the TPU on real hardware)."""
        dev = pick_device(["cpu.99"])
        assert dev.platform == "cpu"

    def test_cross_device_handoff_is_moved_not_ignored(self):
        """An upstream filter's device-resident output pinned elsewhere is
        moved to this filter's device, and compute runs there."""
        import jax
        from nnstreamer_tpu.backends.jax_xla import JaxXla

        b1, b2 = JaxXla(), JaxXla()
        b1.open("accl_affine", {"accelerators": ["cpu.1"]})
        b2.open("accl_affine", {"accelerators": ["cpu.3"]})
        try:
            (o1,) = b1.invoke([np.float32([1.0])])
            assert list(o1.devices()) == [jax.devices("cpu")[1]]
            (o2,) = b2.invoke([o1])  # committed to cpu.1, pinned cpu.3
            assert list(o2.devices()) == [jax.devices("cpu")[3]]
            assert float(np.asarray(o2)[0]) == 3.0
        finally:
            b1.close()
            b2.close()
