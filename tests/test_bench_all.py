"""Sweep-runner logic tests (tools/bench_all.py).

The sweep is the critical action of a rare tunnel-up window: resume must
keep real rows, re-measure stale/unknown ones, abort fast on both outage
signatures, and never corrupt the artifact.  bench.py itself is faked —
these tests exercise the RUNNER, not the measurement.
"""

import importlib.util
import json
import os
import sys

import pytest

_BA = os.path.join(
    os.path.dirname(os.path.dirname(__file__)), "tools", "bench_all.py"
)
_spec = importlib.util.spec_from_file_location("bench_all_module", _BA)
ba = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ba)


class _FakeRun:
    """Stands in for subprocess.run(bench.py): returns queued JSON rows."""

    def __init__(self, rows):
        self.rows = list(rows)
        self.calls = []

    def __call__(self, argv, capture_output, text, env):
        self.calls.append(dict(env))
        row = self.rows.pop(0) if self.rows else {"value": None,
                                                  "error": "exhausted"}

        class R:
            returncode = 0
            stdout = json.dumps(row) + "\n"
            stderr = ""

        return R()


@pytest.fixture
def runner(tmp_path, monkeypatch):
    out = str(tmp_path / "ROWS.json")
    monkeypatch.setattr(sys, "argv", ["bench_all.py", out])
    monkeypatch.chdir(tmp_path)
    # ambient shell knobs (e.g. left over from a manual sweep) must not
    # flip test outcomes
    for k in ("BENCH_ALL_RESUME", "BENCH_ALL_KEEP_GOING",
              "BENCH_PROBE_TRIES", "BENCH_PROBE_TIMEOUT"):
        monkeypatch.delenv(k, raising=False)

    def run(rows, env=None):
        fake = _FakeRun(rows)
        monkeypatch.setattr(ba.subprocess, "run", fake)
        for k, v in (env or {}).items():
            monkeypatch.setenv(k, v)
        rc = ba.main()
        with open(out) as f:
            return rc, json.load(f), fake

    return run, out


GOOD = {"metric": "m", "value": 100.0, "unit": "fps", "vs_baseline": None}


def test_all_rows_executed_and_written(runner):
    run, _ = runner
    rc, rows, fake = run([GOOD] * len(ba.ROWS))
    assert rc == 0
    assert len(rows) == len(ba.ROWS)
    assert all(r["value"] == 100.0 and "_sig" in r for r in rows)


def test_abort_on_unavailable(runner):
    run, _ = runner
    bad = {"value": None, "error": "accelerator backend unavailable: x"}
    rc, rows, fake = run([bad] + [GOOD] * 5)
    assert len(rows) == 1  # aborted after the first outage row
    assert len(fake.calls) == 1


def test_abort_on_midrun_wedge_stale_row(runner):
    run, _ = runner
    stale = {
        "value": 1821.1, "stale": True,
        "live_error": "run exceeded deadline; re-probe: probe timed out",
    }
    rc, rows, fake = run([stale] + [GOOD] * 5)
    assert len(rows) == 1
    assert len(fake.calls) == 1


def test_keep_going_overrides_abort(runner):
    run, _ = runner
    bad = {"value": None, "error": "accelerator backend unavailable: x"}
    rc, rows, fake = run(
        [bad] * len(ba.ROWS), env={"BENCH_ALL_KEEP_GOING": "1"}
    )
    assert len(rows) == len(ba.ROWS)


class TestResume:
    def _prior(self, out, rows):
        with open(out, "w") as f:
            json.dump(rows, f)

    def test_resume_keeps_good_rows_and_measures_rest(self, runner):
        run, out = runner
        model0, extra0 = ba.ROWS[0]
        self._prior(out, [
            {**GOOD, "value": 555.0, "_sig": ba._row_sig(model0, extra0)},
        ])
        rc, rows, fake = run(
            [GOOD] * (len(ba.ROWS) - 1), env={"BENCH_ALL_RESUME": "1"}
        )
        assert len(rows) == len(ba.ROWS)
        assert rows[0]["value"] == 555.0  # kept, not re-measured
        assert len(fake.calls) == len(ba.ROWS) - 1

    def test_resume_remeasures_stale_and_null_and_unknown(self, runner):
        run, out = runner
        model0, extra0 = ba.ROWS[0]
        model1, extra1 = ba.ROWS[1]
        self._prior(out, [
            {**GOOD, "stale": True, "_sig": ba._row_sig(model0, extra0)},
            {"value": None, "_sig": ba._row_sig(model1, extra1)},
            {**GOOD, "_sig": {"model": "retired-config"}},
            {**GOOD},  # sig-less pre-resume row
        ])
        rc, rows, fake = run(
            [GOOD] * len(ba.ROWS), env={"BENCH_ALL_RESUME": "1"}
        )
        assert len(fake.calls) == len(ba.ROWS)  # everything re-measured
        # originals preserved in .bak before being dropped
        with open(out + ".bak") as f:
            assert len(json.load(f)) == 4

    def test_resume_corrupt_prior_starts_fresh(self, runner):
        run, out = runner
        with open(out, "w") as f:
            f.write("{broken")
        rc, rows, fake = run(
            [GOOD] * len(ba.ROWS), env={"BENCH_ALL_RESUME": "1"}
        )
        assert len(rows) == len(ba.ROWS)

    def test_duplicate_sigs_kept_once(self, runner):
        run, out = runner
        model0, extra0 = ba.ROWS[0]
        sig = ba._row_sig(model0, extra0)
        self._prior(out, [
            {**GOOD, "value": 1.0, "_sig": sig},
            {**GOOD, "value": 2.0, "_sig": sig},
        ])
        rc, rows, fake = run(
            [GOOD] * (len(ba.ROWS) - 1), env={"BENCH_ALL_RESUME": "1"}
        )
        kept = [r for r in rows if r.get("_sig") == sig]
        assert len(kept) == 1 and kept[0]["value"] == 1.0


def test_probe_budget_shortened_after_first_executed_row(runner):
    run, _ = runner
    rc, rows, fake = run([GOOD] * len(ba.ROWS))
    assert "BENCH_PROBE_TRIES" not in fake.calls[0] or (
        fake.calls[0].get("BENCH_PROBE_TRIES") != "1"
    )
    assert fake.calls[1]["BENCH_PROBE_TRIES"] == "1"
    assert fake.calls[1]["BENCH_PROBE_TIMEOUT"] == "60"


def test_rows_include_block_int8_latency_and_host_last(runner):
    # the sweep must carry the VERDICT-demanded configurations, and the
    # risky host-sourced row must run LAST (tunnel kill hazard)
    extras = [e for _, e in ba.ROWS]
    assert {"BENCH_RAW": "1", "BENCH_INGEST": "block"} in extras
    assert any(e.get("BENCH_QUANT") == "1" for e in extras)
    assert any(e.get("BENCH_BATCH_TIMEOUT") == "2" for e in extras)
    assert any(
        e.get("BENCH_INGEST") == "block" and e.get("BENCH_QUANT") == "1"
        for e in extras
    )
    assert ba.ROWS[-1][1].get("BENCH_HOST") == "1"
    assert int(ba.ROWS[-1][1].get("BENCH_FRAMES", "4096")) <= 512
