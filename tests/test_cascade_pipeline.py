"""Capstone cascade: the reference's flagship demo shape in one pipeline —

  camera -> tee -> [detector -> tensor_region]  (crop-info branch)
              \\-> tensor_crop (raw branch)
  tensor_crop -> python3 resize -> classifier -> image_label -> sink

Exercises tee fan-out, two jax-xla filters (SSD detector + MobileNet
classifier), the tensor_region/tensor_crop pairing, a python3 scriptable
filter in the middle, and decoder labeling — the multi-model composition
story (SURVEY §2.3 "model parallelism (composition)").
"""

import numpy as np
import pytest

from nnstreamer_tpu.pipeline import parse_pipeline

# tier-1 budget: the two-model cascade costs ~60s of XLA compile; every
# mechanism it composes (tee, region/crop, python3 filter, decoders) has
# its own fast test — the capstone composition runs in the slow tier
pytestmark = pytest.mark.slow

RESIZE_SCRIPT = """
import numpy as np

SIZE = 64

class CustomFilter:
    def invoke(self, tensors):
        # nearest-neighbor resize of a (H, W[, C]) crop to SIZE x SIZE
        img = np.asarray(tensors[0])
        if img.ndim == 2:
            img = img[:, :, None].repeat(3, axis=2)
        H, W = img.shape[:2]
        ys = (np.arange(SIZE) * H // SIZE).clip(0, H - 1)
        xs = (np.arange(SIZE) * W // SIZE).clip(0, W - 1)
        return [img[ys][:, xs].astype(np.uint8)]
"""


def test_detect_crop_classify_cascade(tmp_path):
    from nnstreamer_tpu.backends.jax_xla import register_jax_model
    from nnstreamer_tpu.models import build
    from nnstreamer_tpu.models.ssd_mobilenet import write_box_priors

    det_fn, det_p, det_i, det_o = build(
        "ssd_mobilenet_v2", {"dtype": "float32"}
    )
    register_jax_model("cascade_det", det_fn, det_p, det_i, det_o)
    cls_fn, cls_p, cls_i, cls_o = build(
        "mobilenet_v2", {"dtype": "float32", "size": "64"}
    )
    register_jax_model("cascade_cls", cls_fn, cls_p, cls_i, cls_o)

    priors = write_box_priors(str(tmp_path / "priors.txt"))
    labels = tmp_path / "labels.txt"
    labels.write_text("\n".join(f"class{i}" for i in range(1001)))
    resize = tmp_path / "resize.py"
    resize.write_text(RESIZE_SCRIPT)

    n_frames = 3
    pipe = parse_pipeline(
        "appsrc name=cam ! tee name=t "
        "t. ! queue ! c. "
        "t. ! queue ! tensor_filter framework=jax-xla model=cascade_det ! "
        f"tensor_decoder mode=tensor_region option1=2 option3={priors} "
        "option4=300:300 ! c. "
        "tensor_crop name=c ! "
        f"tensor_filter framework=python3 model={resize} ! "
        "tensor_filter framework=jax-xla model=cascade_cls ! "
        f"tensor_decoder mode=image_labeling option1={labels} ! "
        "tensor_sink name=out",
        name="cascade",
    )
    pipe.start()
    rng = np.random.default_rng(7)
    for _ in range(n_frames):
        pipe["cam"].push(rng.integers(0, 255, (300, 300, 3), np.uint8))
    pipe["cam"].end_of_stream()
    pipe.wait(timeout=180)
    outs = pipe["out"].frames
    pipe.stop()

    # one labeled frame per camera frame (top crop region classified)
    assert len(outs) == n_frames
    for f in outs:
        assert f.meta.get("label", "").startswith("class")
        idx = int(np.asarray(f.tensors[0])[0])
        assert 0 <= idx < 1001
