"""Data-plane integrity (ISSUE 4): wire envelope v2 checksums, typed
bounded decode, version negotiation, corruption fault injection and the
client/server corruption semantics, crash-atomic datarepo, fuzz smoke.

Acceptance contract (Documentation/wire-protocol.md):
* every malformed input raises a typed WireError subclass — truncation
  at every field boundary, oversize declared lengths, bad magic/version/
  count each pin to WireTruncationError/WireCorruptionError;
* servers answer corrupt requests with 'C' (tcp) / DATA_LOSS (grpc) and
  stay alive; clients count corruption_detected, retry resend-safe, and
  sustained corruption trips the breaker while one blip does not;
* a v2 client round-trips against a v1-framed peer (negotiation);
* tools/fuzz_wire.py runs >= 10k seeded mutations with zero uncaught
  exceptions, hangs, or over-MAX_BODY allocations.
"""

import json
import os
import socket
import struct
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.resilience import FAULTS, is_transient
from nnstreamer_tpu.distributed import tcp_query, wire
from nnstreamer_tpu.distributed.wire import (
    WireCorruptionError,
    WireError,
    WireTruncationError,
)
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture(autouse=True)
def _reset_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def frame(v=1.0, n=4):
    return TensorFrame([np.full((n,), v, np.float32)], pts=0.5,
                       meta={"tag": "t"})


# ---------------------------------------------------------------------------
# envelope round trips + version knobs
# ---------------------------------------------------------------------------
class TestEnvelopeRoundtrip:
    @pytest.mark.parametrize("version", [1, 2])
    def test_roundtrip_preserves_everything(self, version):
        f = TensorFrame(
            [np.arange(12, dtype=np.float32).reshape(3, 4),
             np.uint8([[1], [2]])],
            pts=1.25, meta={"k": "v", "n": [1, 2]})
        f.seq = 42
        g = wire.decode_frame(wire.encode_frame(f, version=version))
        assert wire.frame_version(wire.encode_frame(f, version=version)) == version
        np.testing.assert_array_equal(g.tensors[0], f.tensors[0])
        np.testing.assert_array_equal(g.tensors[1], f.tensors[1])
        assert g.pts == 1.25 and g.seq == 42 and g.meta["k"] == "v"

    def test_v2_is_default_and_v1_still_decodes(self):
        assert wire.frame_version(wire.encode_frame(frame())) == 2
        g = wire.decode_frame(wire.encode_frame(frame(3.0), version=1))
        assert float(g.tensors[0][0]) == 3.0

    def test_env_knob_pins_v1(self, monkeypatch):
        monkeypatch.setenv("NNS_WIRE_V", "1")
        assert wire.default_version() == 1
        assert wire.frame_version(wire.encode_frame(frame())) == 1
        monkeypatch.delenv("NNS_WIRE_V")
        assert wire.default_version() == 2

    def test_bitflip_detected_everywhere_in_v2(self):
        buf = bytearray(wire.encode_frame(frame()))
        # flip one bit at a spread of positions: header, meta, payload
        for pos in (6, 25, len(buf) // 2, len(buf) - 1):
            bad = bytearray(buf)
            bad[pos] ^= 0x10
            with pytest.raises(WireCorruptionError):
                wire.decode_frame(bad)

    def test_verify_off_skips_crc(self):
        bad = bytearray(wire.encode_frame(frame()))
        bad[-1] ^= 1  # payload corruption only
        g = wire.decode_frame(bad, verify=False)  # garbage-tolerant debug mode
        assert g.tensors[0].shape == (4,)

    @pytest.mark.parametrize("version", [1, 2])
    def test_batch_roundtrip(self, version):
        frames = [frame(i) for i in range(3)]
        out = wire.decode_frames(wire.encode_frames(frames, version=version))
        assert [float(f.tensors[0][0]) for f in out] == [0.0, 1.0, 2.0]

    def test_batch_skeleton_crc_verified(self):
        buf = bytearray(wire.encode_frames([frame(1), frame(2)]))
        # a flipped bit in the crc field itself: structure walks clean,
        # the skeleton checksum is what refuses it
        bad = bytearray(buf)
        bad[6] ^= 1  # crc field of the 'NNSC' header
        with pytest.raises(WireCorruptionError, match="batch checksum"):
            wire.decode_frames(bad)
        # a flipped bit in a length prefix is caught typed too (bounds
        # walk or checksum, whichever fires first)
        bad = bytearray(buf)
        bad[_b2head_size()] ^= 1
        with pytest.raises(WireError):
            wire.decode_frames(bad)

    def test_is_batch_payload_both_magics(self):
        assert wire.is_batch_payload(wire.encode_frames([frame()], version=1))
        assert wire.is_batch_payload(wire.encode_frames([frame()], version=2))
        assert not wire.is_batch_payload(wire.encode_frame(frame()))


def _b2head_size():
    return struct.calcsize("<IHI")


# ---------------------------------------------------------------------------
# malformed-input truth table (satellite): every case pinned to its type
# ---------------------------------------------------------------------------
class TestMalformedTruthTable:
    def _boundaries(self, buf):
        sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
        try:
            import fuzz_wire
        finally:
            sys.path.pop(0)
        return fuzz_wire._walk_frame_boundaries(bytes(buf))

    def test_v1_truncation_at_every_field_boundary(self):
        buf = wire.encode_frame(frame(), version=1)
        for cut in self._boundaries(buf):
            if cut == len(buf):
                continue
            with pytest.raises(WireTruncationError):
                wire.decode_frame(buf[:cut])

    def test_v2_truncation_with_verify_reads_as_corruption(self):
        # the checksum pass sees any truncated v2 buffer first
        buf = wire.encode_frame(frame(), version=2)
        with pytest.raises(WireCorruptionError):
            wire.decode_frame(buf[: len(buf) - 3])
        # sub-header cuts can't even reach the crc: truncation
        with pytest.raises(WireTruncationError):
            wire.decode_frame(buf[:10])
        # with verification off the classification is structural again
        for cut in self._boundaries(buf):
            if cut == len(buf):
                continue
            with pytest.raises(WireTruncationError):
                wire.decode_frame(buf[:cut], verify=False)

    def test_empty_and_bad_magic(self):
        with pytest.raises(WireTruncationError):
            wire.decode_frame(b"")
        with pytest.raises(WireCorruptionError):
            wire.decode_frame(b"XXXXXXXXXX" + b"\0" * 30)

    def test_unsupported_version(self):
        # a flipped bit INSIDE the version field evades the CRC (it
        # selects which header to verify), so this must classify as
        # corruption — typed and transient like every other case
        buf = bytearray(wire.encode_frame(frame(), version=1))
        struct.pack_into("<H", buf, 4, 7)
        with pytest.raises(WireCorruptionError,
                           match="unsupported wire version"):
            wire.decode_frame(buf)
        try:
            wire.decode_frame(buf)
        except WireError as e:
            assert is_transient(e)

    def test_meta_len_hostile(self):
        v1 = bytearray(wire.encode_frame(frame(), version=1))
        # implausibly huge -> corruption BEFORE any allocation
        struct.pack_into("<I", v1, 22, 0xFFFFFFFF)
        with pytest.raises(WireCorruptionError, match="implausible meta"):
            wire.decode_frame(v1)
        # plausible but past the buffer -> truncation
        struct.pack_into("<I", v1, 22, len(v1) + 100)
        with pytest.raises(WireTruncationError):
            wire.decode_frame(v1)

    def test_meta_not_json_or_not_object(self):
        f = TensorFrame([np.float32([1.0])], meta={})
        buf = bytearray(wire.encode_frame(f, version=1))
        # meta is b"{}" at offset 26: overwrite with junk / a JSON array
        assert bytes(buf[26:28]) == b"{}"
        buf[26:28] = b"\xff\xfe"
        with pytest.raises(WireCorruptionError, match="meta"):
            wire.decode_frame(buf)
        buf[26:28] = b"[]"
        with pytest.raises(WireCorruptionError, match="not a JSON object"):
            wire.decode_frame(buf)

    def test_tensor_count_hostile(self):
        buf = bytearray(wire.encode_frame(frame(), version=1))
        meta_len = struct.unpack_from("<I", buf, 22)[0]
        nt_off = 26 + meta_len
        struct.pack_into("<H", buf, nt_off, 60000)  # over TENSOR_COUNT_LIMIT
        with pytest.raises(WireCorruptionError, match="tensor count"):
            wire.decode_frame(buf)
        struct.pack_into("<H", buf, nt_off, 3)  # plausible, data for 1
        with pytest.raises(WireTruncationError):
            wire.decode_frame(buf)

    def test_payload_len_contradicts_header(self):
        buf = bytearray(wire.encode_frame(frame(), version=1))
        # payload_len is the u64 right before the 16-byte payload
        off = len(buf) - 16 - 8
        struct.pack_into("<Q", buf, off, 2**62)
        with pytest.raises(WireCorruptionError, match="contradicts"):
            wire.decode_frame(buf)

    def test_bad_flex_dtype_is_corruption(self):
        buf = bytearray(wire.encode_frame(frame(), version=1))
        idx = bytes(buf).find(b"float32")
        buf[idx : idx + 7] = b"flort32"
        with pytest.raises(WireCorruptionError):
            wire.decode_frame(buf)

    def test_trailing_garbage_rejected(self):
        buf = wire.encode_frame(frame(), version=1) + b"\x00\x01"
        with pytest.raises(WireCorruptionError, match="trailing"):
            wire.decode_frame(buf)

    def test_batch_truth_table(self):
        frames = [frame(1), frame(2)]
        v1 = bytearray(wire.encode_frames(frames, version=1))
        with pytest.raises(WireCorruptionError, match="batch magic"):
            wire.decode_frames(b"XXXX" + bytes(v1[4:]))
        # count says 3, data holds 2 -> truncation
        bad = bytearray(v1)
        struct.pack_into("<H", bad, 4, 3)
        with pytest.raises(WireTruncationError):
            wire.decode_frames(bad)
        # entry length beyond MAX_BODY -> corruption before allocation
        bad = bytearray(v1)
        struct.pack_into("<Q", bad, 6, wire.MAX_BODY + 1)
        with pytest.raises(WireCorruptionError, match="cap"):
            wire.decode_frames(bad)
        # entry length beyond the buffer -> truncation
        bad = bytearray(v1)
        struct.pack_into("<Q", bad, 6, len(v1))
        with pytest.raises(WireTruncationError):
            wire.decode_frames(bad)
        # trailing bytes -> corruption
        with pytest.raises(WireCorruptionError, match="trailing"):
            wire.decode_frames(bytes(v1) + b"\x00")

    def test_typed_errors_are_transient_valueerrors(self):
        for exc in (WireCorruptionError("x"), WireTruncationError("x")):
            assert isinstance(exc, WireError)
            assert isinstance(exc, ValueError)
            assert is_transient(exc)  # nns_transient marker wins


# ---------------------------------------------------------------------------
# tcp_query message framing: parse truth table + crc
# ---------------------------------------------------------------------------
class TestTcpMessageFraming:
    @pytest.mark.parametrize("version", [1, 2])
    def test_roundtrip(self, version):
        body = wire.encode_frame(frame(), version=version)
        msg = tcp_query.encode_msg(ord("Q"), body, 2.5, version=version)
        mtype, got, deadline = tcp_query.parse_msg(msg, version=version)
        assert mtype == ord("Q") and deadline == 2.5
        assert bytes(got) == body

    def test_header_truncation(self):
        msg = tcp_query.encode_msg(ord("Q"), b"abc", version=2)
        for cut in (0, 5, 12, 20):
            with pytest.raises(WireTruncationError):
                tcp_query.parse_msg(msg[:cut], version=2)

    def test_body_truncation(self):
        msg = tcp_query.encode_msg(ord("Q"), b"abcdef", version=1)
        with pytest.raises(WireTruncationError):
            tcp_query.parse_msg(msg[:-2], version=1)

    def test_oversize_declared_body(self):
        head = struct.pack("<BQd", ord("Q"), wire.MAX_BODY + 1, 0.0)
        with pytest.raises(WireCorruptionError, match="exceeds"):
            tcp_query.parse_msg(head, version=1)

    def test_v2_crc_mismatch_and_verify_off(self):
        msg = bytearray(tcp_query.encode_msg(ord("Q"), b"abcdef", version=2))
        msg[-1] ^= 1
        with pytest.raises(WireCorruptionError, match="message checksum"):
            tcp_query.parse_msg(msg, version=2)
        mtype, body, _ = tcp_query.parse_msg(msg, version=2, verify=False)
        assert bytes(body) == b"abcde\x67"


# ---------------------------------------------------------------------------
# FaultInjector corrupt= kind
# ---------------------------------------------------------------------------
class TestCorruptFaults:
    def test_deterministic_bitflip(self):
        data = bytes(range(64))
        FAULTS.arm("site", corrupt="bitflip", every=1, seed=5)
        a = FAULTS.mangle("site", data)
        FAULTS.arm("site", corrupt="bitflip", every=1, seed=5)
        b = FAULTS.mangle("site", data)
        assert a == b != data
        assert len(a) == len(data)
        # exactly one bit differs
        diff = [x ^ y for x, y in zip(a, data)]
        assert sum(bin(d).count("1") for d in diff) == 1

    def test_truncate_kind_and_stats(self):
        data = bytes(range(64))
        FAULTS.arm("site", corrupt="truncate", every=2, seed=5)
        outs = [FAULTS.mangle("site", data) for _ in range(4)]
        assert len(outs[0]) < 64 and outs[1] == data
        assert len(outs[2]) < 64 and outs[3] == data
        assert FAULTS.stats("site") == {"calls": 4, "fired": 2}

    def test_check_ignores_corrupt_plans(self):
        FAULTS.arm("site", corrupt="bitflip", every=1)
        FAULTS.check("site")  # must not raise, must not consume
        assert FAULTS.stats("site")["calls"] == 0

    def test_unarmed_site_passthrough(self):
        data = b"hello"
        assert FAULTS.mangle("nope", data) is data
        FAULTS.arm("other", exc=ValueError)
        assert FAULTS.mangle("nope", data) is data  # raise plan elsewhere

    def test_bad_kind_rejected(self):
        with pytest.raises(ValueError, match="bitflip|truncate"):
            FAULTS.arm("site", corrupt="scramble")

    def test_mangle_parts_joins_only_when_armed(self):
        parts = [b"ab", memoryview(b"cd")]
        assert FAULTS.mangle_parts("site", parts) is parts
        FAULTS.arm("site", corrupt="bitflip", every=1, seed=1)
        (out,) = FAULTS.mangle_parts("site", parts)
        assert len(out) == 4 and out != b"abcd"


# ---------------------------------------------------------------------------
# client corruption semantics (unit, fake connections)
# ---------------------------------------------------------------------------
class TestCorruptClientUnit:
    def make_client(self, corrupt_retries=2, breaker_threshold=3):
        from nnstreamer_tpu.elements.query import TensorQueryClient, _PoolState

        q = TensorQueryClient("q")
        q.set_property("corrupt-retries", corrupt_retries)
        q.set_property("breaker-threshold", breaker_threshold)
        q.set_property("retries", 0)
        q.set_property("retry-backoff", 0.0)
        return q, _PoolState

    def test_single_corruption_retried_no_breaker_trip(self):
        q, _PoolState = self.make_client()

        class CorruptOnce:
            addr = "fake:1"
            calls = 0

            def invoke(self, f, timeout):
                type(self).calls += 1
                if type(self).calls == 1:
                    raise WireCorruptionError("frame checksum mismatch")
                return f

        q._pstate = _PoolState((CorruptOnce(),), (("fake", 1),), 0)
        q._stopped = False
        f = frame(7.0)
        assert q._invoke_failover(f, 0) is f
        h = q.health_info()
        assert h["corruption_detected"] == 1
        assert h["retried"] == 1 and h["delivered"] == 1
        snap = h["breakers"]["fake:1"]
        # ONE corrupt reply is recorded but never trips the breaker
        assert snap["state"] == "closed" and snap["trips"] == 0
        assert snap["recent_failures"] == 0  # cleared by the success

    def test_sustained_corruption_trips_breaker(self):
        q, _PoolState = self.make_client(corrupt_retries=3,
                                         breaker_threshold=2)

        class AlwaysCorrupt:
            addr = "fake:1"

            def invoke(self, f, timeout):
                raise WireCorruptionError("frame checksum mismatch")

        q._pstate = _PoolState((AlwaysCorrupt(),), (("fake", 1),), 0)
        q._stopped = False
        with pytest.raises(WireCorruptionError):
            q._invoke_failover(frame(), 0)
        h = q.health_info()
        assert h["corruption_detected"] >= 2
        assert h["breakers"]["fake:1"]["trips"] >= 1
        assert h["delivered"] == 0


# ---------------------------------------------------------------------------
# negotiation: v2 client <-> v1 peer, both transports of the claim
# ---------------------------------------------------------------------------
class EchoCore:
    """Minimal stand-in core for transport-level tests."""

    corrupt_requests = 0

    def check_caps(self, caps):
        return caps

    def process(self, frames, timeout):
        return [TensorFrame([np.asarray(t) * 2 for t in f.tensors])
                for f in frames]


class TestNegotiation:
    def test_v2_client_v1_server_roundtrip(self):
        srv = tcp_query.TcpQueryServer(EchoCore(), port=0, wire_version=1)
        srv.start()
        try:
            conn = tcp_query.TcpQueryConnection("127.0.0.1", srv.port,
                                                timeout=5)
            try:
                out = conn.invoke(frame(3.0))
                assert float(out.tensors[0][0]) == 6.0
                outs = conn.invoke_batch([frame(1.0), frame(2.0)])
                assert [float(o.tensors[0][0]) for o in outs] == [2.0, 4.0]
                assert conn._peer_v1  # learned the peer speaks v1
                assert set(conn._sock_ver.values()) <= {1}
            finally:
                conn.close()
        finally:
            srv.stop()

    def test_v1_client_v2_server_roundtrip(self):
        srv = tcp_query.TcpQueryServer(EchoCore(), port=0)
        srv.start()
        try:
            conn = tcp_query.TcpQueryConnection("127.0.0.1", srv.port,
                                                timeout=5, wire_version=1)
            try:
                out = conn.invoke(frame(5.0))
                assert float(out.tensors[0][0]) == 10.0
            finally:
                conn.close()
        finally:
            srv.stop()

    def test_v2_peers_upgrade(self):
        srv = tcp_query.TcpQueryServer(EchoCore(), port=0)
        srv.start()
        try:
            conn = tcp_query.TcpQueryConnection("127.0.0.1", srv.port,
                                                timeout=5)
            try:
                conn.invoke(frame(1.0))
                assert not conn._peer_v1
                assert set(conn._sock_ver.values()) == {2}
            finally:
                conn.close()
        finally:
            srv.stop()

    def test_server_honors_peer_advertised_max_v1(self):
        """A conforming peer that probes 'V' but advertises max version 1
        must NOT be upgraded: the server answers with the AGREED version
        (min of both maxes) and keeps that connection on v1 framing."""
        srv = tcp_query.TcpQueryServer(EchoCore(), port=0)
        srv.start()
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            try:
                s.settimeout(5)
                tcp_query._send_msg(s, ord("V"), [b"1"], version=1)
                rtype, body, _ = tcp_query._recv_msg(s, version=1)
                assert rtype == ord("V")
                assert bytes(body) == b"1"  # agreed = min(1, server max)
                # the connection stayed v1-framed: a v1 exchange works
                buf = wire.encode_frame(frame(4.0), version=1)
                tcp_query._send_msg(s, ord("Q"), [buf], version=1)
                rtype, body, _ = tcp_query._recv_msg(s, version=1)
                assert rtype == ord("Q")
                out = wire.decode_frame(body)
                assert float(out.tensors[0][0]) == 8.0
            finally:
                s.close()
        finally:
            srv.stop()

    def test_serversrc_clamps_wire_version_prop(self):
        """An out-of-range wire-version on the serversrc is clamped to a
        version the codecs speak BEFORE it reaches the reply encoders
        (the gRPC path hands core.wire_version straight to
        encode_frame, which refuses unknown versions per request)."""
        pipe = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=973 port=0 "
            "connect-type=tcp wire-version=7 ! "
            "tensor_query_serversink id=973")
        pipe.start()
        try:
            assert pipe["ssrc"]._core.wire_version == 2
        finally:
            pipe.stop()

    def test_pipeline_v2_client_against_v1_framed_peer(self):
        """Acceptance: a v2 client pipeline round-trips against a server
        pinned to wire-version=1 (legacy framing, no checksums)."""
        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=961 port=0 "
            "connect-type=tcp wire-version=1 ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=961")
        server.start()
        port = server["ssrc"].props["port"]
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"host=localhost port={port} timeout=10 ! tensor_sink name=out")
        client.start()
        try:
            for i in range(4):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=30)
            vals = [float(f.tensors[0][0]) for f in client["out"].frames]
            assert vals == [0.0, 2.0, 4.0, 6.0]
            # the client element's pool actually negotiated down to v1
            assert all(c._peer_v1 for c in client["q"]._conns)
            assert client.health()["q"]["delivered"] == 4
        finally:
            client.stop()
            server.stop()


# ---------------------------------------------------------------------------
# server survives hostile bytes (raw socket)
# ---------------------------------------------------------------------------
class TestServerHostileInput:
    def _server(self, sid):
        pipe = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port=0 "
            "connect-type=tcp ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            f"tensor_query_serversink id={sid}")
        pipe.start()
        return pipe, pipe["ssrc"].props["port"]

    def _recv_reply(self, s):
        head = b""
        while len(head) < 17:
            chunk = s.recv(17 - len(head))
            assert chunk, "server hung up before reply"
            head += chunk
        mtype, blen, _ = struct.unpack("<BQd", head)
        body = b""
        while len(body) < blen:
            chunk = s.recv(blen - len(body))
            assert chunk, "server hung up mid-reply"
            body += chunk
        return mtype, body

    def test_corrupt_query_gets_C_and_connection_survives(self):
        pipe, port = self._server(962)
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            bad = bytearray(wire.encode_frame(frame(3.0)))
            bad[-1] ^= 1
            s.sendall(tcp_query.encode_msg(ord("Q"), bytes(bad), 10.0))
            mtype, body = self._recv_reply(s)
            assert mtype == ord("C") and b"checksum" in body
            # SAME connection keeps working
            good = wire.encode_frame(frame(3.0), version=1)
            s.sendall(tcp_query.encode_msg(ord("Q"), good, 10.0))
            mtype, body = self._recv_reply(s)
            assert mtype == ord("Q")
            out = wire.decode_frame(body)
            assert float(out.tensors[0][0]) == 6.0
            s.close()
            h = pipe.health()["ssrc"]
            assert h["corrupt_requests"] == 1
        finally:
            pipe.stop()

    def test_garbage_and_oversize_do_not_kill_server(self):
        pipe, port = self._server(963)
        try:
            # oversize declared body length: typed refusal, conn dropped
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            s.sendall(struct.pack("<BQd", ord("Q"), wire.MAX_BODY + 1, 0.0))
            mtype, body = self._recv_reply(s)
            assert mtype in (ord("C"), ord("E"))
            s.close()
            # fresh connection still served after the hostile one
            conn = tcp_query.TcpQueryConnection("127.0.0.1", port, timeout=10)
            try:
                out = conn.invoke(frame(4.0))
                assert float(out.tensors[0][0]) == 8.0
            finally:
                conn.close()
        finally:
            pipe.stop()


# ---------------------------------------------------------------------------
# corruption chaos e2e (acceptance)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
class TestCorruptionChaosE2E:
    def _run(self, site, sid, n=24):
        server = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port=0 "
            "connect-type=tcp ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            f"tensor_query_serversink id={sid}")
        server.start()
        port = server["ssrc"].props["port"]
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"host=localhost port={port} retries=2 retry-backoff=0.01 "
            "corrupt-retries=3 breaker-threshold=0 degrade=skip timeout=10 "
            "max-in-flight=2 ! tensor_sink name=out")
        client.start()
        # arm AFTER start: the caps handshake must not draw faults
        FAULTS.arm(site, corrupt="bitflip", every=3, seed=11)
        try:
            for i in range(n):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=60)
            fired = FAULTS.stats(site)["fired"]
            h = client.health()["q"]
            vals = sorted(float(f.tensors[0][0])
                          for f in client["out"].frames)
            return n, fired, h, vals, server.health()["ssrc"]
        finally:
            FAULTS.reset()
            client.stop()
            server.stop()

    def test_send_corruption_exact_accounting_server_alive(self):
        """corrupt= on tcp_query.send: the server answers every corrupt
        request with 'C' and never dies; the client resends and delivers
        everything, with exact delivered/retried/corruption accounting."""
        n, fired, h, vals, server_h = self._run("tcp_query.send", 971)
        assert fired > 0
        # every fired corruption was DETECTED (nothing served garbage)
        assert h["corruption_detected"] == fired
        # exact delivery accounting: answered + skipped == pushed
        assert h["delivered"] + h["degraded_frames"] == n
        assert len(vals) + h["degraded_frames"] == n
        assert set(vals) <= {i * 2.0 for i in range(n)}
        assert len(set(vals)) == len(vals)
        # every detection was either retried or (rarely) degraded
        assert h["retried"] >= h["corruption_detected"] - h["degraded_frames"]
        assert h["degraded_frames"] <= 2
        # the server counted and survived every corrupt request
        assert server_h["corrupt_requests"] == fired

    def test_recv_corruption_exact_accounting(self):
        """corrupt= on tcp_query.recv: corrupted REPLIES are detected at
        decode, counted, and re-asked (resend-safe per the integrity
        contract) — the stream still delivers everything."""
        n, fired, h, vals, server_h = self._run("tcp_query.recv", 972)
        assert fired > 0
        assert h["corruption_detected"] == fired
        assert h["delivered"] + h["degraded_frames"] == n
        assert len(vals) + h["degraded_frames"] == n
        assert len(set(vals)) == len(vals)
        assert h["degraded_frames"] <= 2
        # reply corruption happens client-side; the server saw clean requests
        assert server_h["corrupt_requests"] == 0

    def test_sustained_corruption_trips_breaker_single_does_not(self):
        """Acceptance: one corrupt reply never trips the breaker;
        corruption on EVERY exchange does."""
        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=973 port=0 "
            "connect-type=tcp ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=973")
        server.start()
        port = server["ssrc"].props["port"]
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"host=localhost port={port} retries=0 retry-backoff=0 "
            "corrupt-retries=2 breaker-threshold=3 breaker-reset=60 "
            "degrade=skip timeout=10 max-in-flight=1 ! tensor_sink name=out")
        client.start()
        try:
            # phase 1: exactly one corrupt exchange
            FAULTS.arm("tcp_query.send", corrupt="bitflip", every=1,
                       times=1, seed=3)
            client["src"].push(np.float32([1]))
            deadline = time.time() + 20
            while (client.health()["q"]["delivered"] < 1
                   and time.time() < deadline):
                time.sleep(0.05)
            h = client.health()["q"]
            assert h["corruption_detected"] == 1
            snap = h["breakers"][f"localhost:{port}"]
            assert snap["state"] == "closed" and snap["trips"] == 0
            # phase 2: corruption on every exchange trips it
            FAULTS.arm("tcp_query.send", corrupt="bitflip", every=1, seed=3)
            for i in range(4):
                client["src"].push(np.float32([10 + i]))
            client["src"].end_of_stream()
            client.wait(timeout=60)
            h = client.health()["q"]
            assert h["breakers"][f"localhost:{port}"]["trips"] >= 1
            assert h["corruption_detected"] > 1
            # nothing lost silently: delivered + degraded == pushed
            assert h["delivered"] + h["degraded_frames"] == 5
        finally:
            FAULTS.reset()
            client.stop()
            server.stop()


# ---------------------------------------------------------------------------
# grpc transport: DATA_LOSS parity
# ---------------------------------------------------------------------------
class TestGrpcCorruptRequest:
    def test_corrupt_request_data_loss_and_server_survives(self):
        import grpc

        from nnstreamer_tpu.distributed.service import QueryConnection

        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=974 port=0 ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            "tensor_query_serversink id=974")
        server.start()
        port = server["ssrc"].props["port"]
        conn = QueryConnection("localhost", port, timeout=10)
        try:
            bad = bytearray(wire.encode_frame(frame(3.0)))
            bad[-2] ^= 1
            with pytest.raises(WireCorruptionError):
                try:
                    conn._invoke(bytes(bad), timeout=10)
                except grpc.RpcError as e:
                    assert e.code() == grpc.StatusCode.DATA_LOSS
                    conn._map_busy(e)
                    raise
            # the server survived and still answers clean requests
            out = conn.invoke(frame(3.0))
            assert float(out.tensors[0][0]) == 6.0
            assert server.health()["ssrc"]["corrupt_requests"] == 1
        finally:
            conn.close()
            server.stop()


# ---------------------------------------------------------------------------
# pub/sub transports: verify-on-decode drops corrupt frames, stream lives
# ---------------------------------------------------------------------------
class TestPubSubCorruptDrop:
    def test_tcp_edge_corrupt_frames_dropped_and_counted(self):
        tx = parse_pipeline(
            "appsrc name=src ! edgesink name=es connect-type=tcp port=0 "
            "topic=integ")
        tx.start()
        port = tx["es"].props["port"]
        rx = parse_pipeline(
            f"edgesrc name=e connect-type=tcp dest-host=127.0.0.1 "
            f"dest-port={port} topic=integ ! tensor_sink name=out")
        rx.start()
        try:
            deadline = time.time() + 10
            while (tx["es"]._tcp.subscriber_count("integ") < 1
                   and time.time() < deadline):
                time.sleep(0.02)
            FAULTS.arm("tcp_edge.publish", corrupt="bitflip", every=2, seed=2)
            for i in range(6):
                tx["src"].push(np.float32([i]))
            deadline = time.time() + 15
            while (len(rx["out"].frames) < 3 and time.time() < deadline):
                time.sleep(0.05)
            fired = FAULTS.stats("tcp_edge.publish")["fired"]
            assert fired == 3  # every=2 over 6 publishes
            vals = [float(f.tensors[0][0]) for f in rx["out"].frames]
            assert vals == [1.0, 3.0, 5.0]  # corrupted 0/2/4 dropped
            assert rx.health()["e"]["corrupt_dropped"] == 3
        finally:
            FAULTS.reset()
            tx["src"].end_of_stream()
            tx.wait(timeout=10)
            rx.stop()
            tx.stop()

    def test_mqtt_corrupt_frames_dropped_and_counted(self):
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        broker = MiniBroker()
        try:
            rx = parse_pipeline(
                f"mqttsrc name=m host=127.0.0.1 port={broker.port} "
                "sub-topic=integ num-buffers=2 sub-timeout=20000 ! "
                "tensor_sink name=out")
            rx.start()
            assert broker.wait_subscriber("integ", 10.0)
            tx = parse_pipeline(
                f"appsrc name=src ! mqttsink host=127.0.0.1 "
                f"port={broker.port} pub-topic=integ")
            tx.start()
            FAULTS.arm("mqtt.publish", corrupt="bitflip", every=2, seed=4)
            for i in range(4):
                tx["src"].push(np.float32([i]))
            tx["src"].end_of_stream()
            tx.wait(timeout=15)
            rx.wait(timeout=30)
            vals = [float(f.tensors[0][0]) for f in rx["out"].frames]
            assert vals == [1.0, 3.0]  # messages 0/2 corrupted, dropped
            assert rx.health()["m"]["corrupt_dropped"] == 2
            tx.stop()
            rx.stop()
        finally:
            FAULTS.reset()
            broker.close()


# ---------------------------------------------------------------------------
# datarepo: crash-atomic writes + truncation-tolerant reads (satellite)
# ---------------------------------------------------------------------------
class TestDatarepoCrashAtomic:
    def _write_repo(self, data, meta, n=4):
        pipe = parse_pipeline(
            f"appsrc name=src ! datareposink location={data} json={meta}")
        pipe.start()
        for i in range(n):
            pipe["src"].push(np.full((2,), i, np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=20)
        pipe.stop()

    def test_killed_writer_leaves_previous_repo_intact(self, tmp_path):
        from nnstreamer_tpu.elements.datarepo import DataRepoSink

        data, meta = tmp_path / "d.bin", tmp_path / "d.json"
        self._write_repo(data, meta, n=2)
        before = data.read_bytes()
        # second run killed mid-write: render without stop()
        sink = DataRepoSink("s")
        sink.set_property("location", str(data))
        sink.set_property("json", str(meta))
        sink.start()
        sink.render(TensorFrame([np.float32([9.0, 9.0])]))
        # no stop(): simulated kill.  The published repo is untouched
        assert data.read_bytes() == before
        assert json.loads(meta.read_text())["total_samples"] == 2
        # the partial write sits in a dot-tmp sibling only
        assert any(p.name.startswith(".tmp-") for p in tmp_path.iterdir())

    def test_clean_stop_publishes_atomically(self, tmp_path):
        data, meta = tmp_path / "d.bin", tmp_path / "d.json"
        self._write_repo(data, meta, n=3)
        assert data.stat().st_size == 3 * 8
        m = json.loads(meta.read_text())
        assert m["total_samples"] == 3 and m["sample_size"] == 8
        assert not any(p.name.startswith(".tmp-") for p in tmp_path.iterdir())

    def test_truncated_trailing_sample_reported_not_crashed(self, tmp_path):
        data, meta = tmp_path / "d.bin", tmp_path / "d.json"
        self._write_repo(data, meta, n=4)
        # a killed writer left 2 complete samples + half a third
        data.write_bytes(data.read_bytes()[: 2 * 8 + 3])
        pipe = parse_pipeline(
            f"datareposrc name=r location={data} json={meta} ! "
            "tensor_sink name=out")
        pipe.start()
        pipe.wait(timeout=20)
        vals = [float(f.tensors[0][0]) for f in pipe["out"].frames]
        assert vals == [0.0, 1.0]  # the complete prefix, in order
        assert pipe.health()["r"]["truncated_samples"] == 2
        pipe.stop()

    def test_zero_complete_samples_still_fatal(self, tmp_path):
        from nnstreamer_tpu.elements.datarepo import DataRepoSrc
        from nnstreamer_tpu.pipeline.element import ElementError

        data, meta = tmp_path / "d.bin", tmp_path / "d.json"
        self._write_repo(data, meta, n=2)
        data.write_bytes(b"\x00" * 3)
        src = DataRepoSrc("r")
        src.set_property("location", str(data))
        src.set_property("json", str(meta))
        with pytest.raises(ElementError, match="no complete sample"):
            src.start()

    def test_image_mode_atomic_no_tmp_left(self, tmp_path):
        pytest.importorskip("PIL")
        pipe = parse_pipeline(
            f"appsrc name=src ! datareposink "
            f"location={tmp_path}/s_%03d.png json={tmp_path}/s.json")
        pipe.start()
        rng = np.random.default_rng(0)
        for _ in range(2):
            pipe["src"].push(rng.integers(0, 255, (8, 8, 3)).astype(np.uint8))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=20)
        pipe.stop()
        names = sorted(p.name for p in tmp_path.iterdir())
        assert names == ["s.json", "s_000.png", "s_001.png"]
        assert json.loads((tmp_path / "s.json").read_text())[
            "total_samples"] == 2


# ---------------------------------------------------------------------------
# fuzz smoke (tier-1 gate) + integrity-tax bench row
# ---------------------------------------------------------------------------
@pytest.mark.fuzz
def test_fuzz_wire_fixed_seed_smoke():
    """CI contract: the deterministic fuzzer runs >= 10k seeded
    mutations inside tier-1 with zero uncaught exceptions, zero hangs,
    zero over-MAX_BODY allocations (exit 0)."""
    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    try:
        import fuzz_wire
    finally:
        sys.path.pop(0)
    assert fuzz_wire.main(["--seed", "7", "--iterations", "10000", "-q"]) == 0


@pytest.mark.perf
def test_wire_checksum_overhead_is_measured():
    """The integrity tax is measured, not guessed: the bench row exists,
    and CRC verification sustains a sane floor (very generous bound —
    zlib.crc32 does >1 GB/s on any modern core)."""
    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    try:
        import bench_wire
    finally:
        sys.path.pop(0)
    (row,) = bench_wire.run([65536], 200)
    assert row["v1_rps"] > 0 and row["v2_rps"] > 0
    assert "integrity_tax_pct" in row
    assert row["verify_crc_mb_s"] is None or row["verify_crc_mb_s"] >= 50


def test_fuzz_marker_registered():
    text = (Path(__file__).parent.parent / "pyproject.toml").read_text()
    assert '"fuzz:' in text  # registered marker: tier-1 is warning-clean
