"""Int8 quantized inference (ops/quantize.py + mobilenet quantize:int8).

Reference analog: the flagship pipeline's model is quantized tflite
(``mobilenet_v2_1.0_224_quant.tflite``); here quantization is int8 MXU
matmuls/convs with per-channel weight scales and dynamic activation
scales, executed in-graph by XLA."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.ops.quantize import (
    int8_conv,
    int8_dense,
    quantize_symmetric,
)


def test_quantize_symmetric_roundtrip(rng):
    x = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
    q, s = quantize_symmetric(x)
    assert q.dtype == jnp.int8
    # dequantized within half a quantization step of the original
    assert float(jnp.max(jnp.abs(q * s - x))) <= float(s) * 0.5 + 1e-7


def test_quantize_per_channel_scales(rng):
    w = jnp.asarray(rng.normal(size=(3, 3, 8, 16)), jnp.float32)
    q, s = quantize_symmetric(w, axes=(0, 1, 2))
    assert s.shape == (1, 1, 1, 16)
    # each channel uses its own full int8 range
    assert int(jnp.min(jnp.max(jnp.abs(q), axis=(0, 1, 2)))) == 127


@pytest.mark.parametrize("groups", [1, 8])
def test_int8_conv_matches_float(rng, groups):
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 8)), jnp.float32)
    cout = 8 if groups == 8 else 16
    w = jnp.asarray(
        rng.normal(size=(3, 3, 8 // groups, cout)), jnp.float32
    )
    y_q = jax.jit(
        lambda a, b: int8_conv(
            a, b, feature_group_count=groups, out_dtype=jnp.float32
        )
    )(x, w)
    y_f = jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )
    rel = float(jnp.max(jnp.abs(y_q - y_f)) / jnp.max(jnp.abs(y_f)))
    assert rel < 0.05, rel


def test_int8_dense_matches_float(rng):
    x = jnp.asarray(rng.normal(size=(4, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 10)), jnp.float32)
    y_q = jax.jit(int8_dense)(x, w)
    rel = float(jnp.max(jnp.abs(y_q - x @ w)) / jnp.max(jnp.abs(x @ w)))
    assert rel < 0.05, rel


@pytest.mark.slow  # tier-1 budget: ~18s mobilenet PTQ compile; the
# int8_dense/int8 matmul kernels above keep quantization covered
def test_mobilenet_quantized_runs(rng):
    from nnstreamer_tpu.models import build

    fn, params, in_spec, out_spec = build(
        "mobilenet_v2",
        {"dtype": "float32", "quantize": "int8", "size": "64"},
    )
    imgs = rng.integers(0, 255, (2, 64, 64, 3), np.uint8)
    out = jax.jit(lambda p, x: fn(p, [x])[0])(params, imgs)
    assert out.shape == (2, 1001)
    assert np.all(np.isfinite(np.asarray(out)))


@pytest.mark.slow  # tier-1 budget: the mobilenet variants keep this
# family's assertions in the fast run; the heavier SSD/YOLO compiles
# run in the slow tier
def test_ssd_quantized_shares_weights_and_tracks_float(rng):
    """int8 SSD backbone: same param tree as the float build (heads stay
    float32), finite outputs, box/score signal correlated with float."""
    from nnstreamer_tpu.models import build

    f_q, p_q, _, _ = build(
        "ssd_mobilenet_v2",
        {"dtype": "float32", "quantize": "int8", "seed": "3"},
    )
    f_f, p_f, _, _ = build(
        "ssd_mobilenet_v2", {"dtype": "float32", "seed": "3"}
    )
    for a, b in zip(jax.tree.leaves(p_q), jax.tree.leaves(p_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    imgs = rng.integers(0, 255, (1, 300, 300, 3), np.uint8)
    loc_q, conf_q = (np.asarray(o) for o in f_q(p_q, [imgs]))
    loc_f, conf_f = (np.asarray(o) for o in f_f(p_f, [imgs]))
    assert loc_q.shape == loc_f.shape and conf_q.shape == conf_f.shape
    assert np.all(np.isfinite(loc_q)) and np.all(np.isfinite(conf_q))
    corr = np.corrcoef(conf_q.ravel(), conf_f.ravel())[0, 1]
    assert corr > 0.8, corr


@pytest.mark.slow  # tier-1 budget: the mobilenet variants keep this
# family's assertions in the fast run; the heavier SSD/YOLO compiles
# run in the slow tier
def test_yolov5_quantized_shares_weights_and_tracks_float(rng):
    """int8 yolov5 backbone/neck at a tiny size: weight-shared with the
    float build, finite head outputs, correlated predictions."""
    from nnstreamer_tpu.models import build

    f_q, p_q, _, _ = build(
        "yolov5s",
        {"dtype": "float32", "quantize": "int8", "size": "64", "seed": "2"},
    )
    f_f, p_f, _, _ = build(
        "yolov5s", {"dtype": "float32", "size": "64", "seed": "2"}
    )
    for a, b in zip(jax.tree.leaves(p_q), jax.tree.leaves(p_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    imgs = rng.integers(0, 255, (1, 64, 64, 3), np.uint8)
    y_q = np.asarray(f_q(p_q, [imgs])[0])
    y_f = np.asarray(f_f(p_f, [imgs])[0])
    assert y_q.shape == y_f.shape
    assert np.all(np.isfinite(y_q))
    corr = np.corrcoef(y_q.ravel(), y_f.ravel())[0, 1]
    assert corr > 0.8, corr


@pytest.mark.slow  # tier-1 budget: ~37s double mobilenet build; the
# kernel-level PTQ accuracy checks above stay in tier-1
def test_mobilenet_quantized_tracks_float(rng):
    """Same weights, quantized vs float forward: logits stay correlated
    (dynamic-range PTQ keeps the prediction signal)."""
    from nnstreamer_tpu.models import build

    f_q, p_q, _, _ = build(
        "mobilenet_v2",
        {"dtype": "float32", "quantize": "int8", "size": "64", "seed": "3"},
    )
    f_f, p_f, _, _ = build(
        "mobilenet_v2", {"dtype": "float32", "size": "64", "seed": "3"}
    )
    imgs = rng.integers(0, 255, (4, 64, 64, 3), np.uint8)
    # QuantConv(name="Conv_0") keeps the param path — and flax's RNG fold
    # — identical to nn.Conv, so both builds hold the SAME weights
    for a, b in zip(jax.tree.leaves(p_q), jax.tree.leaves(p_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    y_q = np.asarray(f_q(p_q, [imgs])[0])
    y_f = np.asarray(f_f(p_f, [imgs])[0])
    corr = np.corrcoef(y_q.ravel(), y_f.ravel())[0, 1]
    assert corr > 0.8, corr
