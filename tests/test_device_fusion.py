"""Device-fusion pass + batch-through flow.

The TPU-first replacement for the reference's host-side decode hop
(tensor_filter invoke -> mapped CPU memory -> tensordec-imagelabel.c
argmax): the pipeline folds the decoder's device half into the filter's
XLA program (`Pipeline._fuse_device_chains`), and micro-batches travel as
single device-resident BatchFrames until the first host boundary.
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends.jax_xla import register_jax_model, unregister_jax_model
from nnstreamer_tpu.core.buffer import BatchFrame, TensorFrame
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture
def labels(tmp_path):
    p = tmp_path / "labels.txt"
    p.write_text("\n".join(f"cls{i}" for i in range(8)))
    return str(p)


@pytest.fixture
def scale_model():
    import jax.numpy as jnp

    # logits = x * w: argmax is wherever the input is largest
    def fn(params, xs):
        return [xs[0].astype(jnp.float32) * params["w"]]

    register_jax_model("fusion_scale", fn, {"w": np.float32(2.0)})
    yield "fusion_scale"
    unregister_jax_model("fusion_scale")


def push_frames(pipe, n=8, classes=8):
    rng = np.random.default_rng(7)
    expected = []
    for i in range(n):
        x = rng.normal(0, 1, (classes,)).astype(np.float32)
        x[i % classes] += 10.0  # deterministic argmax
        expected.append(i % classes)
        pipe["src"].push(TensorFrame([x], pts=float(i)))
    pipe["src"].end_of_stream()
    return expected


class TestDeviceFusion:
    def pipeline(self, model, labels, extra=""):
        return parse_pipeline(
            "appsrc name=src ! "
            f"tensor_filter name=f framework=jax-xla model={model} "
            "max-batch=4 batch-timeout=30 ! "
            f"tensor_decoder name=d mode=image_labeling option1={labels} "
            f"{extra} ! tensor_sink name=out"
        )

    def test_fused_results_match_host_decode(self, scale_model, labels):
        results = {}
        for fused, extra in (("yes", ""), ("no", "device-fused=never")):
            pipe = self.pipeline(scale_model, labels, extra)
            pipe.start()
            expected = push_frames(pipe)
            pipe.wait(timeout=30)
            assert pipe["d"]._fused is (fused == "yes")
            if fused == "yes":
                # the pass must also have switched the filter to
                # device-resident batch-through emission
                assert pipe["f"].batch_through_active is True
                # the user-visible prop must stay untouched (restart without
                # re-fusing must not inherit batch-through)
                assert pipe["f"].props["batch-through"] is False
            frames = list(pipe["out"].frames)
            pipe.stop()
            assert [f.meta["label_index"] for f in frames] == expected
            assert [f.meta["label"] for f in frames] == [
                f"cls{i}" for i in expected
            ]
            results[fused] = [
                (f.meta["label_index"], round(f.meta["label_score"], 4))
                for f in frames
            ]
        assert results["yes"] == results["no"]

    def test_fused_preserves_order_and_pts(self, scale_model, labels):
        pipe = self.pipeline(scale_model, labels)
        pipe.start()
        push_frames(pipe, n=11)  # odd count: exercises partial batches
        pipe.wait(timeout=30)
        frames = list(pipe["out"].frames)
        pipe.stop()
        assert [f.pts for f in frames] == [float(i) for i in range(11)]

    def test_no_fusion_across_tee(self, scale_model, labels):
        # two consumers on the filter's src pad: fusing would corrupt the
        # second branch's schema, so the pass must leave the chain alone
        pipe = parse_pipeline(
            "appsrc name=src ! "
            f"tensor_filter name=f framework=jax-xla model={scale_model} ! "
            "tee name=t "
            f"t. ! tensor_decoder name=d mode=image_labeling option1={labels} "
            "! tensor_sink name=out "
            "t. ! tensor_sink name=raw"
        )
        pipe.start()
        expected = push_frames(pipe, n=4)
        pipe.wait(timeout=30)
        assert pipe["d"]._fused is False
        idxs = [f.meta["label_index"] for f in pipe["out"].frames]
        raw = [f.tensors[0].shape for f in pipe["raw"].frames]
        pipe.stop()
        assert idxs == expected
        assert raw == [(8,)] * 4  # untouched full score tensors


class TestBoundingBoxFusion:
    """Device-fused bounding-box decode (≙ tensordec-boundingbox.c, but the
    box decode + NMS run inside the filter's XLA program; only top-K
    surviving boxes cross the device->host boundary)."""

    C = 3  # classes

    def _yolo_pred(self, boxes_px):
        """Build a (N, 5+C) yolov5 head: a few confident boxes + noise rows.

        ``boxes_px``: list of (cx, cy, w, h, obj, cls) with coords in 0..1.
        """
        rng = np.random.default_rng(11)
        n = 16
        pred = np.zeros((n, 5 + self.C), np.float32)
        pred[:, :4] = rng.uniform(0.3, 0.7, (n, 4)).astype(np.float32)
        pred[:, 4] = 0.01  # low objectness: below conf threshold
        pred[:, 5:] = rng.uniform(0.1, 0.9, (n, self.C)).astype(np.float32)
        for i, (cx, cy, w, h, obj, cls) in enumerate(boxes_px):
            pred[i, :5] = (cx, cy, w, h, obj)
            pred[i, 5:] = 0.05
            pred[i, 5 + int(cls)] = 0.99
        return pred

    def _frames(self):
        # frame 0: two separated boxes (cls 0, cls 1)
        # frame 1: same-class overlap (NMS keeps the higher score) plus a
        #          different-class box at the same spot (per-class NMS
        #          keeps it)
        # frame 2: odd count -> the last micro-batch is a SINGLE frame,
        #          exercising the unbatched invoke path through device_fn
        return [
            self._yolo_pred([
                (0.25, 0.25, 0.2, 0.2, 0.9, 0),
                (0.75, 0.75, 0.2, 0.3, 0.8, 1),
            ]),
            self._yolo_pred([
                (0.5, 0.5, 0.3, 0.3, 0.9, 2),
                (0.52, 0.5, 0.3, 0.3, 0.7, 2),   # suppressed (IoU ~0.8)
                (0.5, 0.5, 0.3, 0.3, 0.85, 1),   # other class: survives
            ]),
            self._yolo_pred([
                (0.4, 0.6, 0.25, 0.2, 0.95, 1),
            ]),
        ]

    def _run(self, mode_opts, preds, n_inputs=1, extra=""):
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter name=f framework=jax-xla model=fusion_passthru "
            "max-batch=2 batch-timeout=50 ! "
            f"tensor_decoder name=d mode=bounding_boxes {mode_opts} "
            f"{extra} ! tensor_sink name=out"
        )
        pipe.start()
        for i, p in enumerate(preds):
            ts = [np.asarray(t) for t in (p if n_inputs > 1 else [p])]
            pipe["src"].push(TensorFrame(ts, pts=float(i)))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=60)
        fused = pipe["d"]._fused
        frames = list(pipe["out"].frames)
        pipe.stop()
        return fused, frames

    @staticmethod
    def _boxes(frames):
        return [f.meta["boxes"] for f in frames]

    @staticmethod
    def _assert_same_boxes(got, want):
        assert len(got) == len(want)
        for g_frame, w_frame in zip(got, want):
            assert len(g_frame) == len(w_frame)
            for g, w in zip(g_frame, w_frame):
                assert g["class"] == w["class"] and g["label"] == w["label"]
                for k in ("x", "y", "w", "h"):
                    assert g[k] == pytest.approx(w[k], abs=0.1)
                assert g["score"] == pytest.approx(w["score"], rel=1e-4)

    def test_yolov5_fused_matches_host(self, labels):
        import jax  # noqa: F401

        def passthru(params, xs):
            return list(xs)

        register_jax_model("fusion_passthru", passthru, {})
        try:
            preds = self._frames()
            opts = f"option1=yolov5 option2={labels}"
            fused, f_frames = self._run(opts, preds)
            assert fused is True
            unfused, h_frames = self._run(opts, preds, extra="device-fused=never")
            assert unfused is False
        finally:
            unregister_jax_model("fusion_passthru")
        host = self._boxes(h_frames)
        # sanity: the scenario exercises NMS (frame 1 lost its overlap)
        assert [len(b) for b in host] == [2, 2, 1]
        assert sorted(b["class"] for b in host[1]) == [1, 2]
        self._assert_same_boxes(self._boxes(f_frames), host)

    def test_mobilenet_ssd_fused_matches_host(self, tmp_path, labels):
        P = 8
        rng = np.random.default_rng(5)
        yc = rng.uniform(0.25, 0.75, P)
        xc = rng.uniform(0.25, 0.75, P)
        yc[1], xc[1] = yc[0] + 0.01, xc[0] + 0.01  # overlapping prior pair
        hw = np.full(P, 0.22)
        priors = tmp_path / "priors.txt"
        priors.write_text("\n".join(
            " ".join(f"{v:.6f}" for v in row) for row in (yc, xc, hw, hw)
        ))
        # logits: priors 0,1 confident class 1 (NMS pair), prior 2 class 2,
        # rest below threshold
        frames = []
        for _ in range(2):
            loc = rng.normal(0, 0.5, (P, 4)).astype(np.float32)
            sc = np.full((P, self.C), -4.0, np.float32)
            sc[0, 1], sc[1, 1], sc[2, 2] = 3.0, 2.0, 2.5
            frames.append((loc, sc))

        def passthru(params, xs):
            return list(xs)

        register_jax_model("fusion_passthru", passthru, {})
        try:
            opts = f"option1=mobilenet-ssd option2={labels} option3={priors}"
            fused, f_frames = self._run(opts, frames, n_inputs=2)
            assert fused is True
            unfused, h_frames = self._run(
                opts, frames, n_inputs=2, extra="device-fused=never")
            assert unfused is False
        finally:
            unregister_jax_model("fusion_passthru")
        host = self._boxes(h_frames)
        assert all(len(b) >= 2 for b in host)  # NMS dropped the weaker twin
        self._assert_same_boxes(self._boxes(f_frames), host)

    def test_untraceable_mode_stays_on_host(self, labels):
        # tf-ssd postprocess mode has a dynamic valid-count: must not fuse
        def passthru(params, xs):
            return list(xs)

        register_jax_model("fusion_passthru", passthru, {})
        try:
            boxes = np.asarray(
                [[0.1, 0.1, 0.5, 0.5], [0.6, 0.6, 0.9, 0.9]], np.float32)
            classes = np.asarray([0.0, 1.0], np.float32)
            scores = np.asarray([0.9, 0.8], np.float32)
            count = np.asarray([2.0], np.float32)
            fused, frames = self._run(
                f"option1=tf-ssd option2={labels}",
                [(boxes, classes, scores, count)], n_inputs=4)
            assert fused is False
            assert len(frames[0].meta["boxes"]) == 2
        finally:
            unregister_jax_model("fusion_passthru")


class TestFusionOnMesh:
    """Device fusion composes with mesh-sharded serving: the decoder's
    device half compiles into the SAME GSPMD program that spreads the
    filter across the device mesh — the multi-chip serving shape (fused
    postprocess included, only the packed result leaves the mesh)."""

    def test_fused_sharded_matches_fused_single(self, scale_model, labels):
        results = {}
        for key, custom in (("single", ""), ("mesh", "mesh_dp:2,mesh_tp:2")):
            pipe = parse_pipeline(
                "appsrc name=src ! "
                f"tensor_filter name=f framework=jax-xla model={scale_model} "
                f"custom={custom} max-batch=4 batch-timeout=30 ! "
                f"tensor_decoder name=d mode=image_labeling option1={labels} "
                "! tensor_sink name=out"
            )
            pipe.start()
            expected = push_frames(pipe)
            pipe.wait(timeout=60)
            assert pipe["d"]._fused is True  # fusion engaged on the mesh too
            if key == "mesh":
                assert pipe["f"].backend._mesh is not None
            frames = list(pipe["out"].frames)
            pipe.stop()
            assert [f.meta["label_index"] for f in frames] == expected
            results[key] = [
                (f.meta["label_index"], round(f.meta["label_score"], 4))
                for f in frames
            ]
        assert results["mesh"] == results["single"]


class TestPoseFusion:
    """Device-fused pose decode (≙ tensordec-pose.c): keypoint argmax +
    offset gather run in the filter's XLA program; only (K,3) keypoints
    cross the device->host boundary instead of the full heatmaps."""

    K, GH, GW = 14, 9, 9

    def _frames(self, n=3, offsets=False):
        rng = np.random.default_rng(13)
        frames = []
        for _ in range(n):
            heat = rng.normal(-4, 0.5, (self.GH, self.GW, self.K))
            peaks = rng.integers(0, self.GH * self.GW, self.K)
            for i, p in enumerate(peaks):
                heat[p // self.GW, p % self.GW, i] = 4.0 + rng.uniform(0, 1)
            ts = [heat.astype(np.float32)]
            if offsets:
                ts.append(rng.normal(0, 3, (self.GH, self.GW, 2 * self.K))
                          .astype(np.float32))
            frames.append(tuple(ts))
        return frames

    def _run(self, preds, mode_opt="", extra=""):
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter name=f framework=jax-xla model=fusion_passthru "
            "max-batch=2 batch-timeout=50 ! "
            f"tensor_decoder name=d mode=pose_estimation option1=257:257 "
            f"option2=257:257 {mode_opt} {extra} ! tensor_sink name=out"
        )
        pipe.start()
        for i, ts in enumerate(preds):
            pipe["src"].push(TensorFrame([np.asarray(t) for t in ts],
                                         pts=float(i)))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=60)
        fused = pipe["d"]._fused
        kps = [f.meta["keypoints"] for f in pipe["out"].frames]
        pipe.stop()
        return fused, kps

    @pytest.mark.parametrize("offsets", [False, True])
    def test_fused_matches_host(self, offsets):
        def passthru(params, xs):
            return list(xs)

        register_jax_model("fusion_passthru", passthru, {})
        try:
            preds = self._frames(offsets=offsets)
            opt = "option4=heatmap-offset" if offsets else ""
            fused, f_kps = self._run(preds, opt)
            assert fused is True
            unfused, h_kps = self._run(preds, opt, extra="device-fused=never")
            assert unfused is False
        finally:
            unregister_jax_model("fusion_passthru")
        assert len(f_kps) == len(h_kps) == len(preds)
        for fk, hk in zip(f_kps, h_kps):
            assert len(fk) == len(hk) == self.K
            for (fx, fy, fs), (hx, hy, hs) in zip(fk, hk):
                assert fx == pytest.approx(hx, abs=0.1)
                assert fy == pytest.approx(hy, abs=0.1)
                assert fs == pytest.approx(hs, rel=1e-4)


class TestSegmentFusion:
    """Device-fused segmentation (≙ tensordec-imagesegment.c): per-pixel
    argmax runs in the filter's XLA program; a uint8 class grid crosses
    the boundary instead of the float score volume."""

    def test_fused_matches_host(self):
        def passthru(params, xs):
            return list(xs)

        register_jax_model("fusion_passthru", passthru, {})
        try:
            rng = np.random.default_rng(21)
            preds = [
                rng.normal(0, 1, (16, 16, 21)).astype(np.float32)
                for _ in range(3)
            ]
            results = {}
            for key, extra in (("fused", ""), ("host", "device-fused=never")):
                pipe = parse_pipeline(
                    "appsrc name=src ! "
                    "tensor_filter name=f framework=jax-xla "
                    "model=fusion_passthru max-batch=2 batch-timeout=50 ! "
                    "tensor_decoder name=d mode=image_segment "
                    f"option1=tflite-deeplab {extra} ! tensor_sink name=out"
                )
                pipe.start()
                for i, p in enumerate(preds):
                    pipe["src"].push(TensorFrame([p], pts=float(i)))
                pipe["src"].end_of_stream()
                pipe.wait(timeout=60)
                assert pipe["d"]._fused is (key == "fused")
                results[key] = [
                    (np.asarray(f.tensors[0]).copy(),
                     f.meta["classes_present"])
                    for f in pipe["out"].frames
                ]
                pipe.stop()
        finally:
            unregister_jax_model("fusion_passthru")
        assert len(results["fused"]) == len(results["host"]) == 3
        for (f_rgba, f_cls), (h_rgba, h_cls) in zip(
            results["fused"], results["host"]
        ):
            np.testing.assert_array_equal(f_rgba, h_rgba)
            assert f_cls == h_cls


class TestBatchFrame:
    def test_split_roundtrip(self):
        frames = [
            TensorFrame([np.full((3,), i, np.float32)], pts=float(i),
                        meta={"k": i})
            for i in range(5)
        ]
        stacked = np.stack([f.tensors[0] for f in frames])
        bf = BatchFrame.from_frames([stacked], frames)
        assert bf.batch_size == 5
        back = bf.split()
        assert [f.pts for f in back] == [float(i) for i in range(5)]
        assert [f.meta["k"] for f in back] == list(range(5))
        for i, f in enumerate(back):
            np.testing.assert_array_equal(f.tensors[0], frames[i].tensors[0])

    def test_with_tensors_preserves_batch(self):
        frames = [TensorFrame([np.zeros((2,))], pts=float(i)) for i in range(3)]
        bf = BatchFrame.from_frames([np.zeros((3, 2))], frames)
        out = bf.with_tensors([np.ones((3, 4))])
        assert isinstance(out, BatchFrame)
        assert out.batch_size == 3

    def test_chained_filter_passes_batch_through(self, scale_model, labels):
        # filter1 (batch-through) -> filter2 -> sink: the BatchFrame flows
        # through the second jax filter as one batched invoke and splits
        # only at the sink
        import jax.numpy as jnp

        def plus_one(params, xs):
            return [xs[0] + jnp.float32(1.0)]

        register_jax_model("fusion_plus1", plus_one, {})
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! "
                f"tensor_filter name=f1 framework=jax-xla model={scale_model} "
                "max-batch=4 batch-timeout=30 batch-through=true ! "
                "tensor_filter name=f2 framework=jax-xla model=fusion_plus1 ! "
                "tensor_sink name=out"
            )
            pipe.start()
            rng = np.random.default_rng(3)
            xs = [rng.normal(0, 1, (8,)).astype(np.float32) for _ in range(6)]
            for i, x in enumerate(xs):
                pipe["src"].push(TensorFrame([x], pts=float(i)))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            frames = list(pipe["out"].frames)
            pipe.stop()
            assert [f.pts for f in frames] == [float(i) for i in range(6)]
            for x, f in zip(xs, frames):
                np.testing.assert_allclose(
                    f.tensors[0], x * 2.0 + 1.0, rtol=1e-5
                )
        finally:
            unregister_jax_model("fusion_plus1")
