"""Elastic multi-host: a 4-process gang on a 2-D DCN hybrid mesh loses a
worker mid-training and a rejoined gang resumes bit-identically.

This is the resume path parallel/multihost.py advertises ("elastic
behavior is restart-from-checkpoint"): the runtime is gang-scheduled, so
one dead process fails the whole job; recovery is a fresh gang restoring
the periodic checkpoint.  Reference analog: the reference inherits
restartability from GStreamer pipeline relaunch + tensor_trainer
model-save (SURVEY §5.4); the TPU build must prove it across processes.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

import _env_capabilities

pytestmark = pytest.mark.skipif(
    not _env_capabilities.multihost_cpu_ok(),
    reason="multi-process CPU gang needs >= 2 cores (workers get "
    "virtual devices via jax_num_cpu_devices or the XLA_FLAGS "
    "fallback; on one core the gang starves gloo barriers)",
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "tests", "_multihost_elastic_worker.py")

NPROC, NLOCAL = 4, 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_gang(phase: str, ckpt: str, kill_pid: int = -1):
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(NPROC):
        env = dict(
            os.environ,
            NNS_TPU_COORDINATOR=coord,
            NNS_TPU_NUM_PROCS=str(NPROC),
            NNS_TPU_PROC_ID=str(pid),
            NNS_TPU_LOCAL_DEVICES=str(NLOCAL),
            JAX_PLATFORMS="cpu",
            NNS_ELASTIC_PHASE=phase,
            NNS_ELASTIC_CKPT=ckpt,
            NNS_ELASTIC_KILL_PID=str(kill_pid),
        )
        env.pop("XLA_FLAGS", None)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    return procs


def _result_line(out: str):
    for ln in reversed(out.splitlines()):
        if ln.startswith("RESULT "):
            return json.loads(ln[len("RESULT "):])
    return None


def _reap(procs, timeout):
    """Collect (rc, stdout, stderr) per worker; kill stragglers at the
    deadline (survivors of a gang death block in dead collectives)."""
    deadline = time.time() + timeout
    outs = {}
    for pid, p in enumerate(procs):
        left = max(1.0, deadline - time.time())
        try:
            out, err = p.communicate(timeout=left)
        except subprocess.TimeoutExpired:
            p.send_signal(signal.SIGKILL)
            out, err = p.communicate()
        outs[pid] = (p.returncode, out, err)
    return outs


def test_gang_death_and_rejoin_resume(tmp_path):
    ckpt = str(tmp_path / "elastic_ck")
    victim = 3

    # phase A: gang of 4 trains + checkpoints; worker 3 dies hard
    gang_a = _spawn_gang("A", ckpt, kill_pid=victim)
    outs_a = _reap(gang_a, timeout=300)

    a_results = {}
    for pid, (rc, out, err) in outs_a.items():
        r = _result_line(out)
        assert r is not None, (
            f"phase-A worker {pid} produced no RESULT (rc={rc}):\n"
            f"{err[-2000:]}"
        )
        a_results[pid] = r
        # the gang must NOT have completed the post-kill step anywhere
        assert "UNREACHABLE" not in out, f"worker {pid} survived gang death"
    assert outs_a[victim][0] == 1  # the victim died with its exit code
    # the checkpoint landed before the death
    assert os.path.isdir(os.path.join(ckpt, "step_2"))
    # 2-D DCN hybrid mesh came up as requested on every process
    for r in a_results.values():
        assert r["mesh"] == {"dp": 2, "sp": 2, "tp": NLOCAL}
    # same global program: training losses agree across processes
    losses0 = a_results[0]["losses"]
    assert all(r["losses"] == losses0 for r in a_results.values())

    # phase B: fresh gang, same checkpoint dir — restore and continue
    gang_b = _spawn_gang("B", ckpt)
    outs_b = _reap(gang_b, timeout=300)
    b_results = {}
    for pid, (rc, out, err) in outs_b.items():
        assert rc == 0, f"phase-B worker {pid} rc={rc}:\n{err[-2000:]}"
        r = _result_line(out)
        assert r is not None, f"phase-B worker {pid} printed no RESULT"
        b_results[pid] = r

    for pid in range(NPROC):
        # bit-identical restore: every process's local shards match what
        # it checkpointed in the dead gang
        assert b_results[pid]["fingerprint"] == a_results[pid]["fingerprint"], (
            f"worker {pid} restored different bits"
        )
        assert b_results[pid]["mesh"] == a_results[pid]["mesh"]
    # the rejoined gang actually trains: one more global step, same loss
    # everywhere, finite
    loss3 = b_results[0]["loss3"]
    assert all(abs(r["loss3"] - loss3) < 1e-6 for r in b_results.values())
    assert loss3 == loss3 and abs(loss3) < 1e6  # finite sanity
