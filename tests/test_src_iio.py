"""tensor_src_iio against a fake IIO sysfs tree (the reference tests its
element the same way — dummy sysfs under tests/nnstreamer_source/)."""

import os
import struct

import numpy as np
import pytest

from nnstreamer_tpu.elements.src_iio import IIOChannel
from nnstreamer_tpu.pipeline import parse_pipeline
from nnstreamer_tpu.pipeline.element import ElementError


def make_fake_iio(root, samples, *, scale=0.5, offset=2.0):
    """Two channels: accel_x le:s12/16>>4 (idx 0), accel_y le:u8/8 (idx 1).
    `samples` = list of (x_raw, y_raw) already-encoded raw ints."""
    base = root / "sys"
    dev = base / "iio:device0"
    scan = dev / "scan_elements"
    scan.mkdir(parents=True)
    (dev / "buffer").mkdir()
    (dev / "name").write_text("fake_accel\n")
    (dev / "sampling_frequency").write_text("100\n")
    (dev / "in_accel_x_scale").write_text(str(scale))
    (dev / "in_accel_x_offset").write_text(str(offset))
    (scan / "in_accel_x_en").write_text("1")
    (scan / "in_accel_x_index").write_text("0")
    (scan / "in_accel_x_type").write_text("le:s12/16>>4")
    (scan / "in_accel_y_en").write_text("1")
    (scan / "in_accel_y_index").write_text("1")
    (scan / "in_accel_y_type").write_text("le:u8/8>>0")
    (dev / "buffer" / "enable").write_text("0")
    (dev / "buffer" / "length").write_text("0")
    devdir = root / "dev"
    devdir.mkdir()
    payload = b""
    for x, y in samples:
        payload += struct.pack("<H", x) + struct.pack("<B", y)
    (devdir / "iio:device0").write_bytes(payload)
    return str(base), str(devdir)


class TestChannelDecode:
    def test_signed_shift_mask(self):
        ch = IIOChannel("c", 0, "le:s12/16>>4", scale=1.0, offset=0.0)
        # raw storage: value 0xFFF0 -> >>4 = 0xFFF -> signed 12-bit = -1
        out = ch.decode(np.array([0xFFF0], np.uint64))
        assert out[0] == -1.0
        out = ch.decode(np.array([0x0150], np.uint64))  # 0x15 << 4... -> 0x15
        assert out[0] == 21.0

    def test_scale_offset(self):
        ch = IIOChannel("c", 0, "le:u8/8", scale=0.5, offset=2.0)
        assert ch.decode(np.array([10], np.uint64))[0] == pytest.approx(6.0)

    def test_bad_type_string(self):
        with pytest.raises(ElementError):
            IIOChannel("c", 0, "gibberish")


class TestSrcIIO:
    def test_merged_capture(self, tmp_path):
        # x raw: value v encoded as (v & 0xFFF) << 4 (12 bits shifted by 4)
        samples = [((i & 0xFFF) << 4, 100 + i) for i in range(4)]
        base, dev = make_fake_iio(tmp_path, samples)
        pipe = parse_pipeline(
            f"tensor_src_iio device=fake_accel iio-base-dir={base} "
            f"dev-dir={dev} buffer-capacity=2 num-buffers=2 frequency=200 "
            f"poll-timeout=500 ! tensor_sink name=out"
        )
        pipe.start()
        pipe.wait(timeout=30)
        pipe.stop()
        frames = pipe["out"].frames
        assert len(frames) == 2
        t = frames[0].tensors[0]
        assert t.shape == (2, 2) and t.dtype == np.float32
        # x: (raw + 2.0) * 0.5 ; y: raw * 1.0
        np.testing.assert_allclose(t[0], [(0 + 2) * 0.5, (1 + 2) * 0.5])
        np.testing.assert_allclose(t[1], [100, 101])
        # frequency + buffer enable were written to sysfs
        assert open(os.path.join(base, "iio:device0",
                                 "sampling_frequency")).read() == "200"
        assert open(os.path.join(base, "iio:device0", "buffer",
                                 "enable")).read() == "0"  # stop() disables

    def test_unmerged_per_channel(self, tmp_path):
        samples = [(0x10, 1), (0x20, 2)]
        base, dev = make_fake_iio(tmp_path, samples)
        pipe = parse_pipeline(
            f"tensor_src_iio device-number=0 iio-base-dir={base} dev-dir={dev} "
            f"merge-channels-data=false buffer-capacity=1 num-buffers=2 "
            f"poll-timeout=500 ! tensor_sink name=out"
        )
        pipe.start()
        pipe.wait(timeout=30)
        pipe.stop()
        f0 = pipe["out"].frames[0]
        assert len(f0.tensors) == 2
        assert f0.tensors[0].shape == (1,)

    def test_channel_selection(self, tmp_path):
        samples = [(0x10, 7)]
        base, dev = make_fake_iio(tmp_path, samples)
        pipe = parse_pipeline(
            f"tensor_src_iio device=fake_accel iio-base-dir={base} "
            f"dev-dir={dev} channels=in_accel_y num-buffers=1 "
            f"poll-timeout=500 ! tensor_sink name=out"
        )
        pipe.start()
        pipe.wait(timeout=30)
        pipe.stop()
        t = pipe["out"].frames[0].tensors[0]
        assert t.shape == (1, 1)
        # NOTE: selecting only in_accel_y means the remaining stream layout
        # is just the y byte — the fake payload interleaves x too, but the
        # element recomputes frame_bytes from enabled channels; craft a
        # y-only payload instead
        # (covered implicitly: x_en toggled to 0 in sysfs)
        assert open(os.path.join(base, "iio:device0", "scan_elements",
                                 "in_accel_x_en")).read() == "0"

    def test_natural_alignment_padding(self, tmp_path):
        # kernel scan records align each element to its own storage size
        # (iio_compute_scan_bytes): s16 @0, s64 timestamp @8, record = 16B
        base = tmp_path / "sys"
        dev = base / "iio:device0"
        scan = dev / "scan_elements"
        scan.mkdir(parents=True)
        (dev / "buffer").mkdir()
        (dev / "name").write_text("padded\n")
        (scan / "in_accel_x_en").write_text("1")
        (scan / "in_accel_x_index").write_text("0")
        (scan / "in_accel_x_type").write_text("le:s16/16>>0")
        (scan / "in_timestamp_en").write_text("1")
        (scan / "in_timestamp_index").write_text("1")
        (scan / "in_timestamp_type").write_text("le:s64/64>>0")
        (dev / "buffer" / "enable").write_text("0")
        devdir = tmp_path / "dev"
        devdir.mkdir()
        payload = b""
        for i in range(3):
            payload += struct.pack("<h", 100 + i) + b"\x00" * 6  # pad to 8
            payload += struct.pack("<q", 10_000 + i)
        (devdir / "iio:device0").write_bytes(payload)
        pipe = parse_pipeline(
            f"tensor_src_iio device=padded iio-base-dir={base} "
            f"dev-dir={devdir} buffer-capacity=3 num-buffers=1 "
            f"poll-timeout=500 ! tensor_sink name=out"
        )
        pipe.start()
        pipe.wait(timeout=30)
        pipe.stop()
        t = pipe["out"].frames[0].tensors[0]
        np.testing.assert_allclose(t[0], [100, 101, 102])
        np.testing.assert_allclose(t[1], [10_000, 10_001, 10_002])

    def test_shared_scale_fallback(self, tmp_path):
        samples = [(0x10, 4)]
        base, dev = make_fake_iio(tmp_path, samples)
        # remove the per-component scale, provide the shared in_accel_scale
        os.remove(os.path.join(base, "iio:device0", "in_accel_x_scale"))
        os.remove(os.path.join(base, "iio:device0", "in_accel_x_offset"))
        with open(os.path.join(base, "iio:device0", "in_accel_scale"), "w") as f:
            f.write("0.25")
        pipe = parse_pipeline(
            f"tensor_src_iio device=fake_accel iio-base-dir={base} "
            f"dev-dir={dev} num-buffers=1 poll-timeout=500 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        pipe.wait(timeout=30)
        pipe.stop()
        t = pipe["out"].frames[0].tensors[0]
        assert t[0, 0] == pytest.approx(1 * 0.25)  # x raw=1, shared scale

    def test_missing_device_errors(self, tmp_path):
        base, dev = make_fake_iio(tmp_path, [(0, 0)])
        pipe = parse_pipeline(
            f"tensor_src_iio device=nope iio-base-dir={base} dev-dir={dev} "
            "! tensor_sink name=out"
        )
        with pytest.raises(Exception):
            pipe.start()
            pipe.wait(timeout=10)
            pipe.stop()
