"""Flash attention Pallas kernel vs the exact-attention oracle.

Runs the real kernel in Pallas interpret mode on CPU (same kernel code
the TPU compiles); the driver's TPU bench exercises the compiled path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.ops.flash_attention import flash_attention
from nnstreamer_tpu.parallel.ring_attention import reference_attention


def _qkv(B=2, T=128, H=2, D=32, dtype=jnp.float32, seed=0):
    rng = jax.random.PRNGKey(seed)
    return tuple(
        jax.random.normal(r, (B, T, H, D), dtype)
        for r in jax.random.split(rng, 3)
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out = flash_attention(
            q, k, v, causal=causal, block_q=32, block_k=32, interpret=True
        )
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5
        )

    def test_uneven_q_k_blocks(self):
        # block_q != block_k exercises the causal diagonal-crossing blocks
        q, k, v = _qkv(T=192, seed=1)
        out = flash_attention(
            q, k, v, causal=True, block_q=64, block_k=32, interpret=True
        )
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)

    def test_bf16(self):
        q, k, v = _qkv(dtype=jnp.bfloat16, seed=2)
        out = flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32, interpret=True
        )
        ref = reference_attention(
            *(x.astype(jnp.float32) for x in (q, k, v)), causal=True
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.08
        )

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize(
        "bq,bk", [(64, 64), (128, 64), (64, 128), (96, 64)]
    )
    def test_indivisible_seq_pads_and_masks(self, causal, bq, bk):
        """T not divisible by the block (incl. MIXED block sizes with T
        below the larger one): the wrapper pads K/V/Q to a common block
        multiple and the kernel masks padded columns via static
        valid_len — results must equal the reference exactly (padding
        must never leak into the softmax, and no K columns / Q rows may
        be silently dropped)."""
        q, k, v = _qkv(T=100)
        out = flash_attention(
            q, k, v, causal=causal, block_q=bq, block_k=bk, interpret=True
        )
        ref = reference_attention(q, k, v, causal=causal)
        assert out.shape == q.shape
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_transformer_attn_prop(self):
        from nnstreamer_tpu.models import build

        fn, params, _, _ = build(
            "transformer",
            {"dtype": "float32", "vocab": "64", "d_model": "32",
             "heads": "2", "layers": "1", "seq": "64", "attn": "flash"},
        )
        toks = np.arange(64, dtype=np.int32) % 64
        out = np.asarray(fn(params, [toks])[0])
        assert out.shape == (64, 64) and np.isfinite(out).all()
