"""Flash attention Pallas kernel vs the exact-attention oracle.

Runs the real kernel in Pallas interpret mode on CPU (same kernel code
the TPU compiles); the driver's TPU bench exercises the compiled path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import _env_capabilities

from nnstreamer_tpu.ops.flash_attention import flash_attention
from nnstreamer_tpu.parallel.ring_attention import reference_attention


def _qkv(B=2, T=128, H=2, D=32, dtype=jnp.float32, seed=0):
    rng = jax.random.PRNGKey(seed)
    return tuple(
        jax.random.normal(r, (B, T, H, D), dtype)
        for r in jax.random.split(rng, 3)
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out = flash_attention(
            q, k, v, causal=causal, block_q=32, block_k=32, interpret=True
        )
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5
        )

    def test_uneven_q_k_blocks(self):
        # block_q != block_k exercises the causal diagonal-crossing blocks
        q, k, v = _qkv(T=192, seed=1)
        out = flash_attention(
            q, k, v, causal=True, block_q=64, block_k=32, interpret=True
        )
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)

    def test_bf16(self):
        q, k, v = _qkv(dtype=jnp.bfloat16, seed=2)
        out = flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32, interpret=True
        )
        ref = reference_attention(
            *(x.astype(jnp.float32) for x in (q, k, v)), causal=True
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.08
        )

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize(
        "bq,bk", [(64, 64), (128, 64), (64, 128), (96, 64)]
    )
    def test_indivisible_seq_pads_and_masks(self, causal, bq, bk):
        """T not divisible by the block (incl. MIXED block sizes with T
        below the larger one): the wrapper pads K/V/Q to a common block
        multiple and the kernel masks padded columns via static
        valid_len — results must equal the reference exactly (padding
        must never leak into the softmax, and no K columns / Q rows may
        be silently dropped)."""
        q, k, v = _qkv(T=100)
        out = flash_attention(
            q, k, v, causal=causal, block_q=bq, block_k=bk, interpret=True
        )
        ref = reference_attention(q, k, v, causal=causal)
        assert out.shape == q.shape
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_transformer_attn_prop(self):
        from nnstreamer_tpu.models import build

        fn, params, _, _ = build(
            "transformer",
            {"dtype": "float32", "vocab": "64", "d_model": "32",
             "heads": "2", "layers": "1", "seq": "64", "attn": "flash"},
        )
        toks = np.arange(64, dtype=np.int32) % 64
        out = np.asarray(fn(params, [toks])[0])
        assert out.shape == (64, 64) and np.isfinite(out).all()


class TestFlashAttentionLse:
    """flash_attention_lse: the (out, lse) pair whose exact two-partial
    merge composes the kernel across ring hops (sequence parallelism)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference_lse(self, causal):
        from nnstreamer_tpu.ops.flash_attention import (
            flash_attention_lse,
            reference_attention_lse,
        )

        q, k, v = _qkv(T=64, seed=3)
        out, lse = flash_attention_lse(
            q, k, v, causal=causal, block_q=32, block_k=32, interpret=True
        )
        ref_out, ref_lse = reference_attention_lse(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref_out), atol=3e-5
        )
        np.testing.assert_allclose(
            np.asarray(lse), np.asarray(ref_lse), atol=3e-5
        )

    def test_split_key_merge_is_exact(self):
        """Two disjoint-key partials merged by the (out, lse) recurrence
        must equal attention over the concatenated keys — the ring-hop
        contract in isolation."""
        from nnstreamer_tpu.ops.flash_attention import (
            flash_attention_lse,
            reference_attention_lse,
        )

        q, k, v = _qkv(T=64, seed=4)
        k1, k2 = k[:, :32], k[:, 32:]
        v1, v2 = v[:, :32], v[:, 32:]
        o1, l1 = flash_attention_lse(q, k1, v1, causal=False,
                                     block_q=32, block_k=32, interpret=True)
        o2, l2 = flash_attention_lse(q, k2, v2, causal=False,
                                     block_q=32, block_k=32, interpret=True)
        lse = jnp.logaddexp(l1, l2)
        a1 = jnp.exp(l1 - lse).transpose(0, 2, 1)[..., None]
        a2 = jnp.exp(l2 - lse).transpose(0, 2, 1)[..., None]
        merged = o1.astype(jnp.float32) * a1 + o2.astype(jnp.float32) * a2
        want, _ = reference_attention_lse(q, k, v, causal=False)
        np.testing.assert_allclose(
            np.asarray(merged), np.asarray(want), atol=3e-5
        )


@pytest.mark.skipif(
    not _env_capabilities.spmd_stack_ok(),
    reason="jax lacks the shard_map feature set (check_vma/pvary/pallas "
    "replication rule) the mesh ring composition needs",
)
class TestRingFlash:
    """ring_attention(use_flash=True): the Pallas kernel as the per-hop
    block primitive, exact across the sp ring (long-context composition)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference_on_mesh(self, causal):
        from jax.sharding import Mesh

        from nnstreamer_tpu.parallel.ring_attention import ring_attention

        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("dp", "sp"))
        q, k, v = _qkv(B=2, T=32, H=2, D=8, seed=5)
        out = ring_attention(
            q, k, v, mesh, causal=causal, use_flash=True, interpret=True
        )
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5
        )

    def test_flash_and_jnp_rings_agree_bf16(self):
        from jax.sharding import Mesh

        from nnstreamer_tpu.parallel.ring_attention import ring_attention

        devs = np.array(jax.devices()[:4]).reshape(1, 4)
        mesh = Mesh(devs, ("dp", "sp"))
        q, k, v = _qkv(B=1, T=32, H=2, D=8, dtype=jnp.bfloat16, seed=6)
        a = ring_attention(q, k, v, mesh, causal=True, use_flash=True,
                           interpret=True)
        b = ring_attention(q, k, v, mesh, causal=True, use_flash=False)
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=2e-2, rtol=2e-2,
        )


class TestFlashAttentionGrad:
    """flash_attention_grad: kernel forward, recompute backward — grads
    must match full XLA autodiff through the reference."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        from nnstreamer_tpu.ops.flash_attention import flash_attention_grad

        q, k, v = _qkv(B=1, T=32, H=2, D=8, seed=9)

        def loss_flash(q, k, v):
            o = flash_attention_grad(q, k, v, causal, 16, 16, True)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = reference_attention(q, k, v, causal=causal).astype(q.dtype)
            return jnp.sum(o * o)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5
            )

    def test_forward_value_is_kernel_output(self):
        from nnstreamer_tpu.ops.flash_attention import (
            flash_attention,
            flash_attention_grad,
        )

        q, k, v = _qkv(B=1, T=32, H=2, D=8, seed=10)
        a = flash_attention_grad(q, k, v, True, 16, 16, True)
        b = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                            interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
