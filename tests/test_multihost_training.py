"""Multi-host TRAINING: the sharded transformer train step over a mesh
spanning two OS processes (dp across hosts, tp/sp within a host).

This is the DCN-scale analog of the reference's NCCL/MPI training
backends: the single-process `make_train_step` runs unchanged; only the
mesh and the data placement change.  Every process must observe the
identical (replicated) loss sequence, and it must decrease.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

import _env_capabilities

pytestmark = pytest.mark.skipif(
    not _env_capabilities.multihost_cpu_ok(),
    reason="multi-process CPU gang needs >= 2 cores (workers get "
    "virtual devices via jax_num_cpu_devices or the XLA_FLAGS "
    "fallback; on one core the gang starves gloo barriers)",
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "tests", "_multihost_train_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_host_dp_training():
    nproc, nlocal = 2, 4
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(nproc):
        env = dict(
            os.environ,
            NNS_TPU_COORDINATOR=coord,
            NNS_TPU_NUM_PROCS=str(nproc),
            NNS_TPU_PROC_ID=str(pid),
            NNS_TPU_LOCAL_DEVICES=str(nlocal),
            JAX_PLATFORMS="cpu",
        )
        env.pop("XLA_FLAGS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = {}
    try:
        for pid, p in enumerate(procs):
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker {pid} failed:\n{err[-2000:]}"
            line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
            assert line, f"worker {pid} printed no RESULT:\n{out[-500:]}"
            results[pid] = json.loads(line[-1][len("RESULT "):])
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()

    a, b = results[0]["losses"], results[1]["losses"]
    assert a == b, f"hosts disagree on the replicated loss: {a} vs {b}"
    assert a[-1] < a[0], f"loss did not decrease: {a}"
    assert results[0]["mesh"]["dp"] == nproc
    assert results[0]["mesh"]["tp"] == 2
